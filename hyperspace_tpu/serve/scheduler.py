"""Admission-controlled concurrent query scheduler.

The serving layer that turns the one-query-at-a-time engine into a
multi-query server: ``submit()`` enqueues a query under a bounded run
queue, an admission controller dispatches up to
``HYPERSPACE_MAX_CONCURRENT_QUERIES`` of them onto named worker threads
(highest priority first, FIFO within a priority), and every admitted query
executes its *unchanged* ``collect()`` path under a ``QueryContext`` — the
PR-2 scan pipeline and PR-3 join streamer become tasks interleaved across
queries by construction: query A's worker blocks in device dispatch while
query B's chunks decode on the shared engine IO pool, all read-ahead
reserving through the one global byte budget (serve/budget.py).

Concurrent execution stays bit-identical to serial per query: workers run
the exact same plan/executor/kernel code a direct ``collect()`` runs, the
shared caches are race-proven (PR 6), and the budget only throttles
*scheduling* of read-ahead, never results. ``tools/serve_smoke.py`` gates
exactly that.

Per-query attribution rides the existing telemetry: the trace stack is
thread-local, so each admitted query's spans root at its own
``serve:query`` span; ``serve:admit`` marks the admission decision on the
submitter's thread.

Cancellation: ``QueryHandle.cancel()`` flips the context flag; a queued
query resolves immediately, a running one unwinds at its next chunk
boundary (see serve/context.py), releasing budget reservations and
read-ahead futures through the streamers' ``finally`` blocks.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Optional

from ..exceptions import HyperspaceError
from ..staticcheck.concurrency import TrackedLock
from ..telemetry import trace
from ..utils import env
from .budget import global_budget
from .context import QueryCancelledError, QueryContext, query_scope


class AdmissionRejected(HyperspaceError):
    """The run queue is full (``HYPERSPACE_SERVE_QUEUE_DEPTH``): shed load
    at admission instead of queueing unboundedly."""


class SchedulerShutdown(HyperspaceError):
    """submit() after shutdown()."""


_QUEUED, _RUNNING, _DONE, _FAILED, _CANCELLED = (
    "queued", "running", "done", "failed", "cancelled",
)


class QueryHandle:
    """The submitter's view of one query: status, result, cancellation."""

    __slots__ = (
        "ctx", "_fn", "_sched", "status", "_result", "_error", "_done",
        "_submit_t", "_admit_t", "_finish_t",
    )

    def __init__(self, ctx: QueryContext, fn: Callable, sched=None):
        self.ctx = ctx
        self._fn = fn
        self._sched = sched
        self.status = _QUEUED
        self._result = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        self._submit_t = 0.0
        self._admit_t = 0.0
        self._finish_t = 0.0

    @property
    def query_id(self) -> int:
        return self.ctx.query_id

    @property
    def label(self) -> str:
        return self.ctx.label

    @property
    def priority(self) -> int:
        return self.ctx.priority

    @property
    def queue_wait_s(self) -> float:
        """Submission → admission wall time (0 until admitted)."""
        return max(0.0, self._admit_t - self._submit_t) if self._admit_t else 0.0

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block for the query's outcome. Re-raises the query's failure or
        ``QueryCancelledError``; ``TimeoutError`` when still in flight."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"query {self.query_id} ({self.label}) still {self.status} "
                f"after {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._result

    def cancel(self) -> None:
        """Cooperative cancel: a queued query resolves immediately; a
        running one unwinds at its next chunk boundary."""
        if self._sched is not None:
            self._sched.cancel(self)
        else:
            self.ctx.cancel()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"QueryHandle(id={self.query_id}, {self.label!r}, {self.status})"


class QueryScheduler:
    """Bounded-queue, priority-ordered admission controller over a fixed
    worker pool. One instance serves many submitters; all state transitions
    happen under one TrackedLock, metric emission outside it."""

    def __init__(
        self,
        max_concurrent: Optional[int] = None,
        queue_depth: Optional[int] = None,
    ):
        from ..utils.workers import io_pool

        self.max_concurrent = max(
            1,
            max_concurrent
            if max_concurrent is not None
            else env.env_int("HYPERSPACE_MAX_CONCURRENT_QUERIES"),
        )
        self.queue_depth = max(
            1,
            queue_depth
            if queue_depth is not None
            else env.env_int("HYPERSPACE_SERVE_QUEUE_DEPTH"),
        )
        self._lock = TrackedLock("serve.scheduler")
        self._heap: list = []  # (-priority, seq, handle); lazy-removed
        self._seq = itertools.count()
        self._queued = 0  # live (non-cancelled) heap entries
        self._active: dict[int, QueryHandle] = {}
        self._handles: set = set()  # every non-terminal handle (drain())
        self._totals = {
            "admitted": 0, "done": 0, "failed": 0,
            "cancelled": 0, "rejected": 0,
        }
        self._down = False
        self._unrun: list = []  # ctx of queued-cancelled queries, drained
        # outside the lock into the query log (_flush_unrun)
        self._pool = io_pool(self.max_concurrent, "hs-serve")
        # knob-gated observability plane (HYPERSPACE_METRICS_PORT /
        # HYPERSPACE_SNAPSHOT_FILE): a serving process is exactly where the
        # exporter should come up; completely off otherwise
        from ..telemetry import exporter as _exporter

        _exporter.maybe_start_from_env()

    # --- submission -------------------------------------------------------

    def submit(
        self,
        fn: Callable,
        *,
        priority: Optional[int] = None,
        label: str = "query",
    ) -> QueryHandle:
        """Enqueue a zero-arg callable (typically ``df.collect``) and
        return its handle. Raises ``AdmissionRejected`` when the bounded
        queue is full, ``SchedulerShutdown`` after shutdown."""
        if priority is None:
            priority = env.env_int("HYPERSPACE_SERVE_DEFAULT_PRIORITY")
        ctx = QueryContext(label=label, priority=priority)
        h = QueryHandle(ctx, fn, self)
        now = time.perf_counter()
        with trace.span(
            "serve:admit", query_id=ctx.query_id, label=label,
            priority=priority,
        ) as sp:
            with self._lock:
                if self._down:
                    raise SchedulerShutdown("scheduler is shut down")
                if self._queued >= self.queue_depth:
                    self._totals["rejected"] += 1
                    rejected = True
                else:
                    rejected = False
                    h._submit_t = now
                    heapq.heappush(
                        self._heap, (-priority, next(self._seq), h)
                    )
                    self._queued += 1
                    self._totals["admitted"] += 1
                    self._handles.add(h)
                    self._dispatch_locked()
                queued, active = self._queued, len(self._active)
            sp.set_attr("rejected", rejected)
            sp.set_attr("queued", queued)
        from ..telemetry.metrics import REGISTRY

        if rejected:
            REGISTRY.counter("serve.rejected").inc()
            raise AdmissionRejected(
                f"run queue full ({self.queue_depth} queued); "
                f"query {ctx.query_id} ({label}) rejected"
            )
        REGISTRY.counter("serve.admitted").inc()
        REGISTRY.gauge("serve.queue_depth").set(queued)
        REGISTRY.gauge("serve.active_queries").set(active)
        self._flush_unrun()
        return h

    def submit_query(self, df, *, priority: Optional[int] = None,
                     label: str = "query") -> QueryHandle:
        """Convenience: submit a DataFrame's collect()."""
        return self.submit(df.collect, priority=priority, label=label)

    # --- dispatch (lock held) ---------------------------------------------

    def _dispatch_locked(self) -> None:
        while self._heap and len(self._active) < self.max_concurrent:
            _, _, h = heapq.heappop(self._heap)
            if h.status != _QUEUED:
                continue  # cancelled while queued: lazily removed
            if h.ctx.cancelled:
                # context cancelled without going through scheduler.cancel
                # (direct ctx.cancel()): resolve without running
                self._finish_locked(h, _CANCELLED, None,
                                    QueryCancelledError(
                                        f"query {h.query_id} cancelled"))
                h._done.set()
                # hslint: HS302 — caller holds self._lock (_locked contract)
                self._unrun.append(h.ctx)
                continue
            self._queued -= 1
            h.status = _RUNNING
            h._admit_t = time.perf_counter()
            self._active[h.query_id] = h
            self._pool.submit(self._run, h)

    def _finish_locked(self, h: QueryHandle, status: str, result,
                       error) -> None:
        if h.status == _QUEUED:
            self._queued -= 1
        h.status = status
        h._result = result
        h._error = error
        h._finish_t = time.perf_counter()
        self._active.pop(h.query_id, None)
        self._handles.discard(h)
        # hslint: HS302 — every caller holds self._lock (_locked contract)
        self._totals[status] += 1

    def _flush_unrun(self) -> None:
        """Append query-log records for queries resolved inside the lock
        without ever running (queued-cancel): the ledger append and metric
        emission must happen outside the scheduler lock."""
        with self._lock:
            pending, self._unrun = self._unrun, []
        if pending:
            from ..telemetry.attribution import LEDGER

            for ctx in pending:
                LEDGER.record_unrun(ctx)

    # --- worker -----------------------------------------------------------

    def _run(self, h: QueryHandle) -> None:
        from ..telemetry import attribution
        from ..telemetry.metrics import REGISTRY

        REGISTRY.histogram("serve.queue_wait_ms").observe(
            h.queue_wait_s * 1000
        )
        # open the per-query attribution entry and install it for the whole
        # execution: every counter/histogram write on this thread — and on
        # IO-pool tasks bound via attribution.bound() — charges this query
        stats = attribution.LEDGER.begin(h.ctx, queue_wait_s=h.queue_wait_s)
        try:
            with query_scope(h.ctx), attribution.scope(stats):
                with trace.span(
                    "serve:query", query_id=h.query_id, label=h.label,
                    priority=h.priority,
                ) as sp:
                    out = h._fn()
                    sp.set_attr("status", "done")
            status, result, error = _DONE, out, None
        except QueryCancelledError as e:
            status, result, error = _CANCELLED, None, e
        except BaseException as e:  # noqa: BLE001 - stored, re-raised in result()
            status, result, error = _FAILED, None, e
        with self._lock:
            self._finish_locked(h, status, result, error)
            self._dispatch_locked()
            queued, active = self._queued, len(self._active)
        h._done.set()
        # finish AFTER the scope exited so the rollup metrics are not
        # charged back to the query they describe
        attribution.LEDGER.finish(stats, outcome=status, error=error)
        self._flush_unrun()
        REGISTRY.counter(f"serve.{status}").inc()
        REGISTRY.gauge("serve.queue_depth").set(queued)
        REGISTRY.gauge("serve.active_queries").set(active)

    # --- control ----------------------------------------------------------

    def cancel(self, h: QueryHandle) -> None:
        """Handle-level cancel with immediate resolution for queued
        queries (running ones resolve at their next chunk boundary)."""
        h.ctx.cancel()
        notify = False
        with self._lock:
            if h.status == _QUEUED:
                self._finish_locked(
                    h, _CANCELLED, None,
                    QueryCancelledError(f"query {h.query_id} cancelled"),
                )
                self._dispatch_locked()
                notify = True
            queued, active = self._queued, len(self._active)
        if notify:
            from ..telemetry.attribution import LEDGER
            from ..telemetry.metrics import REGISTRY

            h._done.set()
            LEDGER.record_unrun(h.ctx, queue_wait_s=h.queue_wait_s)
            REGISTRY.counter("serve.cancelled").inc()
            REGISTRY.gauge("serve.queue_depth").set(queued)
            REGISTRY.gauge("serve.active_queries").set(active)
        self._flush_unrun()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait until every submitted query reached a terminal state."""
        deadline = None if timeout is None else time.perf_counter() + timeout
        while True:
            with self._lock:
                pending = list(self._handles)
            if not pending:
                return True
            remaining = None
            if deadline is not None:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    return False
            pending[0]._done.wait(remaining)

    def shutdown(self, wait: bool = True, cancel: bool = False) -> None:
        """Stop admitting; optionally cancel everything in flight. With
        ``wait`` the worker pool joins (running queries finish or unwind)."""
        with self._lock:
            self._down = True
            pending = list(self._handles) if cancel else []
        for h in pending:
            self.cancel(h)
        self._pool.shutdown(wait=wait)

    # --- introspection ----------------------------------------------------

    def state(self) -> dict:
        """Aggregate serving state for hs.profile / tools: active + queued
        queries with their waits, totals, and the global budget ledger."""
        now = time.perf_counter()
        with self._lock:
            active = [
                {
                    "query_id": h.query_id,
                    "label": h.label,
                    "priority": h.priority,
                    "queue_wait_ms": round(h.queue_wait_s * 1000, 3),
                    "running_ms": round((now - h._admit_t) * 1000, 3),
                }
                for h in self._active.values()
            ]
            queued = [
                {
                    "query_id": h.query_id,
                    "label": h.label,
                    "priority": h.priority,
                    "waited_ms": round((now - h._submit_t) * 1000, 3),
                }
                for _, _, h in sorted(self._heap)
                if h.status == _QUEUED
            ]
            totals = dict(self._totals)
        return {
            "max_concurrent": self.max_concurrent,
            "queue_depth_limit": self.queue_depth,
            "active": active,
            "queued": queued,
            "totals": totals,
            "budget": global_budget().state(),
            "device_budget": _device_budget_state(),
        }


# --- process-default scheduler ----------------------------------------------

_default_lock = TrackedLock("serve.scheduler_singleton")
_DEFAULT: Optional[QueryScheduler] = None


def get_scheduler() -> QueryScheduler:
    """The process-default scheduler (knob-configured), created on first
    use — the REPL/server entry point; tests build their own instances."""
    global _DEFAULT
    with _default_lock:
        if _DEFAULT is None:
            _DEFAULT = QueryScheduler()
        return _DEFAULT


def reset_scheduler(wait: bool = True) -> None:
    """Shut the default scheduler down and forget it (tests)."""
    global _DEFAULT
    with _default_lock:
        sched, _DEFAULT = _DEFAULT, None
    if sched is not None:
        sched.shutdown(wait=wait, cancel=True)


def submit(fn: Callable, *, priority: Optional[int] = None,
           label: str = "query") -> QueryHandle:
    """Module-level convenience on the default scheduler."""
    return get_scheduler().submit(fn, priority=priority, label=label)


def serve_state() -> dict:
    """Serving state without forcing a scheduler into existence: the
    default scheduler's state when one exists, else an idle snapshot with
    the budget ledger (hs.profile renders this)."""
    with _default_lock:
        sched = _DEFAULT
    if sched is not None:
        return sched.state()
    return {
        "max_concurrent": None,
        "queue_depth_limit": None,
        "active": [],
        "queued": [],
        "totals": {},
        "budget": global_budget().state(),
        "device_budget": _device_budget_state(),
    }


def _device_budget_state() -> dict:
    """Device-ledger occupancy + spill counters: the device-memory block
    rendered by hs.profile, tools/hs_top.py, and the exporter /snapshot."""
    from ..telemetry.metrics import REGISTRY
    from .budget import device_budget

    st = device_budget().state()
    for name in ("parks", "spills", "resumes"):
        st[name] = REGISTRY.counter(f"join.spill.{name}").value
    return st
