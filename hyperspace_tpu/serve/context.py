"""Per-query serving context: identity, priority, cooperative cancellation.

Every query the scheduler admits runs its whole execution (plan → streamers
→ kernels) on one worker thread under a ``QueryContext`` installed via
``query_scope``. The context is what makes a query addressable while it
runs: the budget accountant tags reservations with it, the trace layer's
``serve:query`` span carries its id, and ``cancel()`` flips the one flag
every streaming loop polls.

Cancellation is cooperative and chunk-granular: ``check_cancelled()`` sits
inside the ordered chunk/pair streamers (columnar/io.iter_chunks,
bucket_join._iter_bucket_pairs), the pipelined fold loops (plan/tpu_exec),
and the per-node executor walk (plan/executor.execute_plan), so a cancelled
query unwinds at the next chunk boundary. The unwind path is the streams'
existing ``finally`` blocks — read-ahead futures cancel, IO pools release,
and budget reservations return to the global accountant — which is exactly
the "releases everything within a scheduler tick" contract tests pin.

``QueryCancelledError`` deliberately derives from ``BaseException`` (the
``InjectedCrash`` precedent in utils/faults.py): the device tier wraps its
streamed execution in ``except Exception`` handlers that degrade to a host
re-run via the breaker, and a swallowed cancellation would *re-execute* the
query on the host instead of stopping it. No ``except Exception`` on the
way out may absorb a cancel.
"""

from __future__ import annotations

import contextvars
import itertools
import threading
from typing import Optional


class QueryCancelledError(BaseException):
    """The running query was cancelled via its handle. BaseException so the
    device tier's ``except Exception`` degrade-to-host handlers can never
    swallow a cancel into a breaker event + host re-execution (see module
    docstring); catch it explicitly via ``QueryHandle.result()``."""


_ids = itertools.count(1)


class QueryContext:
    """Identity + cancellation flag of one admitted query. ``tenant`` is
    the owning tenant's name (the QoS dimension: per-tenant queues, budget
    partitions, and ledger rollups all key on it; "default" when the
    submitter never said otherwise) and ``deadline_s`` the optional SLO
    the admission door checked against. ``device_home`` is the mesh device
    ordinal the scheduler placed this query on (tenant-weighted occupancy
    argmin at dispatch; None outside the scheduler or with the mesh off) —
    the skew-aware placer rotates its packing from it so concurrent
    queries spread across the mesh."""

    __slots__ = ("query_id", "label", "priority", "tenant", "deadline_s",
                 "device_home", "approx_fraction", "_cancelled")

    def __init__(self, label: str = "query", priority: int = 0,
                 tenant: str = "default",
                 deadline_s: Optional[float] = None):
        self.query_id = next(_ids)
        self.label = label
        self.priority = priority
        self.tenant = tenant
        self.deadline_s = deadline_s
        self.device_home: Optional[int] = None
        # sampling fraction the QoS degrade policy selected for this query
        # (serve/scheduler.py); None = exact. plan/sampling.py reads it at
        # collect time and engages the sampled tier when eligible.
        self.approx_fraction: Optional[float] = None
        self._cancelled = threading.Event()

    def cancel(self) -> None:
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"QueryContext(id={self.query_id}, label={self.label!r})"


# the running query of the current thread (None outside the serving layer);
# a contextvar so nested scopes restore correctly on exit
_current: contextvars.ContextVar = contextvars.ContextVar(
    "hyperspace_serve_query", default=None
)


def current_query() -> Optional[QueryContext]:
    """The QueryContext this thread is executing under, or None (direct
    ``collect()`` callers outside any scheduler)."""
    return _current.get()


class query_scope:
    """Install ``ctx`` as the thread's current query for the duration."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: QueryContext):
        self._ctx = ctx
        self._token = None

    def __enter__(self) -> QueryContext:
        self._token = _current.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc) -> bool:
        _current.reset(self._token)
        return False


def check_cancelled() -> None:
    """Raise ``QueryCancelledError`` when the current query was cancelled.
    One contextvar read + one Event check — cheap enough for per-chunk and
    per-plan-node call sites; a no-op outside the serving layer."""
    ctx = _current.get()
    if ctx is not None and ctx.cancelled:
        raise QueryCancelledError(
            f"query {ctx.query_id} ({ctx.label}) cancelled"
        )
