"""Weighted-fair scheduling and SLO-aware admission for the serving plane.

The PR-8 scheduler drained ONE priority+FIFO run queue, so a tenant
flooding heavy scans monopolized the worker slots while every other tenant
queued behind it. This module replaces that queue with per-tenant queues
drained by **weighted-fair queueing over delivered cost**:

- Each tenant owns a (-priority, seq) heap — within a tenant, dispatch
  order is exactly the old FIFO+priority order, so a single-tenant process
  is bit-identical to the pre-QoS scheduler.
- Each tenant carries a *virtual-cost clock*: ``vclock += cost / weight``
  charged at query completion from the attribution ledger's ACTUAL
  per-query cost (run wall + io bytes + device transfer bytes, bytes
  normalized at ``HYPERSPACE_QOS_COST_MBPS``). Dispatch always picks the
  backlogged tenant with the smallest vclock, so the clocks — and
  therefore delivered cost *per unit weight* — equalize across backlogged
  tenants regardless of how lopsided their query sizes are.
- An idle tenant's clock does not accumulate credit: on wake (first entry
  into an empty queue) the clock jumps forward to the smallest clock among
  busy tenants (or the high-water mark when all are idle), so returning
  from idle buys fair treatment *from now on*, never a monopoly replaying
  the idle period.
- Queue-wait aging (``HYPERSPACE_SERVE_AGING_MS`` > 0): a queued entry's
  effective priority grows by one level per aging interval waited, capped
  at ``HYPERSPACE_SERVE_AGING_CAP`` — bounded escape hatch for the
  priority-0-starves-forever failure mode under a sustained high-priority
  flood. 0 (default) disables aging and preserves exact static-priority
  order.

``TenantQueues`` is NOT internally locked: every method is called under
the owning scheduler's lock (the ``_locked`` contract scheduler.py already
uses), which is what keeps vclock reads and heap mutation atomic with the
admission bookkeeping.

SLO-aware admission: ``CostModel`` keeps a per-label EWMA of observed run
wall seconds, corrected by the PR-13 estimator-accuracy ledger's observed
``serve.wall`` factor (telemetry/plan_stats.ACCURACY). A query submitted
with a deadline gets a fast feasibility check at the door — predicted run
cost plus expected queue wait against the deadline — and an unmeetable
deadline rejects *at submit time* (typed ``DeadlineUnmeetable``) instead
of queueing a query that is already dead. Completions with a prediction
observe (predicted, actual) back into ``ACCURACY`` so the correction
factor converges exactly like the scan/join estimators.

Graceful degradation (PR 19): when the approximate tier is enabled
(``HYPERSPACE_APPROX``) and the submitter allowed it, an unmeetable
deadline degrades to sampled execution instead of rejecting —
``choose_degrade_tier`` picks the largest sample fraction whose per-tier
cost prediction fits the deadline. Degraded walls feed the cost model
under ``tier_label(label, f)`` and never the exact label, so exact
predictions stay honest.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Optional

from ..utils import env
from .tenant import TENANTS

_QUEUED = "queued"  # scheduler's QueryHandle.status value for live entries


class _TenantQueue:
    """One tenant's run queue + virtual clock + delivered totals."""

    __slots__ = ("name", "heap", "queued", "active", "vclock", "totals")

    def __init__(self, name: str):
        self.name = name
        self.heap: list = []  # (-priority, seq, handle); lazily removed
        self.queued = 0  # live (status == queued) entries
        self.active = 0  # dispatched, not yet finished
        self.vclock = 0.0
        self.totals = {
            "admitted": 0, "done": 0, "failed": 0, "cancelled": 0,
            "rejected_rate": 0, "rejected_quota": 0, "rejected_deadline": 0,
            "aging_boosts": 0, "degraded": 0, "cost_s": 0.0,
        }


class TenantQueues:
    """Per-tenant queues + WFQ clocks. Every method runs under the owning
    scheduler's lock (the ``_locked`` contract) — no internal lock."""

    def __init__(self, registry=None):
        self._registry = registry if registry is not None else TENANTS
        self._q: dict[str, _TenantQueue] = {}
        self._vmax = 0.0  # high-water vclock (idle-wake floor)

    def _tq(self, name: str) -> _TenantQueue:
        tq = self._q.get(name)
        if tq is None:
            tq = self._q[name] = _TenantQueue(name)
        return tq

    # --- admission bookkeeping -------------------------------------------

    def counts(self, name: str) -> tuple[int, int]:
        tq = self._q.get(name)
        return (tq.queued, tq.active) if tq is not None else (0, 0)

    def push(self, name: str, entry: tuple) -> None:
        tq = self._tq(name)
        if tq.queued == 0 and tq.active == 0:
            # idle wake: jump the clock forward so the idle period never
            # converts into a backlog-monopolizing credit
            busy = [
                t.vclock for t in self._q.values()
                if t is not tq and (t.queued or t.active)
            ]
            tq.vclock = max(tq.vclock, min(busy) if busy else self._vmax)
        heapq.heappush(tq.heap, entry)
        tq.queued += 1
        tq.totals["admitted"] += 1

    def on_dequeue(self, name: str) -> None:
        self._tq(name).queued -= 1

    def on_activate(self, name: str) -> None:
        self._tq(name).active += 1

    def on_deactivate(self, name: str) -> None:
        self._tq(name).active -= 1

    def note_outcome(self, name: str, status: str) -> None:
        tq = self._tq(name)
        if status in tq.totals:
            tq.totals[status] += 1

    def note_rejection(self, name: str, kind: str) -> None:
        self._tq(name).totals[f"rejected_{kind}"] += 1

    def note_degrade(self, name: str) -> None:
        """An admitted query the deadline door degraded to the sampled
        tier instead of rejecting (counted on top of ``admitted``)."""
        self._tq(name).totals["degraded"] += 1

    # --- WFQ dispatch -----------------------------------------------------

    def pop_locked(self, aging_ms: float = 0.0, aging_cap: int = 0,
                   now: Optional[float] = None):
        """Next dispatchable ``(tenant, handle)``: the smallest-vclock
        tenant with a live queued entry and worker-slot headroom (its
        ``max_active`` quota), or None. Stale (already-cancelled) heap
        entries are skipped without touching counts — their counts were
        released when the scheduler resolved them."""
        while True:
            cands = []
            for tq in self._q.values():
                if tq.queued <= 0:
                    continue
                cap = self._registry.get(tq.name).max_active
                if cap is not None and tq.active >= cap:
                    continue
                cands.append(tq)
            if not cands:
                return None
            tq = min(cands, key=lambda t: (t.vclock, t.name))
            h = self._pop_live(tq, aging_ms, aging_cap, now)
            if h is None:
                tq.queued = 0  # count drifted past an all-stale heap
                continue
            return tq.name, h

    def _pop_live(self, tq: _TenantQueue, aging_ms: float, aging_cap: int,
                  now: Optional[float]):
        if not (aging_ms and aging_ms > 0):
            while tq.heap:
                _, _, h = heapq.heappop(tq.heap)
                if h.status == _QUEUED:
                    return h
            return None
        # aging: effective priority = priority + min(cap, waited/aging_ms);
        # bounded queues make the linear scan cheap, and order genuinely
        # changes with wait time so a static heap order cannot serve
        if now is None:
            now = time.perf_counter()
        best = static = None
        best_key = static_key = None
        for entry in tq.heap:
            pri_neg, seq, h = entry
            if h.status != _QUEUED:
                continue
            waited_ms = max(0.0, (now - h._submit_t) * 1000.0)
            boost = min(int(aging_cap), int(waited_ms / aging_ms))
            key = (pri_neg - boost, seq)
            if best_key is None or key < best_key:
                best, best_key = entry, key
            skey = (pri_neg, seq)
            if static_key is None or skey < static_key:
                static, static_key = entry, skey
        if best is None:
            return None
        if best is not static:
            tq.totals["aging_boosts"] += 1
        tq.heap.remove(best)
        heapq.heapify(tq.heap)
        return best[2]

    # --- virtual-cost charging -------------------------------------------

    def charge(self, name: str, cost_s: float) -> None:
        """Charge a finished query's delivered cost to its tenant's clock.
        Weight is read NOW (not at admission), so reweighting mid-stream
        takes effect on the very next charge."""
        tq = self._tq(name)
        weight = max(1e-6, float(self._registry.get(name).weight))
        tq.vclock += cost_s / weight
        tq.totals["cost_s"] += cost_s
        if tq.vclock > self._vmax:
            self._vmax = tq.vclock

    # --- introspection (still under the scheduler lock) -------------------

    def queued_entries(self) -> list[tuple]:
        """Live ``(tenant, -priority, seq, handle)`` across every queue."""
        out = []
        for tq in self._q.values():
            for pri_neg, seq, h in tq.heap:
                if h.status == _QUEUED:
                    out.append((tq.name, pri_neg, seq, h))
        return out

    def state(self) -> dict:
        """Per-tenant QoS snapshot (weights/quotas from the registry,
        clocks/totals/delivered share from this scheduler)."""
        total_cost = sum(tq.totals["cost_s"] for tq in self._q.values())
        out = {}
        for name, tq in sorted(self._q.items()):
            cfg = self._registry.get(name).config()
            out[name] = {
                **cfg,
                "queued": tq.queued,
                "active": tq.active,
                "vclock": round(tq.vclock, 6),
                "delivered_share": (
                    round(tq.totals["cost_s"] / total_cost, 4)
                    if total_cost > 0 else 0.0
                ),
                **{k: (round(v, 6) if isinstance(v, float) else v)
                   for k, v in tq.totals.items()},
            }
        return out


# ---------------------------------------------------------------------------
# cost normalization + the SLO cost model
# ---------------------------------------------------------------------------

def query_cost(record: dict) -> float:
    """A finished query's delivered cost in seconds, from its attribution
    record: run wall + attributed bytes (scan io + device transfers)
    normalized at ``HYPERSPACE_QOS_COST_MBPS`` — so a byte-heavy query that
    overlapped its io under a cheap wall still pays for the ledger share it
    consumed."""
    mbps = env.env_float("HYPERSPACE_QOS_COST_MBPS")
    nbytes = (
        record.get("bytes_read", 0)
        + record.get("upload_bytes", 0)
        + record.get("fetch_bytes", 0)
    )
    return record.get("total_ms", 0.0) / 1000.0 + nbytes / max(1.0, mbps * 1e6)


class CostModel:
    """Per-label EWMA of observed run wall seconds — the deadline-admission
    predictor. Its lock is a plain leaf (read under the scheduler's
    TrackedLock; never acquires anything itself). Predictions multiply by
    the PR-13 accuracy ledger's observed ``serve.wall`` correction factor,
    so a label the EWMA consistently mis-prices converges from truth."""

    _ALPHA = 0.3

    def __init__(self):
        self._lock = threading.Lock()
        self._ewma: dict[str, float] = {}
        self._n: dict[str, int] = {}
        self._global: Optional[float] = None

    def update(self, label: str, run_s: float) -> None:
        with self._lock:
            prev = self._ewma.get(label)
            self._ewma[label] = (
                run_s if prev is None
                else (1 - self._ALPHA) * prev + self._ALPHA * run_s
            )
            self._n[label] = self._n.get(label, 0) + 1
            self._global = (
                run_s if self._global is None
                else (1 - self._ALPHA) * self._global + self._ALPHA * run_s
            )

    def predict(self, label: str) -> Optional[float]:
        """Corrected run-cost prediction for a label; None = no history
        (an unknown workload is admitted, never guessed at)."""
        with self._lock:
            base = self._ewma.get(label)
        if base is None:
            return None
        from ..telemetry.plan_stats import ACCURACY

        return base * ACCURACY.correction("serve.wall", index=label)

    def mean_run_s(self) -> Optional[float]:
        with self._lock:
            return self._global

    def observations(self, label: str) -> int:
        with self._lock:
            return self._n.get(label, 0)

    def reset_for_testing(self) -> None:
        with self._lock:
            self._ewma.clear()
            self._n.clear()
            self._global = None


COST_MODEL = CostModel()


def observe_wall(label: str, predicted_s: float, actual_s: float) -> None:
    """Feed a (predicted, actual) run-wall pair into the PR-13 accuracy
    ledger. MUST be called inside the query's attribution scope so the
    ``estimator.qerror.serve.wall`` histogram stays conserved (per-query
    attributed counts == global deltas)."""
    from ..telemetry.plan_stats import observe

    observe("serve.wall", predicted_s, actual_s, index=label)


def tier_label(label: str, fraction: float) -> str:
    """Cost-model label for a query label running at a sampled fraction.
    Kept separate from the exact label on purpose: sampled walls feeding
    the exact EWMA would teach the door that exact queries are cheap and
    stop it degrading (or rejecting) exactly when it should."""
    return f"{label}|f={fraction:g}"


def choose_degrade_tier(label: str, deadline_s: float, queued: int,
                        max_concurrent: int) -> Optional[dict]:
    """Pick the sampled tier for a query whose exact-tier deadline verdict
    came back unmeetable: the LARGEST configured fraction (most accurate
    answer) whose predicted completion fits the deadline, falling back to
    the smallest fraction when none fits (serve a coarse answer inside a
    best-effort wall rather than reject). Per-tier predictions come from
    the tier's own EWMA once observed; before any observation the exact
    prediction scaled by the fraction is the prior — sampled scan cost is
    ~linear in kept rows. None when approximation is off (no fractions
    configured / ``HYPERSPACE_APPROX`` disabled) — the caller then rejects
    exactly as before."""
    from ..models import sample_store

    if not sample_store.approx_enabled():
        return None
    fractions = sample_store.sample_fractions()
    if not fractions:
        return None
    exact = COST_MODEL.predict(label)
    mean = COST_MODEL.mean_run_s()
    base = exact if exact is not None else mean
    if base is None:
        return None  # no evidence at all: verdict admits, never degrades
    chosen = None
    for f in sorted(fractions, reverse=True):
        pred = COST_MODEL.predict(tier_label(label, f))
        if pred is None:
            pred = base * f
        wait = (queued / max(1, max_concurrent)) * (
            mean if mean is not None else pred
        )
        tier = {"fraction": f, "predicted_s": pred,
                "expected_s": wait + pred}
        if tier["expected_s"] <= deadline_s:
            return tier
        chosen = tier  # loop is descending: ends at the smallest fraction
    return chosen


def deadline_verdict(label: str, deadline_s: float, queued: int,
                     max_concurrent: int) -> dict:
    """Fast feasibility check at the admission door. Expected completion =
    predicted run cost (per-label, corrected) + expected queue wait
    (queue depth / worker slots × global mean run cost). With zero history
    the query is admitted — rejection requires evidence, not a guess."""
    predicted = COST_MODEL.predict(label)
    mean = COST_MODEL.mean_run_s()
    if predicted is None and mean is None:
        return {"admit": True, "predicted_s": None, "expected_s": None}
    run = predicted if predicted is not None else mean
    wait = (queued / max(1, max_concurrent)) * (mean if mean is not None else run)
    expected = wait + run
    return {
        "admit": expected <= deadline_s,
        "predicted_s": predicted,
        "expected_s": expected,
    }
