"""The global streaming byte budget: one ledger for every read-ahead stream.

Before the serving layer, each streaming consumer accounted
``HYPERSPACE_IO_BUDGET_MB`` privately: the pipelined scan streamer
(columnar/io.iter_chunks) and the bucketed-join pair loader
(bucket_join._iter_bucket_pairs) each held their own counter, so a query
that both streamed a scan and ran a bucketed join reserved the budget
twice, and N concurrent queries multiplied it by N. The
``BudgetAccountant`` below is the single process-wide ledger both paths —
and any number of concurrent queries — now reserve through, bounded by
``HYPERSPACE_GLOBAL_BUDGET_MB``.

Deadlock freedom by construction: reservations NEVER block.

- A stream holding zero bytes is always granted, even past the limit (the
  *progress guarantee*: every admitted stream can always decode its next
  chunk, so no admission order can wedge).
- A stream already holding bytes is granted only while the global total
  stays within the limit; otherwise ``try_reserve`` returns False and the
  stream simply stops pumping read-ahead until its own deliveries free
  bytes.

Backpressure therefore stalls exactly the streams that already hold decoded
bytes — the hungriest streams wait the longest — and a stalled stream can
never block another stream's first chunk, which is how a saturated
low-priority scan cannot starve a freshly admitted high-priority query.

Cancellation integration: ``BudgetStream.close()`` (wired into the
streamers' ``finally`` blocks) returns every outstanding byte to the
ledger, so a cancelled query's reservations release the moment its stream
generator unwinds.

Tenant partitioning (multi-tenant QoS): every stream opened under a
serving query carries its tenant, and while bytes are held by more than
one tenant each tenant is additionally capped at its share of the limit
(``budget_fraction`` when configured on the tenant, else its
weight-proportional share among the tenants currently holding bytes) —
a stalled hog tenant saturates only its own partition, never the whole
ledger (``serve.budget.tenant_stalls`` counts the partition stalls). The
zero-holder progress grant is untouched, and the check is only consulted
when ≥2 tenants hold bytes, so single-tenant behavior is bit-identical.

Second ledger — DEVICE-resident bytes: the same accountant class, bounded
by ``HYPERSPACE_DEVICE_BUDGET_MB``, accounts the padded upload footprint of
in-flight bucketed-join band waves (``plan/device_join._BandScheduler``
reserves a wave before dispatch and releases it when the wave's results
have been fetched back to the host). Instead of declining to the host tier
when a build side exceeds device memory, the join *parks* the wave —
spilling already-dispatched waves' results to the host to drain its own
reservations — and re-admits it when reservations drain; the identical
zero-holder force-grant rule keeps N concurrent spilling joins deadlock-
free on the shared ledger. ``wait_for_release`` is the park path's bounded
wait primitive: parked consumers sleep on the release condition instead of
spinning, and every release/close wakes them.
"""

from __future__ import annotations

import threading

from typing import Optional

from ..staticcheck.concurrency import TrackedLock, guarded_by
from ..staticcheck.lifecycle import release_resource, tracked_resource
from ..utils import env
from .context import current_query


class BudgetStream:
    """One consumer's handle on the global ledger (a scan stream, a join
    pair loader). Not thread-safe across consumers by design — each stream
    is pumped from exactly one consumer thread; the accountant's lock
    serializes the shared ledger. ``tenant`` is the owning serving
    tenant's name (None outside the scheduler) — the key the per-tenant
    budget partition stalls on."""

    __slots__ = ("_acct", "label", "query_id", "tenant", "held", "_closed",
                 "_lc")

    def __init__(self, acct: "BudgetAccountant", label: str, query_id,
                 tenant: "str | None" = None):
        self._acct = acct
        self.label = label
        self.query_id = query_id
        self.tenant = tenant
        self.held = 0
        self._closed = False
        self._lc = tracked_resource(
            "budget.stream", f"{acct.name}/{label}", query=query_id,
            tenant=tenant,
        )

    def try_reserve(self, nbytes: int) -> bool:
        """Reserve ``nbytes`` for one in-flight chunk; False = over budget
        (stop pumping read-ahead and retry after the next delivery)."""
        return self._acct._reserve(self, nbytes)

    def release(self, nbytes: int) -> None:
        """Return ``nbytes`` on chunk delivery."""
        self._acct._release(self, nbytes)

    def close(self) -> None:
        """Return any outstanding reservation (abort/cancel path included);
        idempotent."""
        if not self._closed:
            self._closed = True
            self._acct._close(self)
            release_resource(self._lc)

    def __enter__(self) -> "BudgetStream":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class BudgetAccountant:
    """Process-wide byte ledger. All mutation under one TrackedLock so the
    lock-order audit covers it; metric emission stays outside the lock."""

    def __init__(self, max_bytes: int, name: str = "serve.budget"):
        self.max_bytes = max_bytes
        self.name = name  # metric prefix: <name>.{reservations,...}, <name>_bytes
        self._lock = TrackedLock(name)
        self._held = 0
        self._streams: dict[int, BudgetStream] = {}
        # release notification for parked consumers (plain leaf Condition:
        # never held while acquiring anything else, so it skips the audit
        # by the same rule as the per-metric value locks)
        self._released = threading.Condition(threading.Lock())

    # --- stream lifecycle -------------------------------------------------

    def stream(self, label: str, query=None, tenant=None) -> BudgetStream:
        """Open a consumer handle; ``query``/``tenant`` default to the
        thread's current serving context (None outside the scheduler)."""
        if query is None:
            ctx = current_query()
            if ctx is not None:
                query = ctx.query_id
                if tenant is None:
                    tenant = getattr(ctx, "tenant", None)
        s = BudgetStream(self, label, query, tenant)
        with self._lock:
            self._streams[id(s)] = s
        return s

    def _tenant_over_share_locked(self, s: BudgetStream, nbytes: int) -> bool:
        """Per-tenant partition of the ledger: while bytes are held by MORE
        THAN ONE tenant, each tenant is capped at its share of the limit —
        ``budget_fraction`` when configured, else weight-proportional among
        the tenants currently holding bytes — so one stalled hog tenant
        cannot pin the whole ledger. With zero or one tenant in play (the
        whole pre-QoS world, and any single-tenant process) this is never
        consulted, keeping that path bit-identical."""
        if s.tenant is None:
            return False
        holders = {
            st.tenant
            for st in self._streams.values()
            if st.held > 0 and st.tenant is not None
        }
        holders.add(s.tenant)
        if len(holders) <= 1:
            return False
        from .tenant import TENANTS

        tenants = {name: TENANTS.get(name) for name in holders}
        total_weight = sum(
            max(1e-6, t.weight) for t in tenants.values()
        )
        mine = tenants[s.tenant]
        share = (
            mine.budget_fraction
            if mine.budget_fraction is not None
            else max(1e-6, mine.weight) / total_weight
        )
        limit = self.max_bytes * max(0.0, min(1.0, share))
        held_t = sum(
            st.held for st in self._streams.values()
            if st.tenant == s.tenant
        )
        return held_t + nbytes > limit

    def _reserve(self, s: BudgetStream, nbytes: int) -> bool:
        forced = False
        tenant_stall = False
        with self._lock:
            if s.held > 0 and self._held + nbytes > self.max_bytes:
                granted = False
            elif s.held > 0 and self._tenant_over_share_locked(s, nbytes):
                granted = False
                tenant_stall = True
            else:
                granted = True
                forced = self._held + nbytes > self.max_bytes
                s.held += nbytes
                self._held += nbytes
            occupancy = self._held
        from ..telemetry.metrics import REGISTRY

        if granted:
            REGISTRY.counter(f"{self.name}.reservations").inc()
            if forced:
                # zero-holder progress grant past the limit
                REGISTRY.counter(f"{self.name}.force_grants").inc()
            REGISTRY.gauge(f"{self.name}_bytes").set(occupancy)
        else:
            REGISTRY.counter(f"{self.name}.stalls").inc()
            if tenant_stall:
                REGISTRY.counter(f"{self.name}.tenant_stalls").inc()
        return granted

    def _release(self, s: BudgetStream, nbytes: int) -> None:
        with self._lock:
            n = min(nbytes, s.held)
            s.held -= n
            self._held -= n
            occupancy = self._held
        self._notify_released()
        from ..telemetry.metrics import REGISTRY

        REGISTRY.gauge(f"{self.name}_bytes").set(occupancy)

    def _close(self, s: BudgetStream) -> None:
        with self._lock:
            self._held -= s.held
            s.held = 0
            self._streams.pop(id(s), None)
            occupancy = self._held
        self._notify_released()
        from ..telemetry.metrics import REGISTRY

        REGISTRY.gauge(f"{self.name}_bytes").set(occupancy)

    def _notify_released(self) -> None:
        with self._released:
            self._released.notify_all()

    def wait_for_release(self, timeout: float) -> None:
        """Block until some stream releases/closes or ``timeout`` elapses —
        the parked-consumer wait primitive. Callers MUST loop (a wakeup is
        a hint, not a grant) and poll cancellation between waits; the
        bounded timeout is what keeps the park path deadlock-free even if
        every other holder is itself parked."""
        with self._released:
            self._released.wait(timeout)

    # --- introspection ----------------------------------------------------

    def held_bytes(self) -> int:
        with self._lock:
            return self._held

    def state(self) -> dict:
        """Aggregate + per-stream + per-tenant occupancy for hs.profile /
        serve_state."""
        with self._lock:
            streams = [
                {"label": s.label, "query": s.query_id,
                 "tenant": s.tenant, "held_bytes": s.held}
                for s in self._streams.values()
            ]
            held = self._held
        tenants: dict[str, int] = {}
        for s in streams:
            if s["tenant"] is not None and s["held_bytes"]:
                tenants[s["tenant"]] = (
                    tenants.get(s["tenant"], 0) + s["held_bytes"]
                )
        return {
            "limit_bytes": self.max_bytes,
            "held_bytes": held,
            "streams": streams,
            "tenants": tenants,
        }

    def check_consistency(self) -> bool:
        """Ledger invariant at quiescence: the total equals the per-stream
        sum, and nothing is held once every stream closed (smoke gates)."""
        with self._lock:
            return self._held == sum(s.held for s in self._streams.values())


def configured_budget_bytes() -> int:
    """``HYPERSPACE_GLOBAL_BUDGET_MB`` in bytes. Migration fallback: an
    explicitly-set legacy ``HYPERSPACE_IO_BUDGET_MB`` (the old per-stream
    knob) keeps meaning when the global knob is unset, so existing
    deployments' memory ceilings carry over — now enforced globally instead
    of per stream."""
    raw = env.read_raw("HYPERSPACE_GLOBAL_BUDGET_MB")
    if raw is None:
        legacy = env.read_raw("HYPERSPACE_IO_BUDGET_MB")
        if legacy is not None:
            raw = legacy
    try:
        if raw is not None:
            return int(float(raw) * 2**20)
    except ValueError:
        pass
    return int(env.knob("HYPERSPACE_GLOBAL_BUDGET_MB").default * 2**20)


_global_lock = TrackedLock("serve.budget_singleton")  # guards the swap only
_GLOBAL: Optional[BudgetAccountant] = None


def global_budget() -> BudgetAccountant:
    """The process-wide accountant every streaming consumer reserves
    through. Budget size is read once at first use; tests swap it with
    ``reset_global_budget()`` after changing the knob."""
    global _GLOBAL
    with _global_lock:
        if _GLOBAL is None:
            _GLOBAL = BudgetAccountant(configured_budget_bytes())
        return _GLOBAL


def reset_global_budget() -> BudgetAccountant:
    """Re-read the knob and install a fresh ledger (tests; never mid-query)."""
    global _GLOBAL
    with _global_lock:
        _GLOBAL = BudgetAccountant(configured_budget_bytes())
        return _GLOBAL


# ---------------------------------------------------------------------------
# the device-resident ledger (memory-adaptive spilling joins)
# ---------------------------------------------------------------------------


def configured_device_budget_bytes() -> int:
    """``HYPERSPACE_DEVICE_BUDGET_MB`` in bytes; 0 disables the ledger
    (joins keep the pre-adaptive fixed-threshold behavior)."""
    try:
        return int(env.env_float("HYPERSPACE_DEVICE_BUDGET_MB") * 2**20)
    except ValueError:
        return int(env.knob("HYPERSPACE_DEVICE_BUDGET_MB").default * 2**20)


def _device_budget_name(ordinal: int) -> str:
    # ordinal 0 keeps the historical metric prefix EXACTLY so mesh-off
    # telemetry (and every existing dashboard/test) is byte-for-byte
    # unchanged; mesh ordinals suffix .d<N>
    return (
        "serve.device_budget" if ordinal == 0
        else f"serve.device_budget.d{ordinal}"
    )


# keyed by mesh device ordinal; every lookup/install is under _global_lock
_DEVICES: dict[int, BudgetAccountant] = guarded_by(
    {}, _global_lock, name="serve.budget._DEVICES"
)


def device_budget(ordinal: int = 0) -> BudgetAccountant:
    """The DEVICE-byte accountant for one mesh device ordinal — every
    bucketed-join band scheduler reserves wave footprints through these
    (N concurrent spilling joins share each device's ledger). Ordinal 0
    is the historical single-device ledger; under ``HYPERSPACE_MESH`` a
    wave placed on device d reserves through ordinal d, so concurrent
    spilling joins pack across the mesh instead of queueing on one chip.
    Each ledger is sized by ``HYPERSPACE_DEVICE_BUDGET_MB`` (the knob is
    per device: a mesh multiplies the fleet budget by its size) at first
    use; ``reset_device_budget()`` re-reads the knob (tests/bench)."""
    with _global_lock:
        acct = _DEVICES.get(ordinal)
        if acct is None:
            acct = BudgetAccountant(
                configured_device_budget_bytes(),
                name=_device_budget_name(ordinal),
            )
            _DEVICES[ordinal] = acct
        return acct


def device_budgets() -> dict[int, BudgetAccountant]:
    """Snapshot of every instantiated per-device accountant (telemetry
    rollups; ordinals appear lazily as placement first targets them)."""
    with _global_lock:
        return dict(_DEVICES)


def reset_device_budget() -> BudgetAccountant:
    """Re-read the knob and install fresh device ledgers (tests/bench;
    never mid-query). Drops every mesh ordinal and returns the fresh
    ordinal-0 ledger."""
    with _global_lock:
        _DEVICES.clear()
        acct = BudgetAccountant(
            configured_device_budget_bytes(), name=_device_budget_name(0)
        )
        _DEVICES[0] = acct
        return acct
