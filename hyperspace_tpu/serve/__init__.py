"""Concurrent multi-query serving: scheduler, global budget, cancellation.

Public surface of the serving layer (docs/performance.md "Concurrent
serving"):

- ``QueryScheduler`` / ``get_scheduler()`` / ``submit()`` — admission-
  controlled concurrent execution with per-query priorities, a bounded run
  queue, and first-class cancellation.
- ``global_budget()`` — the process-wide streaming byte budget every
  read-ahead stream (scan chunks, join pair loads) reserves through.
- ``device_budget()`` — the device-resident byte ledger bucketed-join band
  waves reserve their upload footprint through (park/spill admission).
- ``current_query()`` / ``check_cancelled()`` — the per-query context the
  engine's streaming loops poll.
- ``serve_state()`` — aggregate serving snapshot (active/queued queries,
  budget occupancy) rendered by ``hs.profile``.
"""

from .budget import (
    BudgetAccountant,
    BudgetStream,
    configured_budget_bytes,
    configured_device_budget_bytes,
    device_budget,
    global_budget,
    reset_device_budget,
    reset_global_budget,
)
from .context import (
    QueryCancelledError,
    QueryContext,
    check_cancelled,
    current_query,
    query_scope,
)
from .scheduler import (
    AdmissionRejected,
    QueryHandle,
    QueryScheduler,
    SchedulerShutdown,
    get_scheduler,
    reset_scheduler,
    serve_state,
    submit,
)

__all__ = [
    "AdmissionRejected",
    "BudgetAccountant",
    "BudgetStream",
    "QueryCancelledError",
    "QueryContext",
    "QueryHandle",
    "QueryScheduler",
    "SchedulerShutdown",
    "check_cancelled",
    "configured_budget_bytes",
    "configured_device_budget_bytes",
    "current_query",
    "device_budget",
    "get_scheduler",
    "global_budget",
    "query_scope",
    "reset_device_budget",
    "reset_global_budget",
    "reset_scheduler",
    "serve_state",
    "submit",
]
