"""Concurrent multi-query serving: scheduler, QoS, global budget, cancellation.

Public surface of the serving layer (docs/performance.md "Concurrent
serving" and "Multi-tenant QoS"):

- ``QueryScheduler`` / ``get_scheduler()`` / ``submit()`` — admission-
  controlled concurrent execution with per-tenant weighted-fair
  scheduling, per-query priorities, a bounded run queue, and first-class
  cancellation.
- ``TENANTS`` / ``Tenant`` — the process-wide tenant registry: weights,
  token-bucket rate limits, in-flight/active quotas, budget fractions
  (``HYPERSPACE_TENANTS`` bootstraps it). ``TenantQuotaExceeded`` is the
  typed door rejection, distinct from global ``AdmissionRejected``;
  ``DeadlineUnmeetable`` is the SLO-admission fast rejection.
- ``global_budget()`` — the process-wide streaming byte budget every
  read-ahead stream (scan chunks, join pair loads) reserves through,
  partitioned per tenant while several tenants hold bytes.
- ``device_budget()`` — the device-resident byte ledger bucketed-join band
  waves reserve their upload footprint through (park/spill admission).
- ``current_query()`` / ``check_cancelled()`` — the per-query context the
  engine's streaming loops poll.
- ``serve_state()`` — aggregate serving snapshot (active/queued queries,
  tenants, budget occupancy) rendered by ``hs.profile``.
"""

from .budget import (
    BudgetAccountant,
    BudgetStream,
    configured_budget_bytes,
    configured_device_budget_bytes,
    device_budget,
    global_budget,
    reset_device_budget,
    reset_global_budget,
)
from .context import (
    QueryCancelledError,
    QueryContext,
    check_cancelled,
    current_query,
    query_scope,
)
from .qos import COST_MODEL, CostModel, TenantQueues, query_cost
from .scheduler import (
    AdmissionRejected,
    DeadlineUnmeetable,
    QueryHandle,
    QueryScheduler,
    SchedulerShutdown,
    get_scheduler,
    reset_scheduler,
    serve_state,
    submit,
)
from .tenant import (
    DEFAULT_TENANT,
    TENANTS,
    Tenant,
    TenantQuotaExceeded,
    TenantRegistry,
    TenantSpecError,
    TokenBucket,
)

__all__ = [
    "AdmissionRejected",
    "BudgetAccountant",
    "BudgetStream",
    "COST_MODEL",
    "CostModel",
    "DEFAULT_TENANT",
    "DeadlineUnmeetable",
    "QueryCancelledError",
    "QueryContext",
    "QueryHandle",
    "QueryScheduler",
    "SchedulerShutdown",
    "TENANTS",
    "Tenant",
    "TenantQueues",
    "TenantQuotaExceeded",
    "TenantRegistry",
    "TenantSpecError",
    "TokenBucket",
    "check_cancelled",
    "configured_budget_bytes",
    "configured_device_budget_bytes",
    "current_query",
    "device_budget",
    "get_scheduler",
    "global_budget",
    "query_cost",
    "query_scope",
    "reset_device_budget",
    "reset_global_budget",
    "reset_scheduler",
    "serve_state",
    "submit",
]
