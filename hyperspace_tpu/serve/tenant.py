"""Tenant identity, configuration, and door-side quota machinery.

Every query the scheduler admits belongs to exactly one *tenant* (the
``default`` tenant when the caller never says otherwise — the zero-config
path is bit-identical to the pre-tenancy scheduler). A tenant carries the
QoS contract the serving plane enforces:

- ``weight`` — the weighted-fair share of delivered resource (wall + io
  bytes + device bytes, charged from the attribution ledger's actual
  per-query costs; see serve/qos.py). A weight-3 tenant receives 3x the
  delivered cost share of a weight-1 tenant while both are backlogged.
- ``rate_qps`` / ``burst`` — a token bucket checked at the admission door;
  an empty bucket rejects with the typed ``TenantQuotaExceeded`` *before*
  the query ever queues.
- ``max_in_flight`` — ceiling on the tenant's queued + running queries;
  past it the door rejects (typed), bounding how much of the run queue one
  tenant can occupy.
- ``max_active`` — ceiling on the tenant's concurrently *running* queries;
  enforced at dispatch (the query waits in its tenant queue, it is not
  rejected), bounding worker-slot occupancy.
- ``budget_fraction`` — explicit share of the global read-ahead byte
  ledger (serve/budget.py); unset, the share is weight-proportional among
  the tenants currently holding bytes.

Configuration is process-wide (``TENANTS`` registry) and env-bootstrapped:
``HYPERSPACE_TENANTS`` accepts ``name:key=value,key=value;name2:...``
(e.g. ``gold:weight=4,rate_qps=50;bulk:weight=1,max_active=1``). A typo'd
spec raises ``TenantSpecError`` at registry construction — the
``HYPERSPACE_FAULTS`` precedent: a silently-ignored QoS contract is worse
than a loud one.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..exceptions import HyperspaceError
from ..staticcheck.concurrency import TrackedLock
from ..utils import env

DEFAULT_TENANT = "default"


class TenantQuotaExceeded(HyperspaceError):
    """A per-tenant quota (token bucket, ``max_in_flight``) rejected the
    submission at the door. Deliberately NOT an ``AdmissionRejected``
    subclass: global load shedding means *the server* is full, this means
    *your tenant* is over its contract — callers back off differently."""


class TenantSpecError(HyperspaceError):
    """Malformed ``HYPERSPACE_TENANTS`` spec."""


class TokenBucket:
    """Classic token bucket: ``rate_qps`` tokens/second refill up to
    ``burst`` capacity; ``try_acquire`` never blocks. The clock is
    injectable for deterministic tests. Its lock is a plain leaf — nothing
    is ever acquired while holding it (the per-metric-lock rule)."""

    __slots__ = ("rate_qps", "burst", "_tokens", "_t_last", "_clock", "_lock")

    def __init__(self, rate_qps: float, burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.rate_qps = float(rate_qps)
        self.burst = float(burst) if burst is not None else max(
            1.0, 2.0 * self.rate_qps
        )
        self._tokens = self.burst  # a fresh tenant starts with a full burst
        self._clock = clock
        self._t_last = clock()
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> bool:
        now = self._clock()
        with self._lock:
            elapsed = max(0.0, now - self._t_last)
            self._t_last = now
            self._tokens = min(self.burst, self._tokens + elapsed * self.rate_qps)
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def tokens(self) -> float:
        """Current (refilled) token count — introspection only."""
        now = self._clock()
        with self._lock:
            elapsed = max(0.0, now - self._t_last)
            return min(self.burst, self._tokens + elapsed * self.rate_qps)


class Tenant:
    """One tenant's QoS contract. Mutable via ``TenantRegistry.configure``
    (reweighting mid-stream takes effect on the next vclock charge)."""

    __slots__ = (
        "name", "weight", "rate_qps", "burst", "max_in_flight",
        "max_active", "budget_fraction", "_bucket",
    )

    def __init__(self, name: str, weight: float = 1.0,
                 rate_qps: Optional[float] = None,
                 burst: Optional[float] = None,
                 max_in_flight: Optional[int] = None,
                 max_active: Optional[int] = None,
                 budget_fraction: Optional[float] = None):
        self.name = name
        self.weight = float(weight)
        self.rate_qps = rate_qps
        self.burst = burst
        self.max_in_flight = max_in_flight
        self.max_active = max_active
        self.budget_fraction = budget_fraction
        self._bucket: Optional[TokenBucket] = (
            TokenBucket(rate_qps, burst) if rate_qps is not None else None
        )

    def try_acquire_token(self) -> bool:
        """Door-side rate limit; always granted for unlimited tenants."""
        return self._bucket is None or self._bucket.try_acquire()

    def config(self) -> dict:
        return {
            "weight": self.weight,
            "rate_qps": self.rate_qps,
            "burst": self.burst,
            "max_in_flight": self.max_in_flight,
            "max_active": self.max_active,
            "budget_fraction": self.budget_fraction,
            "rate_tokens": (
                round(self._bucket.tokens(), 3)
                if self._bucket is not None else None
            ),
        }


_SPEC_FIELDS = {
    "weight": float,
    "rate_qps": float,
    "burst": float,
    "max_in_flight": int,
    "max_active": int,
    "budget_fraction": float,
}


def parse_tenant_spec(spec: str) -> dict[str, dict]:
    """``name:key=value,key=value;name2:...`` → {name: kwargs}. A bare
    ``name`` (no colon) declares a tenant with all defaults."""
    out: dict[str, dict] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, body = part.partition(":")
        name = name.strip()
        if not name:
            raise TenantSpecError(f"empty tenant name in {part!r}")
        kwargs: dict = {}
        for kv in filter(None, (s.strip() for s in body.split(","))):
            key, eq, raw = kv.partition("=")
            key = key.strip()
            if not eq or key not in _SPEC_FIELDS:
                raise TenantSpecError(
                    f"bad tenant field {kv!r} for {name!r} "
                    f"(known: {', '.join(sorted(_SPEC_FIELDS))})"
                )
            try:
                kwargs[key] = _SPEC_FIELDS[key](raw.strip())
            except ValueError as e:
                raise TenantSpecError(
                    f"unparseable value in {kv!r} for {name!r}: {e}"
                ) from None
        out[name] = kwargs
    return out


class TenantRegistry:
    """Process-wide tenant configuration. ``get`` auto-creates unknown
    tenants with defaults so tenancy is zero-config for existing callers;
    ``configure`` creates-or-updates. Bootstrapped from the
    ``HYPERSPACE_TENANTS`` spec knob at construction."""

    def __init__(self):
        self._lock = TrackedLock("serve.tenants")
        self._tenants: dict[str, Tenant] = {}
        spec = env.env_str("HYPERSPACE_TENANTS")
        if spec:
            for name, kwargs in parse_tenant_spec(spec).items():
                self._tenants[name] = Tenant(name, **kwargs)

    def get(self, name: str) -> Tenant:
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                t = self._tenants[name] = Tenant(name)
            return t

    def configure(self, name: str, **kwargs) -> Tenant:
        """Create or update a tenant's contract; unknown kwargs raise."""
        bad = set(kwargs) - set(_SPEC_FIELDS)
        if bad:
            raise TenantSpecError(
                f"unknown tenant field(s) {sorted(bad)} for {name!r}"
            )
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                t = self._tenants[name] = Tenant(name, **kwargs)
                return t
            for key, value in kwargs.items():
                setattr(t, key, value)
            if "rate_qps" in kwargs or "burst" in kwargs:
                t._bucket = (
                    TokenBucket(t.rate_qps, t.burst)
                    if t.rate_qps is not None else None
                )
            return t

    def known(self) -> list[str]:
        with self._lock:
            return sorted(self._tenants)

    def state(self) -> dict:
        with self._lock:
            tenants = list(self._tenants.values())
        return {t.name: t.config() for t in tenants}

    def reset_for_testing(self) -> None:
        """Drop all configuration and re-bootstrap from the env spec."""
        with self._lock:
            self._tenants.clear()
            spec = env.env_str("HYPERSPACE_TENANTS")
            if spec:
                for name, kwargs in parse_tenant_spec(spec).items():
                    self._tenants[name] = Tenant(name, **kwargs)


TENANTS = TenantRegistry()
