"""Index ↔ source staleness detection via plan signatures.

Reference parity:
- FileBasedSignatureProvider.scala:30-62 — md5 over concatenation of
  per-relation signatures, each derived from file (name, size, mtime).
- PlanSignatureProvider.scala — hash over the logical plan's operator kinds.
- IndexSignatureProvider.scala:27-51 — md5(file-signature ⊕ plan-signature).
- LogicalPlanSignatureProvider.scala:36-63 — factory pluggable by class name.

Providers operate on any plan object satisfying the small structural protocol
below (the plan IR in plan/nodes.py implements it): `preorder_kinds()` gives
operator type names; `leaf_file_infos()` gives per-relation FileInfo lists.
"""

from __future__ import annotations

from typing import Iterable, Optional, Protocol

from .entry import FileInfo
from ..utils.hashing import md5_hex


class SignablePlan(Protocol):
    def preorder_kinds(self) -> list[str]: ...
    def leaf_file_infos(self) -> list[list[FileInfo]]: ...


def _files_signature(files: Iterable[FileInfo]) -> str:
    parts = sorted(f"{f.name}:{f.size}:{f.modified_time}" for f in files)
    return md5_hex("".join(parts))


class FileBasedSignatureProvider:
    """Signature from source files only; robust to plan-shape changes."""

    NAME = "hyperspace_tpu.meta.signatures.FileBasedSignatureProvider"

    def sign(self, plan: SignablePlan) -> Optional[str]:
        leaves = plan.leaf_file_infos()
        if not leaves:
            return None
        return md5_hex("".join(_files_signature(files) for files in leaves))


class PlanSignatureProvider:
    """Signature from operator kinds only; robust to data changes."""

    NAME = "hyperspace_tpu.meta.signatures.PlanSignatureProvider"

    def sign(self, plan: SignablePlan) -> Optional[str]:
        kinds = plan.preorder_kinds()
        if not kinds:
            return None
        return md5_hex("".join(kinds))


class IndexSignatureProvider:
    """Default provider: combines file- and plan-signatures, so either data
    or shape drift invalidates the index (ref: IndexSignatureProvider:27-51)."""

    NAME = "hyperspace_tpu.meta.signatures.IndexSignatureProvider"

    def sign(self, plan: SignablePlan) -> Optional[str]:
        f = FileBasedSignatureProvider().sign(plan)
        p = PlanSignatureProvider().sign(plan)
        if f is None or p is None:
            return None
        return md5_hex(f + p)


_PROVIDERS = {
    FileBasedSignatureProvider.NAME: FileBasedSignatureProvider,
    PlanSignatureProvider.NAME: PlanSignatureProvider,
    IndexSignatureProvider.NAME: IndexSignatureProvider,
}


def get_provider(name: str):
    """Factory (ref: LogicalPlanSignatureProvider.scala:36-63). Falls back to
    importing a dotted path for user-supplied providers."""
    cls = _PROVIDERS.get(name)
    if cls is not None:
        return cls()
    import importlib

    mod_name, _, cls_name = name.rpartition(".")
    mod = importlib.import_module(mod_name)
    return getattr(mod, cls_name)()


DEFAULT_PROVIDER_NAME = IndexSignatureProvider.NAME
