from .entry import (
    Content,
    Directory,
    FileInfo,
    FileIdTracker,
    IndexLogEntry,
    LogEntry,
    LogicalPlanFingerprint,
    Relation,
    Signature,
    Source,
    SourcePlan,
    Update,
)
from .log_manager import IndexLogManager
from .data_manager import IndexDataManager
from .path_resolver import PathResolver

__all__ = [
    "Content",
    "Directory",
    "FileInfo",
    "FileIdTracker",
    "IndexLogEntry",
    "LogEntry",
    "LogicalPlanFingerprint",
    "Relation",
    "Signature",
    "Source",
    "SourcePlan",
    "Update",
    "IndexLogManager",
    "IndexDataManager",
    "PathResolver",
]
