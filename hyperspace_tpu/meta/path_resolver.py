"""Resolve index roots under the system path.

Reference parity: index/PathResolver.scala — getIndexPath :29-57 (existing
directory matched case-insensitively wins; otherwise exact-case new path),
systemPath :64-68 (conf `spark.hyperspace.system.path`).
"""

from __future__ import annotations

import os

from .. import constants as C
from ..config import HyperspaceConf


class PathResolver:
    def __init__(self, conf: HyperspaceConf, warehouse_dir: str = "."):
        self._conf = conf
        self._warehouse = warehouse_dir

    @property
    def system_path(self) -> str:
        p = self._conf.get(C.SYSTEM_PATH)
        if p:
            return str(p)
        return os.path.join(self._warehouse, C.INDEXES_DIR)

    def get_index_path(self, name: str) -> str:
        """Case-insensitive match against existing index directories; falls
        back to <system>/<name> for a new index."""
        root = self.system_path
        if os.path.isdir(root):
            for existing in os.listdir(root):
                if existing.lower() == name.lower() and os.path.isdir(
                    os.path.join(root, existing)
                ):
                    return os.path.join(root, existing)
        return os.path.join(root, name)
