"""Versioned index-data directories.

Reference parity: index/IndexDataManager.scala — layout doc :24-37, impl
:50-108. Index data for version n lives at <index>/v__=<n>/; each refresh or
rebuild writes a fresh version directory, never mutating old ones.

Crash safety (beyond the reference): maintenance ops never write into a
``v__=<n>`` directory directly. They write into ``<index>/_staging/<n>``
(``stage_version``) and atomically rename it into place (``publish``) after
the op succeeds — so a live version directory is all-or-nothing, and a crash
mid-build leaves only a staging dir that ``IndexManager.recover()`` sweeps.
The ``_staging`` name starts with ``_`` and carries no ``v__=`` segment, so
content listings (``index_content_from_path``) and ``get_all_versions`` are
structurally blind to it. The ``data.publish`` fault point brackets the
rename for the chaos gate's crash-before/crash-after matrix.
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Optional

from .. import constants as C
from ..exceptions import HyperspaceError
from ..utils import faults

_VERSION_RE = re.compile(re.escape(C.INDEX_VERSION_DIR_PREFIX) + r"=(\d+)$")

STAGING_DIR = "_staging"


class IndexDataManager:
    def __init__(self, index_path: str):
        self.index_path = index_path

    def version_path(self, version: int) -> str:
        return os.path.join(
            self.index_path, f"{C.INDEX_VERSION_DIR_PREFIX}={version}"
        )

    def get_all_versions(self) -> list[int]:
        if not os.path.isdir(self.index_path):
            return []
        out = []
        for name in os.listdir(self.index_path):
            m = _VERSION_RE.match(name)
            if m and os.path.isdir(os.path.join(self.index_path, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def get_latest_version(self) -> Optional[int]:
        versions = self.get_all_versions()
        return versions[-1] if versions else None

    def delete_version(self, version: int) -> None:
        p = self.version_path(version)
        if os.path.isdir(p):
            shutil.rmtree(p)

    # --- staged writes + atomic publish --------------------------------------

    def staging_path(self, version: int) -> str:
        return os.path.join(self.index_path, STAGING_DIR, str(version))

    def stage_version(self, version: int) -> str:
        """Fresh staging dir for building version ``version``: any leftover
        from a previous failed attempt of the SAME version is engine-owned
        temp data and is replaced (a retried action must not merge with a
        half-written build)."""
        p = self.staging_path(version)
        if os.path.isdir(p):
            shutil.rmtree(p)
        os.makedirs(p)
        return p

    def publish(self, version: int) -> None:
        """Atomically promote ``_staging/<n>`` to ``v__=<n>``: one rename on
        the same filesystem, so readers see the whole version or none of it.
        A missing staging dir publishes nothing (an op may legitimately
        write zero files); a pre-existing target means a crashed publish
        that recovery has not swept yet — refuse rather than merge."""
        src = self.staging_path(version)
        if not os.path.isdir(src):
            return
        dst = self.version_path(version)
        faults.fire("data.publish", version=version)
        if os.path.isdir(dst):
            raise HyperspaceError(
                f"cannot publish index data version {version}: {dst} already "
                f"exists (orphan of a crashed publish? run recover())"
            )
        os.rename(src, dst)
        faults.fire_after("data.publish", version=version)
        self._prune_staging_root()

    # --- recovery surface ----------------------------------------------------

    def staged_versions(self) -> list[int]:
        """Versions with a (possibly half-written) staging dir — after a
        clean publish there are none; anything here post-crash is orphan."""
        root = os.path.join(self.index_path, STAGING_DIR)
        if not os.path.isdir(root):
            return []
        return sorted(int(n) for n in os.listdir(root) if n.isdigit())

    def clear_staging(self) -> int:
        """Remove every staged (unpublished) build that is NOT a live
        in-process maintenance output; returns count removed. A staged
        version a running ingest/compaction transaction has protected
        (ingest.snapshots.protected_version) is work in flight, not
        debris — sweeping it from under the action (e.g. a concurrent
        recover() in the same process) would corrupt the build. A crashed
        process leaves no protection, so post-crash recovery sweeps
        everything exactly as before."""
        live = self._live_staged()
        removed = 0
        for v in self.staged_versions():
            if v in live:
                continue
            shutil.rmtree(os.path.join(self.index_path, STAGING_DIR, str(v)))
            removed += 1
        self._prune_staging_root()
        return removed

    def _live_staged(self) -> set:
        """Staged versions protected by a live in-process transaction."""
        from ..ingest.snapshots import REGISTRY as _SNAPSHOTS

        return _SNAPSHOTS.protected_versions(os.path.abspath(self.index_path))

    def orphan_version_dirs(self, referenced: set) -> list[int]:
        """Published ``v__=N`` dirs referenced by no committed entry AND
        neither pinned by an in-flight query snapshot nor protected by a
        live maintenance transaction (a compaction output between
        ``publish`` and its final log commit is live, not debris)."""
        from ..ingest.snapshots import REGISTRY as _SNAPSHOTS

        path = os.path.abspath(self.index_path)
        return [
            v
            for v in self.get_all_versions()
            if v not in referenced
            and not _SNAPSHOTS.is_pinned(path, v)
            and not _SNAPSHOTS.is_protected(path, v)
        ]

    def _prune_staging_root(self) -> None:
        root = os.path.join(self.index_path, STAGING_DIR)
        try:
            os.rmdir(root)  # only succeeds when empty — exactly the intent
        except OSError:
            pass  # hslint: HS402 — non-empty or absent root stays put
