"""Versioned index-data directories.

Reference parity: index/IndexDataManager.scala — layout doc :24-37, impl
:50-108. Index data for version n lives at <index>/v__=<n>/; each refresh or
rebuild writes a fresh version directory, never mutating old ones.
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Optional

from .. import constants as C

_VERSION_RE = re.compile(re.escape(C.INDEX_VERSION_DIR_PREFIX) + r"=(\d+)$")


class IndexDataManager:
    def __init__(self, index_path: str):
        self.index_path = index_path

    def version_path(self, version: int) -> str:
        return os.path.join(
            self.index_path, f"{C.INDEX_VERSION_DIR_PREFIX}={version}"
        )

    def get_all_versions(self) -> list[int]:
        if not os.path.isdir(self.index_path):
            return []
        out = []
        for name in os.listdir(self.index_path):
            m = _VERSION_RE.match(name)
            if m and os.path.isdir(os.path.join(self.index_path, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def get_latest_version(self) -> Optional[int]:
        versions = self.get_all_versions()
        return versions[-1] if versions else None

    def delete_version(self, version: int) -> None:
        p = self.version_path(version)
        if os.path.isdir(p):
            shutil.rmtree(p)
