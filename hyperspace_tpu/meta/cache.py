"""Creation-time-expiring cache for index log entries.

Reference parity: index/Cache.scala:22-41 (CreationTimeBasedCache) and
CachingIndexCollectionManager.scala:38-117 — read path caches the full
Seq[IndexLogEntry]; any mutating operation clears it; entries expire
`cache.expiryDurationInSeconds` (default 300 s) after being cached.
"""

from __future__ import annotations

import time
from typing import Generic, Optional, TypeVar

T = TypeVar("T")


class CreationTimeBasedCache(Generic[T]):
    def __init__(self, expiry_seconds_fn):
        # expiry read lazily so runtime conf changes take effect (ref:
        # CachingIndexCollectionManager reads conf on each access).
        self._expiry_seconds_fn = expiry_seconds_fn
        self._value: Optional[T] = None
        self._cached_at: float = 0.0

    def get(self) -> Optional[T]:
        if self._value is None:
            return None
        if time.time() - self._cached_at > float(self._expiry_seconds_fn()):
            self._value = None
            return None
        return self._value

    def set(self, value: T) -> None:
        self._value = value
        self._cached_at = time.time()

    def clear(self) -> None:
        self._value = None
