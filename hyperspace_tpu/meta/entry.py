"""On-disk index metadata model.

Reference parity: index/IndexLogEntry.scala — Content:40-113, Directory:123-303,
FileInfo:308-332, Signature/LogicalPlanFingerprint:337-374, Relation:379-384,
Source:386-406, IndexLogEntry:408-590 (runtime tag map 537-589),
FileIdTracker:627-703; LogEntry envelope index/LogEntry.scala:21-47.

Layout on disk is a versioned JSON envelope:
  {"version": "0.1", "id": N, "state": "...", "timestamp": ms, "enabled": true,
   "name": ..., "derivedDataset": {...}, "content": {...}, "source": {...},
   "properties": {...}}

`derivedDataset` is polymorphic on its "kind" field; index kinds register
themselves in INDEX_KIND_REGISTRY (models/base.py) the way the reference uses
Jackson @JsonTypeInfo on the Index trait.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

from ..exceptions import HyperspaceError
from ..utils.lru import BoundedLRU

LOG_VERSION = "0.1"

# Deserializers for polymorphic derivedDataset, keyed by "kind".
# models/base.py populates this at import time.
INDEX_KIND_REGISTRY: dict[str, Callable[[dict], Any]] = {}


# ---------------------------------------------------------------------------
# FileInfo / Directory / Content
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class FileInfo:
    """One source or index data file: (name, size, mtime, stable id).

    `name` is the file name only when nested in a Directory tree, matching the
    reference's normalized form (IndexLogEntry.scala:308-332). Equality and
    hashing ignore `id` like the reference's equals/hashCode (:318-327).
    """

    name: str
    size: int
    modified_time: int  # epoch millis
    id: int = -1

    UNKNOWN_FILE_ID = -1

    def __eq__(self, other):
        return (
            isinstance(other, FileInfo)
            and self.name == other.name
            and self.size == other.size
            and self.modified_time == other.modified_time
        )

    def __hash__(self):
        return hash((self.name, self.size, self.modified_time))

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "size": self.size,
            "modifiedTime": self.modified_time,
            "id": self.id,
        }

    @staticmethod
    def from_dict(d: dict) -> "FileInfo":
        return FileInfo(d["name"], d["size"], d["modifiedTime"], d.get("id", -1))

    @staticmethod
    def from_path(path: str, file_id: int = -1) -> "FileInfo":
        st = os.stat(path)
        return FileInfo(path, st.st_size, int(st.st_mtime * 1000), file_id)


@dataclass
class Directory:
    """Tree node of the Content hierarchy (ref: IndexLogEntry.scala:123-303).

    `name` is a single path component except at the root, where it is the
    filesystem root prefix (e.g. "/" or "C:\\"). Files hold leaf names only.
    """

    name: str
    files: list[FileInfo] = field(default_factory=list)
    subdirs: list["Directory"] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "files": [f.to_dict() for f in self.files],
            "subDirs": [d.to_dict() for d in self.subdirs],
        }

    @staticmethod
    def from_dict(d: dict) -> "Directory":
        return Directory(
            d["name"],
            [FileInfo.from_dict(f) for f in d.get("files", [])],
            [Directory.from_dict(s) for s in d.get("subDirs", [])],
        )

    @staticmethod
    def from_files(files: Iterable[FileInfo]) -> "Directory":
        """Build a minimal directory tree from absolute file paths
        (ref: Directory.fromLeafFiles IndexLogEntry.scala:195-260)."""
        root = Directory(name="/")
        for f in files:
            parts = [p for p in os.path.abspath(f.name).split(os.sep) if p]
            node = root
            for comp in parts[:-1]:
                child = next((s for s in node.subdirs if s.name == comp), None)
                if child is None:
                    child = Directory(name=comp)
                    node.subdirs.append(child)
                node = child
            node.files.append(
                FileInfo(parts[-1], f.size, f.modified_time, f.id)
            )
        return root

    @staticmethod
    def merge(a: "Directory", b: "Directory") -> "Directory":
        """Merge two trees, deduplicating identical files
        (ref: Directory.merge IndexLogEntry.scala:262-303); used by
        RefreshIncrementalAction's Merge update mode."""
        if a.name != b.name:
            raise HyperspaceError(
                f"Merging directories with different names: {a.name} != {b.name}"
            )
        files = list(a.files)
        seen = set(files)
        for f in b.files:
            if f not in seen:
                files.append(f)
                seen.add(f)
        subdirs: list[Directory] = []
        b_by_name = {d.name: d for d in b.subdirs}
        a_names = set()
        for d in a.subdirs:
            a_names.add(d.name)
            if d.name in b_by_name:
                subdirs.append(Directory.merge(d, b_by_name[d.name]))
            else:
                subdirs.append(d)
        for d in b.subdirs:
            if d.name not in a_names:
                subdirs.append(d)
        return Directory(a.name, files, subdirs)


@dataclass
class Content:
    """Root of a Directory tree with flattened-path helpers
    (ref: Content IndexLogEntry.scala:40-113)."""

    root: Directory

    def to_dict(self) -> dict:
        return {"root": self.root.to_dict()}

    @staticmethod
    def from_dict(d: dict) -> "Content":
        return Content(Directory.from_dict(d["root"]))

    @staticmethod
    def from_files(files: Iterable[FileInfo]) -> "Content":
        return Content(Directory.from_files(files))

    @staticmethod
    def from_directory_path(
        path: str,
        file_id_tracker: Optional["FileIdTracker"] = None,
        path_filter: Callable[[str], bool] | None = None,
    ) -> "Content":
        """List leaf files under `path` recursively (ref: Content.fromDirectory)."""
        infos = []
        for dirpath, _dirnames, filenames in os.walk(path):
            for fn in sorted(filenames):
                full = os.path.join(dirpath, fn)
                if path_filter is not None and not path_filter(full):
                    continue
                st = os.stat(full)
                size, mtime = st.st_size, int(st.st_mtime * 1000)
                fid = -1
                if file_id_tracker is not None:
                    fid = file_id_tracker.add_file(full, size, mtime)
                infos.append(FileInfo(full, size, mtime, fid))
        return Content.from_files(infos)

    def files(self) -> list[str]:
        """All file paths, absolute (ref: Content.files :46-52)."""
        return [f.name for f in self.file_infos()]

    def file_infos(self) -> list[FileInfo]:
        """FileInfos with `name` re-expanded to the absolute path
        (ref: Content.fileInfos :54-65)."""
        out: list[FileInfo] = []

        def walk(node: Directory, prefix: str):
            base = (
                node.name
                if prefix == ""
                else os.path.join(prefix, node.name)
                if node.name != "/"
                else "/"
            )
            for f in node.files:
                out.append(
                    FileInfo(os.path.join(base, f.name), f.size, f.modified_time, f.id)
                )
            for d in node.subdirs:
                walk(d, base)

        walk(self.root, "")
        return out

    @property
    def size_in_bytes(self) -> int:
        return sum(f.size for f in self.file_infos())


# ---------------------------------------------------------------------------
# Signatures / fingerprint
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Signature:
    provider: str
    value: str

    def to_dict(self) -> dict:
        return {"provider": self.provider, "value": self.value}

    @staticmethod
    def from_dict(d: dict) -> "Signature":
        return Signature(d["provider"], d["value"])


@dataclass
class LogicalPlanFingerprint:
    """Fingerprint of the source logical plan at index-build time
    (ref: IndexLogEntry.scala:337-374)."""

    signatures: list[Signature]
    kind: str = "LogicalPlan"

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "properties": {"signatures": [s.to_dict() for s in self.signatures]},
        }

    @staticmethod
    def from_dict(d: dict) -> "LogicalPlanFingerprint":
        return LogicalPlanFingerprint(
            [Signature.from_dict(s) for s in d["properties"]["signatures"]],
            d.get("kind", "LogicalPlan"),
        )


# ---------------------------------------------------------------------------
# Relation / Source
# ---------------------------------------------------------------------------

@dataclass
class Update:
    """Source-file delta recorded by quick refresh, consumed by Hybrid Scan
    (ref: Update IndexLogEntry.scala / RefreshQuickAction)."""

    appended_files: Content | None = None
    deleted_files: Content | None = None

    def to_dict(self) -> dict:
        return {
            "appendedFiles": self.appended_files.to_dict()
            if self.appended_files
            else None,
            "deletedFiles": self.deleted_files.to_dict()
            if self.deleted_files
            else None,
        }

    @staticmethod
    def from_dict(d: dict | None) -> "Update | None":
        if d is None:
            return None
        return Update(
            Content.from_dict(d["appendedFiles"]) if d.get("appendedFiles") else None,
            Content.from_dict(d["deletedFiles"]) if d.get("deletedFiles") else None,
        )


@dataclass
class Relation:
    """Serialized source relation: enough to re-load the source DataFrame at
    refresh time (ref: Relation IndexLogEntry.scala:379-384 and
    RefreshActionBase.df:54-77)."""

    root_paths: list[str]
    content: Content  # source files at index-build time ("data")
    schema: list[dict]  # [{"name":..., "type":...}, ...] in source column order
    file_format: str
    options: dict[str, str] = field(default_factory=dict)
    update: Update | None = None

    def to_dict(self) -> dict:
        return {
            "rootPaths": self.root_paths,
            "data": {
                "properties": {
                    "content": self.content.to_dict(),
                    "update": self.update.to_dict() if self.update else None,
                },
                "kind": "HDFS",
            },
            "dataSchemaJson": self.schema,
            "fileFormat": self.file_format,
            "options": self.options,
        }

    @staticmethod
    def from_dict(d: dict) -> "Relation":
        props = d["data"]["properties"]
        return Relation(
            d["rootPaths"],
            Content.from_dict(props["content"]),
            d["dataSchemaJson"],
            d["fileFormat"],
            d.get("options", {}),
            Update.from_dict(props.get("update")),
        )


@dataclass
class SourcePlan:
    """Source logical plan descriptor (ref: SparkPlan in IndexLogEntry.scala:386-395;
    here the plan is our own IR so the field names say what they are)."""

    relations: list[Relation]
    raw_plan: str  # rendered logical plan, informational
    fingerprint: LogicalPlanFingerprint

    def to_dict(self) -> dict:
        return {
            "properties": {
                "relations": [r.to_dict() for r in self.relations],
                "rawPlan": self.raw_plan,
                "fingerprint": self.fingerprint.to_dict(),
            },
            "kind": "Plan",
        }

    @staticmethod
    def from_dict(d: dict) -> "SourcePlan":
        p = d["properties"]
        return SourcePlan(
            [Relation.from_dict(r) for r in p["relations"]],
            p.get("rawPlan", ""),
            LogicalPlanFingerprint.from_dict(p["fingerprint"]),
        )


@dataclass
class Source:
    plan: SourcePlan

    def to_dict(self) -> dict:
        return {"plan": self.plan.to_dict()}

    @staticmethod
    def from_dict(d: dict) -> "Source":
        return Source(SourcePlan.from_dict(d["plan"]))


# ---------------------------------------------------------------------------
# Log entries
# ---------------------------------------------------------------------------

@dataclass
class LogEntry:
    """Versioned JSON envelope (ref: index/LogEntry.scala:21-47)."""

    state: str
    id: int = 0
    timestamp: int = 0
    enabled: bool = True

    def stamp(self) -> None:
        self.timestamp = int(time.time() * 1000)

    def to_dict(self) -> dict:
        return {
            "version": LOG_VERSION,
            "id": self.id,
            "state": self.state,
            "timestamp": self.timestamp,
            "enabled": self.enabled,
        }

    @staticmethod
    def from_dict(d: dict) -> "LogEntry | IndexLogEntry":
        if d.get("version") != LOG_VERSION:
            raise HyperspaceError(f"Unsupported log version: {d.get('version')}")
        if "name" in d:
            return IndexLogEntry.from_dict(d)
        e = LogEntry(d["state"], d["id"], d["timestamp"], d.get("enabled", True))
        return e


class IndexLogEntry(LogEntry):
    """Full index metadata entry (ref: IndexLogEntry.scala:408-590)."""

    def __init__(
        self,
        name: str,
        derived_dataset: Any,  # models.base.Index
        content: Content,
        source: Source,
        properties: dict[str, str] | None = None,
        state: str = "",
        id: int = 0,
        timestamp: int = 0,
        enabled: bool = True,
    ):
        super().__init__(state=state, id=id, timestamp=timestamp, enabled=enabled)
        self.name = name
        self.derived_dataset = derived_dataset
        self.content = content
        self.source = source
        self.properties: dict[str, str] = dict(properties or {})
        # Runtime-only per-plan tag map (ref: IndexLogEntry tags :537-589);
        # never serialized. Keyed by (plan_key, tag_name). Bounded LRU
        # (touch-on-get): tags are consumed within one optimization pass, but
        # entries live in the collection cache across many queries with
        # globally-unique plan ids — unbounded growth would be a slow leak on
        # long-lived sessions. The cap is far above any single pass's needs.
        self._tags: BoundedLRU = BoundedLRU(self._MAX_TAGS)

    # --- convenience accessors (ref: IndexLogEntry.scala:430-530) ---
    @property
    def kind(self) -> str:
        return self.derived_dataset.kind

    @property
    def relations(self) -> list[Relation]:
        return self.source.plan.relations

    @property
    def relation(self) -> Relation:
        # Indexes cover exactly one relation today (ref: RelationUtils).
        if len(self.relations) != 1:
            raise HyperspaceError("Index must have exactly one source relation")
        return self.relations[0]

    @property
    def signature(self) -> LogicalPlanFingerprint:
        return self.source.plan.fingerprint

    def source_file_infos(self) -> set[FileInfo]:
        return set(self.relation.content.file_infos())

    def source_files_size_in_bytes(self) -> int:
        return self.relation.content.size_in_bytes

    def source_update(self) -> Update | None:
        return self.relation.update

    def appended_files(self) -> set[FileInfo]:
        u = self.source_update()
        if u and u.appended_files:
            return set(u.appended_files.file_infos())
        return set()

    def deleted_files(self) -> set[FileInfo]:
        u = self.source_update()
        if u and u.deleted_files:
            return set(u.deleted_files.file_infos())
        return set()

    def index_data_files(self) -> list[FileInfo]:
        return self.content.file_infos()

    def index_data_size_in_bytes(self) -> int:
        return self.content.size_in_bytes

    def has_lineage_column(self) -> bool:
        return str(self.properties.get("lineage", "false")).lower() == "true"

    def index_version_dirs(self) -> list[str]:
        """Distinct data-version directories referenced by content."""
        from .. import constants as C

        dirs = set()
        for f in self.content.files():
            parts = f.split(os.sep)
            for p in parts:
                if p.startswith(C.INDEX_VERSION_DIR_PREFIX + "="):
                    dirs.add(p)
        return sorted(dirs)

    def with_update(
        self,
        appended: Iterable[FileInfo],
        deleted: Iterable[FileInfo],
        fingerprint: "LogicalPlanFingerprint | None" = None,
    ) -> "IndexLogEntry":
        """Copy with relation.update set (ref: IndexLogEntry.copyWithUpdate,
        used by RefreshQuickAction.logEntry:69-79); quick refresh also swaps
        in the fingerprint of the *current* source so the entry signature-
        matches at query time."""
        appended = list(appended)
        deleted = list(deleted)
        rel = self.relation
        new_rel = Relation(
            rel.root_paths,
            rel.content,
            rel.schema,
            rel.file_format,
            dict(rel.options),
            Update(
                Content.from_files(appended) if appended else None,
                Content.from_files(deleted) if deleted else None,
            ),
        )
        plan = SourcePlan(
            [new_rel],
            self.source.plan.raw_plan,
            fingerprint if fingerprint is not None else self.source.plan.fingerprint,
        )
        e = IndexLogEntry(
            self.name,
            self.derived_dataset,
            self.content,
            Source(plan),
            dict(self.properties),
            self.state,
            self.id,
            self.timestamp,
            self.enabled,
        )
        return e

    _MAX_TAGS = 65536

    # --- runtime tags ---
    def set_tag(self, plan_key: Any, tag: str, value: Any) -> None:
        self._tags.set((plan_key, tag), value)

    def get_tag(self, plan_key: Any, tag: str) -> Any:
        return self._tags.get((plan_key, tag))

    def unset_tag(self, plan_key: Any, tag: str) -> None:
        self._tags.pop((plan_key, tag), None)

    # --- serialization ---
    def to_dict(self) -> dict:
        d = super().to_dict()
        d.update(
            {
                "name": self.name,
                "derivedDataset": self.derived_dataset.to_dict(),
                "content": self.content.to_dict(),
                "source": self.source.to_dict(),
                "properties": self.properties,
            }
        )
        return d

    @staticmethod
    def from_dict(d: dict) -> "IndexLogEntry":
        dd = d["derivedDataset"]
        kind = dd.get("kind")
        if kind not in INDEX_KIND_REGISTRY:
            raise HyperspaceError(f"Unknown index kind: {kind!r}")
        derived = INDEX_KIND_REGISTRY[kind](dd)
        return IndexLogEntry(
            d["name"],
            derived,
            Content.from_dict(d["content"]),
            Source.from_dict(d["source"]),
            d.get("properties", {}),
            d["state"],
            d["id"],
            d["timestamp"],
            d.get("enabled", True),
        )

    def __eq__(self, other):
        return (
            isinstance(other, IndexLogEntry)
            and self.name == other.name
            and self.state == other.state
            and self.id == other.id
            and self.to_dict() == other.to_dict()
        )

    def __hash__(self):
        return hash((self.name, self.state, self.id))


# ---------------------------------------------------------------------------
# FileIdTracker
# ---------------------------------------------------------------------------

class FileIdTracker:
    """Assigns stable monotonically-increasing ids to (path, size, mtime)
    triples; ids survive refreshes so lineage columns stay valid
    (ref: FileIdTracker IndexLogEntry.scala:627-703)."""

    def __init__(self):
        self._ids: dict[tuple[str, int, int], int] = {}
        self._max_id = -1

    @property
    def max_id(self) -> int:
        return self._max_id

    def add_file_info(self, files: Iterable[FileInfo]) -> None:
        """Seed from an existing log entry's recorded files (keeps their ids)."""
        for f in files:
            if f.id == FileInfo.UNKNOWN_FILE_ID:
                raise HyperspaceError(f"Cannot seed tracker with unknown id: {f.name}")
            key = (f.name, f.size, f.modified_time)
            existing = self._ids.get(key)
            if existing is not None and existing != f.id:
                raise HyperspaceError(
                    f"Conflicting file id for {key}: {existing} vs {f.id}"
                )
            self._ids[key] = f.id
            self._max_id = max(self._max_id, f.id)

    def add_file(self, path: str, size: int, mtime: int) -> int:
        key = (path, size, mtime)
        if key not in self._ids:
            self._max_id += 1
            self._ids[key] = self._max_id
        return self._ids[key]

    def get_file_id(self, path: str, size: int, mtime: int) -> int | None:
        return self._ids.get((path, size, mtime))

    def file_to_id_map(self) -> dict[tuple[str, int, int], int]:
        return dict(self._ids)
