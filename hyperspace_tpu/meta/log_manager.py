"""Index transaction log with optimistic concurrency.

Reference parity: index/IndexLogManager.scala — trait :34-55, writeLog
temp-file + atomic "rename-if-absent" :178-194, getLatestStableLog backward
scan respecting CREATING/VACUUMING barriers :102-127, latestStable pointer
:57-99, createLatestStableLog :144-162.

Layout under each index root:
    <index>/_hyperspace_log/<id>          immutable JSON log entries
    <index>/_hyperspace_log/latestStable  pointer file (JSON copy of entry)

POSIX os.rename overwrites, so rename-if-absent is implemented with
os.link(temp, target) — hard-link creation fails with EEXIST if the id was
already committed, which is exactly the optimistic-concurrency check. On
filesystems without hard links (some overlay/FUSE/SMB mounts raise EPERM or
EOPNOTSUPP, not EEXIST) the commit falls back to an O_CREAT|O_EXCL
exclusive create of the target — the same lose-if-present semantics through
a different syscall.

Crash safety: the ``log.write`` fault point brackets the CAS so the chaos
gate can kill the process immediately before (entry never committed) or
immediately after (entry committed, every follow-up step lost) the commit;
``IndexManager.recover()`` must repair both worlds. Stale ``.tmp-*`` spool
files a hard kill leaves behind are swept by recovery via
``stale_temp_files``/``clear_temp_files``.
"""

from __future__ import annotations

import errno
import json
import os
import tempfile
import time
from typing import Optional

from .. import constants as C
from .entry import IndexLogEntry, LogEntry
from ..exceptions import HyperspaceError
from ..utils import faults

# States that may appear as the latest entry of a *stable* log tail.
# (ref: actions/Constants.scala STABLE_STATES; barrier states below from
# IndexLogManager.getLatestStableLog:102-127)
STABLE_STATES = frozenset({"ACTIVE", "DELETED", "DOESNOTEXIST"})
_BARRIER_STATES = frozenset({"CREATING", "VACUUMING"})


class IndexLogManager:
    def __init__(self, index_path: str):
        self.index_path = index_path
        self.log_dir = os.path.join(index_path, C.HYPERSPACE_LOG)

    # --- read ---
    def _entry_path(self, log_id: int) -> str:
        return os.path.join(self.log_dir, str(log_id))

    def get_log(self, log_id: int) -> Optional[LogEntry]:
        p = self._entry_path(log_id)
        if not os.path.exists(p):
            return None
        with open(p, "r", encoding="utf-8") as f:
            return LogEntry.from_dict(json.load(f))

    def get_latest_id(self) -> Optional[int]:
        if not os.path.isdir(self.log_dir):
            return None
        ids = [int(n) for n in os.listdir(self.log_dir) if n.isdigit()]
        return max(ids) if ids else None

    def get_latest_log(self) -> Optional[LogEntry]:
        latest = self.get_latest_id()
        return self.get_log(latest) if latest is not None else None

    def get_latest_stable_log(self) -> Optional[LogEntry]:
        """Prefer the latestStable pointer; fall back to a backward scan that
        stops at CREATING/VACUUMING barriers (an index being created or
        vacuumed has no usable earlier state)."""
        ptr = os.path.join(self.log_dir, C.LATEST_STABLE_LOG)
        if os.path.exists(ptr):
            with open(ptr, "r", encoding="utf-8") as f:
                entry = LogEntry.from_dict(json.load(f))
            if entry.state in STABLE_STATES:
                return entry
        latest = self.get_latest_id()
        if latest is None:
            return None
        for log_id in range(latest, -1, -1):
            entry = self.get_log(log_id)
            if entry is None:
                continue
            if entry.state in STABLE_STATES:
                return entry
            if entry.state in _BARRIER_STATES:
                return None
        return None

    def get_index_versions(self, states: list[str] | None = None) -> list[int]:
        """All committed log ids, optionally filtered by state, newest first
        (ref: IndexLogManagerImpl.getIndexVersions)."""
        if not os.path.isdir(self.log_dir):
            return []
        out = []
        for n in sorted(os.listdir(self.log_dir), key=lambda s: -int(s) if s.isdigit() else 0):
            if not n.isdigit():
                continue
            entry = self.get_log(int(n))
            if entry is not None and (states is None or entry.state in states):
                out.append(int(n))
        return out

    # --- write ---
    def write_log(self, log_id: int, entry: LogEntry) -> bool:
        """Commit `entry` as id `log_id`; returns False if the id is taken
        (optimistic-concurrency loss). Write is atomic: temp file + hard-link
        CAS, with an O_CREAT|O_EXCL fallback on linkless filesystems. The
        temp file is removed on every exit path — success, loss, or a
        failing fsync/close."""
        os.makedirs(self.log_dir, exist_ok=True)
        target = self._entry_path(log_id)
        if os.path.exists(target):
            return False
        entry.id = log_id
        fd, tmp = tempfile.mkstemp(dir=self.log_dir, prefix=".tmp-")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(entry.to_dict(), f, indent=2)
                f.flush()
                os.fsync(f.fileno())
            faults.fire("log.write", id=log_id, state=entry.state)
            try:
                os.link(tmp, target)  # fails iff target exists => atomic CAS
            except FileExistsError:
                return False
            except OSError as e:
                if e.errno not in (
                    errno.EPERM,
                    errno.EOPNOTSUPP,
                    errno.ENOTSUP,
                    errno.EMLINK,
                ):
                    raise
                # no hard links here: O_EXCL create has the same
                # lose-if-present atomicity
                if not self._exclusive_create(tmp, target):
                    return False
            faults.fire_after("log.write", id=log_id, state=entry.state)
            return True
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass  # hslint: HS402 — temp cleanup is best-effort by design

    @staticmethod
    def _exclusive_create(tmp: str, target: str) -> bool:
        """Copy ``tmp``'s bytes into an O_CREAT|O_EXCL-opened ``target``:
        the exclusive open IS the CAS; False on loss."""
        try:
            out = os.open(target, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
        except FileExistsError:
            return False
        try:
            with open(tmp, "rb") as src, os.fdopen(out, "wb") as dst:
                dst.write(src.read())
                dst.flush()
                os.fsync(dst.fileno())
        except OSError:
            # a half-written target must not look committed: remove it
            # before propagating the root cause
            try:
                os.unlink(target)
            except OSError:
                pass  # hslint: HS402 — already raising the root cause
            raise
        return True

    def create_latest_stable_log(self, log_id: int) -> bool:
        entry = self.get_log(log_id)
        if entry is None or entry.state not in STABLE_STATES:
            return False
        ptr = os.path.join(self.log_dir, C.LATEST_STABLE_LOG)
        fd, tmp = tempfile.mkstemp(dir=self.log_dir, prefix=".tmp-")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(entry.to_dict(), f, indent=2)
            os.replace(tmp, ptr)  # pointer overwrite is fine: atomic rename
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass  # hslint: HS402 — temp cleanup is best-effort by design
            raise
        return True

    def stable_pointer_id(self) -> Optional[int]:
        """Log id recorded in the latestStable pointer file, or None when
        the pointer is absent/unreadable (recovery compares this against the
        actual latest stable entry to detect a crash between the final
        log.write and the pointer rewrite)."""
        ptr = os.path.join(self.log_dir, C.LATEST_STABLE_LOG)
        try:
            with open(ptr, "r", encoding="utf-8") as f:
                return int(json.load(f)["id"])
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            return None

    def delete_latest_stable_log(self) -> bool:
        ptr = os.path.join(self.log_dir, C.LATEST_STABLE_LOG)
        try:
            os.unlink(ptr)
        except FileNotFoundError:
            pass
        return True

    # --- recovery surface ---
    def stale_temp_files(self, min_age_s: float = 0.0) -> list[str]:
        """Leftover ``.tmp-*`` spool files (a hard kill between mkstemp and
        the finally-unlink strands them); never includes committed entries.
        ``min_age_s`` shields a LIVE writer's in-flight spool file (the
        mkstemp→link window is microseconds; a non-forced sweep passes 60)."""
        if not os.path.isdir(self.log_dir):
            return []
        out = []
        for n in sorted(os.listdir(self.log_dir)):
            if not n.startswith(".tmp-"):
                continue
            p = os.path.join(self.log_dir, n)
            try:
                if time.time() - os.stat(p).st_mtime < min_age_s:
                    continue
            except OSError:
                continue  # vanished mid-scan: its writer is alive and done
            out.append(p)
        return out

    def clear_temp_files(self, min_age_s: float = 0.0) -> int:
        removed = 0
        for p in self.stale_temp_files(min_age_s):
            try:
                os.unlink(p)
                removed += 1
            except OSError:
                pass  # hslint: HS402 — sweep is best-effort; retried next pass
        return removed
