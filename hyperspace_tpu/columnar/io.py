"""Parquet/CSV/JSON ↔ ColumnBatch via pyarrow.

The reference leans on Spark's datasource layer; here pyarrow is the host-side
file substrate. Strings arrive dictionary-encoded for TPU-friendliness;
date32 stays as days-since-epoch int32.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Iterator, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson
import pyarrow.parquet as pq

from ..constants import INDEX_COMPRESSION_DEFAULT

from .table import Column, ColumnBatch, Schema, Field, STRING, DATE32
from ..exceptions import HyperspaceError
from ..serve import budget as _serve_budget
from ..serve import context as _serve_ctx
from ..telemetry import attribution as _attr
from ..utils import env, faults, retry

_ARROW_TO_LOGICAL = {
    pa.int8(): "int8",
    pa.int16(): "int16",
    pa.int32(): "int32",
    pa.int64(): "int64",
    pa.float32(): "float32",
    pa.float64(): "float64",
    pa.bool_(): "bool",
    pa.date32(): DATE32,
    pa.string(): STRING,
    pa.large_string(): STRING,
}

_LOGICAL_TO_ARROW = {
    "int8": pa.int8(),
    "int16": pa.int16(),
    "int32": pa.int32(),
    "int64": pa.int64(),
    "float32": pa.float32(),
    "float64": pa.float64(),
    "bool": pa.bool_(),
    DATE32: pa.date32(),
    STRING: pa.string(),
}


NESTED_PREFIX = "__hs_nested."


def _leaf_logical(t: pa.DataType, name: str) -> str:
    if pa.types.is_dictionary(t):
        t = t.value_type
    logical = _ARROW_TO_LOGICAL.get(t)
    if logical is None:
        if pa.types.is_timestamp(t):
            logical = "int64"
        elif pa.types.is_decimal(t):
            logical = "float64"
        else:
            raise HyperspaceError(f"Unsupported arrow type {t} for {name}")
    return logical


def _flatten_struct_field(f: pa.Field, prefix: str) -> list[Field]:
    """Struct leaves become flat fields named '<NESTED_PREFIX>a.b.c'
    (ref: ResolverUtils.ResolvedColumn's __hs_nested. normalization)."""
    out: list[Field] = []
    for sub in f.type:
        path = f"{prefix}.{sub.name}"
        if pa.types.is_struct(sub.type):
            out.extend(_flatten_struct_field(sub, path))
        else:
            out.append(Field(NESTED_PREFIX + path, _leaf_logical(sub.type, path)))
    return out


def arrow_schema_to_schema(sch: pa.Schema) -> Schema:
    fields = []
    for f in sch:
        if pa.types.is_struct(f.type):
            fields.extend(_flatten_struct_field(f, f.name))
            continue
        fields.append(Field(f.name, _leaf_logical(f.type, f.name)))
    return Schema(fields)


def _chunked_to_column(arr: pa.ChunkedArray, logical: str) -> Column:
    combined = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    validity = None
    if combined.null_count:
        validity = np.asarray(combined.is_valid())
    if logical == STRING:
        if pa.types.is_dictionary(combined.type):
            dict_arr = combined
        else:
            dict_arr = combined.dictionary_encode()
        codes = np.asarray(dict_arr.indices.fill_null(0)).astype(np.int32)
        vocab = dict_arr.dictionary.to_pylist()
        if not vocab:
            vocab = [""]
        return Column(codes, STRING, validity, [str(v) for v in vocab])
    if logical == DATE32:
        data = np.asarray(combined.cast(pa.int32()).fill_null(0))
        return Column(data.astype(np.int32), DATE32, validity)
    np_dtype = {"int8": pa.int8(), "int16": pa.int16(), "int32": pa.int32(),
                "int64": pa.int64(), "float32": pa.float32(),
                "float64": pa.float64(), "bool": pa.bool_()}[logical]
    if pa.types.is_timestamp(combined.type):
        combined = combined.cast(pa.int64())
    elif pa.types.is_decimal(combined.type):
        combined = combined.cast(pa.float64())
    if validity is None and combined.type == np_dtype:
        # hot path (index builds decode GBs here): non-null, type-exact
        # arrays view the arrow buffer zero-copy — no cast, no fill_null
        data = np.asarray(combined)
    else:
        data = np.asarray(combined.cast(np_dtype).fill_null(0))
    return Column(np.ascontiguousarray(data), logical, validity)


def _nested_leaf(table: pa.Table, flat_name: str) -> pa.ChunkedArray:
    """Extract the struct leaf behind a '<NESTED_PREFIX>a.b.c' flat name;
    parent-struct nulls propagate to the leaf."""
    import pyarrow.compute as pc

    path = flat_name[len(NESTED_PREFIX):].split(".")
    arr = table.column(path[0])
    for seg in path[1:]:
        arr = pc.struct_field(arr, seg)
    return arr


def table_to_batch(table: pa.Table) -> ColumnBatch:
    schema = arrow_schema_to_schema(table.schema)
    cols = {}
    top_names = set(table.schema.names)
    for f in schema:
        if f.name in top_names:
            arr = table.column(f.name)
        else:
            arr = _nested_leaf(table, f.name)
        cols[f.name] = _chunked_to_column(arr, f.dtype)
    return ColumnBatch(cols)


def batch_to_table(batch: ColumnBatch) -> pa.Table:
    arrays = []
    names = []
    for name, col in batch.columns.items():
        names.append(name)
        mask = None if col.validity is None else ~col.validity
        if col.dtype == STRING:
            # emit the dictionary codes directly — materializing an object
            # array of python strings costs ~5x the whole parquet write
            arrays.append(
                pa.DictionaryArray.from_arrays(
                    pa.array(col.data, mask=mask),
                    pa.array([str(v) for v in col.dictionary], type=pa.string()),
                )
            )
        elif col.dtype == DATE32:
            arrays.append(
                pa.array(col.data, type=pa.int32(), mask=mask).cast(pa.date32())
            )
        else:
            arrays.append(
                pa.array(col.data, type=_LOGICAL_TO_ARROW[col.dtype], mask=mask)
            )
    return pa.table(dict(zip(names, arrays)))


# --- readers -----------------------------------------------------------------

class _BytesBoundedLRU:
    """Decoded-chunk cache for engine-owned index files: on TPU the design
    keeps index chunks device-resident across queries; on the host the
    analogue is keeping the decoded columns. Keyed by (path, mtime, size)
    per file so any rewrite invalidates; bounded by bytes with LRU
    eviction. Raw source scans are never cached — indexes are the bounded,
    curated working set the engine owns."""

    def __init__(self, max_bytes: int, metric_name: str = ""):
        from collections import OrderedDict

        from ..staticcheck.concurrency import TrackedLock

        self.max_bytes = max_bytes
        self.metric_name = metric_name  # metrics-registry prefix (cache.<name>.*)
        self._d: "OrderedDict" = OrderedDict()
        self._bytes = 0
        self._lock = TrackedLock(f"io.cache.{metric_name or 'anon'}")
        self._inflight: dict = {}

    def _count(self, event: str, n: int = 1) -> None:
        if self.metric_name:
            from ..telemetry.metrics import REGISTRY

            REGISTRY.counter(f"cache.{self.metric_name}.{event}").inc(n)

    def _gauge(self, value: int) -> None:
        if self.metric_name:
            from ..telemetry.metrics import REGISTRY

            REGISTRY.gauge(f"cache.{self.metric_name}.bytes").set(value)

    def get(self, key):
        with self._lock:
            hit = self._d.get(key)
            if hit is not None:
                self._d.move_to_end(key)
                self._count("hits")
                return hit[0]
            self._count("misses")
            return None

    def set(self, key, value, nbytes: int) -> None:
        if nbytes > self.max_bytes:
            return
        with self._lock:
            old = self._d.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._d[key] = (value, nbytes)
            self._bytes += nbytes
            evicted_n = evicted_b = 0
            while self._bytes > self.max_bytes and self._d:
                _, (_v, b) = self._d.popitem(last=False)
                self._bytes -= b
                evicted_n += 1
                evicted_b += b
            occupancy = self._bytes
        if evicted_n:
            self._count("evictions", evicted_n)
            self._count("evicted_bytes", evicted_b)
        self._gauge(occupancy)

    def get_or_put(self, key, factory):
        """The cached value for ``key``, building ``(value, nbytes)`` with
        ``factory()`` exactly once across concurrently missing threads.
        Single-flight: the first missing thread decodes while the key is
        in-flight; the rest wait and re-read instead of double-decoding the
        same chunk (and double-paying the evictions the duplicate insert
        used to cause). The factory runs OUTSIDE the map lock — a parquet
        decode must not serialize unrelated keys. A failed build wakes the
        waiters so one takes over."""
        import threading as _threading

        while True:
            with self._lock:
                hit = self._d.get(key)
                if hit is not None:
                    self._d.move_to_end(key)
                    self._count("hits")
                    return hit[0]
                event = self._inflight.get(key)
                if event is None:
                    event = self._inflight[key] = _threading.Event()
                    building = True
                else:
                    building = False
            if not building:
                event.wait()
                continue
            try:
                value, nbytes = factory()
            except BaseException:
                with self._lock:
                    self._inflight.pop(key, None)
                event.set()
                raise
            self._count("misses")
            self.set(key, value, nbytes)
            with self._lock:
                self._inflight.pop(key, None)
            event.set()
            return value

    def check_consistency(self) -> bool:
        """Byte-accounting invariant at quiescence: occupancy equals the sum
        of resident entry sizes, within budget, no leaked in-flight markers
        (race-stress gate)."""
        with self._lock:
            return (
                self._bytes == sum(nb for _v, nb in self._d.values())
                and self._bytes <= max(self.max_bytes, 0)
                and not self._inflight
            )

    def clear(self) -> None:
        with self._lock:
            self._d.clear()
            self._bytes = 0
        self._gauge(0)


_INDEX_CHUNK_CACHE = _BytesBoundedLRU(
    env.env_int("HYPERSPACE_INDEX_CACHE_MB") * 1024 * 1024,
    metric_name="index_chunk",
)

# Maintenance-scoped decoded SOURCE column cache: building several indexes
# over one table (the common maintenance session — e.g. the Q3/Q6/Q17 index
# set over lineitem) decodes the same parquet columns repeatedly; actions
# enable this scope so the second create reuses the first one's decode,
# column-granular. Query-path scans NEVER see this cache (the scope flag is
# only set inside maintenance ops), so raw-vs-indexed comparisons stay
# honest.
_SOURCE_COL_CACHE = _BytesBoundedLRU(
    env.env_int("HYPERSPACE_BUILD_CACHE_MB") * 1024 * 1024,
    metric_name="source_col",
)
_SOURCE_CACHE_DEPTH = 0

# Row-group statistics cache: per-file parquet footer stats (min/max/nulls
# per row group) backing predicate-driven row-group skipping. Footers are
# small (~KB) but point lookups consult them on every query, so repeats must
# not re-open and re-parse every index file. Keyed like _INDEX_CHUNK_CACHE
# ((path, mtime_ns, ino, size) + requested columns) so any rewrite
# invalidates.
_ROWGROUP_STATS_CACHE = _BytesBoundedLRU(
    env.env_int("HYPERSPACE_STATS_CACHE_MB") * 1024 * 1024,
    metric_name="rowgroup_stats",
)


class source_cache_scope:
    """Context manager marking a maintenance op: parquet reads inside it
    serve/populate the decoded source-column cache. Reentrant."""

    def __enter__(self):
        global _SOURCE_CACHE_DEPTH
        _SOURCE_CACHE_DEPTH += 1
        return self

    def __exit__(self, *exc):
        global _SOURCE_CACHE_DEPTH
        _SOURCE_CACHE_DEPTH -= 1
        return False


def _col_nbytes(col: Column) -> int:
    nbytes = col.data.nbytes + (
        col.validity.nbytes if col.validity is not None else 0
    )
    if col.dictionary:
        nbytes += sum(len(s) for s in col.dictionary)
    return nbytes


def _source_cached_read(
    paths, cols: list[str], arrow_filter=None, row_groups=None
) -> ColumnBatch | None:
    """Per-(file, column) cached read for maintenance scans; None when the
    shape is not cacheable (nested refs — handled by the generic path).
    Multi-file reads additionally cache the CONCATENATED column keyed by the
    whole file-set fingerprint: back-to-back index builds over the same
    source (the six-index TPC-H set) skip the per-build concat copy too.

    Filtered / row-group-selected reads cache too: the filter repr and the
    per-file row-group selection extend the key (a filtered read is a
    different decoded value, not an uncacheable one)."""
    if any(c.startswith(NESTED_PREFIX) for c in cols):
        return None
    try:
        stats = [(p, os.stat(p)) for p in paths]
    except OSError:
        return None
    filt = repr(arrow_filter) if arrow_filter is not None else None

    def extend(key, p=None):
        sel = tuple(row_groups[p]) if row_groups and p in row_groups else None
        return key if filt is None and sel is None else key + (filt, sel)

    fkeys = [(p, st.st_mtime_ns, st.st_ino, st.st_size) for p, st in stats]
    set_sel = (
        tuple((p, tuple(row_groups[p])) for p in paths if p in row_groups)
        if row_groups
        else None
    )
    set_key = (
        (tuple(fkeys) if filt is None and set_sel is None else (tuple(fkeys), filt, set_sel))
        if len(fkeys) > 1
        else None
    )
    whole: dict[str, Column] = {}
    todo = list(cols)
    if set_key is not None:
        for c in cols:
            hit = _SOURCE_COL_CACHE.get((set_key, c))
            if hit is not None:
                whole[c] = hit
        todo = [c for c in cols if c not in whole]
        if not todo:
            return ColumnBatch({c: whole[c] for c in cols})
    per_file: list[ColumnBatch] = []
    for (p, _st), fkey in zip(stats, fkeys):
        have: dict[str, Column] = {}
        missing: list[str] = []
        for c in todo:
            hit = _SOURCE_COL_CACHE.get(extend((fkey, c), p))
            if hit is not None:
                have[c] = hit
            else:
                missing.append(c)
        if missing:
            batch = table_to_batch(
                _read_one_table(p, missing, arrow_filter, _file_row_groups(row_groups, p))
            )
            for c in missing:
                col = batch.column(c)
                _SOURCE_COL_CACHE.set(extend((fkey, c), p), col, _col_nbytes(col))
                have[c] = col
        per_file.append(ColumnBatch({c: have[c] for c in todo}))
    if len(per_file) == 1:  # zero-copy reuse: no concat on the common layout
        merged = per_file[0]
    else:
        try:
            # only the columns missing from the set-level cache concatenate;
            # previously merged columns reuse their cached buffers
            merged = ColumnBatch.concat(per_file)
        except HyperspaceError:
            # cross-file dtype drift: the generic pa.concat_tables path
            # promotes permissively where per-file decode cannot
            return None
        if set_key is not None:
            for c in todo:
                col = merged.column(c)
                _SOURCE_COL_CACHE.set((set_key, c), col, _col_nbytes(col))
    return ColumnBatch(
        {c: whole[c] if c in whole else merged.column(c) for c in cols}
    )


def _batch_nbytes(batch: ColumnBatch) -> int:
    total = 0
    for col in batch.columns.values():
        total += col.data.nbytes
        if col.validity is not None:
            total += col.validity.nbytes
        if col.dictionary:
            total += sum(len(s) for s in col.dictionary) + 48 * len(col.dictionary)
    return total


# --- parallel multi-file IO --------------------------------------------------
#
# Decoding dominates multi-file scans (snappy/lz4 inflate + arrow->numpy),
# and it releases the GIL inside pyarrow, so a small thread pool scales
# near-linearly. Two consumers: `_pmap_ordered` (materializing reads decode
# every file concurrently, results in path order — output is bitwise
# identical to the serial loop) and `iter_chunks` (the pipelined executor's
# ordered chunk stream with bounded read-ahead under a byte budget).

def io_threads() -> int:
    """Reader pool width: ``HYPERSPACE_IO_THREADS``, default min(8, nproc).
    Values <= 1 mean fully serial reads (the pipeline's serial fallback).
    Delegates to the shared ``utils.workers`` helper so every IO pool in
    the engine (reader, bucket-join loaders, compaction) sizes uniformly."""
    from ..utils.workers import io_thread_cap

    return io_thread_cap()


def io_byte_budget() -> int:
    """Estimated bytes of decoded-but-unconsumed chunks the streaming reader
    may hold (``HYPERSPACE_IO_BUDGET_MB``, default 512). Legacy per-stream
    knob: the streamers now reserve through the GLOBAL accountant
    (serve/budget.py, ``HYPERSPACE_GLOBAL_BUDGET_MB``), which inherits this
    value when it is the only one set."""
    try:
        return int(env.env_float("HYPERSPACE_IO_BUDGET_MB") * 2**20)
    except ValueError:
        return 512 * 2**20


def stream_chunk_bytes() -> int:
    """Target file bytes per streamed chunk (``HYPERSPACE_STREAM_CHUNK_MB``,
    default 64): consecutive small files coalesce into one chunk so kernel
    dispatch count stays bounded; a larger file is its own chunk."""
    try:
        return int(env.env_float("HYPERSPACE_STREAM_CHUNK_MB") * 2**20)
    except ValueError:
        return 64 * 2**20


def _pmap_ordered(fn, items):
    """[fn(x) for x in items] with the calls running on the IO pool; results
    keep item order, and a worker exception propagates to the caller."""
    items = list(items)
    width = min(io_threads(), len(items))
    if width <= 1 or len(items) < 2:
        return [fn(x) for x in items]
    from ..telemetry.metrics import REGISTRY
    from ..utils.workers import io_pool

    REGISTRY.counter("io.parallel_reads").inc(len(items))
    # per-file work (decode, retry, cache counters) runs on pool threads:
    # carry the submitting thread's attribution target along so a serving
    # query's charges don't escape its ledger entry
    with io_pool(width) as pool:
        return list(pool.map(_attr.bound(fn), items))


def _stream_pool(width: int):
    """(pool, owned) for a streamer's read-ahead: under a serving-layer
    query the process-wide shared engine pool (total decode parallelism
    bounded across all concurrent queries; owned=False — never shut it
    down), otherwise a private per-iterator pool exactly as before."""
    from ..utils.workers import io_pool, shared_io_pool

    if _serve_ctx.current_query() is not None:
        return shared_io_pool(), False
    return io_pool(width), True


class StreamChunk:
    """One decoded chunk of an ordered multi-file scan."""

    __slots__ = ("batch", "index", "paths", "decode_s", "nbytes")

    def __init__(self, batch: ColumnBatch, index: int, paths: list[str],
                 decode_s: float, nbytes: int):
        self.batch = batch
        self.index = index
        self.paths = paths
        self.decode_s = decode_s
        self.nbytes = nbytes


def plan_chunk_groups(paths: Sequence[str], target_bytes: int | None = None) -> list[list[str]]:
    """Partition ``paths`` (order preserved) into chunk groups of roughly
    ``target_bytes`` file bytes each: the streaming unit of IO, upload, and
    dispatch. Unstattable paths fall into their own group."""
    target = target_bytes if target_bytes is not None else stream_chunk_bytes()
    groups: list[list[str]] = []
    cur: list[str] = []
    cur_bytes = 0
    for p in paths:
        try:
            sz = os.path.getsize(p)
        except OSError:
            sz = target  # unknown size: isolate it
        if cur and cur_bytes + sz > target:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(p)
        cur_bytes += sz
    if cur:
        groups.append(cur)
    return groups


def count_chunk_groups(paths: Sequence[str], target_bytes: int | None = None) -> int:
    """How many chunks ``iter_chunks`` will stream for ``paths`` — the same
    grouping plan, no IO.  The adaptive scan monitor's total-chunk
    denominator (aborting after the last chunk would save nothing)."""
    return len(plan_chunk_groups(paths, target_bytes))


class ChunkReadError(HyperspaceError):
    """A chunk decode failed on an IO worker. Wrapped so executors can tell
    host IO failures (propagate like any scan error) apart from device
    failures (latch the fail-open breaker)."""


def iter_chunks(
    paths: Sequence[str],
    columns: Sequence[str] | None = None,
    cache: bool = False,
    target_bytes: int | None = None,
    overlap: bool = True,
    row_groups=None,
) -> Iterator[StreamChunk]:
    """Ordered chunk stream over a multi-file parquet/arrow scan.

    With ``overlap`` (the pipelined default), chunk groups decode
    concurrently on the IO pool with bounded read-ahead: at most
    ``io_threads() + 2`` groups in flight and — beyond the first — at most
    ``io_byte_budget()`` estimated decoded bytes undelivered, so a slow
    consumer cannot balloon host memory. Chunks are yielded strictly in
    file order either way, and each chunk is produced by the same
    ``read_parquet`` call the materializing path would make, so
    concatenating the stream reproduces the monolithic read column for
    column (modulo cross-file dtype promotion, which aborts the stream as a
    dtype mismatch downstream).

    ``overlap=False`` (serial fallback, ``HYPERSPACE_PIPELINE=0``) decodes
    each group on the caller's thread only when requested.

    ``row_groups`` ({path: kept row-group indices}) restricts listed files
    to those groups — the streamed analogue of ``read_parquet``'s
    selection, so a pruned stream concatenates to exactly the pruned
    monolithic read."""
    from ..telemetry.metrics import REGISTRY

    groups = plan_chunk_groups(paths, target_bytes)

    def _decode(group: list[str]):
        t0 = time.perf_counter()
        try:
            batch = read_parquet(group, columns, cache=cache, row_groups=row_groups)
        except Exception as e:  # noqa: BLE001 - wrapped for the executor
            raise ChunkReadError(f"chunk decode failed for {group}: {e}") from e
        dt = time.perf_counter() - t0
        REGISTRY.histogram("io.chunk_decode_ms").observe(dt * 1000)
        _attr.charge_phase("io", dt)
        return batch, dt

    def _emit(i: int, batch: ColumnBatch, dt: float) -> StreamChunk:
        nbytes = _batch_nbytes(batch)
        REGISTRY.counter("io.chunks").inc()
        REGISTRY.counter("io.bytes_decoded").inc(nbytes)
        REGISTRY.counter("io.rows_decoded").inc(batch.num_rows)
        return StreamChunk(batch, i, groups[i], dt, nbytes)

    width = min(io_threads(), len(groups))
    if not overlap or width <= 1 or len(groups) < 2:
        for i, g in enumerate(groups):
            _serve_ctx.check_cancelled()
            batch, dt = _decode(g)
            yield _emit(i, batch, dt)
        return

    # estimated decoded bytes per group: file bytes x2 (columnar compression
    # ratios vary; the budget is a backstop, not an accounting system)
    ests = [
        max(1, sum(os.path.getsize(p) for p in g if os.path.exists(p))) * 2
        for g in groups
    ]
    max_inflight = width + 2
    pool, owned = _stream_pool(width)
    # read-ahead reserves through the GLOBAL ledger: one byte budget across
    # every stream of every concurrent query. try_reserve never blocks — a
    # zero-holder stream is always granted (progress guarantee), a holder
    # over the shared limit just stops pumping until its deliveries free
    # bytes, so backpressure stalls the hungriest stream and cannot deadlock.
    bstream = _serve_budget.global_budget().stream("scan")
    futures: dict = {}
    state = {"next": 0}

    def _pump() -> None:
        while (
            state["next"] < len(groups)
            and len(futures) < max_inflight
            and bstream.try_reserve(ests[state["next"]])
        ):
            i = state["next"]
            futures[i] = pool.submit(_attr.bound(_decode), groups[i])
            state["next"] += 1

    try:
        _pump()
        for i in range(len(groups)):
            _serve_ctx.check_cancelled()
            batch, dt = futures.pop(i).result()
            bstream.release(ests[i])
            _pump()
            yield _emit(i, batch, dt)
    finally:
        try:
            for f in futures.values():
                f.cancel()
            if owned:
                pool.shutdown(wait=False)
        finally:
            # returns any outstanding reservation (cancel path); must run
            # even if a cancel/shutdown above raises
            bstream.close()


def file_num_rows(path: str) -> int:
    """Row count from file metadata only (no data pages)."""
    if path.endswith(ARROW_EXT):
        return arrow_file_num_rows(path)
    return pq.ParquetFile(path).metadata.num_rows


def read_rowgroup_stats(path: str, columns: Sequence[str]) -> list[dict] | None:
    """Per-row-group footer statistics for ``columns`` (plus group row and
    byte counts): ``[{"num_rows", "nbytes", "cols": {col: (min, max,
    null_count) | None}}]``.  Footer-only — no data pages — and cached in
    the row-group stats cache keyed like the decoded-chunk cache, so repeat
    pruning decisions cost a dict lookup.  None when the footer is
    unreadable (callers must keep the file)."""
    if path.endswith(ARROW_EXT):
        return None  # IPC files carry no row-group statistics
    try:
        st = os.stat(path)
    except OSError:
        return None
    cols = tuple(sorted(columns))
    key = ((path, st.st_mtime_ns, st.st_ino, st.st_size), cols)

    def _parse():
        """(stats list, approx nbytes) — raises _UnreadableFooter instead of
        caching a None for footers that fail to parse (possibly transient:
        a file mid-write keeps being retried, not remembered as bad)."""

        def _open_footer():
            faults.fire("io.footer", path=os.path.basename(path))
            return pq.ParquetFile(path).metadata

        try:
            # transient IO errors retry with backoff; an exhausted or
            # permanent failure degrades to keep-the-file (never cached),
            # so a flaky footer can delay pruning but never change results
            md = retry.retry_call(_open_footer, what="io.footer")
        except Exception:
            raise _UnreadableFooter
        want = set(cols)
        out: list[dict] = []
        nbytes = 64
        for g in range(md.num_row_groups):
            rg = md.row_group(g)
            entry: dict = {
                "num_rows": rg.num_rows,
                "nbytes": rg.total_byte_size,
                "cols": {},
            }
            for j in range(rg.num_columns):
                cmeta = rg.column(j)
                name = cmeta.path_in_schema
                if name not in want:
                    continue
                try:
                    stats = cmeta.statistics if cmeta.is_stats_set else None
                    if stats is not None and stats.has_min_max:
                        nulls = stats.null_count if stats.has_null_count else None
                        entry["cols"][name] = (stats.min, stats.max, nulls)
                    else:
                        entry["cols"][name] = None
                except Exception:  # undecodable stats: treat as absent (keep)
                    entry["cols"][name] = None
                nbytes += 96
            out.append(entry)
            nbytes += 64
        return out, nbytes

    try:
        if _ROWGROUP_STATS_CACHE.max_bytes > 0:
            # atomic check-then-insert: concurrent point lookups over one
            # file parse its footer once, not once per thread
            return _ROWGROUP_STATS_CACHE.get_or_put(key, _parse)
        return _parse()[0]
    except _UnreadableFooter:
        return None


class _UnreadableFooter(Exception):
    """Footer parse failed — callers must keep the file (never cached)."""


def read_parquet_schema(path: str) -> Schema:
    if path.endswith(ARROW_EXT):
        with pa.memory_map(path) as src:
            return arrow_schema_to_schema(pa.ipc.open_file(src).schema)
    return arrow_schema_to_schema(pq.read_schema(path))


def _file_row_groups(row_groups, p: str):
    """Per-path selection lookup tolerating a None mapping."""
    if row_groups is None:
        return None
    sel = row_groups.get(p)
    return list(sel) if sel is not None else None


def read_parquet(
    paths: Sequence[str],
    columns: Sequence[str] | None = None,
    arrow_filter=None,
    cache: bool = False,
    row_groups=None,
) -> ColumnBatch:
    """arrow_filter: optional pyarrow.compute Expression applied at read time
    (prunes parquet row groups via statistics, then masks rows). cache=True
    (index-file reads only) serves repeats from the decoded-chunk cache.
    row_groups: optional {path: row-group indices} — listed files read ONLY
    those groups (predicate-driven row-group skipping); absent paths read
    whole."""
    cols = list(columns) if columns else None
    if (
        _SOURCE_CACHE_DEPTH > 0
        and cols
        and not cache
        and _SOURCE_COL_CACHE.max_bytes > 0
    ):
        hit = _source_cached_read(paths, cols, arrow_filter, row_groups)
        if hit is not None:
            return hit
    cache_key = None
    if cache and _INDEX_CHUNK_CACHE.max_bytes > 0:
        try:
            # st_mtime_ns + st_ino: a same-size rewrite within coarse mtime
            # resolution must not serve stale decoded data
            stats = tuple(
                (p, s.st_mtime_ns, s.st_ino, s.st_size)
                for p, s in ((p, os.stat(p)) for p in paths)
            )
            cache_key = (
                stats,
                tuple(cols) if cols else None,
                repr(arrow_filter) if arrow_filter is not None else None,
                tuple(
                    (p, tuple(row_groups[p])) for p in paths if p in row_groups
                )
                if row_groups
                else None,
            )
        except OSError:
            cache_key = None

    def _decode_all() -> ColumnBatch:
        tables = _pmap_ordered(
            lambda p: _read_one_table(p, cols, arrow_filter, _file_row_groups(row_groups, p)),
            paths,
        )
        if not tables:
            return ColumnBatch({})
        if len(tables) > 1:
            tables = _unify_string_encoding(tables)
        table = pa.concat_tables(tables, promote_options="permissive")
        batch = table_to_batch(table)
        if cols is not None and list(batch.columns.keys()) != cols:
            batch = batch.select(cols)
        return batch

    if cache_key is not None:
        def _decode_for_cache():
            # store a private shallow copy so every caller's batch can have
            # columns rebound without corrupting the cache
            batch = _decode_all()
            return ColumnBatch(batch.columns), _batch_nbytes(batch)

        # atomic check-then-insert: concurrent queries missing on the same
        # decoded chunk decode it once (single-flight), instead of N threads
        # double-decoding and double-paying evictions on insert
        stored = _INDEX_CHUNK_CACHE.get_or_put(cache_key, _decode_for_cache)
        # shallow copy: callers may rebind columns on their batch; the
        # shared Column objects themselves are immutable
        return ColumnBatch(stored.columns)
    return _decode_all()


def _read_one_table(p: str, cols, arrow_filter, row_group_sel=None) -> pa.Table:
    """One file -> pa.Table (the per-path unit the IO pool parallelizes).
    ``partitioning=None``: index data lives under ``v__=<n>/`` directories
    and pyarrow's hive inference would otherwise graft a ``v__`` partition
    column onto every schema. ``row_group_sel`` reads only the listed row
    groups (stats-driven skipping); the pushed filter then applies as a
    post-read mask — the same rows a full filtered read yields for any
    selection that keeps every possibly-matching group.

    This is THE per-file transient-failure boundary: the decode retries
    under the bounded-backoff policy (utils/retry.py) so one IO hiccup
    doesn't kill a 200-file streamed scan, and the ``io.read_file`` fault
    point fires inside the retried unit so injected transient errors are
    absorbed exactly like real ones."""
    return retry.retry_call(
        lambda: _read_one_table_once(p, cols, arrow_filter, row_group_sel),
        what="io.read_file",
    )


def _read_one_table_once(p: str, cols, arrow_filter, row_group_sel=None) -> pa.Table:
    faults.fire("io.read_file", path=os.path.basename(p))
    if p.endswith(ARROW_EXT):
        return _read_arrow_file(p, cols, arrow_filter)
    read_cols = cols
    if cols is not None and any(c.startswith(NESTED_PREFIX) for c in cols):
        # a '__hs_nested.a.b' column is physical in index files but lives
        # inside the struct 'a' in source files: read the struct there
        phys = set(pq.read_schema(p).names)
        expanded = []
        for c in cols:
            if c not in phys and c.startswith(NESTED_PREFIX):
                expanded.append(c[len(NESTED_PREFIX):].split(".", 1)[0])
            else:
                expanded.append(c)
        read_cols = list(dict.fromkeys(expanded))
    if row_group_sel is not None:
        table = pq.ParquetFile(p).read_row_groups(
            list(row_group_sel), columns=read_cols
        )
        if arrow_filter is not None:
            table = table.filter(arrow_filter)
        return table
    return pq.read_table(
        p, columns=read_cols, filters=arrow_filter, partitioning=None
    )


def _unify_string_encoding(tables: list[pa.Table]) -> list[pa.Table]:
    """Dictionary-encode plain string columns when any sibling table carries
    the same column dictionary-typed: files written before the dictionary-
    emission change (or by external writers) must concat with files written
    after it — permissive concat cannot merge string with dictionary."""
    dict_cols = set()
    plain_cols = set()
    for t in tables:
        for f in t.schema:
            if pa.types.is_dictionary(f.type):
                dict_cols.add(f.name)
            elif pa.types.is_string(f.type) or pa.types.is_large_string(f.type):
                plain_cols.add(f.name)
    mixed = dict_cols & plain_cols
    if not mixed:
        return tables
    out = []
    for t in tables:
        for name in mixed:
            i = t.schema.get_field_index(name)
            if i >= 0 and not pa.types.is_dictionary(t.schema.field(i).type):
                enc = t.column(i).dictionary_encode()
                t = t.set_column(i, pa.field(name, enc.type), enc)
        out.append(t)
    return out


def _retried_file_reader(read_fn):
    """Per-file decode unit for the non-parquet readers: same retry
    boundary and ``io.read_file`` fault point as ``_read_one_table``."""

    def unit(p):
        def once():
            faults.fire("io.read_file", path=os.path.basename(p))
            return read_fn(p)

        return retry.retry_call(once, what="io.read_file")

    return unit


def read_csv(paths: Sequence[str], columns: Sequence[str] | None = None) -> ColumnBatch:
    tables = _pmap_ordered(_retried_file_reader(pacsv.read_csv), paths)
    table = pa.concat_tables(tables, promote_options="permissive")
    if columns:
        table = table.select(list(columns))
    return table_to_batch(table)


def read_json(paths: Sequence[str], columns: Sequence[str] | None = None) -> ColumnBatch:
    tables = _pmap_ordered(_retried_file_reader(pajson.read_json), paths)
    table = pa.concat_tables(tables, promote_options="permissive")
    if columns:
        table = table.select(list(columns))
    return table_to_batch(table)


def read_orc(paths: Sequence[str], columns: Sequence[str] | None = None) -> ColumnBatch:
    from pyarrow import orc as paorc

    tables = _pmap_ordered(_retried_file_reader(paorc.read_table), paths)
    table = pa.concat_tables(tables, promote_options="permissive")
    if columns:
        table = table.select(list(columns))
    return table_to_batch(table)


def write_orc(batch: ColumnBatch, path: str) -> None:
    from pyarrow import orc as paorc

    os.makedirs(os.path.dirname(path), exist_ok=True)
    table = batch_to_table(batch)
    # ORC has no dictionary type: decode categorical strings to plain
    for i, f in enumerate(table.schema):
        if pa.types.is_dictionary(f.type):
            plain = table.column(i).cast(f.type.value_type)
            table = table.set_column(i, pa.field(f.name, f.type.value_type), plain)
    paorc.write_table(table, path)


def read_text(paths: Sequence[str], columns: Sequence[str] | None = None) -> ColumnBatch:
    """Spark's `text` source shape: one string column named `value`, one row
    per line (trailing newline dropped; no header, no parsing). Rows split
    on '\\n' only (CRLF tolerated) — NOT Unicode line boundaries, so values
    containing U+2028/U+2029 stay one row like the reference's source."""
    lines: list[str] = []
    for p in paths:
        with open(p, encoding="utf-8", newline="") as f:
            content = f.read()
        if content:  # only a truly EMPTY file yields 0 rows ("\n" is [""])
            body = content[:-1] if content.endswith("\n") else content
            lines.extend(s[:-1] if s.endswith("\r") else s for s in body.split("\n"))
    table = pa.table({"value": pa.array(lines, type=pa.string())})
    if columns:
        table = table.select(list(columns))
    return table_to_batch(table)


def write_text(batch: ColumnBatch, path: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    values = batch.column("value").decode()
    with open(path, "w", encoding="utf-8") as f:
        for v in values:
            f.write(f"{v}\n")


def read_avro(paths: Sequence[str], columns: Sequence[str] | None = None) -> ColumnBatch:
    """Avro rows via fastavro when present (neither pyarrow nor this image
    bundles an avro reader); a clear error otherwise — the format stays in
    the default supported list for reference parity
    (DefaultFileBasedSource.scala:53-75), gated on the codec being
    importable at read time."""
    try:
        import fastavro
    except ImportError as e:  # pragma: no cover - environment-dependent
        raise HyperspaceError(
            "avro support requires the 'fastavro' package, which is not "
            "installed in this environment"
        ) from e
    # field names come from every file's WRITER SCHEMA (not the first
    # record): schema-evolved multi-file sets keep late-added columns,
    # null-filled for files written before them — and zero records still
    # yield an empty batch like every other reader
    rows: list[dict] = []  # pragma: no cover - exercised only with fastavro
    names: list[str] = []  # pragma: no cover
    for p in paths:  # pragma: no cover
        with open(p, "rb") as f:
            r = fastavro.reader(f)
            for fld in (r.writer_schema or {}).get("fields", []):
                if fld["name"] not in names:
                    names.append(fld["name"])
            rows.extend(r)
    cols = {k: [r.get(k) for r in rows] for k in names}  # pragma: no cover
    table = pa.table(cols)  # pragma: no cover
    if columns:  # pragma: no cover
        table = table.select(list(columns))
    return table_to_batch(table)  # pragma: no cover


def read_files(
    fmt: str, paths: Sequence[str], columns: Sequence[str] | None = None
) -> ColumnBatch:
    if fmt == "parquet":
        return read_parquet(paths, columns)
    if fmt == "csv":
        return read_csv(paths, columns)
    if fmt == "json":
        return read_json(paths, columns)
    if fmt == "orc":
        return read_orc(paths, columns)
    if fmt == "text":
        return read_text(paths, columns)
    if fmt == "avro":
        return read_avro(paths, columns)
    raise HyperspaceError(f"Unsupported format: {fmt}")


def read_schema(fmt: str, path: str) -> Schema:
    if fmt == "parquet":
        return read_parquet_schema(path)
    # csv/json: infer from a full read of one file (fine for metadata ops)
    return read_files(fmt, [path]).schema


# Codec for INDEX data files when no session conf reaches the writer
# (session-driven writes read hyperspace.tpu.index.compression): lz4
# decodes ~2x faster than snappy at equal size and write cost — and index
# files are only read by this engine, so external-reader compatibility
# doesn't constrain them. Aliased from the conf default so the two can
# never diverge.
INDEX_COMPRESSION = INDEX_COMPRESSION_DEFAULT

# Index data files default to parquet (reference layout parity:
# IndexDataManager's `v__=<n>/` parquet dirs, SURVEY §7 stage 4). The
# opt-in "arrow" format (conf hyperspace.tpu.index.format) writes Arrow IPC
# files instead: ~3x faster single-core encode and near-zero-copy mmap
# reads — worth it for build-throughput-bound deployments since index files
# are engine-owned. Readers dispatch per file extension, so mixed layouts
# (e.g. a refresh under a different session conf) stay readable.
ARROW_EXT = ".arrow"


def index_file_ext(fmt: str) -> str:
    return ARROW_EXT if fmt == "arrow" else ".parquet"


def write_arrow(batch: ColumnBatch, path: str) -> None:
    # uncompressed IPC: ~3x faster to write than lz4 frames AND the mmap
    # read path stays zero-copy (no decode); index data trades ~30% disk
    # for build and scan speed — it is engine-owned and GC'd by vacuum
    os.makedirs(os.path.dirname(path), exist_ok=True)
    table = batch_to_table(batch)
    with pa.OSFile(path, "wb") as sink:
        with pa.ipc.new_file(sink, table.schema) as writer:
            writer.write_table(table)


def _read_arrow_file(path: str, cols, arrow_filter) -> pa.Table:
    with pa.memory_map(path) as src:
        table = pa.ipc.open_file(src).read_all()
    if cols is not None:
        table = table.select(list(cols))
    if arrow_filter is not None:
        # IPC has no row-group statistics; the pushed filter applies as a
        # post-read mask (same semantics as parquet's residual filtering)
        table = table.filter(arrow_filter)
    return table


def arrow_file_num_rows(path: str) -> int:
    with pa.memory_map(path) as src:
        reader = pa.ipc.open_file(src)
        return sum(
            reader.get_batch(i).num_rows for i in range(reader.num_record_batches)
        )


def write_index_file(
    batch: ColumnBatch,
    path: str,
    row_group_size: int | None = None,
    stats_columns: "Sequence[str] | None" = None,
    compression: str | None = None,
) -> None:
    """Write one index data file in the format implied by ``path``'s
    extension (callers pick the extension via ``index_file_ext``).

    ``stats_columns`` limits parquet row-group statistics to the named
    columns: index layouts cluster rows by their sort/z-order columns, so
    only THOSE columns' min/max prune row groups — statistics on the
    unclustered include columns span the full domain every group and only
    cost encode time (~20% on numeric-heavy slices). None keeps stats on
    every column.

    Both knobs are parquet-only by design: the arrow format has no
    row-group statistics, and it stays uncompressed so the mmap read path
    remains zero-copy (see write_arrow)."""
    if path.endswith(ARROW_EXT):
        write_arrow(batch, path)
    else:
        write_parquet(
            batch, path, row_group_size=row_group_size,
            compression=compression or INDEX_COMPRESSION, keep_dictionary=True,
            stats_columns=stats_columns,
        )


def write_parquet(
    batch: ColumnBatch,
    path: str,
    row_group_size: int | None = None,
    compression: str = "snappy",
    keep_dictionary: bool = False,
    stats_columns: "Sequence[str] | None" = None,
) -> None:
    """User-facing exports keep the widely compatible snappy default AND a
    plain-string schema: batch_to_table emits dictionary-typed strings for
    speed, but that type round-trips through parquet (ARROW:schema), and
    external readers would see categorical columns where they wrote
    strings. Engine-owned index files (write_index_file) opt in via
    keep_dictionary to skip the cast."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    table = batch_to_table(batch)
    if not keep_dictionary:
        for i, f in enumerate(table.schema):
            if pa.types.is_dictionary(f.type):
                plain = table.column(i).cast(f.type.value_type)
                table = table.set_column(i, pa.field(f.name, f.type.value_type), plain)
    # dictionary-encode only string columns: numeric dictionary attempts
    # cost ~25% write time on high-cardinality data and then fall back anyway
    str_cols = [
        f.name
        for f in table.schema
        if pa.types.is_string(f.type) or pa.types.is_dictionary(f.type)
    ]
    write_statistics: bool | list[str] = True
    if stats_columns is not None:
        # intersect with the schema: callers pass logical sort columns and
        # a slice may not carry all of them (e.g. lineage-only rewrites)
        present = [f.name for f in table.schema if f.name in set(stats_columns)]
        # empty intersection (degenerate slice, e.g. a lineage-only rewrite):
        # keep normal all-column stats rather than dropping stats entirely
        write_statistics = present if present else True
    pq.write_table(
        table, path, row_group_size=row_group_size,
        compression=compression,
        use_dictionary=str_cols if str_cols else False,
        write_statistics=write_statistics,
    )
