"""Parquet/CSV/JSON ↔ ColumnBatch via pyarrow.

The reference leans on Spark's datasource layer; here pyarrow is the host-side
file substrate. Strings arrive dictionary-encoded for TPU-friendliness;
date32 stays as days-since-epoch int32.
"""

from __future__ import annotations

import os
from typing import Sequence

import numpy as np
import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson
import pyarrow.parquet as pq

from .table import Column, ColumnBatch, Schema, Field, STRING, DATE32
from ..exceptions import HyperspaceError

_ARROW_TO_LOGICAL = {
    pa.int8(): "int8",
    pa.int16(): "int16",
    pa.int32(): "int32",
    pa.int64(): "int64",
    pa.float32(): "float32",
    pa.float64(): "float64",
    pa.bool_(): "bool",
    pa.date32(): DATE32,
    pa.string(): STRING,
    pa.large_string(): STRING,
}

_LOGICAL_TO_ARROW = {
    "int8": pa.int8(),
    "int16": pa.int16(),
    "int32": pa.int32(),
    "int64": pa.int64(),
    "float32": pa.float32(),
    "float64": pa.float64(),
    "bool": pa.bool_(),
    DATE32: pa.date32(),
    STRING: pa.string(),
}


def arrow_schema_to_schema(sch: pa.Schema) -> Schema:
    fields = []
    for f in sch:
        t = f.type
        if pa.types.is_dictionary(t):
            t = t.value_type
        logical = _ARROW_TO_LOGICAL.get(t)
        if logical is None:
            if pa.types.is_timestamp(t):
                logical = "int64"
            elif pa.types.is_decimal(t):
                logical = "float64"
            else:
                raise HyperspaceError(f"Unsupported arrow type {t} for {f.name}")
        fields.append(Field(f.name, logical))
    return Schema(fields)


def _chunked_to_column(arr: pa.ChunkedArray, logical: str) -> Column:
    combined = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    validity = None
    if combined.null_count:
        validity = np.asarray(combined.is_valid())
    if logical == STRING:
        if pa.types.is_dictionary(combined.type):
            dict_arr = combined
        else:
            dict_arr = combined.dictionary_encode()
        codes = np.asarray(dict_arr.indices.fill_null(0)).astype(np.int32)
        vocab = dict_arr.dictionary.to_pylist()
        if not vocab:
            vocab = [""]
        return Column(codes, STRING, validity, [str(v) for v in vocab])
    if logical == DATE32:
        data = np.asarray(combined.cast(pa.int32()).fill_null(0))
        return Column(data.astype(np.int32), DATE32, validity)
    np_dtype = {"int8": pa.int8(), "int16": pa.int16(), "int32": pa.int32(),
                "int64": pa.int64(), "float32": pa.float32(),
                "float64": pa.float64(), "bool": pa.bool_()}[logical]
    if pa.types.is_timestamp(combined.type):
        combined = combined.cast(pa.int64())
    elif pa.types.is_decimal(combined.type):
        combined = combined.cast(pa.float64())
    data = np.asarray(combined.cast(np_dtype).fill_null(0))
    return Column(np.ascontiguousarray(data), logical, validity)


def table_to_batch(table: pa.Table) -> ColumnBatch:
    schema = arrow_schema_to_schema(table.schema)
    cols = {}
    for f in schema:
        cols[f.name] = _chunked_to_column(table.column(f.name), f.dtype)
    return ColumnBatch(cols)


def batch_to_table(batch: ColumnBatch) -> pa.Table:
    arrays = []
    names = []
    for name, col in batch.columns.items():
        names.append(name)
        mask = None if col.validity is None else ~col.validity
        if col.dtype == STRING:
            vocab = np.asarray(col.dictionary, dtype=object)
            values = vocab[col.data]
            arrays.append(pa.array(values, type=pa.string(), mask=mask))
        elif col.dtype == DATE32:
            arrays.append(
                pa.array(col.data, type=pa.int32(), mask=mask).cast(pa.date32())
            )
        else:
            arrays.append(
                pa.array(col.data, type=_LOGICAL_TO_ARROW[col.dtype], mask=mask)
            )
    return pa.table(dict(zip(names, arrays)))


# --- readers -----------------------------------------------------------------

def read_parquet_schema(path: str) -> Schema:
    return arrow_schema_to_schema(pq.read_schema(path))


def read_parquet(
    paths: Sequence[str],
    columns: Sequence[str] | None = None,
    arrow_filter=None,
) -> ColumnBatch:
    """arrow_filter: optional pyarrow.compute Expression applied at read time
    (prunes parquet row groups via statistics, then masks rows)."""
    cols = list(columns) if columns else None
    tables = [
        pq.read_table(p, columns=cols, filters=arrow_filter) for p in paths
    ]
    if not tables:
        return ColumnBatch({})
    table = pa.concat_tables(tables, promote_options="permissive")
    return table_to_batch(table)


def read_csv(paths: Sequence[str], columns: Sequence[str] | None = None) -> ColumnBatch:
    tables = [pacsv.read_csv(p) for p in paths]
    table = pa.concat_tables(tables, promote_options="permissive")
    if columns:
        table = table.select(list(columns))
    return table_to_batch(table)


def read_json(paths: Sequence[str], columns: Sequence[str] | None = None) -> ColumnBatch:
    tables = [pajson.read_json(p) for p in paths]
    table = pa.concat_tables(tables, promote_options="permissive")
    if columns:
        table = table.select(list(columns))
    return table_to_batch(table)


def read_files(
    fmt: str, paths: Sequence[str], columns: Sequence[str] | None = None
) -> ColumnBatch:
    if fmt == "parquet":
        return read_parquet(paths, columns)
    if fmt == "csv":
        return read_csv(paths, columns)
    if fmt == "json":
        return read_json(paths, columns)
    raise HyperspaceError(f"Unsupported format: {fmt}")


def read_schema(fmt: str, path: str) -> Schema:
    if fmt == "parquet":
        return read_parquet_schema(path)
    # csv/json: infer from a full read of one file (fine for metadata ops)
    return read_files(fmt, [path]).schema


# Codec for INDEX data files: lz4 decodes ~2x faster than snappy at equal
# size and write cost — and index files are only read by this engine, so
# external-reader compatibility doesn't constrain them.
INDEX_COMPRESSION = "lz4"


def write_parquet(
    batch: ColumnBatch,
    path: str,
    row_group_size: int | None = None,
    compression: str = "snappy",
) -> None:
    # user-facing exports keep the widely compatible snappy default
    os.makedirs(os.path.dirname(path), exist_ok=True)
    pq.write_table(
        batch_to_table(batch), path, row_group_size=row_group_size,
        compression=compression,
    )
