"""ColumnBatch — the in-memory columnar unit of execution.

The reference rides Spark's row iterators / Tungsten format; here the substrate
is columnar numpy on host, placed onto TPU HBM as jax arrays by the executor
(pad-to-static-shape + validity mask, so XLA sees fixed shapes).

Supported logical dtypes: int8/16/32/64, float32/64, bool, date32 (days since
epoch, stored int32), string (dictionary-encoded: int32 codes + vocabulary).
Nulls are tracked with optional boolean validity masks (True = valid).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable, Mapping, Optional, Sequence

import numpy as np

from ..exceptions import HyperspaceError

_NUMPY_DTYPES = {
    "int8": np.int8,
    "int16": np.int16,
    "int32": np.int32,
    "int64": np.int64,
    "float32": np.float32,
    "float64": np.float64,
    "bool": np.bool_,
    "date32": np.int32,
    "string": np.int32,  # dictionary codes
}

STRING = "string"
DATE32 = "date32"


def numpy_dtype(logical: str) -> np.dtype:
    try:
        return np.dtype(_NUMPY_DTYPES[logical])
    except KeyError:
        raise HyperspaceError(f"Unsupported dtype: {logical!r}")


@dataclass(frozen=True)
class Field:
    name: str
    dtype: str  # logical dtype string

    def to_dict(self) -> dict:
        return {"name": self.name, "type": self.dtype}

    @staticmethod
    def from_dict(d: dict) -> "Field":
        return Field(d["name"], d["type"])


class Schema:
    def __init__(self, fields: Sequence[Field]):
        self.fields = list(fields)
        self._by_name = {f.name: f for f in self.fields}
        if len(self._by_name) != len(self.fields):
            raise HyperspaceError("Duplicate column names in schema")

    def __iter__(self):
        return iter(self.fields)

    def __len__(self):
        return len(self.fields)

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def __eq__(self, other):
        return isinstance(other, Schema) and self.fields == other.fields

    def field(self, name: str) -> Field:
        f = self._by_name.get(name)
        if f is None:
            raise HyperspaceError(
                f"Column {name!r} not found; available: {self.names}"
            )
        return f

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def select(self, names: Sequence[str]) -> "Schema":
        return Schema([self.field(n) for n in names])

    def to_list(self) -> list[dict]:
        return [f.to_dict() for f in self.fields]

    @staticmethod
    def from_list(lst: Iterable[Mapping]) -> "Schema":
        return Schema([Field(d["name"], d["type"]) for d in lst])

    def __repr__(self):
        return "Schema(" + ", ".join(f"{f.name}:{f.dtype}" for f in self.fields) + ")"


class Column:
    """One column: numpy data + logical dtype + optional validity + optional
    string dictionary (vocabulary for dictionary-encoded strings)."""

    def __init__(
        self,
        data: np.ndarray,
        dtype: str,
        validity: Optional[np.ndarray] = None,
        dictionary: Optional[list[str]] = None,
    ):
        self.data = data
        self.dtype = dtype
        self.validity = validity  # None => all valid
        self.dictionary = dictionary
        if dtype == STRING and dictionary is None:
            raise HyperspaceError("string column requires a dictionary")

    def __len__(self):
        return len(self.data)

    @property
    def dictionary_is_unique(self) -> bool:
        """True when no value appears under two codes (all in-repo
        constructors guarantee it; externally-built dictionaries are checked
        once and the result cached)."""
        cached = self.__dict__.get("_dict_unique")
        if cached is None:
            cached = self.dictionary is not None and len(set(self.dictionary)) == len(
                self.dictionary
            )
            self.__dict__["_dict_unique"] = cached
        return cached

    @staticmethod
    def from_values(values: Sequence[Any], dtype: str | None = None) -> "Column":
        if dtype is not None and dtype != STRING:
            # explicit non-string dtype: None entries become NULLs, not strings
            validity = np.array([v is not None for v in values], dtype=bool)
            filled = [0 if v is None else v for v in values]
            return Column(
                np.asarray(filled).astype(numpy_dtype(dtype)),
                dtype,
                None if validity.all() else validity,
            )
        arr = np.asarray(values)
        if arr.dtype == object or arr.dtype.kind in ("U", "S"):
            validity = np.array([v is not None for v in values], dtype=bool)
            non_null = [v for v in values if v is not None]
            if non_null and all(isinstance(v, (int, float, bool)) for v in non_null):
                # numeric values with Nones: infer numeric dtype + validity
                if all(isinstance(v, bool) for v in non_null):
                    inferred = "bool"
                elif all(isinstance(v, int) for v in non_null):
                    inferred = "int64"
                else:
                    inferred = "float64"
                filled = [0 if v is None else v for v in values]
                return Column(
                    np.asarray(filled).astype(numpy_dtype(inferred)),
                    inferred,
                    None if validity.all() else validity,
                )
            # dictionary-encode strings
            strs = [v if v is not None else "" for v in values]
            vocab, codes = np.unique(np.asarray(strs, dtype=str), return_inverse=True)
            return Column(
                codes.astype(np.int32),
                STRING,
                None if validity.all() else validity,
                list(vocab),
            )
        if dtype is None:
            if arr.dtype.kind == "b":
                dtype = "bool"
            elif arr.dtype.kind == "i":
                dtype = str(arr.dtype)
            elif arr.dtype.kind == "f":
                dtype = str(arr.dtype)
            else:
                raise HyperspaceError(f"Cannot infer dtype for {arr.dtype}")
        return Column(arr.astype(numpy_dtype(dtype)), dtype)

    def decode(self) -> np.ndarray:
        """Materialize python-visible values (strings decoded)."""
        if self.dtype == STRING:
            vocab = np.asarray(self.dictionary, dtype=object)
            out = vocab[self.data]
        else:
            out = self.data
        if self.validity is not None:
            out = np.asarray(out, dtype=object)
            out[~self.validity] = None
        return out

    def take(self, indices: np.ndarray) -> "Column":
        return Column(
            self.data[indices],
            self.dtype,
            self.validity[indices] if self.validity is not None else None,
            self.dictionary,
        )

    def slice(self, start: int, stop: int) -> "Column":
        """Zero-copy contiguous row range (numpy view). Callers honor the
        immutability contract, so sharing the buffer is safe."""
        return Column(
            self.data[start:stop],
            self.dtype,
            self.validity[start:stop] if self.validity is not None else None,
            self.dictionary,
        )

    def filter(self, mask: np.ndarray) -> "Column":
        return Column(
            self.data[mask],
            self.dtype,
            self.validity[mask] if self.validity is not None else None,
            self.dictionary,
        )


def sort_key_values(col: "Column", ascending: bool = True) -> np.ndarray:
    """Order-exact sort keys for one column with Spark NULL placement
    (NULLS FIRST ascending, NULLS LAST descending). Fast path: plain
    ascending numeric columns sort on raw data with no factorization."""
    plain_numeric = col.dtype != STRING and col.validity is None
    if plain_numeric and ascending:
        return col.data
    if plain_numeric and col.data.dtype.kind in ("f", "b"):
        return -col.data.astype(np.float64 if col.data.dtype.kind == "f" else np.int8)
    if plain_numeric and col.data.dtype.itemsize < 8:
        return -col.data.astype(np.int64)  # exact negation for narrow ints
    # strings, nullable, or int64-descending: factorize (exact for all dtypes)
    if col.dtype == STRING:
        # rank through the (small) dictionary instead of factorizing n
        # string objects: any monotone map of the values sorts identically.
        # np.unique collapses duplicate dictionary ENTRIES to one rank, so
        # equal values sort equal even under a non-unique dictionary.
        vocab = np.asarray(col.dictionary if col.dictionary else [""], dtype=str)
        _, rank = np.unique(vocab, return_inverse=True)
        codes = rank.astype(np.int64)[col.data]
        if col.validity is not None:
            # NULL must not collide with a real value's rank; route through
            # the shared null-placement logic below via a sentinel remap
            codes = codes + 1 if ascending else codes
        if not ascending:
            codes = -codes
        if col.validity is not None:
            null_code = 0 if ascending else codes.max(initial=0) + 1
            codes = np.where(col.validity, codes, null_code)
        return codes
    vals = col.data
    _, codes = np.unique(vals, return_inverse=True)
    codes = codes.astype(np.int64)
    if not ascending:
        codes = -codes
    if col.validity is not None:
        null_code = codes.min(initial=0) - 1 if ascending else codes.max(initial=0) + 1
        codes = np.where(col.validity, codes, null_code)
    return codes


class ColumnBatch:
    """Ordered collection of equal-length Columns."""

    def __init__(self, columns: Mapping[str, Column]):
        self.columns: dict[str, Column] = dict(columns)
        lengths = {len(c) for c in self.columns.values()}
        if len(lengths) > 1:
            raise HyperspaceError(f"Ragged columns: {lengths}")
        self._num_rows = lengths.pop() if lengths else 0

    @property
    def num_rows(self) -> int:
        return self._num_rows

    @property
    def schema(self) -> Schema:
        return Schema([Field(n, c.dtype) for n, c in self.columns.items()])

    def column(self, name: str) -> Column:
        c = self.columns.get(name)
        if c is None:
            raise HyperspaceError(
                f"Column {name!r} not found; available: {list(self.columns)}"
            )
        return c

    @staticmethod
    def from_pydict(data: Mapping[str, Sequence[Any]], schema: Schema | None = None) -> "ColumnBatch":
        import datetime

        cols = {}
        for name, values in data.items():
            dtype = schema.field(name).dtype if schema and name in schema else None
            if dtype == DATE32:
                # accept days-since-epoch ints or datetime.date; None -> NULL
                epoch = datetime.date(1970, 1, 1)
                days = [
                    0 if v is None
                    else (v - epoch).days if isinstance(v, datetime.date)
                    else int(v)
                    for v in values
                ]
                validity = np.array([v is not None for v in values], dtype=bool)
                cols[name] = Column(
                    np.asarray(days, dtype=np.int32),
                    DATE32,
                    None if validity.all() else validity,
                )
            elif dtype == STRING:
                cols[name] = Column.from_values(list(values))
            else:
                cols[name] = Column.from_values(values, dtype)
        return ColumnBatch(cols)

    def to_pydict(self) -> dict[str, list]:
        return {n: list(c.decode()) for n, c in self.columns.items()}

    def select(self, names: Sequence[str]) -> "ColumnBatch":
        return ColumnBatch({n: self.column(n) for n in names})

    def with_column(self, name: str, col: Column) -> "ColumnBatch":
        cols = dict(self.columns)
        cols[name] = col
        return ColumnBatch(cols)

    def filter(self, mask: np.ndarray) -> "ColumnBatch":
        return ColumnBatch({n: c.filter(mask) for n, c in self.columns.items()})

    def take(self, indices: np.ndarray) -> "ColumnBatch":
        return ColumnBatch({n: c.take(indices) for n, c in self.columns.items()})

    def slice(self, start: int, stop: int) -> "ColumnBatch":
        """Zero-copy contiguous row range (see Column.slice)."""
        return ColumnBatch(
            {n: c.slice(start, stop) for n, c in self.columns.items()}
        )

    def rename(self, mapping: Mapping[str, str]) -> "ColumnBatch":
        return ColumnBatch(
            {mapping.get(n, n): c for n, c in self.columns.items()}
        )

    @staticmethod
    def concat(batches: Sequence["ColumnBatch"]) -> "ColumnBatch":
        batches = [b for b in batches if b is not None]
        if not batches:
            return ColumnBatch({})
        names = batches[0].schema.names
        out: dict[str, Column] = {}
        for n in names:
            cols = [b.column(n) for b in batches]
            dtype = cols[0].dtype
            mismatched = {c.dtype for c in cols} - {dtype}
            if mismatched:
                raise HyperspaceError(
                    f"Cannot concat column {n!r}: dtype {dtype} vs {sorted(mismatched)}"
                )
            if dtype == STRING:
                # merge via dictionary union + code remap: O(vocab + n),
                # never factorizing n row values (vocabularies are small)
                vocabs = [c.dictionary if c.dictionary else [""] for c in cols]
                union = sorted(set().union(*vocabs))
                lut = {s: i for i, s in enumerate(union)}
                parts = []
                for c, vocab in zip(cols, vocabs):
                    remap = np.fromiter(
                        (lut[s] for s in vocab), dtype=np.int32, count=len(vocab)
                    )
                    parts.append(remap[c.data])
                data = np.concatenate(parts)
                dictionary = union
            else:
                data = np.concatenate([c.data for c in cols])
                dictionary = None
            if any(c.validity is not None for c in cols):
                validity = np.concatenate(
                    [
                        c.validity
                        if c.validity is not None
                        else np.ones(len(c), dtype=bool)
                        for c in cols
                    ]
                )
            else:
                validity = None
            out[n] = Column(data, dtype, validity, dictionary)
        return ColumnBatch(out)

    def __repr__(self):
        return f"ColumnBatch({self.num_rows} rows, {self.schema})"
