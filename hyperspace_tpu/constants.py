"""Central flag namespace and defaults.

Reference parity: index/IndexConstants.scala:21-170 (spark.hyperspace.* keys).
Keys here drop the `spark.` prefix — this is not Spark — but keep the rest of
the dotted name so reference users recognize every knob.
"""

# --- toggles -----------------------------------------------------------------
APPLY_ENABLED = "hyperspace.apply.enabled"
APPLY_ENABLED_DEFAULT = True

# --- layout ------------------------------------------------------------------
SYSTEM_PATH = "hyperspace.system.path"  # default: <warehouse>/indexes (PathResolver)
INDEXES_DIR = "indexes"

# Transaction-log directory name under each index root
# (ref: index/IndexLogManager.scala:30 "_hyperspace_log").
HYPERSPACE_LOG = "_hyperspace_log"
LATEST_STABLE_LOG = "latestStable"

# Versioned index-data directory prefix (ref: index/IndexDataManager.scala:24-37).
INDEX_VERSION_DIR_PREFIX = "v__"

# --- covering index ----------------------------------------------------------
INDEX_NUM_BUCKETS = "hyperspace.index.numBuckets"
INDEX_NUM_BUCKETS_LEGACY = "hyperspace.num.buckets"  # legacy fallback key
INDEX_NUM_BUCKETS_DEFAULT = 8  # reference defaults to 200 (Spark shuffle default);
# on a TPU mesh one bucket per device-shard is the natural unit.

# Lineage column: stable source-file id recorded per index row
# (ref: index/IndexConstants.scala DATA_FILE_NAME_ID / lineage.enabled).
INDEX_LINEAGE_ENABLED = "hyperspace.index.lineage.enabled"
INDEX_LINEAGE_ENABLED_DEFAULT = False
DATA_FILE_NAME_ID = "_data_file_id"

# Nested (struct) source fields flatten to columns named
# "__hs_nested.<parent>.<leaf>" — the reference's ResolverUtils.ResolvedColumn
# normalization (util/ResolverUtils.scala), kept as the on-disk index column
# naming contract. User references by the bare dotted path ("a.b.c") resolve
# to the prefixed flat column.
NESTED_FIELD_PREFIX = "__hs_nested."

# --- hybrid scan -------------------------------------------------------------
HYBRID_SCAN_ENABLED = "hyperspace.index.hybridscan.enabled"
HYBRID_SCAN_ENABLED_DEFAULT = False
HYBRID_SCAN_MAX_APPENDED_RATIO = "hyperspace.index.hybridscan.maxAppendedRatio"
HYBRID_SCAN_MAX_APPENDED_RATIO_DEFAULT = 0.3
HYBRID_SCAN_MAX_DELETED_RATIO = "hyperspace.index.hybridscan.maxDeletedRatio"
HYBRID_SCAN_MAX_DELETED_RATIO_DEFAULT = 0.2

# --- rules -------------------------------------------------------------------
FILTER_RULE_USE_BUCKET_SPEC = "hyperspace.index.filterRule.useBucketSpec"
FILTER_RULE_USE_BUCKET_SPEC_DEFAULT = False

# --- optimize ----------------------------------------------------------------
OPTIMIZE_FILE_SIZE_THRESHOLD = "hyperspace.index.optimize.fileSizeThreshold"
OPTIMIZE_FILE_SIZE_THRESHOLD_DEFAULT = 256 * 1024 * 1024  # 256 MB
OPTIMIZE_MODE_QUICK = "quick"
OPTIMIZE_MODE_FULL = "full"
OPTIMIZE_MODES = (OPTIMIZE_MODE_QUICK, OPTIMIZE_MODE_FULL)

# --- refresh -----------------------------------------------------------------
REFRESH_MODE_INCREMENTAL = "incremental"
REFRESH_MODE_FULL = "full"
REFRESH_MODE_QUICK = "quick"
REFRESH_MODES = (REFRESH_MODE_INCREMENTAL, REFRESH_MODE_FULL, REFRESH_MODE_QUICK)

# --- caching -----------------------------------------------------------------
INDEX_CACHE_EXPIRY_SECONDS = "hyperspace.index.cache.expiryDurationInSeconds"
INDEX_CACHE_EXPIRY_SECONDS_DEFAULT = 300

# --- z-order covering --------------------------------------------------------
ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION = (
    "hyperspace.index.zorder.targetSourceBytesPerPartition"
)
ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION_DEFAULT = 1024 * 1024 * 1024  # 1 GB
ZORDER_QUANTILE_ENABLED = "hyperspace.index.zorder.quantile.enabled"
ZORDER_QUANTILE_ENABLED_DEFAULT = False
ZORDER_QUANTILE_RELATIVE_ERROR = "hyperspace.index.zorder.quantile.relativeError"
ZORDER_QUANTILE_RELATIVE_ERROR_DEFAULT = 0.01

# --- data skipping -----------------------------------------------------------
DATASKIPPING_TARGET_INDEX_DATA_FILE_SIZE = (
    "hyperspace.index.dataskipping.targetIndexDataFileSize"
)
DATASKIPPING_TARGET_INDEX_DATA_FILE_SIZE_DEFAULT = 256 * 1024 * 1024
DATASKIPPING_MAX_INDEX_DATA_FILE_COUNT = (
    "hyperspace.index.dataskipping.maxIndexDataFileCount"
)
DATASKIPPING_MAX_INDEX_DATA_FILE_COUNT_DEFAULT = 10000
DATASKIPPING_AUTO_PARTITION_SKETCH = (
    "hyperspace.index.dataskipping.autoPartitionSketch"
)
DATASKIPPING_AUTO_PARTITION_SKETCH_DEFAULT = True

# --- telemetry ---------------------------------------------------------------
EVENT_LOGGER_CLASS = "hyperspace.telemetry.eventLoggerClass"

# --- sources -----------------------------------------------------------------
FILE_BASED_SOURCE_BUILDERS = "hyperspace.index.sources.fileBasedBuilders"
# Conf-gated default-source format list (ref: HyperspaceConf.scala:110-115,
# DefaultFileBasedSource.scala:38-95 — same default set, same key shape)
DEFAULT_SOURCE_FORMATS = (
    "hyperspace.index.sources.defaultFileBasedSource.supportedFileFormats"
)
DEFAULT_SOURCE_FORMATS_DEFAULT = "avro,csv,json,orc,parquet,text"
GLOBBING_PATTERN_KEY = "hyperspace.source.globbingPattern"
# scan option carrying the original glob roots so relation reloads re-expand
OPT_GLOB_PATHS = "globPaths"

# --- explain -----------------------------------------------------------------
DISPLAY_MODE = "hyperspace.explain.displayMode"
DISPLAY_MODE_DEFAULT = "plaintext"
HIGHLIGHT_BEGIN_TAG = "hyperspace.explain.displayMode.highlight.beginTag"
HIGHLIGHT_END_TAG = "hyperspace.explain.displayMode.highlight.endTag"

# --- execution (TPU-native; no reference analogue) ---------------------------
# Devices to execute supported fragments over (0 = single-device). With a
# multi-chip mesh, fragment rows shard across devices and only per-group
# partial vectors cross the interconnect.
EXEC_MESH_DEVICES = "hyperspace.tpu.exec.meshDevices"
EXEC_MESH_DEVICES_DEFAULT = 0
# Multi-slice topology: arrange meshDevices as (meshSlices, devices/slice)
# with ("dcn", "ici") axes. Query-fragment aggregates then psum over the
# axis pair — XLA reduces within each slice over ICI and only per-group
# partials cross DCN. 1 = single slice (flat 1-D mesh). Index builds split
# source rows across the slices and exchange on each slice's own submesh,
# so the bucket all_to_all rides ICI only (one sorted run per slice per
# bucket, the streaming-build layout).
EXEC_MESH_SLICES = "hyperspace.tpu.exec.meshSlices"
EXEC_MESH_SLICES_DEFAULT = 1
# Fused-XLA execution of supported plan fragments. Off by default on CPU
# (host numpy path is exact float64); bench/production TPU sessions turn it on.
EXEC_TPU_ENABLED = "hyperspace.tpu.exec.enabled"
EXEC_TPU_ENABLED_DEFAULT = False

# f64 Sum/Avg inputs in the fused device join+aggregate: by default they
# ship as f32 and accumulate on device (per-element relative error <= 2^-24,
# group-sum error well under 1e-6 relative for the small per-key groups the
# fused shape produces — same accuracy class as the scan-side f32 Pallas
# reductions that have always shipped). Setting this true restores the
# strict round-3 behavior: f64 Sum/Avg inputs always take the exact-f64
# host twin, so device and host tiers agree bit-for-bit.
EXEC_EXACT_F64_AGG = "hyperspace.tpu.exec.exactF64Aggregates"
EXEC_EXACT_F64_AGG_DEFAULT = False

# Out-of-core builds: source batches larger than this stream through the
# bucketed writer in file groups (bounded memory; buckets get one sorted run
# per group, compacted later by Optimize).
BUILD_MAX_BYTES_IN_MEMORY = "hyperspace.tpu.build.maxBytesInMemory"
BUILD_MAX_BYTES_IN_MEMORY_DEFAULT = 2 * 1024 * 1024 * 1024  # 2 GB

# Index DATA file format: "parquet" (default; reference layout parity) or
# "arrow" (Arrow IPC: ~3x faster single-core writes, mmap reads). Readers
# dispatch on file extension, so indexes written under either setting stay
# readable regardless of the current conf.
INDEX_FORMAT = "hyperspace.tpu.index.format"
INDEX_FORMAT_DEFAULT = "parquet"

# Parquet row-group statistics scope for index data files: "clustered"
# (default) writes min/max only for the columns the layout actually sorts or
# z-orders by — the only ones whose statistics prune row groups — cutting
# encode time ~20% on numeric-heavy slices; "all" restores stats on every
# column (matches what Spark's parquet writer does for the reference).
INDEX_STATS_COLUMNS = "hyperspace.tpu.index.statsColumns"
INDEX_STATS_COLUMNS_DEFAULT = "clustered"

# Compression codec for index data files ("lz4" default; "none" trades ~2x
# disk for ~20% faster single-core encodes, "zstd"/"snappy"/"gzip" also accepted).
INDEX_COMPRESSION = "hyperspace.tpu.index.compression"
INDEX_COMPRESSION_DEFAULT = "lz4"

# Log-entry id numbering (ref: actions/Action.scala baseId+1 transient, +2 final).
LOG_ID_TRANSIENT_OFFSET = 1
LOG_ID_FINAL_OFFSET = 2
