"""HyperspaceSession — the session object the framework hangs off.

The reference is a library over SparkSession (conf, catalog, optimizer hooks:
ref HyperspaceSparkSessionExtension.scala:44-69, package.scala:31-94). There is
no Spark here, so the session is ours: it owns the mutable conf, the warehouse
directory, the reader, and the optimizer-rule registration that
`enable_hyperspace()` toggles.
"""

from __future__ import annotations

import os
from typing import Any

from . import constants as C
from .config import HyperspaceConf


class HyperspaceSession:
    def __init__(self, warehouse_dir: str = ".", conf: dict[str, Any] | None = None):
        self.warehouse_dir = os.path.abspath(warehouse_dir)
        self._conf: dict[str, Any] = dict(conf or {})
        self.conf = HyperspaceConf(self._conf)
        # Optimizer rules applied to every query plan at execution time when
        # hyperspace is enabled (analogue of extraOptimizations).
        self.extra_optimizations: list[Any] = []
        # Runs an index-maintenance action => rewrite disabled (thread-local
        # guard in the reference, ApplyHyperspace.scala:41-47).
        self._rewrite_disabled_depth = 0

    # --- conf ---
    def set_conf(self, key: str, value: Any) -> None:
        self._conf[key] = value

    def unset_conf(self, key: str) -> None:
        self._conf.pop(key, None)

    def get_conf(self, key: str, default: Any = None) -> Any:
        return self._conf.get(key, default)

    # --- session integration (ref: package.scala Implicits) ---
    def enable_hyperspace(self) -> "HyperspaceSession":
        from .rules.apply import ApplyHyperspace

        self.set_conf(C.APPLY_ENABLED, True)
        if not any(isinstance(r, ApplyHyperspace) for r in self.extra_optimizations):
            self.extra_optimizations.append(ApplyHyperspace(self))
        return self

    def disable_hyperspace(self) -> "HyperspaceSession":
        from .rules.apply import ApplyHyperspace

        self.set_conf(C.APPLY_ENABLED, False)
        self.extra_optimizations = [
            r for r in self.extra_optimizations if not isinstance(r, ApplyHyperspace)
        ]
        return self

    def is_hyperspace_enabled(self) -> bool:
        from .rules.apply import ApplyHyperspace

        return self.conf.apply_enabled and any(
            isinstance(r, ApplyHyperspace) for r in self.extra_optimizations
        )

    # --- reader ---
    @property
    def read(self):
        from .plan.dataframe import DataFrameReader

        return DataFrameReader(self)

    def create_dataframe(self, data: dict, schema=None):
        """Build an in-memory DataFrame from a dict of column -> values."""
        from .plan.dataframe import DataFrame
        from .plan.nodes import InMemoryScan
        from .columnar.table import ColumnBatch

        batch = ColumnBatch.from_pydict(data, schema)
        return DataFrame(self, InMemoryScan(batch))
