"""Index lifecycle managers.

Reference parity: index/IndexManager.scala:24-127 (contract),
IndexCollectionManager.scala:28-206 (enumerate per-index log managers under
the system path, dispatch to Actions), CachingIndexCollectionManager.scala:
38-117 (read-path cache of entries, cleared by every mutation, time-expired).

Beyond the reference: ``recover()`` — the crash-recovery pass. A process
dying mid-action strands exactly three kinds of debris, each repaired per
the FSM's own semantics (docs/robustness.md has the full matrix):

- a *transient* latest log entry (CREATING/REFRESHING/...) whose owner is
  dead → rolled back to the last stable state through CancelAction (the
  FSM's sanctioned rollback; CREATING/VACUUMING barriers terminate at
  DOESNOTEXIST). Age-gated by ``HYPERSPACE_STALE_TX_S`` so a live
  transaction in another process is never cancelled; in-process liveness
  comes from the actions' active-transaction registry.
- *unpublished or unreferenced data*: ``_staging/<n>`` build dirs and
  ``v__=<n>`` version dirs referenced by no committed entry (a crash
  between ``data.publish`` and the final ``log.write``) → removed. A
  DOESNOTEXIST tail finishes a crashed vacuum by removing all data.
- a *missing/stale latestStable pointer* (a crash between the final
  ``log.write`` and the pointer rewrite) → fixed forward by re-deriving
  the pointer from the latest stable entry.

The pass auto-runs (age-gated, non-forcing) once per manager construction,
so a session transparently heals a warehouse a previous process died in.
"""

from __future__ import annotations

import logging
import os
import time
from typing import TYPE_CHECKING, Optional

from . import constants as C
from .actions import states as S
from .actions.base import action_in_progress
from .actions.create import CreateAction
from .actions.lifecycle import (
    CancelAction,
    DeleteAction,
    RestoreAction,
    VacuumAction,
    VacuumOutdatedAction,
)
from .actions.optimize import OptimizeAction
from .actions.refresh import (
    RefreshAction,
    RefreshIncrementalAction,
    RefreshQuickAction,
)
from .exceptions import HyperspaceError
from .meta.cache import CreationTimeBasedCache
from .meta.data_manager import IndexDataManager
from .meta.entry import IndexLogEntry
from .meta.log_manager import IndexLogManager
from .meta.path_resolver import PathResolver
from .telemetry.logger import event_logger_for
from .utils import env

if TYPE_CHECKING:
    from .plan.dataframe import DataFrame
    from .models.base import IndexConfig
    from .session import HyperspaceSession

logger = logging.getLogger(__name__)


class IndexCollectionManager:
    def __init__(self, session: "HyperspaceSession", auto_recover: bool = True):
        self.session = session
        self.resolver = PathResolver(session.conf, session.warehouse_dir)
        if auto_recover:
            self._auto_recover()

    # --- helpers ---
    def _index_path(self, name: str) -> str:
        return self.resolver.get_index_path(name)

    def _managers(self, name: str) -> tuple[str, IndexLogManager, IndexDataManager]:
        path = self._index_path(name)
        return path, IndexLogManager(path), IndexDataManager(path)

    def _existing_log_manager(self, name: str) -> tuple[str, IndexLogManager, IndexDataManager]:
        path, lm, dm = self._managers(name)
        if lm.get_latest_id() is None:
            raise HyperspaceError(f"Index with name {name!r} could not be found")
        return path, lm, dm

    # --- IndexManager API ---
    def create(self, df: "DataFrame", config: "IndexConfig") -> None:
        path, lm, dm = self._managers(config.index_name)
        CreateAction(
            self.session, df, config, path, lm, dm, event_logger_for(self.session)
        ).run()

    def delete(self, name: str) -> None:
        _, lm, _ = self._existing_log_manager(name)
        DeleteAction(lm, event_logger_for(self.session)).run()

    def restore(self, name: str) -> None:
        _, lm, _ = self._existing_log_manager(name)
        RestoreAction(lm, event_logger_for(self.session)).run()

    def vacuum(self, name: str) -> None:
        path, lm, _ = self._existing_log_manager(name)
        VacuumAction(path, lm, event_logger_for(self.session)).run()

    def vacuum_outdated(self, name: str) -> None:
        from .ingest.compaction import writer_lock

        path, lm, dm = self._existing_log_manager(name)
        with writer_lock(path):
            VacuumOutdatedAction(path, lm, dm, event_logger_for(self.session)).run()

    def refresh(self, name: str, mode: str = C.REFRESH_MODE_FULL) -> None:
        path, lm, dm = self._existing_log_manager(name)
        cls = {
            C.REFRESH_MODE_FULL: RefreshAction,
            C.REFRESH_MODE_INCREMENTAL: RefreshIncrementalAction,
            C.REFRESH_MODE_QUICK: RefreshQuickAction,
        }.get(mode)
        if cls is None:
            raise HyperspaceError(
                f"Invalid refresh mode {mode!r}; valid: {C.REFRESH_MODES}"
            )
        cls(self.session, path, lm, dm, event_logger_for(self.session)).run()

    def optimize(self, name: str, mode: str = C.OPTIMIZE_MODE_QUICK) -> None:
        path, lm, dm = self._existing_log_manager(name)
        OptimizeAction(
            self.session, path, lm, dm, mode, event_logger_for(self.session)
        ).run()

    # --- continuous ingestion (hyperspace_tpu/ingest/) ---

    def append(self, name: str, df: "DataFrame") -> None:
        """Index ``df``'s NEW source files as append-only delta runs in a
        fresh atomic data version (log-structured ingest; no rebuild), then
        schedule background compaction when the run threshold is crossed.
        In-process writers (the ingest stream, background maintenance)
        serialize on the per-index writer mutex; cross-process writers go
        through the log's optimistic concurrency as always."""
        from .cache.view_maintenance import maybe_refresh
        from .ingest.actions import IngestAppendAction
        from .ingest.compaction import maybe_schedule, writer_lock

        path, lm, dm = self._existing_log_manager(name)
        with writer_lock(path):
            IngestAppendAction(
                self.session, path, lm, dm, df, event_logger_for(self.session)
            ).run()
        maybe_schedule(self.session, name)
        # version advance: fold-eligible cached results over this index
        # refresh to the new snapshot in the background (delta cost)
        maybe_refresh(self.session, name)

    def compact(self, name: str, min_runs: int | None = None) -> None:
        """Merge delta runs of buckets holding >= min_runs files
        (default HYPERSPACE_COMPACT_RUNS) into one sorted file each."""
        from .ingest.actions import IngestCompactAction
        from .ingest.compaction import writer_lock

        path, lm, dm = self._existing_log_manager(name)
        with writer_lock(path):
            IngestCompactAction(
                self.session, path, lm, dm, min_runs, event_logger_for(self.session)
            ).run()

    def cancel(self, name: str) -> None:
        _, lm, _ = self._existing_log_manager(name)
        CancelAction(lm, event_logger_for(self.session)).run()

    def get_indexes(self, states: list[str] | None = None) -> list[IndexLogEntry]:
        from .actions import states as S

        root = self.resolver.system_path
        out: list[IndexLogEntry] = []
        if not os.path.isdir(root):
            return out
        for name in sorted(os.listdir(root)):
            path = os.path.join(root, name)
            if not os.path.isdir(path):
                continue
            lm = IndexLogManager(path)
            entry = lm.get_latest_log()
            if entry is not None and (
                not isinstance(entry, IndexLogEntry)
                or entry.state not in S.STABLE_STATES
            ):
                # a transient tail is another writer's in-flight transaction
                # (ingest append, compaction, refresh...): readers serve the
                # last STABLE snapshot instead of losing the index for the
                # duration — the reader-side half of snapshot isolation
                stable = lm.get_latest_stable_log()
                if isinstance(stable, IndexLogEntry):
                    entry = stable
            if entry is None or not isinstance(entry, IndexLogEntry):
                continue
            if states is None or entry.state in states:
                out.append(entry)
        return out

    def get_index(self, name: str, log_version: int | None = None) -> Optional[IndexLogEntry]:
        path, lm, _ = self._managers(name)
        if log_version is not None:
            e = lm.get_log(log_version)
        else:
            e = lm.get_latest_log()
        return e if isinstance(e, IndexLogEntry) else None

    def get_index_versions(self, name: str, states: list[str] | None = None) -> list[int]:
        _, lm, _ = self._managers(name)
        return lm.get_index_versions(states)

    # --- crash recovery (module docstring has the repair matrix) ---

    def _auto_recover(self) -> None:
        """Construction-time pass. Must never block session start: a failed
        repair is logged and left for an explicit recover() call."""
        try:
            report = self.recover()
            if report["repaired"]:
                logger.warning("index recovery repaired crash debris: %s", report)
        except Exception as e:
            logger.warning("automatic index recovery failed: %s", e)

    def recover(self, name: str | None = None, force: bool = False) -> dict:
        """Detect and repair crash debris across the warehouse (or one
        index). ``force`` ignores the ``HYPERSPACE_STALE_TX_S`` age gate and
        rolls back ANY dead transient entry — only safe when no other
        process is running maintenance on this warehouse."""
        from .telemetry.metrics import REGISTRY

        root = self.resolver.system_path
        report: dict = {"indexes_scanned": 0, "repaired": False, "per_index": {}}
        if name is not None:
            names = [name]
        elif os.path.isdir(root):
            names = sorted(
                n for n in os.listdir(root) if os.path.isdir(os.path.join(root, n))
            )
        else:
            names = []
        REGISTRY.counter("recovery.runs").inc()
        for n in names:
            r = self._recover_index(n, force)
            report["indexes_scanned"] += 1
            repaired = bool(
                r["rolled_back"] or r["pointer_fixed"] or r["staging_removed"]
                or r["orphan_versions"] or r["temp_files"]
            )
            if repaired or r["skipped"]:
                report["per_index"][n] = r
            report["repaired"] = report["repaired"] or repaired
            if r["rolled_back"]:
                REGISTRY.counter("recovery.rolled_back").inc()
            if r["pointer_fixed"]:
                REGISTRY.counter("recovery.pointer_fixed").inc()
            REGISTRY.counter("recovery.staging_removed").inc(r["staging_removed"])
            REGISTRY.counter("recovery.orphan_versions").inc(len(r["orphan_versions"]))
            REGISTRY.counter("recovery.temp_files").inc(r["temp_files"])
        return report

    def _recover_index(self, name: str, force: bool) -> dict:
        path, lm, dm = self._managers(name)
        r: dict = {
            "rolled_back": None, "pointer_fixed": False, "staging_removed": 0,
            "orphan_versions": [], "temp_files": 0, "skipped": None,
        }
        if action_in_progress(path):
            r["skipped"] = "live-transaction"
            return r
        latest_id = lm.get_latest_id()
        latest = lm.get_log(latest_id) if latest_id is not None else None
        if latest is not None and latest.state not in S.STABLE_STATES:
            age_ms = time.time() * 1000 - (latest.timestamp or 0)
            if not force and age_ms < env.env_float("HYPERSPACE_STALE_TX_S") * 1000:
                # possibly another process's live transaction: leave the
                # entry AND its staging/temp artifacts alone
                r["skipped"] = f"fresh-transient:{latest.state}"
                return r
            CancelAction(lm, event_logger_for(self.session)).run()
            r["rolled_back"] = latest.state
            latest_id = lm.get_latest_id()
            latest = lm.get_log(latest_id) if latest_id is not None else None
        # log tail is stable (or empty): every staged build and .tmp- spool
        # file is dead-transaction debris
        r["staging_removed"] = dm.clear_staging()
        r["temp_files"] = lm.clear_temp_files(0.0 if force else 60.0)
        if latest is None:
            # no committed entry references anything: aborted-create debris.
            # Pinned/protected versions (orphan_version_dirs excludes them)
            # survive even here — a pin means an in-flight query resolved
            # files from this dir, and recovery must never race it.
            for v in dm.orphan_version_dirs(set()):
                dm.delete_version(v)
                r["orphan_versions"].append(v)
            self._rmdir_if_empty(lm.log_dir)
            self._rmdir_if_empty(path)
            return r
        if latest.state == S.DOESNOTEXIST:
            # terminal state: finish a crashed vacuum — all (unpinned) data goes
            doomed = dm.orphan_version_dirs(set())
        else:
            doomed = dm.orphan_version_dirs(self._referenced_versions(lm))
        for v in doomed:
            dm.delete_version(v)
            r["orphan_versions"].append(v)
        if latest.state in S.STABLE_STATES and lm.stable_pointer_id() != latest_id:
            # crash between the final log.write and the pointer rewrite
            lm.delete_latest_stable_log()
            if lm.create_latest_stable_log(latest_id):
                r["pointer_fixed"] = True
        return r

    @staticmethod
    def _referenced_versions(lm: IndexLogManager) -> set:
        """Data versions referenced by ANY committed entry (conservative:
        an old entry keeping a version alive is vacuum_outdated's business,
        not recovery's — recovery removes only true orphans)."""
        refs: set = set()
        if not os.path.isdir(lm.log_dir):
            return refs
        for n in os.listdir(lm.log_dir):
            if not n.isdigit():
                continue
            e = lm.get_log(int(n))
            if isinstance(e, IndexLogEntry):
                for d in e.index_version_dirs():
                    try:
                        refs.add(int(d.split("=")[1]))
                    except (IndexError, ValueError):
                        continue
        return refs

    @staticmethod
    def _rmdir_if_empty(path: str) -> None:
        try:
            os.rmdir(path)  # only succeeds when empty — exactly the intent
        except OSError:
            pass  # hslint: HS402 — non-empty or absent dir stays put


class CachingIndexCollectionManager(IndexCollectionManager):
    """get_indexes cache with creation-time expiry; any mutation clears it
    (ref: CachingIndexCollectionManager.scala:38-117)."""

    def __init__(self, session: "HyperspaceSession"):
        # cache first: the construction-time recovery pass in
        # super().__init__ goes through the cache-clearing recover() wrapper
        self._cache: CreationTimeBasedCache[list[IndexLogEntry]] = (
            CreationTimeBasedCache(lambda: session.conf.cache_expiry_seconds)
        )
        super().__init__(session)

    def clear_cache(self) -> None:
        self._cache.clear()

    def get_indexes(self, states: list[str] | None = None) -> list[IndexLogEntry]:
        cached = self._cache.get()
        if cached is None:
            cached = super().get_indexes(None)
            self._cache.set(cached)
        if states is None:
            return list(cached)
        return [e for e in cached if e.state in states]

    def _mutating(fn):  # type: ignore[misc]
        def wrapper(self, *a, **kw):
            self.clear_cache()
            try:
                return fn(self, *a, **kw)
            finally:
                self.clear_cache()

        wrapper.__name__ = fn.__name__
        return wrapper

    create = _mutating(IndexCollectionManager.create)
    delete = _mutating(IndexCollectionManager.delete)
    restore = _mutating(IndexCollectionManager.restore)
    vacuum = _mutating(IndexCollectionManager.vacuum)
    vacuum_outdated = _mutating(IndexCollectionManager.vacuum_outdated)
    refresh = _mutating(IndexCollectionManager.refresh)
    optimize = _mutating(IndexCollectionManager.optimize)
    append = _mutating(IndexCollectionManager.append)
    compact = _mutating(IndexCollectionManager.compact)
    cancel = _mutating(IndexCollectionManager.cancel)
    recover = _mutating(IndexCollectionManager.recover)


def index_manager_for(session: "HyperspaceSession") -> CachingIndexCollectionManager:
    m = getattr(session, "_index_manager", None)
    if m is None:
        m = CachingIndexCollectionManager(session)
        session._index_manager = m
    return m
