"""Index lifecycle managers.

Reference parity: index/IndexManager.scala:24-127 (contract),
IndexCollectionManager.scala:28-206 (enumerate per-index log managers under
the system path, dispatch to Actions), CachingIndexCollectionManager.scala:
38-117 (read-path cache of entries, cleared by every mutation, time-expired).
"""

from __future__ import annotations

import os
from typing import TYPE_CHECKING, Optional

from . import constants as C
from .actions import states as S
from .actions.create import CreateAction
from .actions.lifecycle import (
    CancelAction,
    DeleteAction,
    RestoreAction,
    VacuumAction,
    VacuumOutdatedAction,
)
from .actions.optimize import OptimizeAction
from .actions.refresh import (
    RefreshAction,
    RefreshIncrementalAction,
    RefreshQuickAction,
)
from .exceptions import HyperspaceError
from .meta.cache import CreationTimeBasedCache
from .meta.data_manager import IndexDataManager
from .meta.entry import IndexLogEntry
from .meta.log_manager import IndexLogManager
from .meta.path_resolver import PathResolver
from .telemetry.logger import event_logger_for

if TYPE_CHECKING:
    from .plan.dataframe import DataFrame
    from .models.base import IndexConfig
    from .session import HyperspaceSession


class IndexCollectionManager:
    def __init__(self, session: "HyperspaceSession"):
        self.session = session
        self.resolver = PathResolver(session.conf, session.warehouse_dir)

    # --- helpers ---
    def _index_path(self, name: str) -> str:
        return self.resolver.get_index_path(name)

    def _managers(self, name: str) -> tuple[str, IndexLogManager, IndexDataManager]:
        path = self._index_path(name)
        return path, IndexLogManager(path), IndexDataManager(path)

    def _existing_log_manager(self, name: str) -> tuple[str, IndexLogManager, IndexDataManager]:
        path, lm, dm = self._managers(name)
        if lm.get_latest_id() is None:
            raise HyperspaceError(f"Index with name {name!r} could not be found")
        return path, lm, dm

    # --- IndexManager API ---
    def create(self, df: "DataFrame", config: "IndexConfig") -> None:
        path, lm, dm = self._managers(config.index_name)
        CreateAction(
            self.session, df, config, path, lm, dm, event_logger_for(self.session)
        ).run()

    def delete(self, name: str) -> None:
        _, lm, _ = self._existing_log_manager(name)
        DeleteAction(lm, event_logger_for(self.session)).run()

    def restore(self, name: str) -> None:
        _, lm, _ = self._existing_log_manager(name)
        RestoreAction(lm, event_logger_for(self.session)).run()

    def vacuum(self, name: str) -> None:
        path, lm, _ = self._existing_log_manager(name)
        VacuumAction(path, lm, event_logger_for(self.session)).run()

    def vacuum_outdated(self, name: str) -> None:
        path, lm, dm = self._existing_log_manager(name)
        VacuumOutdatedAction(path, lm, dm, event_logger_for(self.session)).run()

    def refresh(self, name: str, mode: str = C.REFRESH_MODE_FULL) -> None:
        path, lm, dm = self._existing_log_manager(name)
        cls = {
            C.REFRESH_MODE_FULL: RefreshAction,
            C.REFRESH_MODE_INCREMENTAL: RefreshIncrementalAction,
            C.REFRESH_MODE_QUICK: RefreshQuickAction,
        }.get(mode)
        if cls is None:
            raise HyperspaceError(
                f"Invalid refresh mode {mode!r}; valid: {C.REFRESH_MODES}"
            )
        cls(self.session, path, lm, dm, event_logger_for(self.session)).run()

    def optimize(self, name: str, mode: str = C.OPTIMIZE_MODE_QUICK) -> None:
        path, lm, dm = self._existing_log_manager(name)
        OptimizeAction(
            self.session, path, lm, dm, mode, event_logger_for(self.session)
        ).run()

    def cancel(self, name: str) -> None:
        _, lm, _ = self._existing_log_manager(name)
        CancelAction(lm, event_logger_for(self.session)).run()

    def get_indexes(self, states: list[str] | None = None) -> list[IndexLogEntry]:
        root = self.resolver.system_path
        out: list[IndexLogEntry] = []
        if not os.path.isdir(root):
            return out
        for name in sorted(os.listdir(root)):
            path = os.path.join(root, name)
            if not os.path.isdir(path):
                continue
            entry = IndexLogManager(path).get_latest_log()
            if entry is None or not isinstance(entry, IndexLogEntry):
                continue
            if states is None or entry.state in states:
                out.append(entry)
        return out

    def get_index(self, name: str, log_version: int | None = None) -> Optional[IndexLogEntry]:
        path, lm, _ = self._managers(name)
        if log_version is not None:
            e = lm.get_log(log_version)
        else:
            e = lm.get_latest_log()
        return e if isinstance(e, IndexLogEntry) else None

    def get_index_versions(self, name: str, states: list[str] | None = None) -> list[int]:
        _, lm, _ = self._managers(name)
        return lm.get_index_versions(states)


class CachingIndexCollectionManager(IndexCollectionManager):
    """get_indexes cache with creation-time expiry; any mutation clears it
    (ref: CachingIndexCollectionManager.scala:38-117)."""

    def __init__(self, session: "HyperspaceSession"):
        super().__init__(session)
        self._cache: CreationTimeBasedCache[list[IndexLogEntry]] = (
            CreationTimeBasedCache(lambda: session.conf.cache_expiry_seconds)
        )

    def clear_cache(self) -> None:
        self._cache.clear()

    def get_indexes(self, states: list[str] | None = None) -> list[IndexLogEntry]:
        cached = self._cache.get()
        if cached is None:
            cached = super().get_indexes(None)
            self._cache.set(cached)
        if states is None:
            return list(cached)
        return [e for e in cached if e.state in states]

    def _mutating(fn):  # type: ignore[misc]
        def wrapper(self, *a, **kw):
            self.clear_cache()
            try:
                return fn(self, *a, **kw)
            finally:
                self.clear_cache()

        wrapper.__name__ = fn.__name__
        return wrapper

    create = _mutating(IndexCollectionManager.create)
    delete = _mutating(IndexCollectionManager.delete)
    restore = _mutating(IndexCollectionManager.restore)
    vacuum = _mutating(IndexCollectionManager.vacuum)
    vacuum_outdated = _mutating(IndexCollectionManager.vacuum_outdated)
    refresh = _mutating(IndexCollectionManager.refresh)
    optimize = _mutating(IndexCollectionManager.optimize)
    cancel = _mutating(IndexCollectionManager.cancel)


def index_manager_for(session: "HyperspaceSession") -> CachingIndexCollectionManager:
    m = getattr(session, "_index_manager", None)
    if m is None:
        m = CachingIndexCollectionManager(session)
        session._index_manager = m
    return m
