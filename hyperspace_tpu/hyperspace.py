"""Hyperspace — the user-facing facade.

Reference parity: Hyperspace.scala:27-223 — createIndex/deleteIndex/
restoreIndex/vacuumIndex/refreshIndex/optimizeIndex/cancel/indexes/index/
explain/whyNot over the collection manager, with the rewrite rule disabled
during maintenance (ApplyHyperspace.withHyperspaceRuleDisabled).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Optional

from . import constants as C
from .index_manager import index_manager_for
from .meta.entry import IndexLogEntry

if TYPE_CHECKING:
    from .plan.dataframe import DataFrame
    from .models.base import IndexConfig
    from .session import HyperspaceSession


class Hyperspace:
    def __init__(self, session: "HyperspaceSession"):
        self.session = session
        self._manager = index_manager_for(session)

    # --- index CRUD (ref: Hyperspace.scala:43-157) ---
    def create_index(self, df: "DataFrame", config: "IndexConfig") -> None:
        self._manager.create(df, config)

    def delete_index(self, name: str) -> None:
        self._manager.delete(name)

    def restore_index(self, name: str) -> None:
        self._manager.restore(name)

    def vacuum_index(self, name: str) -> None:
        self._manager.vacuum(name)

    def vacuum_outdated_index(self, name: str) -> None:
        self._manager.vacuum_outdated(name)

    def refresh_index(self, name: str, mode: str = C.REFRESH_MODE_FULL) -> None:
        self._manager.refresh(name, mode)

    def optimize_index(self, name: str, mode: str = C.OPTIMIZE_MODE_QUICK) -> None:
        self._manager.optimize(name, mode)

    # --- continuous ingestion (docs/maintenance.md) ---
    def append(self, name: str, df: "DataFrame") -> None:
        """Ingest ``df``'s new source files into the index as append-only
        per-bucket delta runs — an atomically published immutable snapshot,
        cost proportional to the batch (no rebuild). Crosses the
        HYPERSPACE_COMPACT_RUNS threshold => background compaction."""
        self._manager.append(name, df)

    def compact_index(self, name: str, min_runs: int | None = None) -> None:
        """Merge accumulated delta runs (buckets holding >= min_runs files)
        into one sorted file per bucket; superseded versions are retired by
        vacuum only once their snapshot refcounts drain."""
        self._manager.compact(name, min_runs)

    def cancel(self, name: str) -> None:
        self._manager.cancel(name)

    def recover(self, name: str | None = None, force: bool = False) -> dict:
        """Repair crash debris (stranded transient log entries, unpublished
        staging dirs, orphaned data versions, stale latestStable pointers);
        see docs/robustness.md. Runs automatically at session start —
        explicit calls are for post-crash repair with ``force=True``."""
        return self._manager.recover(name, force=force)

    # --- introspection ---
    def indexes(self) -> "DataFrame":
        """Summary DataFrame of all indexes (ref: Hyperspace.indexes ->
        IndexStatistics.INDEX_SUMMARY_COLUMNS)."""
        from .analysis.statistics import index_statistics_df

        return index_statistics_df(self.session, self._manager.get_indexes())

    def index(self, name: str) -> "DataFrame":
        """Detailed statistics for one index (ref: Hyperspace.index)."""
        from .analysis.statistics import index_statistics_df
        from .exceptions import HyperspaceError

        entry = self._manager.get_index(name)
        if entry is None:
            raise HyperspaceError(f"Index with name {name!r} could not be found")
        return index_statistics_df(self.session, [entry], extended=True)

    def get_index_versions(self, name: str, states: list[str] | None = None) -> list[int]:
        return self._manager.get_index_versions(name, states)

    def get_index(self, name: str, log_version: int | None = None) -> Optional[IndexLogEntry]:
        return self._manager.get_index(name, log_version)

    # --- explain / whyNot (ref: Hyperspace.scala:160-192) ---
    def explain(self, df: "DataFrame", verbose: bool = False, redirect=None) -> Optional[str]:
        from .analysis.explain import explain_string

        s = explain_string(self.session, df, verbose)
        if redirect is not None:
            redirect(s)
            return None
        return s

    def why_not(
        self, df: "DataFrame", index_name: str = "", extended: bool = False, redirect=None
    ) -> Optional[str]:
        from .analysis.whynot import why_not_string

        s = why_not_string(self.session, df, index_name or None, extended)
        if redirect is not None:
            redirect(s)
            return None
        return s

    def explain_analyze(self, df: "DataFrame", redirect=None) -> Optional[str]:
        """Execute the query once with the plan-statistics collector on and
        return the optimized plan annotated with per-node actual rows /
        wall time / route / bytes and estimator q-errors — bit-identical
        execution to a plain collect (docs/observability.md "Plan
        statistics & EXPLAIN ANALYZE")."""
        from .analysis.explain import explain_analyze_string

        s = explain_analyze_string(self.session, df)
        if redirect is not None:
            redirect(s)
            return None
        return s

    def profile(self, df: "DataFrame", redirect=None) -> Optional[str]:
        """Execute the query once under tracing and return the per-query
        profile report (span tree + metrics; docs/observability.md)."""
        from .analysis.explain import profile_string

        s = profile_string(self.session, df)
        if redirect is not None:
            redirect(s)
            return None
        return s

    def workload_report(self, redirect=None) -> Optional[str]:
        """The workload-intelligence plane report: durable-journal state,
        the journaled label/shape mix, and drift regressions. Requires
        ``HYPERSPACE_WORKLOAD_DIR`` (docs/observability.md "Workload
        intelligence")."""
        from .analysis.explain import workload_report_string

        s = workload_report_string()
        if redirect is not None:
            redirect(s)
            return None
        return s

    def index_report(self, redirect=None) -> Optional[str]:
        """The per-index utility ledger: counterfactual benefit vs
        maintenance cost per index, net utility ranking, heat, and
        cold-index candidates. Requires ``HYPERSPACE_WORKLOAD_DIR``
        (docs/observability.md "Workload intelligence")."""
        from .analysis.explain import index_report_string

        s = index_report_string()
        if redirect is not None:
            redirect(s)
            return None
        return s
