"""Source-provider abstraction: pluggable file-based data sources.

Reference parity: index/sources/interfaces.scala:43-277 (FileBasedRelation,
FileBasedSourceProvider, FileBasedRelationMetadata). A provider answers, for
a logical-plan leaf: is it supported, what files back it, how to sign it, how
to serialize it into the log entry, and how to reload it at refresh time.
"""

from __future__ import annotations

import os
from typing import TYPE_CHECKING, Optional

from ..columnar.table import Schema
from ..meta.entry import Content, FileIdTracker, FileInfo, Relation
from ..plan.nodes import FileScan, LogicalPlan
from ..exceptions import HyperspaceError

if TYPE_CHECKING:
    from ..session import HyperspaceSession


class FileBasedRelation:
    """View over a supported scan node (ref: FileBasedRelation trait)."""

    def __init__(self, session: "HyperspaceSession", scan: FileScan):
        self.session = session
        self.scan = scan

    @property
    def root_paths(self) -> list[str]:
        return self.scan.root_paths

    def all_files(self) -> list[FileInfo]:
        return list(self.scan.files)

    @property
    def schema(self) -> Schema:
        return self.scan.full_schema

    @property
    def file_format(self) -> str:
        return self.scan.fmt

    @property
    def options(self) -> dict[str, str]:
        return dict(self.scan.options)

    def record_version_history(
        self, properties: dict[str, str], log_version: int
    ) -> None:
        """Record table-version information against the index log version in
        the index properties (snapshot providers override; default no-op).
        Lets actions stay provider-agnostic about time-travel bookkeeping."""

    def create_relation_metadata(self, file_id_tracker: FileIdTracker) -> Relation:
        """Serialize into the log entry, assigning stable file ids
        (ref: DefaultFileBasedRelation.createRelationMetadata)."""
        infos = []
        for f in self.all_files():
            fid = file_id_tracker.add_file(f.name, f.size, f.modified_time)
            infos.append(FileInfo(f.name, f.size, f.modified_time, fid))
        return Relation(
            root_paths=self.root_paths,
            content=Content.from_files(infos),
            schema=self.schema.to_list(),
            file_format=self.file_format,
            options=self.options,
        )


class FileBasedSourceProvider:
    """Provider contract (ref: FileBasedSourceProvider). Returns None for
    "not mine" so the manager can try the next provider."""

    def get_relation(
        self, session: "HyperspaceSession", node: LogicalPlan
    ) -> Optional[FileBasedRelation]:
        raise NotImplementedError

    def is_supported_relation(self, node: LogicalPlan) -> Optional[bool]:
        raise NotImplementedError

    def reload_relation(
        self, session: "HyperspaceSession", metadata: Relation
    ) -> Optional["object"]:
        """Rebuild a DataFrame over the relation's *current* files (used by
        refresh, ref: RefreshActionBase.df:54-77). Returns DataFrame."""
        raise NotImplementedError


def _wildcard_match_is_hidden(pattern: str, match: str) -> bool:
    """True when a WILDCARD segment of the pattern matched a metadata entry
    (leading '_'/'.'); explicitly-literal hidden segments are allowed."""
    import glob as _glob

    ps, ms = pattern.split(os.sep), match.split(os.sep)
    if len(ps) != len(ms):  # '**' patterns: be conservative about any segment
        return any(seg.startswith(("_", ".")) for seg in ms if seg)
    return any(
        _glob.has_magic(pseg) and mseg.startswith(("_", "."))
        for pseg, mseg in zip(ps, ms)
    )


def expand_glob_roots(roots: list[str], allow_empty: bool = False) -> list[str]:
    """Expand wildcard roots; a literal path wins over glob interpretation
    (a directory named 'data[1]' loads as itself); metadata entries matched
    by a wildcard segment never become data roots.

    allow_empty: scope re-expansion at refresh time tolerates components that
    currently match nothing (the scope may legitimately be empty now); load
    time keeps the loud error."""
    import glob as _glob

    out: list[str] = []
    for root in roots:
        if os.path.exists(root) or not _glob.has_magic(root):
            out.append(root)
            continue
        matches = sorted(
            m for m in _glob.glob(root) if not _wildcard_match_is_hidden(root, m)
        )
        if not matches and not allow_empty:
            raise HyperspaceError(f"Glob pattern matched nothing: {root}")
        out.extend(matches)
    if not out and roots:
        # even a tolerant scope re-expansion must not silently produce an
        # empty relation (an unmounted volume would wipe the index on refresh)
        raise HyperspaceError(
            f"Glob scope matched no paths at all: {roots}"
        )
    return out


def encode_glob_paths(roots: list[str]) -> str:
    """JSON-encoded root-pattern list (commas are legal in paths)."""
    import json

    return json.dumps([os.path.abspath(r) for r in roots])


def decode_glob_paths(value: str) -> list[str]:
    import json

    try:
        out = json.loads(value)
        if isinstance(out, list):
            return [str(p) for p in out]
    except ValueError:
        pass
    return [p for p in value.split(",") if p]  # legacy comma form


def relist_files(root_paths: list[str]) -> list[FileInfo]:
    """Fresh recursive listing of data files under the relation roots
    (callers expand recorded glob scopes first — see
    default.DefaultFileBasedSource.reload_relation)."""
    files: list[FileInfo] = []
    for root in root_paths:
        if os.path.isfile(root):
            files.append(FileInfo.from_path(root))
            continue
        if not os.path.isdir(root):
            raise HyperspaceError(f"Source path disappeared: {root}")
        for dirpath, _dirs, names in os.walk(root):
            rel = os.path.relpath(dirpath, root).split(os.sep)
            if any(p.startswith(("_", ".")) for p in rel if p != "."):
                continue
            for fn in sorted(names):
                if fn.startswith(("_", ".")):
                    continue
                files.append(FileInfo.from_path(os.path.join(dirpath, fn)))
    return files
