"""Default file-based source provider: parquet/csv/json directories.

Reference parity: index/sources/default/DefaultFileBasedSource.scala:38-95
(supported formats are conf-gated; delta excluded from the default list) and
DefaultFileBasedRelation.scala:38-245.
"""

from __future__ import annotations

from typing import Optional

from .interfaces import FileBasedRelation, FileBasedSourceProvider, relist_files
from ..columnar.table import Schema
from ..meta.entry import Relation
from ..plan.nodes import FileScan, LogicalPlan

from .. import constants as C

# The reference's default list (DefaultFileBasedSource.scala:53-75), a
# single source of truth shared with the conf default; the session conf
# hyperspace.index.sources.defaultFileBasedSource.supportedFileFormats
# overrides it per session
DEFAULT_SUPPORTED_FORMATS = tuple(C.DEFAULT_SOURCE_FORMATS_DEFAULT.split(","))


class DefaultFileBasedSource(FileBasedSourceProvider):
    def __init__(self, session=None):
        self._session = session

    def _formats(self) -> tuple[str, ...]:
        if self._session is not None:
            try:
                return self._session.conf.default_source_formats
            except Exception:
                pass  # hslint: HS402 — conf objects without the knob fall back to defaults
        return DEFAULT_SUPPORTED_FORMATS

    def _supported(self, node: LogicalPlan) -> bool:
        return (
            isinstance(node, FileScan)
            and node.fmt in self._formats()
            and node.index_info is None  # index scans are not re-indexable sources
            # snapshot tables answer via their own providers, the way the
            # reference's default source list excludes 'delta'
            # (DefaultFileBasedSource.scala:53-75)
            and node.options.get("format")
            not in ("snapshot-parquet", "iceberg-parquet")
        )

    def is_supported_relation(self, node: LogicalPlan) -> Optional[bool]:
        return True if self._supported(node) else None

    def get_relation(self, session, node: LogicalPlan) -> Optional[FileBasedRelation]:
        if not self._supported(node):
            return None
        return FileBasedRelation(session, node)

    def reload_relation(self, session, metadata: Relation):
        from ..plan.dataframe import DataFrame
        from ..utils.partitions import infer_partition_fields

        if metadata.file_format not in self._formats():
            return None
        from .. import constants as C
        from .interfaces import decode_glob_paths, expand_glob_roots

        glob_paths = metadata.options.get(C.OPT_GLOB_PATHS)
        if glob_paths:
            # the CURRENT expansion is the relation's root set (new matching
            # dirs included); a component matching nothing right now is fine
            roots = expand_glob_roots(decode_glob_paths(glob_paths), allow_empty=True)
        else:
            roots = metadata.root_paths
        files = relist_files(roots)
        schema = Schema.from_list(metadata.schema)
        # re-derive hive partition columns: the recorded schema includes them
        # but the parquet files do not
        part_cols = [
            f.name
            for f in infer_partition_fields([fi.name for fi in files], roots)
            if f.name in schema
        ]
        scan = FileScan(
            roots,
            metadata.file_format,
            schema,
            files,
            options=dict(metadata.options),
            partition_columns=part_cols,
        )
        return DataFrame(session, scan)
