"""Source-provider manager.

Reference parity: index/sources/FileBasedSourceProviderManager.scala:38-146 —
providers loaded from conf `hyperspace.index.sources.fileBasedBuilders`
(dotted class paths), each call dispatched so exactly one provider answers
(runWithDefault:126-146).
"""

from __future__ import annotations

import importlib
from typing import TYPE_CHECKING, Callable, Optional

from .default import DefaultFileBasedSource
from .interfaces import FileBasedRelation, FileBasedSourceProvider
from .. import constants as C
from ..exceptions import HyperspaceError
from ..meta.entry import Relation
from ..plan.nodes import LogicalPlan

if TYPE_CHECKING:
    from ..session import HyperspaceSession

_BUILTIN = {
    "hyperspace_tpu.sources.default.DefaultFileBasedSource": DefaultFileBasedSource,
}


class SourceProviderManager:
    def __init__(self, session: "HyperspaceSession"):
        self.session = session
        self._providers: list[FileBasedSourceProvider] = []
        names = session.get_conf(C.FILE_BASED_SOURCE_BUILDERS)
        if names:
            for name in str(names).split(","):
                name = name.strip()
                cls = _BUILTIN.get(name)
                if cls is None:
                    mod, _, cls_name = name.rpartition(".")
                    cls = getattr(importlib.import_module(mod), cls_name)
                self._providers.append(
                    cls(session)
                    if isinstance(cls, type)
                    and issubclass(cls, DefaultFileBasedSource)
                    else cls()
                )
        else:
            from .delta import DeltaStyleSource
            from .iceberg import IcebergStyleSource

            self._providers = [
                DefaultFileBasedSource(session),
                DeltaStyleSource(),
                IcebergStyleSource(),
            ]

    def _run(self, fn: Callable[[FileBasedSourceProvider], Optional[object]], what: str):
        answers = [(p, r) for p in self._providers if (r := fn(p)) is not None]
        if not answers:
            return None
        if len(answers) > 1:
            raise HyperspaceError(
                f"Multiple source providers answered {what}: "
                f"{[type(p).__name__ for p, _ in answers]}"
            )
        return answers[0][1]

    def is_supported_relation(self, node: LogicalPlan) -> bool:
        return bool(self._run(lambda p: p.is_supported_relation(node), "is_supported"))

    def get_relation(self, node: LogicalPlan) -> Optional[FileBasedRelation]:
        return self._run(lambda p: p.get_relation(self.session, node), "get_relation")

    def reload_relation(self, metadata: Relation):
        df = self._run(lambda p: p.reload_relation(self.session, metadata), "reload")
        if df is None:
            raise HyperspaceError(
                f"No source provider can reload format {metadata.file_format!r}"
            )
        return df
