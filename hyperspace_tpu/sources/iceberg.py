"""Manifest/snapshot-id source — the Iceberg-shaped provider.

Reference parity: index/sources/iceberg/IcebergRelation.scala:37-260 — a
table addressed through metadata files and manifests, identified by random
snapshot ids with parent ancestry (NOT sequential versions), signed by
snapshot id, and file-listed by walking the current snapshot's manifest
list. This is deliberately a second, structurally different metadata model
from sources/delta.py's sequential version log, proving the provider plug
point with two real implementations:

    table/
      part-<uuid>.parquet              (immutable data files)
      metadata/
        v<N>.metadata.json             (schema, snapshots, current-snapshot-id)
        snap-<snapshot-id>.json        (manifest list)
        manifest-<uuid>.json           (data-file entries)

Time travel addresses snapshots by id or timestamp, and index-version
matching walks the snapshot *ancestry chain* (parent ids) rather than
numeric order — snapshot ids are random longs, so ordering only exists
through lineage.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import TYPE_CHECKING, Optional

from .interfaces import FileBasedRelation, FileBasedSourceProvider
from ..columnar import io as cio
from ..columnar.table import Schema
from ..exceptions import HyperspaceError
from ..meta.entry import FileIdTracker, FileInfo, Relation
from ..plan.nodes import FileScan, LogicalPlan

if TYPE_CHECKING:
    from ..session import HyperspaceSession

METADATA_DIR = "metadata"
ICEBERG_FORMAT = "iceberg-parquet"
# Index property key recording "index log version -> snapshot id" history.
SNAPSHOT_ID_HISTORY_PROPERTY = "icebergSnapshotIdHistory"
OPT_SNAPSHOT_ID = "icebergSnapshotId"
OPT_TABLE_PATH = "icebergTablePath"


def _new_snapshot_id() -> int:
    return uuid.uuid4().int & ((1 << 63) - 1)


class IcebergStyleTable:
    """A table versioned by snapshots: metadata files point at manifest
    lists, manifest lists at manifests, manifests at data files."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self.meta_dir = os.path.join(self.path, METADATA_DIR)

    # --- metadata reads --------------------------------------------------
    def _metadata_versions(self) -> list[int]:
        if not os.path.isdir(self.meta_dir):
            return []
        out = []
        for n in os.listdir(self.meta_dir):
            if n.startswith("v") and n.endswith(".metadata.json"):
                seg = n[1:-len(".metadata.json")]
                if seg.isdigit():  # foreign/temp files must not break reads
                    out.append(int(seg))
        return sorted(out)

    def _load_metadata(self) -> Optional[dict]:
        vs = self._metadata_versions()
        if not vs:
            return None
        with open(os.path.join(self.meta_dir, f"v{vs[-1]}.metadata.json")) as f:
            return json.load(f)

    def current_snapshot_id(self) -> Optional[int]:
        md = self._load_metadata()
        return None if md is None else md.get("current-snapshot-id")

    def snapshots(self) -> list[dict]:
        md = self._load_metadata()
        return [] if md is None else list(md.get("snapshots", []))

    def _snapshot(self, snapshot_id: int) -> dict:
        for s in self.snapshots():
            if s["snapshot-id"] == snapshot_id:
                return s
        raise HyperspaceError(
            f"Snapshot {snapshot_id} not found at {self.path}"
        )

    def parent_of(self, snapshot_id: int) -> Optional[int]:
        return self._snapshot(snapshot_id).get("parent-snapshot-id")

    def _manifests(self, snapshot_id: int) -> list[str]:
        s = self._snapshot(snapshot_id)
        with open(os.path.join(self.meta_dir, s["manifest-list"])) as f:
            return list(json.load(f)["manifests"])

    def data_files(self, snapshot_id: int) -> list[dict]:
        entries: list[dict] = []
        for m in self._manifests(snapshot_id):
            with open(os.path.join(self.meta_dir, m)) as f:
                entries.extend(json.load(f)["entries"])
        return entries

    # --- commits ---------------------------------------------------------
    def _write_manifest(self, entries: list[dict]) -> str:
        name = f"manifest-{uuid.uuid4().hex}.json"
        with open(os.path.join(self.meta_dir, name), "w") as f:
            json.dump({"entries": entries}, f)
        return name

    def _commit_snapshot(self, manifests: list[str], schema_list: list[dict]) -> int:
        md = self._load_metadata() or {
            "format-version": 1,
            "table-uuid": uuid.uuid4().hex,
            "snapshots": [],
            "current-snapshot-id": None,
        }
        sid = _new_snapshot_id()
        list_name = f"snap-{sid}.json"
        with open(os.path.join(self.meta_dir, list_name), "w") as f:
            json.dump({"manifests": manifests}, f)
        md["snapshots"] = md.get("snapshots", []) + [
            {
                "snapshot-id": sid,
                "parent-snapshot-id": md.get("current-snapshot-id"),
                "timestamp-ms": int(time.time() * 1000),
                "manifest-list": list_name,
                # schema travels with the snapshot (real Iceberg's
                # schema-id-per-snapshot): time travel must not read old
                # data files through the newest schema
                "schema": schema_list,
            }
        ]
        md["current-snapshot-id"] = sid
        md["schema"] = schema_list
        vs = self._metadata_versions()
        nxt = (vs[-1] + 1) if vs else 1
        with open(os.path.join(self.meta_dir, f"v{nxt}.metadata.json"), "w") as f:
            json.dump(md, f)
        return sid

    def commit(self, batch, mode: str = "append") -> int:
        """Write a data file and a new snapshot; returns its snapshot id.
        append: previous manifests carry over; overwrite: only the new one."""
        os.makedirs(self.meta_dir, exist_ok=True)
        fname = f"part-{uuid.uuid4().hex}.parquet"
        fpath = os.path.join(self.path, fname)
        cio.write_parquet(batch, fpath)
        entry = {
            "path": fname,
            "file_size": os.path.getsize(fpath),
            "record_count": batch.num_rows,
        }
        manifests = [self._write_manifest([entry])]
        cur = self.current_snapshot_id()
        if mode == "append" and cur is not None:
            manifests = self._manifests(cur) + manifests
        return self._commit_snapshot(manifests, [f.to_dict() for f in batch.schema])

    def delete_files(self, file_names: list[str]) -> int:
        """New snapshot without the named data files: touched manifests are
        rewritten, untouched manifests carry over as-is."""
        cur = self.current_snapshot_id()
        if cur is None:
            raise HyperspaceError(f"No snapshots at {self.path}")
        drop = set(file_names)
        manifests_out: list[str] = []
        for m in self._manifests(cur):
            with open(os.path.join(self.meta_dir, m)) as f:
                entries = json.load(f)["entries"]
            kept = [e for e in entries if e["path"] not in drop]
            if len(kept) == len(entries):
                manifests_out.append(m)
            elif kept:
                manifests_out.append(self._write_manifest(kept))
        md = self._load_metadata()
        return self._commit_snapshot(manifests_out, md.get("schema", []))

    # --- reads -----------------------------------------------------------
    def snapshot_as_of(self, timestamp_ms: int) -> Optional[int]:
        """Latest snapshot at or before the timestamp (time travel by time)."""
        best = None
        for s in self.snapshots():
            if s["timestamp-ms"] <= timestamp_ms and (
                best is None or s["timestamp-ms"] > best["timestamp-ms"]
            ):
                best = s
        return None if best is None else best["snapshot-id"]

    def scan(
        self,
        session,
        snapshot_id: int | None = None,
        as_of_ms: int | None = None,
    ):
        """DataFrame over a snapshot (current by default) — the analogue of
        spark.read.option('snapshot-id', ...) on an Iceberg table."""
        from ..plan.dataframe import DataFrame

        if snapshot_id is None and as_of_ms is not None:
            snapshot_id = self.snapshot_as_of(as_of_ms)
        if snapshot_id is None:
            snapshot_id = self.current_snapshot_id()
        if snapshot_id is None:
            raise HyperspaceError(f"No snapshots at {self.path}")
        md = self._load_metadata()
        snap = self._snapshot(snapshot_id)
        schema_list = snap.get("schema") or md["schema"]
        files = [
            FileInfo.from_path(os.path.join(self.path, e["path"]))
            for e in self.data_files(snapshot_id)
        ]
        scan = FileScan(
            [self.path],
            "parquet",
            Schema.from_list(schema_list),
            files,
            options={
                OPT_SNAPSHOT_ID: str(snapshot_id),
                OPT_TABLE_PATH: self.path,
                "format": ICEBERG_FORMAT,
            },
        )
        return DataFrame(session, scan)


class IcebergStyleSource(FileBasedSourceProvider):
    """Provider for IcebergStyleTable scans; the serialized relation format
    is ICEBERG_FORMAT so reloads route back here (mirrors the reference's
    per-source builders, IcebergRelation.scala:37-260)."""

    def _supported(self, node: LogicalPlan) -> bool:
        return (
            isinstance(node, FileScan)
            and node.options.get("format") == ICEBERG_FORMAT
            and node.index_info is None
        )

    def is_supported_relation(self, node: LogicalPlan) -> Optional[bool]:
        return True if self._supported(node) else None

    def get_relation(self, session, node: LogicalPlan) -> Optional[FileBasedRelation]:
        if not self._supported(node):
            return None
        return IcebergRelation(session, node)

    def reload_relation(self, session, metadata: Relation):
        if metadata.file_format != ICEBERG_FORMAT:
            return None
        table = IcebergStyleTable(metadata.options[OPT_TABLE_PATH])
        return table.scan(session)  # current snapshot


class IcebergRelation(FileBasedRelation):
    @property
    def snapshot_id(self) -> int:
        return int(self.scan.options[OPT_SNAPSHOT_ID])

    @property
    def file_format(self) -> str:
        return ICEBERG_FORMAT

    def create_relation_metadata(self, file_id_tracker: FileIdTracker) -> Relation:
        rel = super().create_relation_metadata(file_id_tracker)
        return Relation(
            rel.root_paths, rel.content, rel.schema, ICEBERG_FORMAT, rel.options
        )

    def record_version_history(self, properties: dict[str, str], log_version: int) -> None:
        hist = properties.get(SNAPSHOT_ID_HISTORY_PROPERTY, "")
        parts = [p for p in hist.split(",") if p]
        parts.append(f"{log_version}:{self.snapshot_id}")
        properties[SNAPSHOT_ID_HISTORY_PROPERTY] = ",".join(parts)


def parse_snapshot_history(properties: dict[str, str]) -> list[tuple[int, int]]:
    """[(log_version, snapshot_id)]; malformed entries are skipped."""
    out = []
    for p in properties.get(SNAPSHOT_ID_HISTORY_PROPERTY, "").split(","):
        if ":" not in p:
            continue
        a, _, b = p.partition(":")
        try:
            out.append((int(a), int(b)))
        except ValueError:
            continue
    return out


def closest_index_version_by_ancestry(
    table: IcebergStyleTable, properties: dict[str, str], queried_snapshot_id: int
) -> Optional[int]:
    """Walk the queried snapshot's ancestry (parent ids) and return the index
    log version recorded against the first ancestor found. Snapshot ids are
    random longs, so 'closest' only exists through lineage — unlike the
    Delta-style provider's numeric ordering."""
    recorded = {}
    for log_version, sid in parse_snapshot_history(properties):
        recorded[sid] = log_version  # later entries win (newer index builds)
    sid: Optional[int] = queried_snapshot_id
    seen = set()
    while sid is not None and sid not in seen:
        seen.add(sid)
        if sid in recorded:
            return recorded[sid]
        try:
            sid = table.parent_of(sid)
        except HyperspaceError:
            return None
    return None
