"""Snapshot-versioned source — the Delta-Lake-style provider.

Reference parity: index/sources/delta/ — relations backed by a transaction
log of table snapshots, with a version-aware signature and *index-version
time travel*: when a query reads an old snapshot, the rules pick the index
log version whose recorded table version best matches
(DeltaLakeRelation.closestIndex:179-244, version history kept in index
properties DELTA_VERSION_HISTORY_PROPERTY, DeltaLakeRelationMetadata.scala:27-70).

There is no Delta Lake here; the equivalent capability is provided by our own
minimal snapshot format: a table directory with `_snapshots/<v>.json`, each
listing the parquet data files that make up that version. SnapshotTable is
both the writer users call and the relation the provider resolves.
"""

from __future__ import annotations

import json
import os
from typing import TYPE_CHECKING, Optional

from .interfaces import FileBasedRelation, FileBasedSourceProvider
from ..columnar import io as cio
from ..columnar.table import Schema
from ..exceptions import HyperspaceError
from ..meta.entry import Content, FileIdTracker, FileInfo, Relation
from ..plan.nodes import FileScan, LogicalPlan

if TYPE_CHECKING:
    from ..session import HyperspaceSession

SNAPSHOT_DIR = "_snapshots"
SNAPSHOT_FORMAT = "snapshot-parquet"
# Index property key recording "index log version -> table version" history
# (ref: DeltaLakeConstants.DELTA_VERSION_HISTORY_PROPERTY).
VERSION_HISTORY_PROPERTY = "snapshotVersionHistory"
# FileScan option carrying the snapshot version of the scan.
OPT_SNAPSHOT_VERSION = "snapshotVersion"
OPT_TABLE_PATH = "snapshotTablePath"


class SnapshotTable:
    """A versioned table: immutable parquet files + JSON snapshot manifests."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self.snap_dir = os.path.join(self.path, SNAPSHOT_DIR)

    # --- write path ---
    def _next_version(self) -> int:
        v = self.latest_version()
        return 0 if v is None else v + 1

    def commit(self, batch, mode: str = "append") -> int:
        """Write a new snapshot version; `mode` is append (new files added to
        previous snapshot) or overwrite (snapshot = just the new files)."""
        os.makedirs(self.snap_dir, exist_ok=True)
        version = self._next_version()
        fname = f"part-{version:05d}.parquet"
        fpath = os.path.join(self.path, fname)
        cio.write_parquet(batch, fpath)
        files = [fname]
        if mode == "append" and version > 0:
            files = self.snapshot_files(version - 1) + files
        manifest = {
            "version": version,
            "files": files,
            "schema": [f.to_dict() for f in batch.schema],
        }
        with open(os.path.join(self.snap_dir, f"{version}.json"), "w") as f:
            json.dump(manifest, f)
        return version

    def delete_files(self, file_names: list[str]) -> int:
        """New snapshot version without the named files (logical delete)."""
        version = self._next_version()
        prev = self.snapshot_files(version - 1)
        files = [f for f in prev if f not in set(file_names)]
        manifest_prev = self._manifest(version - 1)
        manifest = {"version": version, "files": files, "schema": manifest_prev["schema"]}
        os.makedirs(self.snap_dir, exist_ok=True)
        with open(os.path.join(self.snap_dir, f"{version}.json"), "w") as f:
            json.dump(manifest, f)
        return version

    # --- read path ---
    def latest_version(self) -> Optional[int]:
        if not os.path.isdir(self.snap_dir):
            return None
        vs = [int(n[:-5]) for n in os.listdir(self.snap_dir) if n.endswith(".json")]
        return max(vs) if vs else None

    def _manifest(self, version: int) -> dict:
        p = os.path.join(self.snap_dir, f"{version}.json")
        if not os.path.exists(p):
            raise HyperspaceError(f"Snapshot version {version} not found at {self.path}")
        with open(p) as f:
            return json.load(f)

    def snapshot_files(self, version: int) -> list[str]:
        return list(self._manifest(version)["files"])

    def scan(self, session, version: int | None = None) -> "object":
        """DataFrame over a snapshot (latest by default) — the analogue of
        spark.read.format('delta').option('versionAsOf', v)."""
        from ..plan.dataframe import DataFrame

        v = self.latest_version() if version is None else version
        if v is None:
            raise HyperspaceError(f"No snapshots at {self.path}")
        m = self._manifest(v)
        files = [FileInfo.from_path(os.path.join(self.path, fn)) for fn in m["files"]]
        scan = FileScan(
            [self.path],
            "parquet",
            Schema.from_list(m["schema"]),
            files,
            options={
                OPT_SNAPSHOT_VERSION: str(v),
                OPT_TABLE_PATH: self.path,
                "format": SNAPSHOT_FORMAT,
            },
        )
        return DataFrame(session, scan)


class DeltaStyleSource(FileBasedSourceProvider):
    """Provider for SnapshotTable scans. The relation's serialized format is
    SNAPSHOT_FORMAT so reloads route back here (never to the default
    provider, which excludes it the way the reference excludes 'delta')."""

    def _supported(self, node: LogicalPlan) -> bool:
        return (
            isinstance(node, FileScan)
            and node.options.get("format") == SNAPSHOT_FORMAT
            and node.index_info is None
        )

    def is_supported_relation(self, node: LogicalPlan) -> Optional[bool]:
        return True if self._supported(node) else None

    def get_relation(self, session, node: LogicalPlan) -> Optional[FileBasedRelation]:
        if not self._supported(node):
            return None
        return SnapshotRelation(session, node)

    def reload_relation(self, session, metadata: Relation):
        if metadata.file_format != SNAPSHOT_FORMAT:
            return None
        table = SnapshotTable(metadata.options[OPT_TABLE_PATH])
        return table.scan(session)  # latest snapshot


class SnapshotRelation(FileBasedRelation):
    @property
    def snapshot_version(self) -> int:
        return int(self.scan.options[OPT_SNAPSHOT_VERSION])

    def record_version_history(
        self, properties: dict[str, str], log_version: int
    ) -> None:
        update_version_history(properties, self.snapshot_version, log_version)

    @property
    def file_format(self) -> str:
        return SNAPSHOT_FORMAT

    def create_relation_metadata(self, file_id_tracker: FileIdTracker) -> Relation:
        rel = super().create_relation_metadata(file_id_tracker)
        return Relation(
            rel.root_paths, rel.content, rel.schema, SNAPSHOT_FORMAT, rel.options
        )


def update_version_history(
    properties: dict[str, str], snapshot_version: int, log_version: int
) -> None:
    """Record `index log version -> table snapshot version` for closest-index
    matching (ref: DeltaLakeRelationMetadata.scala:27-70). Pairs are explicit
    ("logv:tablev") — positional alignment with ACTIVE entries breaks the
    moment delete/restore/optimize insert extra ACTIVE log ids."""
    hist = properties.get(VERSION_HISTORY_PROPERTY, "")
    parts = [p for p in hist.split(",") if p]
    parts.append(f"{log_version}:{snapshot_version}")
    properties[VERSION_HISTORY_PROPERTY] = ",".join(parts)


def parse_version_history(properties: dict[str, str]) -> list[tuple[int, int]]:
    """[(log_version, table_version)] pairs; malformed entries are skipped."""
    out = []
    for p in properties.get(VERSION_HISTORY_PROPERTY, "").split(","):
        if ":" not in p:
            continue
        a, _, b = p.partition(":")
        try:
            out.append((int(a), int(b)))
        except ValueError:
            continue
    return out


def closest_index_version(
    properties: dict[str, str], queried_version: int
) -> Optional[int]:
    """The index log version whose recorded table version is the best
    (largest <= queried) match (ref: DeltaLakeRelation.closestIndex:179-244)."""
    best = None
    for log_version, table_version in parse_version_history(properties):
        if table_version <= queried_version and (
            best is None or table_version > best[1]
        ):
            best = (log_version, table_version)
    return best[0] if best else None
