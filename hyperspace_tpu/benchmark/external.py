"""External-engine TPC-H measurements (pandas).

BASELINE.md's north-star denominator is a 32-core Spark-CPU cluster, which
does not exist in this image; pandas is the stand-in external engine so
`vs_baseline` has an honest, independently-implemented denominator instead
of this engine's own raw path. Each query reads the same parquet inputs
end-to-end (IO included, like the engine measurements).
"""

from __future__ import annotations

import os


def _li(root):
    import pandas as pd

    return pd.read_parquet(os.path.join(root, "lineitem"))


def pandas_q1(root):
    df = _li(root)
    df = df[df["l_shipdate"] <= 10470]
    g = df.assign(
        disc_price=df["l_extendedprice"] * (1.0 - df["l_discount"])
    ).groupby(["l_returnflag", "l_linestatus"], as_index=False)
    out = g.agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        avg_qty=("l_quantity", "mean"),
        count_order=("l_quantity", "size"),
    )
    return out.sort_values(["l_returnflag", "l_linestatus"])


def pandas_q3(root):
    import pandas as pd

    li = _li(root)[["l_orderkey", "l_extendedprice", "l_discount"]]
    od = pd.read_parquet(os.path.join(root, "orders"))[
        ["o_orderkey", "o_orderdate"]
    ]
    od = od[od["o_orderdate"] < 9500]
    j = li.merge(od, left_on="l_orderkey", right_on="o_orderkey")
    j["revenue"] = j["l_extendedprice"] * (1.0 - j["l_discount"])
    g = j.groupby(["l_orderkey", "o_orderdate"], as_index=False)["revenue"].sum()
    return g.nlargest(10, "revenue")


def pandas_q6(root):
    df = _li(root)
    m = (
        (df["l_shipdate"] >= 8766)
        & (df["l_shipdate"] < 9131)
        & (df["l_discount"] >= 0.05)
        & (df["l_discount"] <= 0.07)
        & (df["l_quantity"] < 24)
    )
    sub = df[m]
    return float((sub["l_extendedprice"] * sub["l_discount"]).sum())


def pandas_q17(root):
    import pandas as pd

    li = _li(root)[["l_partkey", "l_quantity", "l_extendedprice"]]
    pt = pd.read_parquet(os.path.join(root, "part"))
    pt = pt[pt["p_brand"] == "Brand#3"][["p_partkey"]]
    avg_qty = (
        li.groupby("l_partkey", as_index=False)["l_quantity"]
        .mean()
        .rename(columns={"l_partkey": "ap_partkey", "l_quantity": "avg_qty"})
    )
    j = li.merge(pt, left_on="l_partkey", right_on="p_partkey")
    j = j.merge(avg_qty, left_on="l_partkey", right_on="ap_partkey")
    j = j[j["l_quantity"] < 0.2 * j["avg_qty"]]
    return float(j["l_extendedprice"].sum() / 7.0)


def pandas_q10(root):
    import pandas as pd

    li = _li(root)
    li = li[li["l_returnflag"] == "R"][
        ["l_orderkey", "l_extendedprice", "l_discount"]
    ]
    od = pd.read_parquet(os.path.join(root, "orders"))[
        ["o_orderkey", "o_custkey", "o_orderdate"]
    ]
    od = od[(od["o_orderdate"] >= 8766) & (od["o_orderdate"] < 8856)]
    j = li.merge(od, left_on="l_orderkey", right_on="o_orderkey")
    j["revenue"] = j["l_extendedprice"] * (1.0 - j["l_discount"])
    g = j.groupby("o_custkey", as_index=False)["revenue"].sum()
    return g.sort_values(
        ["revenue", "o_custkey"], ascending=[False, True]
    ).head(20)


def pandas_q18(root):
    import pandas as pd

    li = _li(root)[["l_orderkey", "l_quantity"]]
    big = li.groupby("l_orderkey", as_index=False)["l_quantity"].sum()
    big = big[big["l_quantity"] > 300].rename(columns={"l_quantity": "sum_qty"})
    od = pd.read_parquet(os.path.join(root, "orders"))[
        ["o_orderkey", "o_custkey", "o_orderdate"]
    ]
    j = big.merge(od, left_on="l_orderkey", right_on="o_orderkey")
    return j.sort_values(
        ["sum_qty", "l_orderkey"], ascending=[False, True]
    ).head(100)


PANDAS_TPCH = {
    "q1": pandas_q1,
    "q3": pandas_q3,
    "q6": pandas_q6,
    "q10": pandas_q10,
    "q17": pandas_q17,
    "q18": pandas_q18,
}
