from .tpch import TPCH_QUERIES, generate_tpch, tpch_indexes

__all__ = ["TPCH_QUERIES", "generate_tpch", "tpch_indexes"]
