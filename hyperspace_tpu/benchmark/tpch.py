"""TPC-H benchmark harness: data generation + the BASELINE.md query set.

The reference's analogue is its goldstandard TPC-DS infrastructure
(goldstandard/TPCDSBase.scala schema + PlanStabilitySuite) plus the driver's
BASELINE.json configs. This module generates scaled TPC-H-shaped tables
(lineitem / orders / part), defines Q1/Q3/Q6/Q17 on the DataFrame frontend,
and declares the index set each query is accelerated by.

Scale: `rows_lineitem` drives everything (SF1 ~ 6M lineitem rows). Dates are
int32 days since epoch; keys fit int32 so device paths stay 32-bit.
"""

from __future__ import annotations

import os

import numpy as np

from ..columnar import io as cio
from ..plan.expr import Avg, Count, Sum, col, lit


def generate_tpch(root: str, rows_lineitem: int = 600_000, seed: int = 0) -> dict:
    """Write lineitem/orders/part parquet dirs under `root`; returns sizes."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(seed)
    n_orders = max(1, rows_lineitem // 4)
    n_parts = max(1, rows_lineitem // 30)

    sizes = {}
    li_dir = os.path.join(root, "lineitem")
    os.makedirs(li_dir, exist_ok=True)
    n_files = max(1, rows_lineitem // 500_000)
    per = rows_lineitem // n_files
    total = 0
    for i in range(n_files):
        t = pa.table(
            {
                "l_orderkey": rng.integers(0, n_orders, per),
                "l_partkey": rng.integers(0, n_parts, per),
                "l_suppkey": rng.integers(0, max(1, n_parts // 4), per),
                "l_quantity": rng.integers(1, 51, per).astype(np.float64),
                "l_extendedprice": rng.uniform(900, 105_000, per),
                "l_discount": np.round(rng.uniform(0.0, 0.1, per), 2),
                "l_tax": np.round(rng.uniform(0.0, 0.08, per), 2),
                "l_returnflag": rng.choice(["A", "N", "R"], per),
                "l_linestatus": rng.choice(["O", "F"], per),
                "l_shipdate": rng.integers(8035, 10590, per).astype(np.int32),
            }
        )
        f = os.path.join(li_dir, f"part-{i:04d}.parquet")
        pq.write_table(t, f)
        total += os.path.getsize(f)
    sizes["lineitem"] = total

    od_dir = os.path.join(root, "orders")
    os.makedirs(od_dir, exist_ok=True)
    t = pa.table(
        {
            "o_orderkey": np.arange(n_orders),
            "o_custkey": rng.integers(0, max(1, n_orders // 10), n_orders),
            "o_orderdate": rng.integers(8035, 10590, n_orders).astype(np.int32),
            "o_shippriority": rng.integers(0, 5, n_orders),
        }
    )
    f = os.path.join(od_dir, "part-0.parquet")
    pq.write_table(t, f)
    sizes["orders"] = os.path.getsize(f)

    pt_dir = os.path.join(root, "part")
    os.makedirs(pt_dir, exist_ok=True)
    t = pa.table(
        {
            "p_partkey": np.arange(n_parts),
            "p_brand": rng.choice([f"Brand#{i}" for i in range(1, 6)], n_parts),
            "p_container": rng.choice(["JUMBO PKG", "MED BOX", "SM CASE"], n_parts),
        }
    )
    f = os.path.join(pt_dir, "part-0.parquet")
    pq.write_table(t, f)
    sizes["part"] = os.path.getsize(f)
    return sizes


def tpch_indexes(session, hs, root: str) -> None:
    """The BASELINE.md index set: z-order on the Q6 range column, covering
    join indexes on the Q3/Q17 keys, and the config-3 MinMax data-skipping
    sketch over the lineitem range column (uniformly distributed bench data
    gives it nothing to skip — it participates honestly as a candidate)."""
    from ..models.covering import CoveringIndexConfig
    from ..models.dataskipping import DataSkippingIndexConfig, MinMaxSketch
    from ..models.zorder import ZOrderCoveringIndexConfig

    li = session.read.parquet(os.path.join(root, "lineitem"))
    od = session.read.parquet(os.path.join(root, "orders"))
    pt = session.read.parquet(os.path.join(root, "part"))
    hs.create_index(
        li,
        ZOrderCoveringIndexConfig(
            "li_shipdate_z",
            ["l_shipdate"],
            ["l_extendedprice", "l_discount", "l_quantity"],
        ),
    )
    hs.create_index(
        li,
        CoveringIndexConfig(
            "li_orderkey",
            ["l_orderkey"],
            # l_returnflag serves Q10's pre-join filter, l_quantity Q18's
            # per-order volume aggregate, both over the same bucketed slice
            ["l_extendedprice", "l_discount", "l_returnflag", "l_quantity"],
        ),
    )
    hs.create_index(
        li,
        CoveringIndexConfig(
            "li_partkey", ["l_partkey"], ["l_quantity", "l_extendedprice"]
        ),
    )
    # Q1 (BASELINE config 3's target query): bucketed on the GROUP BY keys,
    # so AggregateIndexRule turns the pricing summary into per-bucket
    # aggregation over the covering slice
    hs.create_index(
        li,
        CoveringIndexConfig(
            "li_flagstatus",
            ["l_returnflag", "l_linestatus"],
            ["l_shipdate", "l_quantity", "l_extendedprice", "l_discount"],
        ),
    )
    hs.create_index(
        od,
        CoveringIndexConfig(
            "od_orderkey", ["o_orderkey"], ["o_orderdate", "o_custkey"]
        ),
    )
    hs.create_index(pt, CoveringIndexConfig("pt_partkey", ["p_partkey"], ["p_brand"]))
    hs.create_index(
        li, DataSkippingIndexConfig("li_shipdate_mm", [MinMaxSketch("l_shipdate")])
    )


# ---------------------------------------------------------------------------
# queries (simplified TPC-H shapes on the frontend's operator set)
# ---------------------------------------------------------------------------

def q1(session, root: str):
    """Pricing summary report: grouped aggregates over a shipdate bound."""
    li = session.read.parquet(os.path.join(root, "lineitem"))
    return (
        li.filter(col("l_shipdate") <= 10470)
        .select(
            "l_returnflag",
            "l_linestatus",
            "l_quantity",
            "l_extendedprice",
            "l_discount",
        )
        .group_by("l_returnflag", "l_linestatus")
        .agg(
            Sum(col("l_quantity")).alias("sum_qty"),
            Sum(col("l_extendedprice")).alias("sum_base_price"),
            Sum(col("l_extendedprice") * (lit(1.0) - col("l_discount"))).alias("sum_disc_price"),
            Avg(col("l_quantity")).alias("avg_qty"),
            Count(lit(1)).alias("count_order"),
        )
        .sort("l_returnflag", "l_linestatus")
    )


def q3(session, root: str):
    """Shipping priority: join lineitem to orders, revenue per order."""
    li = session.read.parquet(os.path.join(root, "lineitem"))
    od = session.read.parquet(os.path.join(root, "orders"))
    return (
        li.select("l_orderkey", "l_extendedprice", "l_discount")
        .join(
            od.select("o_orderkey", "o_orderdate"),
            col("l_orderkey") == col("o_orderkey"),
        )
        .filter(col("o_orderdate") < 9500)
        .group_by("l_orderkey", "o_orderdate")
        .agg(Sum(col("l_extendedprice") * (lit(1.0) - col("l_discount"))).alias("revenue"))
        .sort("revenue", ascending=False)
        .limit(10)
    )


def q6(session, root: str):
    """Forecasting revenue change: tight range filter + global aggregate."""
    li = session.read.parquet(os.path.join(root, "lineitem"))
    return (
        li.filter(
            (col("l_shipdate") >= 8766)
            & (col("l_shipdate") < 9131)
            & (col("l_discount") >= 0.05)
            & (col("l_discount") <= 0.07)
            & (col("l_quantity") < 24)
        )
        .select("l_shipdate", "l_extendedprice", "l_discount", "l_quantity")
        .agg(Sum(col("l_extendedprice") * col("l_discount")).alias("revenue"))
    )


def q17(session, root: str):
    """Small-quantity-order revenue: per-part average quantity joined back
    against lineitem; rows below 20% of their part's average contribute."""
    li = session.read.parquet(os.path.join(root, "lineitem"))
    pt = session.read.parquet(os.path.join(root, "part"))
    avg_qty = (
        li.select("l_partkey", "l_quantity")
        .group_by("l_partkey")
        .agg(Avg(col("l_quantity")).alias("avg_qty"))
        .select(col("l_partkey").alias("ap_partkey"), col("avg_qty"))
    )
    return (
        li.select("l_partkey", "l_quantity", "l_extendedprice")
        .join(
            pt.filter(col("p_brand") == "Brand#3").select("p_partkey"),
            col("l_partkey") == col("p_partkey"),
        )
        .join(avg_qty, col("l_partkey") == col("ap_partkey"))
        .filter(col("l_quantity") < lit(0.2) * col("avg_qty"))
        .agg(Sum(col("l_extendedprice")).alias("total"))
        .select((col("total") / lit(7.0)).alias("avg_yearly"))
    )


def q10(session, root: str):
    """Returned-item reporting: returned lineitems joined to orders in a
    quarter, revenue per customer, top 20. The join output feeds a grouped
    aggregate AND a sort+limit — the shape where the plain co-partitioned
    join and the device top-k both participate."""
    li = session.read.parquet(os.path.join(root, "lineitem"))
    od = session.read.parquet(os.path.join(root, "orders"))
    return (
        li.filter(col("l_returnflag") == "R")
        .select("l_orderkey", "l_extendedprice", "l_discount")
        .join(
            od.select("o_orderkey", "o_custkey", "o_orderdate"),
            col("l_orderkey") == col("o_orderkey"),
        )
        .filter((col("o_orderdate") >= 8766) & (col("o_orderdate") < 8856))
        .group_by("o_custkey")
        .agg(
            Sum(col("l_extendedprice") * (lit(1.0) - col("l_discount"))).alias(
                "revenue"
            )
        )
        # o_custkey breaks revenue near-ties so the top-20 cut is
        # deterministic across engines and execution orders
        .sort("revenue", "o_custkey", ascending=[False, True])
        .limit(20)
    )


def q18(session, root: str):
    """Large-volume customers: orders whose total quantity crosses the
    threshold (HAVING over a per-order aggregate), joined back to orders,
    largest first. Exercises aggregate-as-join-input plus a deterministic
    multi-key sort (quantity ties broken by order key)."""
    li = session.read.parquet(os.path.join(root, "lineitem"))
    od = session.read.parquet(os.path.join(root, "orders"))
    big = (
        li.select("l_orderkey", "l_quantity")
        .group_by("l_orderkey")
        .agg(Sum(col("l_quantity")).alias("sum_qty"))
        .filter(col("sum_qty") > 300)
    )
    return (
        big.join(
            od.select("o_orderkey", "o_custkey", "o_orderdate"),
            col("l_orderkey") == col("o_orderkey"),
        )
        .select("o_custkey", "l_orderkey", "o_orderdate", "sum_qty")
        .sort("sum_qty", "l_orderkey", ascending=[False, True])
        .limit(100)
    )


TPCH_QUERIES = {"q1": q1, "q3": q3, "q6": q6, "q10": q10, "q17": q17, "q18": q18}
