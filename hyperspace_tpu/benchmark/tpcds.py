"""TPC-DS goldstandard analogue.

The reference's plan-stability suite defines the full TPC-DS schema but
enables exactly one query, q1 (goldstandard/TPCDSBase.scala:41,
PlanStabilitySuite.scala:83-289). This module generates the q1-relevant
tables (store_returns, date_dim, store, customer) at a configurable scale
and defines the q1 CORE shape on this frontend: the customer_total_return
aggregation (store_returns joined to date_dim filtered to one year, grouped
by customer and store) and the above-average-returns filter against the
per-store mean — the subquery-free reduction of TPC-DS q1's CTE.
"""

from __future__ import annotations

import os

import numpy as np

from ..plan.expr import Avg, Sum, col


def generate_tpcds(root: str, rows_store_returns: int = 200_000, seed: int = 0) -> dict:
    """Write store_returns/date_dim/store/customer parquet dirs under root."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(seed)
    n_customers = max(1, rows_store_returns // 20)
    n_stores = 25
    n_dates = 365 * 3

    sizes = {}

    def write(name: str, table: "pa.Table", part: int = 0) -> None:
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        f = os.path.join(d, f"part-{part}.parquet")
        pq.write_table(table, f)
        sizes[name] = sizes.get(name, 0) + os.path.getsize(f)

    # store_returns spreads over files with file-local customer ranges
    # (realistic ingest clustering) so bloom/minmax skipping has files to
    # reject for a point key
    n_files = 8
    per = rows_store_returns // n_files
    cust_span = max(1, n_customers // n_files)
    for i in range(n_files):
        write(
            "store_returns",
            pa.table(
                {
                    "sr_returned_date_sk": rng.integers(0, n_dates, per),
                    "sr_customer_sk": rng.integers(
                        i * cust_span, (i + 1) * cust_span, per
                    ),
                    "sr_store_sk": rng.integers(0, n_stores, per),
                    "sr_return_amt": np.round(rng.uniform(1, 500, per), 2),
                }
            ),
            part=i,
        )
    write(
        "date_dim",
        pa.table(
            {
                "d_date_sk": np.arange(n_dates),
                "d_year": 1998 + (np.arange(n_dates) // 365),
            }
        ),
    )
    write(
        "store",
        pa.table(
            {
                "s_store_sk": np.arange(n_stores),
                "s_state": np.asarray(
                    rng.choice(["TN", "CA", "WA"], n_stores), dtype=object
                ),
            }
        ),
    )
    write(
        "customer",
        pa.table(
            {
                "c_customer_sk": np.arange(n_customers),
                "c_customer_id": np.asarray(
                    [f"AAAAAAAA{i:08d}" for i in range(n_customers)], dtype=object
                ),
            }
        ),
    )
    return sizes


def tpcds_indexes(session, hs, root: str) -> None:
    """q1's index set: covering join indexes on the store_returns date key
    and the date_dim key, plus bloom skipping on the high-cardinality
    customer key (BASELINE config 5's store_sales-keys shape)."""
    from ..models.covering import CoveringIndexConfig
    from ..models.dataskipping import BloomFilterSketch, DataSkippingIndexConfig

    sr = session.read.parquet(os.path.join(root, "store_returns"))
    dd = session.read.parquet(os.path.join(root, "date_dim"))
    hs.create_index(
        sr,
        CoveringIndexConfig(
            "sr_datekey",
            ["sr_returned_date_sk"],
            ["sr_customer_sk", "sr_store_sk", "sr_return_amt"],
        ),
    )
    hs.create_index(dd, CoveringIndexConfig("dd_datekey", ["d_date_sk"], ["d_year"]))
    hs.create_index(
        sr,
        DataSkippingIndexConfig(
            "sr_cust_bloom", [BloomFilterSketch("sr_customer_sk", 50_000, 0.01)]
        ),
    )


def q1_customer_total_return(session, root: str):
    """TPC-DS q1's CTE: per-(customer, store) return totals for one year."""
    sr = session.read.parquet(os.path.join(root, "store_returns"))
    dd = session.read.parquet(os.path.join(root, "date_dim"))
    return (
        sr.select("sr_returned_date_sk", "sr_customer_sk", "sr_store_sk", "sr_return_amt")
        .join(
            dd.select("d_date_sk", "d_year").filter(col("d_year") == 2000),
            col("sr_returned_date_sk") == col("d_date_sk"),
        )
        .group_by("sr_customer_sk", "sr_store_sk")
        .agg(Sum(col("sr_return_amt")).alias("ctr_total_return"))
    )


def q1_store_avg(session, root: str):
    """The correlated-subquery half, decorrelated: per-store mean of the
    customer totals (the threshold q1 compares against)."""
    return (
        q1_customer_total_return(session, root)
        .group_by("sr_store_sk")
        .agg(Avg(col("ctr_total_return")).alias("avg_return"))
    )
