"""hyperspace_tpu.cache — snapshot-keyed result caching for the serving plane.

- ``result_cache``: the process-wide, byte-bounded cross-query result
  store, keyed by (canonical plan fingerprint, pinned snapshot version) —
  exact invalidation, single-flight population, verify mode;
- ``view_maintenance``: incremental maintenance of cached aggregates over
  the ingest log — exactly-foldable fragments answer post-append queries
  as ``cached_result_at_vN ⊕ fold(delta runs)`` instead of recomputing,
  and background refresh re-anchors hot entries after version advances.

docs/performance.md ("Result cache & incremental views") has the key
structure, fold rules, and knobs.
"""

from __future__ import annotations

from .result_cache import (
    RESULT_CACHE,
    CachedResult,
    ResultCache,
    batch_nbytes,
    enabled,
    is_verify,
    result_cache_state_string,
    serve_collect,
)
from .view_maintenance import (
    FoldSpec,
    classify_plan,
    fold_results,
    maybe_refresh,
    refresh_idle,
    try_fold,
)

__all__ = [
    "RESULT_CACHE",
    "CachedResult",
    "FoldSpec",
    "ResultCache",
    "batch_nbytes",
    "classify_plan",
    "enabled",
    "fold_results",
    "is_verify",
    "maybe_refresh",
    "refresh_idle",
    "result_cache_state_string",
    "serve_collect",
    "try_fold",
]
