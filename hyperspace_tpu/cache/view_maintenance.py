"""Incremental view maintenance over the ingest log.

The fold discipline ("Partial Partial Aggregates", PAPERS.md) the PR-2
pipelined executor already applies WITHIN one query — count/min/max/int-sum
partials combine exactly across chunks — extends ACROSS snapshots: an
``hs.append`` publishes a new immutable version whose content is
``old ∪ delta``, so for an exactly-foldable fragment

    agg(files_vM) == agg(files_vN) ⊕ agg(files_vM − files_vN)

bit for bit (integer adds are associative; min/max are idempotent
semilattice ops; SQL NULL means "no qualifying rows", the fold identity).
This module owns the three pieces:

- :func:`classify_plan` — fold-eligibility of a whole optimized plan: the
  PR-2 fragment shape (global Aggregate ← [Project] ← [Filter] ← FileScan,
  exactly one scan) with every output a Count, a non-string Min/Max, or an
  integer-typed Sum. Anything else recomputes and re-caches on miss.
- :func:`try_fold` — given a cache miss and same-template candidates at
  older snapshots, pick one whose file set is a SUBSET of the new plan's,
  execute the fragment over only the delta files, and fold the two
  single-row results. Folding rides the same executor as any query (the
  delta scan streams, prunes, and dispatches normally), so the per-append
  cost is proportional to the batch, not the table.
- :func:`maybe_refresh` — the background half: a version advance (append
  commit, or compaction retiring delta runs) schedules one task per stale
  foldable entry on the shared IO pool; each task re-resolves the stored
  query template against the live source and re-runs it through the cache
  path, which folds when the advance was additive and recomputes when
  compaction rewrote the layout. Refresh work is charged to its own
  attribution-ledger record (label ``cache:refresh``), so the serving
  plane's conservation invariant keeps holding while views refresh.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from ..staticcheck.concurrency import TrackedLock, guarded_by

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class FoldSpec:
    """Per-output fold kinds of an exactly-foldable global aggregate."""

    names: tuple  # output column names, plan order
    kinds: tuple  # "count" | "sum" | "min" | "max" per name


def classify_plan(plan) -> Optional[FoldSpec]:
    """FoldSpec when ``plan`` is an exactly-foldable fragment, else None.
    Grouped aggregates are excluded deliberately: their output row order
    follows global first-occurrence, which an append can reorder — the
    exactness bar here is bit-identity, not value-identity."""
    from ..columnar.table import STRING
    from ..plan import expr as X
    from ..plan.executor import _unwrap_agg
    from ..plan.nodes import FileScan
    from ..plan.tpu_exec import _match_fragment

    frag = _match_fragment(plan)
    if frag is None or frag.agg.group_exprs:
        return None
    if sum(isinstance(n, FileScan) for n in plan.preorder()) != 1:
        return None
    schema = plan.schema
    names, kinds = [], []
    for e in frag.agg.agg_exprs:
        name, agg = _unwrap_agg(e)
        if isinstance(agg, X.Count):
            kinds.append("count")
        elif isinstance(agg, (X.Min, X.Max)):
            if schema.field(name).dtype == STRING:
                return None  # dictionary identity is not decomposition-stable
            kinds.append("min" if isinstance(agg, X.Min) else "max")
        elif isinstance(agg, X.Sum) and schema.field(name).dtype.startswith("int"):
            kinds.append("sum")
        else:
            return None  # float sums / avgs: not decomposition-invariant
        names.append(name)
    return FoldSpec(tuple(names), tuple(kinds))


def _is_null_scalar(col) -> bool:
    return col.validity is not None and not bool(col.validity[0])


def fold_results(old, delta, spec: FoldSpec):
    """Combine two single-row aggregate batches under ``spec``. SQL NULL
    (zero qualifying rows) is the identity of every non-count fold; when
    both sides are non-NULL their dtypes agree (both are the plan schema's
    dtype), which the helper asserts rather than trusts."""
    import numpy as np

    from ..columnar.table import Column, ColumnBatch
    from ..exceptions import HyperspaceError

    out = {}
    for name, kind in zip(spec.names, spec.kinds):
        a = old.column(name)
        b = delta.column(name)
        if kind == "count":
            out[name] = Column(
                (a.data.astype(np.int64) + b.data.astype(np.int64)), "int64"
            )
            continue
        if _is_null_scalar(a):
            out[name] = b
            continue
        if _is_null_scalar(b):
            out[name] = a
            continue
        if a.dtype != b.dtype:
            raise HyperspaceError(
                f"fold dtype drift on {name!r}: {a.dtype} vs {b.dtype}"
            )
        if kind == "sum":
            data = a.data + b.data
        elif kind == "min":
            data = np.minimum(a.data, b.data)
        else:
            data = np.maximum(a.data, b.data)
        out[name] = Column(data, a.dtype)
    return ColumnBatch(out)


def _delta_scan_files(candidate, plan):
    """Per-file delta (new − old) when the candidate's single scan is a
    strict-or-equal subset of the new plan's; None when the advance was
    not additive (compaction rewrote runs → recompute)."""
    from ..plan.nodes import FileScan

    scans = [n for n in plan.preorder() if isinstance(n, FileScan)]
    if len(scans) != 1 or len(candidate.scan_files) != 1:
        return None
    new_ids = {
        (f.name, f.size, f.modified_time): f for f in scans[0].files
    }
    old_ids = candidate.scan_files[0]
    if not old_ids <= set(new_ids):
        return None
    return scans[0], [new_ids[i] for i in sorted(set(new_ids) - old_ids)]


def _delta_rows(files) -> int:
    """Delta input rows from footer metadata (cached; diagnostics only)."""
    from ..columnar import io as cio

    try:
        return sum(cio.file_num_rows(f.name) for f in files)
    except Exception:
        return 0


def try_fold(session, plan, spec: FoldSpec, candidates):
    """(result, fold_depth) via the cheapest additive candidate, or None
    (caller recomputes). The delta fragment executes through the ordinary
    executor under a ``cache:fold`` span."""
    from ..plan.executor import execute_plan
    from ..telemetry import trace
    from ..telemetry.metrics import REGISTRY

    cap = max(1, _fold_depth_cap())
    for cand in candidates:
        if cand.fold_spec != spec or cand.fold_depth >= cap:
            continue
        located = _delta_scan_files(cand, plan)
        if located is None:
            continue
        scan, delta = located
        if not delta:
            # same bytes under a new entry id (e.g. a metadata-only
            # advance): the old result IS the new result
            return cand.result, cand.fold_depth
        with trace.span("cache:fold", delta_files=len(delta)):
            delta_plan = plan.transform_up(
                lambda n: n.copy(files=delta) if n is scan else n
            )
            delta_result = execute_plan(delta_plan, session)
            result = fold_results(cand.result, delta_result, spec)
        REGISTRY.counter("cache.result.folds").inc()
        REGISTRY.counter("cache.result.fold_rows").inc(_delta_rows(delta))
        return result, cand.fold_depth + 1
    return None


def _fold_depth_cap() -> int:
    from ..utils import env

    return env.env_int("HYPERSPACE_RESULT_CACHE_FOLD_DEPTH")


# ---------------------------------------------------------------------------
# background refresh (the ingest-log hook)
# ---------------------------------------------------------------------------

_REFRESH_LOCK = TrackedLock("cache.result_refresh")
_REFRESH_INFLIGHT: set = guarded_by(
    set(),  # abspath(index_path) strings with refresh tasks in flight
    _REFRESH_LOCK,
    name="cache.view_maintenance._REFRESH_INFLIGHT",
    note="one refresh wave per index at a time",
)


def refresh_idle() -> bool:
    """True when no background refresh wave is scheduled or running
    (gates drain on this before quiescent-state assertions)."""
    with _REFRESH_LOCK:
        return not _REFRESH_INFLIGHT


def maybe_refresh(session, index_name: str) -> int:
    """Schedule background refreshes of every stale foldable cache entry
    pinned to ``index_name`` (called after an append commit and after a
    background compaction cycle). Returns the number of entries scheduled;
    0 when the cache is off/empty or a wave is already in flight."""
    import os

    from .result_cache import RESULT_CACHE, enabled

    if not enabled():
        return 0
    from ..meta.path_resolver import PathResolver

    index_path = os.path.abspath(
        PathResolver(session.conf, session.warehouse_dir).get_index_path(
            index_name
        )
    )
    latest = _latest_entry_id(session, index_name)
    if latest is None:
        return 0
    stale = [
        e
        for e in RESULT_CACHE.entries_for_index(index_path)
        if e.fold_spec is not None
        and e.raw_plan is not None
        and any(
            s.index_path == index_path and s.entry_id < latest
            for s in e.snapshots
        )
    ]
    if not stale:
        return 0
    with _REFRESH_LOCK:
        if index_path in _REFRESH_INFLIGHT:
            return 0
        _REFRESH_INFLIGHT.add(index_path)
    from ..utils.workers import shared_io_pool

    shared_io_pool().submit(_refresh_wave, index_path, stale)
    return len(stale)


def _latest_entry_id(session, index_name: str) -> Optional[int]:
    from ..ingest import latest_stable_entry

    entry = latest_stable_entry(session, index_name)
    return None if entry is None else entry.id


def _refresh_wave(index_path: str, entries) -> None:
    """Run every scheduled refresh for one index, then clear the in-flight
    marker. One template refresh failing (session gone, index dropped
    underfoot) never blocks the others."""
    try:
        for entry in entries:
            try:
                _refresh_entry(entry)
            except BaseException:
                logger.warning(
                    "background result-cache refresh failed", exc_info=True
                )
    finally:
        with _REFRESH_LOCK:
            _REFRESH_INFLIGHT.discard(index_path)


def _refresh_entry(entry) -> None:
    """Re-run one cached query template against the live source: fresh
    file resolution (the stored raw plan's leaves predate the append),
    then an ordinary collect — which probes the cache, folds when additive,
    recomputes otherwise, and stores the result at the new snapshot. The
    work is charged to its own ledger record so per-query attribution
    stays conserved while refreshes interleave with serving traffic."""
    from ..plan.dataframe import DataFrame
    from ..serve.context import QueryContext
    from ..telemetry import attribution, trace
    from ..telemetry.attribution import LEDGER
    from ..telemetry.metrics import REGISTRY

    session = entry.session_ref() if entry.session_ref is not None else None
    if session is None:
        return
    ctx = QueryContext(label="cache:refresh")
    stats = LEDGER.begin(ctx)
    try:
        with trace.span("cache:refresh"), attribution.scope(stats):
            plan = _reresolve_sources(session, entry.raw_plan)
            DataFrame(session, plan).collect()
            # inside the scope: the refresh's own counters (this one
            # included) charge its ledger record — conservation holds
            REGISTRY.counter("cache.result.refreshes").inc()
    except BaseException as e:
        LEDGER.finish(stats, "failed", e)
        raise
    LEDGER.finish(stats, "done")


def _reresolve_sources(session, raw_plan):
    """The stored pre-optimization plan with every source FileScan's file
    list re-resolved from its roots (append_batch wrote new parts the old
    listing predates; the index rewrite only matches when the query's
    source file set equals what the latest entry signed)."""
    from ..plan.nodes import FileScan

    def fresh(n):
        if not isinstance(n, FileScan) or n.index_info is not None:
            return n
        reader = session.read
        reader._options = dict(n.options)
        return reader._load(n.fmt, n.root_paths).plan

    return raw_plan.transform_up(fresh)
