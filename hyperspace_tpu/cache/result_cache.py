"""Snapshot-keyed cross-query result cache.

Repeated dashboard-style queries are the dominant serving pattern the north
star targets, and before this module every repeat re-scanned, re-uploaded,
and re-dispatched from scratch. The cache closes that gap with EXACT (never
heuristic) invalidation, because both halves of its key already exist in
the engine:

    key = (plan structure fingerprint, plan files fingerprint,
           pinned snapshot ids)

- the structure/files fingerprints (plan/kernel_cache.py) canonicalize the
  whole optimized plan — node kinds, expression reprs, prune decisions,
  and the resolved (path, size, mtime) identity of every scanned file;
- the snapshot ids are the (index_path, entry_id) pins the query's
  pin scope collected at plan time (ingest/snapshots.py) — the immutable
  data versions PR 10 publishes atomically.

A hit therefore returns a stored result that is *guaranteed* bit-identical
to re-execution: same plan, same immutable bytes. Only plans that pinned at
least one index snapshot are cached (raw source scans have no version
authority; in-memory scans have no stable identity at all).

Incremental view maintenance (view_maintenance.py): an ``hs.append``
publishes a new snapshot whose content is old ∪ delta, so the exact key
misses — but entries over exactly-foldable fragments (global
count/min/max/int-sum aggregates, the PR-2 'partial' route discipline) are
not recomputed from scratch. The miss path finds a same-structure entry
whose file set is a subset of the new plan's, executes the fragment over
ONLY the delta files, and folds:  ``result_vM = result_vN ⊕ agg(delta)``.
Hot aggregates stay warm across sustained ingest at delta cost.

Modes (``HYPERSPACE_RESULT_CACHE``): ``0`` off (default — the repo's
correctness gates pin per-run execution effects, so caching is an explicit
serving-deployment opt-in), ``1`` on, ``verify`` on + every hit and every
fold recomputes from scratch and raises on any divergence (the
``HYPERSPACE_PRUNE=verify`` debug discipline).

Population is single-flight (the ``BoundedLRU.get_or_put`` semantics): N
concurrent identical queries compute once, the rest wait and read. A query
cancelled mid-compute (``QueryCancelledError`` is a BaseException) never
leaves the in-flight marker latched — a waiter wakes and takes over.

The store lock is a LEAF: factories (query execution!) and metric emission
always run outside it.
"""

from __future__ import annotations

import hashlib
import threading
import time
import weakref
from collections import OrderedDict
from typing import Optional

from ..exceptions import HyperspaceError
from ..staticcheck.concurrency import TrackedLock
from ..staticcheck.lifecycle import release_resource, tracked_resource
from ..utils import env


def _mode() -> str:
    return env.env_str("HYPERSPACE_RESULT_CACHE") or "0"


def enabled() -> bool:
    return _mode() != "0"


def is_verify() -> bool:
    return _mode() == "verify"


def _max_bytes() -> int:
    return int(env.env_float("HYPERSPACE_RESULT_CACHE_MB") * 1024 * 1024)


def _fold_depth_cap() -> int:
    return env.env_int("HYPERSPACE_RESULT_CACHE_FOLD_DEPTH")


def _digest(obj) -> str:
    return hashlib.blake2b(repr(obj).encode(), digest_size=16).hexdigest()


def batch_nbytes(batch) -> int:
    """Byte footprint of a ColumnBatch for the cache budget: data +
    validity + a conservative per-entry estimate for string vocabularies."""
    total = 0
    for c in batch.columns.values():
        total += c.data.nbytes
        if c.validity is not None:
            total += c.validity.nbytes
        if c.dictionary is not None:
            total += sum(len(s) for s in c.dictionary) + 8 * len(c.dictionary)
    return total


def _file_ids(scan) -> frozenset:
    return frozenset((f.name, f.size, f.modified_time) for f in scan.files)


class CachedResult:
    """One stored query result plus everything a later probe needs: the
    snapshots it is exact for, the per-scan file identity (the fold path's
    subset test), the fold spec when the fragment folds exactly, and the
    pre-optimization plan + owning session (weakly) so a background refresh
    can re-run the query template after a version advance."""

    __slots__ = (
        "key", "structure_key", "result", "nbytes", "snapshots",
        "scan_files", "fold_spec", "fold_depth", "raw_plan", "session_ref",
        "created_s", "hits",
    )

    def __init__(self, key, structure_key, result, snapshots, scan_files,
                 fold_spec, fold_depth, raw_plan, session):
        self.key = key
        self.structure_key = structure_key
        self.result = result
        self.nbytes = batch_nbytes(result)
        self.snapshots = tuple(snapshots)
        self.scan_files = tuple(scan_files)  # per-scan frozensets, preorder
        self.fold_spec = fold_spec
        self.fold_depth = fold_depth
        self.raw_plan = raw_plan
        self.session_ref = weakref.ref(session) if session is not None else None
        self.created_s = time.time()
        self.hits = 0


class ResultCache:
    """Byte-bounded LRU of CachedResults with a secondary structure index
    (template -> entries) for fold-candidate lookup, and single-flight
    population. Thread-safe; the lock is a leaf."""

    def __init__(self, name: str = "result"):
        self.name = name
        self._lock = TrackedLock(f"cache.{name}")
        self._d: OrderedDict = OrderedDict()  # key -> CachedResult
        self._by_structure: dict = {}  # structure_key -> OrderedDict[key, None]
        self._bytes = 0
        self._inflight: dict = {}
        self._inflight_lc: dict = {}  # key -> lifecycle-audit handle

    # --- metrics (outside the lock) ---------------------------------------

    def _count(self, event: str, n: int = 1) -> None:
        from ..telemetry.metrics import REGISTRY

        REGISTRY.counter(f"cache.{self.name}.{event}").inc(n)

    def _publish_bytes(self) -> None:
        from ..telemetry.metrics import REGISTRY

        with self._lock:
            b = self._bytes
        REGISTRY.gauge(f"cache.{self.name}.bytes").set(b)

    # --- store ------------------------------------------------------------

    def _unlink(self, entry: CachedResult) -> None:
        """Remove ``entry`` from both maps. Caller holds the lock."""
        self._d.pop(entry.key, None)
        self._bytes -= entry.nbytes
        sk = self._by_structure.get(entry.structure_key)
        if sk is not None:
            sk.pop(entry.key, None)
            if not sk:
                self._by_structure.pop(entry.structure_key, None)

    def put(self, entry: CachedResult) -> None:
        evicted = 0
        limit = _max_bytes()
        with self._lock:
            old = self._d.get(entry.key)
            if old is not None:
                self._unlink(old)
            self._d[entry.key] = entry
            self._d.move_to_end(entry.key)
            self._bytes += entry.nbytes
            self._by_structure.setdefault(entry.structure_key, OrderedDict())[
                entry.key
            ] = None
            while self._bytes > limit and len(self._d) > 1:
                _k, victim = next(iter(self._d.items()))
                self._unlink(victim)
                evicted += 1
            # a single over-budget entry is not worth keeping either
            if self._bytes > limit and entry.key in self._d:
                self._unlink(entry)
                evicted += 1
        if evicted:
            self._count("evictions", evicted)
        self._publish_bytes()

    def get(self, key) -> Optional[CachedResult]:
        with self._lock:
            entry = self._d.get(key)
            if entry is not None:
                self._d.move_to_end(key)
                entry.hits += 1
        return entry

    def get_or_compute(self, key, build):
        """(entry, hit: bool) — the ``BoundedLRU.get_or_put`` single-flight
        discipline: the first missing caller runs ``build()`` (which
        executes the query — always outside the lock) while the key is
        marked in flight; concurrent probes of the same key wait on its
        event and read the stored entry. A failed or CANCELLED build
        (QueryCancelledError is a BaseException) clears the marker and
        wakes the waiters so one of them takes over — an abandoned
        in-flight entry can never latch."""
        while True:
            with self._lock:
                entry = self._d.get(key)
                if entry is not None:
                    self._d.move_to_end(key)
                    entry.hits += 1
                    return entry, True
                event = self._inflight.get(key)
                if event is None:
                    event = self._inflight[key] = threading.Event()
                    self._inflight_lc[key] = tracked_resource(
                        "cache.inflight", self.name
                    )
                    building = True
                else:
                    building = False
            if not building:
                event.wait()
                continue
            try:
                entry = build()
            except BaseException:
                with self._lock:
                    self._inflight.pop(key, None)
                    lc = self._inflight_lc.pop(key, 0)
                event.set()
                release_resource(lc)
                raise
            try:
                if entry is not None:
                    self.put(entry)
            finally:
                with self._lock:
                    self._inflight.pop(key, None)
                    lc = self._inflight_lc.pop(key, 0)
                event.set()
                release_resource(lc)
            return entry, False

    # --- fold-candidate / maintenance reads -------------------------------

    def fold_candidates(self, structure_key) -> list:
        """Same-template entries, newest first (the most recently stored
        entry is closest to the new snapshot, so its delta is smallest)."""
        with self._lock:
            keys = list(self._by_structure.get(structure_key, ()))
            out = [self._d[k] for k in reversed(keys) if k in self._d]
        return out

    def entries_for_index(self, index_path: str) -> list:
        with self._lock:
            return [
                e
                for e in self._d.values()
                if any(s.index_path == index_path for s in e.snapshots)
            ]

    def invalidate_version(self, index_path: str, version: int) -> int:
        """Drop every entry pinned to (index_path, version) — called when
        vacuum physically retires the version. Exact keys already make such
        entries unreachable for direct hits; dropping them also removes
        them from the fold-candidate index and frees their bytes."""
        dropped = 0
        with self._lock:
            victims = [
                e
                for e in self._d.values()
                if any(
                    s.index_path == index_path and version in s.versions
                    for s in e.snapshots
                )
            ]
            for e in victims:
                self._unlink(e)
                dropped += 1
        if dropped:
            self._publish_bytes()
        return dropped

    # --- introspection / gates --------------------------------------------

    def check_consistency(self) -> bool:
        """Byte accounting + index coherence + no leaked in-flight markers
        (race/serve gates; call at quiescence)."""
        with self._lock:
            actual = sum(e.nbytes for e in self._d.values())
            indexed = {
                k for sk in self._by_structure.values() for k in sk
            }
            return (
                actual == self._bytes
                and self._bytes <= max(_max_bytes(), 0)
                and indexed == set(self._d)
                and not self._inflight
            )

    def state(self) -> dict:
        from ..telemetry.metrics import REGISTRY

        def val(n: str) -> int:
            m = REGISTRY.get(f"cache.{self.name}.{n}")
            return 0 if m is None else int(m.value)

        with self._lock:
            entries = len(self._d)
            byts = self._bytes
            foldable = sum(1 for e in self._d.values() if e.fold_spec)
        return {
            "mode": _mode(),
            "entries": entries,
            "foldable_entries": foldable,
            "bytes": byts,
            "max_bytes": _max_bytes(),
            "hits": val("hits"),
            "misses": val("misses"),
            "evictions": val("evictions"),
            "folds": val("folds"),
            "fold_rows": val("fold_rows"),
            "refreshes": val("refreshes"),
            "verified": val("verified"),
        }

    def clear(self) -> None:
        with self._lock:
            self._d.clear()
            self._by_structure.clear()
            self._bytes = 0
        self._publish_bytes()

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)


RESULT_CACHE = ResultCache()


# ---------------------------------------------------------------------------
# the collect() integration
# ---------------------------------------------------------------------------

def _canonical_bits(batch) -> tuple:
    """Bit-exact comparable form of a result batch (verify mode): schema,
    dtypes, values with floats at .hex() precision, NULLs explicit."""
    out = []
    for name, c in batch.columns.items():
        vals = [
            x.hex() if isinstance(x, float) else x for x in c.decode().tolist()
        ]
        out.append((name, c.dtype, vals))
    return tuple(out)


def _build_key(plan, pins):
    from ..plan.kernel_cache import (
        plan_files_fingerprint,
        plan_structure_fingerprint,
    )

    structure_key = _digest(plan_structure_fingerprint(plan))
    files_key = _digest(plan_files_fingerprint(plan))
    snap_key = tuple(sorted((s.index_path, s.entry_id) for s in pins))
    return (structure_key, files_key, snap_key), structure_key


def _cacheable(plan, pins) -> bool:
    from ..plan.nodes import InMemoryScan

    if not pins:
        return False  # no snapshot authority: raw/in-memory-only plans
    return not any(isinstance(n, InMemoryScan) for n in plan.preorder())


def _verify_or_raise(session, plan, result, origin: str) -> None:
    """verify mode: recompute from scratch and compare bit-for-bit."""
    from ..plan.executor import execute_plan
    from ..telemetry.metrics import REGISTRY

    fresh = execute_plan(plan, session)
    if _canonical_bits(fresh) != _canonical_bits(result):
        raise HyperspaceError(
            f"result-cache verify divergence on {origin}: cached result "
            f"does not match recomputation (plan:\n{plan.pretty()})"
        )
    REGISTRY.counter("cache.result.verified").inc()


def serve_collect(session, raw_plan, plan):
    """The ``DataFrame.collect`` chokepoint: probe the result cache, serve
    a hit with zero scan/upload/dispatch, fold from a same-template older
    snapshot on an additive miss, or execute and populate. Falls through
    to plain execution whenever the cache is off or the plan is not
    cacheable (no pins / in-memory leaves)."""
    from ..plan.executor import execute_plan
    from ..telemetry import trace
    from ..telemetry.metrics import REGISTRY

    if not enabled():
        return execute_plan(plan, session)
    from ..ingest.snapshots import current_pins

    pins = current_pins()
    if not _cacheable(plan, pins):
        return execute_plan(plan, session)

    with trace.span("cache:probe"):
        key, structure_key = _build_key(plan, pins)
    outcome = {"via": "full"}

    def build() -> CachedResult:
        from .view_maintenance import classify_plan, try_fold
        from ..plan.nodes import FileScan

        REGISTRY.counter("cache.result.misses").inc()
        fold_spec = classify_plan(plan)
        result = None
        depth = 0
        if fold_spec is not None:
            folded = try_fold(
                session, plan, fold_spec,
                RESULT_CACHE.fold_candidates(structure_key),
            )
            if folded is not None:
                result, depth = folded
                outcome["via"] = "fold"
        if result is None:
            result = execute_plan(plan, session)
        return CachedResult(
            key, structure_key, result, pins,
            [_file_ids(n) for n in plan.preorder() if isinstance(n, FileScan)],
            fold_spec, depth, raw_plan, session,
        )

    entry, hit = RESULT_CACHE.get_or_compute(key, build)
    from ..telemetry import plan_stats

    if hit:
        REGISTRY.counter("cache.result.hits").inc()
        plan_stats.note_route(plan.plan_id, "cached")
        _log_cache_index_usage(session, plan, "ResultCacheHit")
        if is_verify():
            _verify_or_raise(session, plan, entry.result, "hit")
    elif outcome["via"] == "fold":
        plan_stats.note_route(plan.plan_id, "folded")
        _log_cache_index_usage(session, plan, "ResultCacheFold")
        if is_verify():
            _verify_or_raise(session, plan, entry.result, "fold")
    return entry.result


def _log_cache_index_usage(session, plan, rule: str) -> None:
    """Cache serves bypass the rule layer entirely, so without this the
    indexes baked into the cached plan are invisible to per-index
    attribution: emit the same ``IndexUsageEvent`` chokepoint the rewrite
    rules use, and credit the avoided index scan to the workload plane."""
    from ..plan.nodes import FileScan
    from ..rules.rule_utils import log_index_usage
    from ..telemetry import workload

    index_bytes: dict[str, int] = {}
    for n in plan.preorder():
        if isinstance(n, FileScan) and n.index_info is not None:
            index_bytes[n.index_info.index_name] = (
                index_bytes.get(n.index_info.index_name, 0)
                + sum(f.size for f in n.files)
            )
    if not index_bytes:
        return
    names = sorted(index_bytes)
    log_index_usage(
        session, rule, names,
        f"Result cache served plan using indexes: {', '.join(names)}",
    )
    for name in names:
        workload.note_index_applied(name, index_bytes[name], rule=rule)


def result_cache_state_string() -> str:
    """The hs.profile Result-cache block."""
    s = RESULT_CACHE.state()
    lines = ["== Result cache =="]
    if s["mode"] == "0":
        lines.append("disabled (HYPERSPACE_RESULT_CACHE=0)")
        return "\n".join(lines)
    looked = s["hits"] + s["misses"]
    ratio = f"{s['hits'] / looked:.2%}" if looked else "n/a"
    lines.append(
        f"mode={s['mode']} entries={s['entries']} "
        f"(foldable={s['foldable_entries']}) "
        f"bytes={s['bytes']}/{s['max_bytes']}"
    )
    lines.append(
        f"hits={s['hits']} misses={s['misses']} hit_ratio={ratio} "
        f"evictions={s['evictions']}"
    )
    lines.append(
        f"folds={s['folds']} fold_rows={s['fold_rows']} "
        f"refreshes={s['refreshes']} verified={s['verified']}"
    )
    return "\n".join(lines)
