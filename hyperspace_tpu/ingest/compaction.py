"""Background compaction + refcount-gated vacuum for ingested indexes.

Policy: every committed append checks the entry's per-bucket run counts
(``runs_per_bucket``); once any bucket holds ``HYPERSPACE_COMPACT_RUNS``
delta runs, a maintenance task is scheduled on the process-wide shared IO
pool (``workers.shared_io_pool`` — the same pool serving-query decodes run
on, so maintenance interleaves with live traffic instead of spawning its
own thread army). The task runs :class:`~.actions.IngestCompactAction`
(merge + re-sort, atomic publish) and then a pin-aware
``vacuum_outdated`` pass that retires superseded versions — but ONLY the
ones whose snapshot refcounts have drained and whose
``HYPERSPACE_VACUUM_GRACE_S`` window has elapsed (see
actions/lifecycle.VacuumOutdatedAction). Versions still pinned by in-flight
queries are deferred (``ingest.vacuum.deferred``) and picked up by the next
maintenance cycle — deletion strictly follows the refcount, never a timer
alone.

At most one maintenance task is in flight per index (the ``_INFLIGHT``
set); a task that loses the optimistic-concurrency race to the ingest
stream retries on the next trigger rather than spinning. Losing a
background cycle is always safe: compaction is a pure space/locality
optimization and vacuum re-evaluates from scratch each pass.
"""

from __future__ import annotations

import logging
from collections import Counter
from typing import TYPE_CHECKING, Optional

from ..staticcheck.concurrency import TrackedLock, guarded_by
from ..utils import env

if TYPE_CHECKING:
    from ..session import HyperspaceSession

logger = logging.getLogger(__name__)

_INFLIGHT_LOCK = TrackedLock("ingest.compaction_inflight")
_INFLIGHT: set = guarded_by(
    set(),  # abspath(index_path) strings with a scheduled/running task
    _INFLIGHT_LOCK,
    name="ingest.compaction._INFLIGHT",
    note="one background maintenance task per index at a time",
)

# Per-index writer mutex: in-process writers (append / compact / vacuum)
# serialize on it so the ingest stream never collides with its OWN
# background maintenance mid-transaction (cross-process writers still go
# through the log's optimistic concurrency + the actions' conflict retry).
_WRITER_LOCKS_LOCK = TrackedLock("ingest.writer_locks")
_WRITER_LOCKS: dict = guarded_by(
    {},  # abspath(index_path) -> TrackedLock
    _WRITER_LOCKS_LOCK,
    name="ingest.compaction._WRITER_LOCKS",
    note="lazily created per-index writer mutexes",
)


def writer_lock(index_path: str) -> TrackedLock:
    """The per-index writer mutex (created on first use). Held across one
    whole maintenance transaction — coarse on purpose: index mutations are
    seconds-scale and correctness-critical, queries never take it."""
    import os

    key = os.path.abspath(index_path)
    with _WRITER_LOCKS_LOCK:
        lock = _WRITER_LOCKS.get(key)
        if lock is None:
            lock = TrackedLock(f"ingest.writer:{os.path.basename(key)}")
            _WRITER_LOCKS[key] = lock
        return lock


def runs_per_bucket(entry) -> dict:
    """bucket id -> file (run) count of the entry's index content; files
    whose name carries no bucket id are ignored (never compacted)."""
    from ..models.covering import bucket_id_from_filename

    counts: Counter = Counter()
    for f in entry.index_data_files():
        b = bucket_id_from_filename(f.name)
        if b is not None:
            counts[b] += 1
    return dict(counts)


def needs_compaction(entry, min_runs: Optional[int] = None) -> bool:
    threshold = max(
        2, min_runs if min_runs is not None else env.env_int("HYPERSPACE_COMPACT_RUNS")
    )
    counts = runs_per_bucket(entry)
    return bool(counts) and max(counts.values()) >= threshold


def maybe_schedule(session: "HyperspaceSession", index_name: str) -> bool:
    """Schedule one background maintenance task (compact + vacuum) for
    ``index_name`` when its latest entry crossed the run threshold and no
    task is already in flight. Returns True when a task was scheduled."""
    import os

    from ..index_manager import index_manager_for
    from ..telemetry.metrics import REGISTRY
    from ..utils.workers import shared_io_pool

    manager = index_manager_for(session)
    entry = manager.get_index(index_name)
    if entry is None or not needs_compaction(entry):
        return False
    key = os.path.abspath(
        manager.resolver.get_index_path(index_name)
    )
    with _INFLIGHT_LOCK:
        if key in _INFLIGHT:
            return False
        _INFLIGHT.add(key)
    REGISTRY.counter("ingest.compact.scheduled").inc()
    shared_io_pool().submit(_run_maintenance, session, index_name, key)
    return True


def _run_maintenance(session: "HyperspaceSession", index_name: str, key: str) -> None:
    """One maintenance cycle: compact eligible buckets, then vacuum
    superseded versions whose refcounts drained. Failures are logged and
    surrendered — the next append past the threshold reschedules."""
    from ..exceptions import HyperspaceError
    from ..index_manager import index_manager_for
    from ..telemetry import trace

    try:
        manager = index_manager_for(session)
        with trace.span("compact:maintenance", index=index_name):
            manager.compact(index_name)
            manager.vacuum_outdated(index_name)
        # compaction rewrote the layout: promoted (fold-eligible) result
        # cache entries re-anchor against the new version in the background
        from ..cache.view_maintenance import maybe_refresh

        maybe_refresh(session, index_name)
    except HyperspaceError as e:
        # lost the optimistic-concurrency race to the ingest stream (or
        # preconditions shifted underfoot): safe to surrender; the next
        # trigger retries
        logger.info("background maintenance of %r yielded: %s", index_name, e)
    except Exception:
        logger.warning(
            "background maintenance of %r failed", index_name, exc_info=True
        )
    finally:
        with _INFLIGHT_LOCK:
            _INFLIGHT.discard(key)


def maintenance_idle() -> bool:
    """True when no background maintenance task is scheduled or running
    (gates drain on this before asserting quiescent-state invariants)."""
    with _INFLIGHT_LOCK:
        return not _INFLIGHT
