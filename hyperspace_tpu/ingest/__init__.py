"""hyperspace_tpu.ingest — continuous ingestion for live indexes.

Log-structured index maintenance with snapshot-isolated reads:

- ``Hyperspace.append(name, df)`` / :func:`append_batch` index new source
  data as append-only per-bucket delta runs, each batch an atomically
  published immutable data version (actions.py);
- queries pin the snapshot they planned against — a refcount keeps every
  pinned version's files on disk until the query drains (snapshots.py);
- background compaction merges delta runs and a refcount-gated vacuum
  retires superseded versions (compaction.py).

docs/maintenance.md has the layout, lifecycle, and recovery matrix.
"""

from __future__ import annotations

import itertools
import os
from typing import TYPE_CHECKING

from .actions import IngestAppendAction, IngestCompactAction
from .compaction import (
    maintenance_idle,
    maybe_schedule,
    needs_compaction,
    runs_per_bucket,
)
from .snapshots import (
    REGISTRY,
    Snapshot,
    SnapshotRegistry,
    observe_pins,
    pin_current,
    pin_scope,
    protected_version,
)

if TYPE_CHECKING:
    from ..session import HyperspaceSession

__all__ = [
    "IngestAppendAction",
    "IngestCompactAction",
    "Snapshot",
    "SnapshotRegistry",
    "REGISTRY",
    "append_batch",
    "latest_stable_entry",
    "maintenance_idle",
    "maybe_schedule",
    "needs_compaction",
    "observe_pins",
    "pin_current",
    "pin_scope",
    "protected_version",
    "runs_per_bucket",
]

_batch_seq = itertools.count()


def latest_stable_entry(session: "HyperspaceSession", index_name: str):
    """The latest STABLE IndexLogEntry of ``index_name`` — robust to a
    concurrent writer's transient tail (mid-transaction the latest log id
    is a bare transient LogEntry; readers get the last committed snapshot
    instead of None). Returns None only when the index truly has no stable
    entry (never created, or vacuumed away)."""
    from ..index_manager import index_manager_for
    from ..meta.entry import IndexLogEntry
    from ..meta.log_manager import IndexLogManager

    manager = index_manager_for(session)
    entry = manager.get_index(index_name)
    if entry is not None:
        return entry
    stable = IndexLogManager(
        manager.resolver.get_index_path(index_name)
    ).get_latest_stable_log()
    return stable if isinstance(stable, IndexLogEntry) else None


def append_batch(
    session: "HyperspaceSession",
    index_name: str,
    data,
    filename: "str | None" = None,
) -> str:
    """Convenience ingest path: write ``data`` (a column dict or
    ColumnBatch) as ONE new parquet part under the index's source root and
    append it to the index in the same call. Returns the new file's path.

    The part name embeds the entry id and a process-unique sequence number,
    so concurrent ingesters in one process never collide; multi-process
    ingest should pass explicit ``filename``s."""
    from ..columnar import io as cio
    from ..columnar.table import ColumnBatch
    from ..exceptions import HyperspaceError
    from ..index_manager import index_manager_for

    manager = index_manager_for(session)
    entry = latest_stable_entry(session, index_name)
    if entry is None:
        raise HyperspaceError(f"Index with name {index_name!r} could not be found")
    roots = entry.relation.root_paths
    if len(roots) != 1 or not os.path.isdir(roots[0]):
        raise HyperspaceError(
            f"append_batch needs a single directory source root, got {roots}"
        )
    batch = data if isinstance(data, ColumnBatch) else ColumnBatch.from_pydict(data)
    name = filename or (
        f"part-ingest-{entry.id:05d}-{os.getpid()}-{next(_batch_seq):06d}.parquet"
    )
    path = os.path.join(roots[0], name)
    if os.path.exists(path):
        raise HyperspaceError(f"append_batch target already exists: {path}")
    cio.write_parquet(batch, path)
    manager.append(index_name, session.read.parquet(path))
    return path
