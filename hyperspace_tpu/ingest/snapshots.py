"""Snapshot-pinned reads: version refcounting for log-structured indexes.

Continuous ingestion makes index data *multi-version and mortal*: every
``append`` publishes a new immutable data version, compaction supersedes old
delta runs, and vacuum eventually deletes them. A query, meanwhile, resolves
its index file set ONCE at plan time (``rule_utils._index_scan`` reads the
log entry's content) and streams those files for the rest of its life. The
contract that keeps concurrent maintenance sound is therefore:

    a file set resolved at plan time stays readable until the query drains.

This module enforces it with a process-wide refcount registry:

- ``DataFrame.collect()`` opens a :class:`pin_scope`; every index scan the
  rewrite produces inside that scope pins a :class:`Snapshot` — the entry id
  plus the data versions (``v__=N`` dirs) its content references — bumping a
  per-``(index_path, version)`` refcount. The scope's ``finally`` releases
  every pin, so cancelled and failed queries (``QueryCancelledError`` is a
  BaseException) release exactly like successful ones.
- Deletion paths consult the registry before touching a version:
  ``VacuumOutdatedAction`` defers pinned versions (``ingest.vacuum.deferred``)
  and retires them on a later pass once the refcount drains;
  ``IndexManager.recover()`` never removes a pinned version dir.
- Maintenance actions *protect* the version they are building
  (:func:`protected_version`): from ``stage_version`` until the final log
  commit, the staged — and, post-publish, published-but-not-yet-logged —
  version is invisible to ``clear_staging`` / orphan sweeps in this process.
  Protection is released in the action's ``finally`` even on a simulated
  crash, so the chaos harness's "restarted process" sees real debris.

The registry lock is a LEAF: nothing else is ever acquired inside it, and
metric emission happens outside, so the lock-order audit stays clean.
"""

from __future__ import annotations

import contextvars
import os
import time
from dataclasses import dataclass
from typing import Optional

from ..staticcheck.concurrency import TrackedLock
from ..staticcheck.lifecycle import release_resource, tracked_resource


@dataclass(frozen=True)
class Snapshot:
    """One query's pinned view of one index: the log entry and the data
    versions (hence files) it resolved at plan time. Immutable — the pin
    IS the guarantee that ``files`` stay on disk until release."""

    index_name: str
    index_path: str  # abspath of the index root
    entry_id: int
    versions: frozenset  # data versions (ints) referenced by the entry
    files: tuple  # resolved file paths (informational / replay key)


def _versions_of_entry(entry) -> frozenset:
    """Data versions referenced by an entry's content (``v__=N`` dirs)."""
    out = set()
    for d in entry.index_version_dirs():
        try:
            out.add(int(d.split("=", 1)[1]))
        except (IndexError, ValueError):
            continue
    return frozenset(out)


class SnapshotRegistry:
    """Process-wide refcounts of (index_path, data_version) pins plus the
    protected-version set of in-flight maintenance builds. All mutation
    under one leaf ``TrackedLock``; counters emitted outside it."""

    def __init__(self):
        self._lock = TrackedLock("ingest.snapshots")
        self._refs: dict = {}  # (index_path, version) -> pin refcount
        self._protected: dict = {}  # (index_path, version) -> nesting depth
        self._superseded_at: dict = {}  # (index_path, version) -> monotonic ts
        self._pins_total = 0
        self._releases_total = 0
        # lifecycle-audit handles: id(Snapshot) -> handle for pins (each
        # pin() returns a distinct Snapshot object), (path, version) ->
        # LIFO handle stack for nested protection
        self._pin_handles: dict = {}
        self._prot_handles: dict = {}

    # --- pinning ----------------------------------------------------------

    def pin(self, index_path: str, entry) -> Snapshot:
        index_path = os.path.abspath(index_path)
        snap = Snapshot(
            index_name=entry.name,
            index_path=index_path,
            entry_id=entry.id,
            versions=_versions_of_entry(entry),
            files=tuple(entry.content.files()),
        )
        lc = tracked_resource("snapshot.pin", f"{snap.index_name}#{snap.entry_id}")
        with self._lock:
            for v in snap.versions:
                key = (index_path, v)
                self._refs[key] = self._refs.get(key, 0) + 1
            self._pins_total += 1
            if lc:
                self._pin_handles[id(snap)] = lc
        from ..telemetry.metrics import REGISTRY

        REGISTRY.counter("ingest.snapshot.pins").inc()
        return snap

    def release(self, snap: Snapshot) -> None:
        with self._lock:
            for v in snap.versions:
                key = (snap.index_path, v)
                n = self._refs.get(key, 0) - 1
                if n <= 0:
                    self._refs.pop(key, None)
                else:
                    self._refs[key] = n
            self._releases_total += 1
            lc = self._pin_handles.pop(id(snap), 0)
        release_resource(lc)
        from ..telemetry.metrics import REGISTRY

        REGISTRY.counter("ingest.snapshot.releases").inc()

    def is_pinned(self, index_path: str, version: int) -> bool:
        key = (os.path.abspath(index_path), version)
        with self._lock:
            return self._refs.get(key, 0) > 0

    def pinned_versions(self, index_path: str) -> set:
        index_path = os.path.abspath(index_path)
        with self._lock:
            return {v for (p, v), n in self._refs.items() if p == index_path and n > 0}

    def active_pins(self) -> int:
        with self._lock:
            return sum(self._refs.values())

    # --- maintenance protection ------------------------------------------

    def protect_version(self, index_path: str, version: int) -> None:
        key = (os.path.abspath(index_path), version)
        lc = tracked_resource("snapshot.protect", f"{key[0]}@v{version}")
        with self._lock:
            self._protected[key] = self._protected.get(key, 0) + 1
            if lc:
                self._prot_handles.setdefault(key, []).append(lc)

    def unprotect_version(self, index_path: str, version: int) -> None:
        key = (os.path.abspath(index_path), version)
        with self._lock:
            depth = self._protected.get(key, 0) - 1
            if depth <= 0:
                self._protected.pop(key, None)
            else:
                self._protected[key] = depth
            stack = self._prot_handles.get(key)
            lc = stack.pop() if stack else 0
            if stack is not None and not stack:
                self._prot_handles.pop(key, None)
        release_resource(lc)

    def is_protected(self, index_path: str, version: int) -> bool:
        key = (os.path.abspath(index_path), version)
        with self._lock:
            return self._protected.get(key, 0) > 0

    def protected_versions(self, index_path: str) -> set:
        index_path = os.path.abspath(index_path)
        with self._lock:
            return {
                v for (p, v), n in self._protected.items() if p == index_path and n > 0
            }

    # --- vacuum grace bookkeeping ----------------------------------------

    def grace_elapsed(self, index_path: str, version: int, grace_s: float) -> bool:
        """True once ``version`` has been observed superseded (unreferenced
        by the latest entry) for at least ``grace_s`` seconds. First
        observation starts the clock — a two-pass contract that closes the
        plan-time window between reading a (cached) entry and pinning it."""
        key = (os.path.abspath(index_path), version)
        now = time.monotonic()
        with self._lock:
            first = self._superseded_at.get(key)
            if first is None:
                self._superseded_at[key] = now
                first = now
        return (now - first) >= grace_s

    def forget_version(self, index_path: str, version: int) -> None:
        """Drop grace bookkeeping for a deleted version (id reuse safety)."""
        key = (os.path.abspath(index_path), version)
        with self._lock:
            self._superseded_at.pop(key, None)

    # --- introspection ----------------------------------------------------

    def state(self) -> dict:
        with self._lock:
            return {
                "active_pins": sum(self._refs.values()),
                "pinned_versions": len(self._refs),
                "protected_versions": len(self._protected),
                "pins_total": self._pins_total,
                "releases_total": self._releases_total,
            }


REGISTRY = SnapshotRegistry()


class protected_version:
    """Context manager protecting one in-flight maintenance output version
    from ``clear_staging`` / orphan sweeps in this process. Nestable and
    exception-safe (released even on a simulated ``InjectedCrash``)."""

    __slots__ = ("_path", "_version")

    def __init__(self, index_path: str, version: int):
        self._path = index_path
        self._version = version

    def __enter__(self):
        REGISTRY.protect_version(self._path, self._version)
        return self

    def __exit__(self, *exc) -> bool:
        REGISTRY.unprotect_version(self._path, self._version)
        return False


# --- per-query pin scope -----------------------------------------------------
#
# ``DataFrame.collect()`` opens a scope; ``rule_utils._index_scan`` pins into
# it. Contextvars keep the scope thread- and task-local, so concurrent
# scheduler workers each carry their own pin list.

_PIN_SCOPE: contextvars.ContextVar = contextvars.ContextVar(
    "hyperspace_pin_scope", default=None
)
# observation sink for tests/gates: records every Snapshot pinned inside
_OBSERVE: contextvars.ContextVar = contextvars.ContextVar(
    "hyperspace_pin_observe", default=None
)


class pin_scope:
    """Collects every snapshot pinned during one query execution and
    releases them all on exit — success, failure, or cancellation."""

    __slots__ = ("_token",)

    def __enter__(self):
        self._token = _PIN_SCOPE.set([])
        return self

    def __exit__(self, *exc) -> bool:
        pins = _PIN_SCOPE.get()
        _PIN_SCOPE.reset(self._token)
        for snap in pins or ():
            REGISTRY.release(snap)
        return False


class observe_pins:
    """Test/gate hook: records every Snapshot pinned while active (across
    nested pin scopes) into ``self.pins``."""

    __slots__ = ("pins", "_token")

    def __init__(self):
        self.pins: list = []

    def __enter__(self):
        self._token = _OBSERVE.set(self.pins)
        return self

    def __exit__(self, *exc) -> bool:
        _OBSERVE.reset(self._token)
        return False


def current_pins() -> tuple:
    """The Snapshots pinned so far by the active pin scope (empty outside a
    scope). The result cache keys on exactly these: the pinned entry ids ARE
    the exact data-version component of a cached result's identity."""
    scope = _PIN_SCOPE.get()
    return tuple(scope) if scope else ()


def pin_current(session, entry) -> Optional[Snapshot]:
    """Pin ``entry``'s snapshot into the active pin scope (no-op outside a
    scope — explain/whyNot walk plans without executing them). Called by
    ``rule_utils._index_scan`` at the moment the file set is resolved."""
    scope = _PIN_SCOPE.get()
    if scope is None:
        return None
    from ..meta.path_resolver import PathResolver

    index_path = PathResolver(session.conf, session.warehouse_dir).get_index_path(
        entry.name
    )
    snap = REGISTRY.pin(index_path, entry)
    scope.append(snap)
    sink = _OBSERVE.get()
    if sink is not None:
        sink.append(snap)
    return snap
