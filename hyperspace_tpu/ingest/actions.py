"""Log-structured index maintenance actions: append and compact.

Continuous ingestion rides the same two-phase transaction FSM as every
other maintenance op (actions/base.py: validate → begin → op → end, with
optimistic-concurrency conflict retry), so the PR-7 crash-recovery matrix
covers it for free — plus two new fault points of its own:

- :class:`IngestAppendAction` (INGESTING → ACTIVE): index ONLY the new
  source files as append-only per-bucket delta runs inside a fresh data
  version (``stage_version`` → ``Index.ingest_delta`` → atomic ``publish``).
  Cost is proportional to the batch; the committed entry's content is the
  old content *merged* with the delta version — a new immutable snapshot.
  The entry's fingerprint is recomputed over the extended source file set,
  so queries over the grown source exact-match the index immediately
  (freshness = one log commit, no rebuild, no hybrid-scan ratios).
- :class:`IngestCompactAction` (COMPACTING → ACTIVE): merge the delta runs
  of buckets that accumulated ``min_runs``+ files into one sorted file per
  bucket (``Index.optimize`` re-sorts, so PR-4 row-group skipping keeps
  working on compacted output), published as its own atomic version. The
  superseded versions stay on disk until their snapshot refcounts drain —
  retirement is vacuum's job (see ingest/compaction.py), never ours.

Both actions *protect* the version they are building (snapshots.py) from
``clear_staging`` / orphan sweeps in this process for the whole
stage→publish→log-commit window, releasing protection in a ``finally`` so
even a simulated crash (``InjectedCrash``) leaves honest debris for the
chaos gate's restarted-process recovery to repair.
"""

from __future__ import annotations

from collections import defaultdict
from typing import TYPE_CHECKING, Optional

from .snapshots import protected_version
from ..actions import states as S
from ..actions.base import IndexMutationAction
from ..actions.create import content_of_version_dir
from ..exceptions import HyperspaceError, NoChangesError
from ..meta.data_manager import IndexDataManager
from ..meta.entry import (
    Content,
    Directory,
    FileIdTracker,
    FileInfo,
    IndexLogEntry,
    LogicalPlanFingerprint,
    Relation,
    Source,
    SourcePlan,
)
from ..meta.log_manager import IndexLogManager
from ..meta.signatures import DEFAULT_PROVIDER_NAME, get_provider
from ..models.base import IndexerContext
from ..models.covering import bucket_id_from_filename
from ..telemetry.events import (
    AppInfo,
    IngestAppendActionEvent,
    IngestCompactActionEvent,
)
from ..utils import env, faults

if TYPE_CHECKING:
    from ..session import HyperspaceSession


class _SourceFilesPlan:
    """Signable stand-in for the source relation after an append: the same
    single-FileScan shape the create-time fingerprint signed and the
    query-time ``_LeafPlan`` signs, over the extended file set. Using the
    entry's recorded files (not a fresh directory listing) keeps the
    fingerprint a function of what THIS transaction logically covers."""

    def __init__(self, files: list[FileInfo]):
        self._files = list(files)

    def preorder_kinds(self) -> list[str]:
        return ["FileScan"]

    def leaf_file_infos(self) -> list[list[FileInfo]]:
        return [self._files]


def _fingerprint_of_files(files: list[FileInfo]) -> LogicalPlanFingerprint:
    from ..meta.entry import Signature

    provider = get_provider(DEFAULT_PROVIDER_NAME)
    sig = provider.sign(_SourceFilesPlan(files))
    if sig is None:
        raise HyperspaceError("Cannot fingerprint the appended source file set")
    return LogicalPlanFingerprint([Signature(DEFAULT_PROVIDER_NAME, sig)])


class _IngestActionBase(IndexMutationAction):
    """Shared shape: entry access that survives conflict retries, and
    version protection released even on a simulated crash."""

    allowed_prior_states = frozenset({S.ACTIVE})

    def __init__(
        self,
        session: "HyperspaceSession",
        index_path: str,
        log_manager: IndexLogManager,
        data_manager: IndexDataManager,
        event_logger=None,
    ):
        super().__init__(log_manager, event_logger)
        self.session = session
        self.index_path = index_path
        self.data_manager = data_manager
        self._protected: list = []

    @property
    def entry(self) -> IndexLogEntry:
        prev = self.previous_entry
        if not isinstance(prev, IndexLogEntry):
            raise HyperspaceError("Latest log entry has no index metadata")
        return prev

    def validate(self) -> None:
        """Like the base check, but a TRANSIENT prior state (another
        writer's in-flight transaction — e.g. a cross-process vacuum racing
        the ingest stream) is a retryable conflict, not a hard error: the
        conflict-retry loop re-reads the log and re-runs once the other
        transaction commits or is rolled back."""
        from ..exceptions import ConcurrentWriteError
        from ..meta.log_manager import STABLE_STATES

        prev = self.log_manager.get_latest_log()
        if prev is None:
            raise HyperspaceError("Index does not exist")
        if prev.state not in self.allowed_prior_states:
            if prev.state not in STABLE_STATES:
                raise ConcurrentWriteError(
                    f"{type(self).__name__} found in-flight transaction "
                    f"state {prev.state}; retrying after it settles"
                )
            raise HyperspaceError(
                f"{type(self).__name__} requires state in "
                f"{sorted(self.allowed_prior_states)}, found {prev.state}"
            )

    def _protect(self, version: int) -> None:
        guard = protected_version(self.index_path, version)
        guard.__enter__()
        self._protected.append(guard)

    def run(self) -> None:
        try:
            super().run()
        finally:
            # protection must NOT outlive the transaction: after the final
            # log commit the version is referenced (safe); after a crash it
            # must look like sweepable debris to a recovering process
            guards, self._protected = self._protected, []
            for g in guards:
                g.__exit__(None, None, None)

    def new_version(self) -> int:
        latest = self.data_manager.get_latest_version()
        staged = self.data_manager.staged_versions()
        floor = max([latest if latest is not None else -1, *staged, -1])
        return floor + 1


class IngestAppendAction(_IngestActionBase):
    """Append-only ingest of new source files as per-bucket delta runs."""

    transient_state = S.INGESTING
    final_state = S.ACTIVE

    def __init__(
        self,
        session: "HyperspaceSession",
        index_path: str,
        log_manager: IndexLogManager,
        data_manager: IndexDataManager,
        df,
        event_logger=None,
    ):
        super().__init__(session, index_path, log_manager, data_manager, event_logger)
        self._df = df
        self._new_files: list[FileInfo] = []
        self._version: Optional[int] = None
        self._tracker: Optional[FileIdTracker] = None
        self._rows = 0

    def validate(self) -> None:
        from ..models.covering import _single_file_scan, resolve_columns

        super().validate()
        entry = self.entry
        scan = _single_file_scan(self._df)
        logged = entry.source_file_infos()
        new = sorted(
            (f for f in scan.files if f not in logged), key=lambda f: f.name
        )
        if not new:
            raise NoChangesError(
                "Append aborted: every file is already covered by the index"
            )
        if entry.source_update() is not None:
            # a quick-refresh delta rides the entry and is served via hybrid
            # scan; appending on top would double-serve any overlap. Make
            # the user materialize it first — explicit beats subtly wrong.
            raise HyperspaceError(
                "Index has a pending quick-refresh source delta; run "
                "refresh (incremental/full) before appending"
            )
        # the delta must be indexable: all referenced columns resolvable in
        # the batch's schema (same resolution the create path used)
        resolve_columns(
            self._df.schema, entry.derived_dataset.referenced_columns()
        )
        self._new_files = new

    def op(self) -> None:
        from ..models.covering import _single_file_scan
        from ..plan.dataframe import DataFrame
        from ..rules.apply import with_hyperspace_rule_disabled
        from ..telemetry import trace
        from ..telemetry.metrics import REGISTRY

        entry = self.entry
        self._version = self.new_version()
        self._protect(self._version)
        self._tracker = FileIdTracker()
        self._tracker.add_file_info(entry.source_file_infos())
        staging = self.data_manager.stage_version(self._version)
        faults.fire("ingest.append", version=self._version)
        with trace.span(
            "ingest:append",
            index=entry.name,
            version=self._version,
            files=len(self._new_files),
        ) as sp:
            ctx = IndexerContext(self.session, self._tracker, staging)
            scan = _single_file_scan(self._df)
            new = self._new_files
            sub = self._df.plan.transform_up(
                lambda n: n.copy(files=new) if n is scan else n
            )
            with with_hyperspace_rule_disabled():
                self._rows = entry.derived_dataset.ingest_delta(
                    ctx, DataFrame(self.session, sub), self._version
                )
            self.data_manager.publish(self._version)
            sp.set_attr("rows", self._rows)
        faults.fire_after("ingest.append", version=self._version)
        REGISTRY.counter("ingest.appends").inc()
        REGISTRY.counter("ingest.rows_appended").inc(self._rows)
        REGISTRY.counter("ingest.files_appended").inc(len(self._new_files))

    def log_entry(self) -> IndexLogEntry:
        entry = self.entry
        # index content: old snapshot ∪ the delta version just published
        delta_content = content_of_version_dir(
            self.data_manager.version_path(self._version)
        )
        content = Content(Directory.merge(entry.content.root, delta_content.root))
        # source relation: extend the recorded file set with the appended
        # files (stable ids via the tracker) + refresh the fingerprint so
        # queries over the grown source exact-match this entry
        rel = entry.relation
        appended_infos = [
            FileInfo(
                f.name,
                f.size,
                f.modified_time,
                self._tracker.add_file(f.name, f.size, f.modified_time),
            )
            for f in self._new_files
        ]
        all_files = list(rel.content.file_infos()) + appended_infos
        new_rel = Relation(
            rel.root_paths,
            Content.from_files(all_files),
            rel.schema,
            rel.file_format,
            dict(rel.options),
            None,  # no pending source delta (validate() enforces it)
        )
        plan = SourcePlan(
            [new_rel],
            entry.source.plan.raw_plan,
            _fingerprint_of_files(all_files),
        )
        return IndexLogEntry(
            name=entry.name,
            derived_dataset=entry.derived_dataset,
            content=content,
            source=Source(plan),
            properties=dict(entry.properties),
        )

    def event(self, message: str):
        name = getattr(self._prev, "name", "")
        return IngestAppendActionEvent(AppInfo.current(), message, index_name=name)


class IngestCompactAction(_IngestActionBase):
    """Merge a bucket's accumulated delta runs into one sorted file."""

    transient_state = S.COMPACTING
    final_state = S.ACTIVE

    def __init__(
        self,
        session: "HyperspaceSession",
        index_path: str,
        log_manager: IndexLogManager,
        data_manager: IndexDataManager,
        min_runs: Optional[int] = None,
        event_logger=None,
    ):
        super().__init__(session, index_path, log_manager, data_manager, event_logger)
        self.min_runs = max(
            2, min_runs if min_runs is not None else env.env_int("HYPERSPACE_COMPACT_RUNS")
        )
        self._to_compact: list[FileInfo] = []
        self._ignored: list[FileInfo] = []
        self._buckets = 0
        self._version: Optional[int] = None

    def _partition_files(self) -> None:
        """Candidates = every file of a bucket holding >= min_runs runs
        (ALL its runs compact together so the output is one fully sorted
        file — compacting a subset would leave overlapping sorted runs and
        lose row-group precision). Unknown-layout files never compact."""
        by_bucket: dict[int, list[FileInfo]] = defaultdict(list)
        unknown: list[FileInfo] = []
        for f in self.entry.index_data_files():
            b = bucket_id_from_filename(f.name)
            if b is None:
                unknown.append(f)
            else:
                by_bucket[b].append(f)
        self._to_compact, self._ignored, self._buckets = [], list(unknown), 0
        for b, fs in sorted(by_bucket.items()):
            if len(fs) >= self.min_runs:
                self._to_compact.extend(fs)
                self._buckets += 1
            else:
                self._ignored.extend(fs)

    def validate(self) -> None:
        super().validate()
        self._partition_files()
        if not self._to_compact:
            raise NoChangesError(
                f"Compaction aborted: no bucket holds >= {self.min_runs} "
                f"delta runs"
            )

    def op(self) -> None:
        from ..rules.apply import with_hyperspace_rule_disabled
        from ..telemetry import trace
        from ..telemetry.metrics import REGISTRY

        entry = self.entry
        self._version = self.new_version()
        self._protect(self._version)
        tracker = FileIdTracker()
        tracker.add_file_info(entry.source_file_infos())
        staging = self.data_manager.stage_version(self._version)
        faults.fire("ingest.compact", version=self._version)
        with trace.span(
            "compact:run",
            index=entry.name,
            version=self._version,
            buckets=self._buckets,
            files=len(self._to_compact),
        ):
            ctx = IndexerContext(self.session, tracker, staging)
            with with_hyperspace_rule_disabled():
                entry.derived_dataset.optimize(ctx, self._to_compact)
            self.data_manager.publish(self._version)
        faults.fire_after("ingest.compact", version=self._version)
        REGISTRY.counter("ingest.compact.runs").inc()
        REGISTRY.counter("ingest.compact.buckets").inc(self._buckets)
        REGISTRY.counter("ingest.compact.files_in").inc(len(self._to_compact))

    def log_entry(self) -> IndexLogEntry:
        entry = self.entry
        new_content = content_of_version_dir(
            self.data_manager.version_path(self._version)
        )
        if self._ignored:
            content = Content(
                Directory.merge(
                    new_content.root, Content.from_files(self._ignored).root
                )
            )
        else:
            content = new_content
        return IndexLogEntry(
            name=entry.name,
            derived_dataset=entry.derived_dataset,
            content=content,
            source=entry.source,
            properties=dict(entry.properties),
        )

    def event(self, message: str):
        name = getattr(self._prev, "name", "")
        return IngestCompactActionEvent(AppInfo.current(), message, index_name=name)
