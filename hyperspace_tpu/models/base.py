"""Index abstraction — the "derived dataset" contract.

Reference parity: index/Index.scala:31-168 (kind/kindAbbr/indexedColumns/
referencedColumns/properties/statistics/canHandleDeletedFiles/write/optimize/
refreshIncremental/refreshFull, UpdateMode Merge|Overwrite, polymorphic
serialization), index/IndexConfigTrait.scala:31-59 (createIndex contract),
index/IndexerContext.scala:24-43.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Callable, Sequence

from ..meta.entry import INDEX_KIND_REGISTRY, FileIdTracker, FileInfo
from ..exceptions import HyperspaceError

if TYPE_CHECKING:
    from ..plan.dataframe import DataFrame
    from ..session import HyperspaceSession


class UpdateMode(enum.Enum):
    """How refresh_incremental's output relates to existing index data
    (ref: Index.scala UpdateMode)."""

    MERGE = "merge"  # new data merged alongside old content
    OVERWRITE = "overwrite"  # new content fully replaces old


@dataclass
class IndexerContext:
    """Handed to index implementations during maintenance ops
    (ref: IndexerContext.scala)."""

    session: "HyperspaceSession"
    file_id_tracker: FileIdTracker
    index_data_path: str


class Index:
    """Base class for all index kinds. Subclasses register their `kind` in
    INDEX_KIND_REGISTRY for polymorphic log-entry deserialization (the
    analogue of Jackson @JsonTypeInfo on the reference's Index trait)."""

    kind: str = "?"
    kind_abbr: str = "?"

    # --- metadata ---
    def indexed_columns(self) -> list[str]:
        raise NotImplementedError

    def referenced_columns(self) -> list[str]:
        raise NotImplementedError

    def properties(self) -> dict[str, str]:
        return {}

    def statistics(self) -> dict[str, Any]:
        """Per-kind extra stats surfaced by hs.index(name)
        (ref: Index.statistics -> IndexStatistics additionalStats)."""
        return {}

    def can_handle_deleted_files(self) -> bool:
        return False

    # --- maintenance ops ---
    def write(self, ctx: IndexerContext, index_data: "DataFrame") -> None:
        raise NotImplementedError

    def optimize(self, ctx: IndexerContext, files_to_optimize: list[FileInfo]) -> None:
        raise NotImplementedError(f"{self.kind} does not support optimize")

    def refresh_incremental(
        self,
        ctx: IndexerContext,
        appended_df: "DataFrame | None",
        deleted_files: list[FileInfo],
        index_content_files: list[FileInfo],
    ) -> tuple["Index", UpdateMode]:
        raise NotImplementedError(f"{self.kind} does not support incremental refresh")

    def ingest_delta(
        self, ctx: IndexerContext, delta_df: "DataFrame", version: int
    ) -> int:
        """Write ONLY ``delta_df``'s rows as append-only runs into the staged
        version dir (log-structured ingest); returns rows written."""
        raise NotImplementedError(f"{self.kind} does not support delta ingestion")

    def refresh_full(
        self, ctx: IndexerContext, df: "DataFrame"
    ) -> tuple["Index", "DataFrame"]:
        raise NotImplementedError

    # --- serialization ---
    def to_dict(self) -> dict:
        raise NotImplementedError

    @classmethod
    def from_dict(cls, d: dict) -> "Index":
        raise NotImplementedError

    def __eq__(self, other):
        return type(self) is type(other) and self.to_dict() == other.to_dict()

    def __hash__(self):
        return hash((self.kind, tuple(self.indexed_columns())))


class IndexConfig:
    """User-visible index configuration (ref: IndexConfigTrait.scala:31-59)."""

    @property
    def index_name(self) -> str:
        raise NotImplementedError

    def referenced_columns(self) -> list[str]:
        """Columns the index needs from the source."""
        raise NotImplementedError

    def create_index(
        self, ctx: IndexerContext, df: "DataFrame", properties: dict[str, str]
    ) -> tuple[Index, "DataFrame"]:
        """Build (index object, index-data DataFrame to be written)."""
        raise NotImplementedError


def register_index_kind(kind: str, loader: Callable[[dict], Index]) -> None:
    INDEX_KIND_REGISTRY[kind] = loader


def validate_column_names(names: Sequence[str], what: str) -> list[str]:
    out = list(names)
    if not out and what == "indexed":
        raise HyperspaceError("At least one indexed column required")
    if len(set(n.lower() for n in out)) != len(out):
        raise HyperspaceError(f"Duplicate {what} columns: {out}")
    return out
