"""Per-index sample runs for the approximate query tier.

Every index data file gets one *sample twin* per configured fraction,
written next to it in the same version directory:

    part-3-b00007.parquet
    _sample.r010000.part-3-b00007.parquet      (fraction 0.01 -> 10000 ppm)
    _sample.r100000.part-3-b00007.parquet      (fraction 0.1 -> 100000 ppm)

The underscore prefix keeps twins invisible to everything that enumerates
index *content* (directory listings in the log manager, vacuum refcounts,
plan-verifier content checks, debris audits) — the same trick the PR-15
sketch sidecars use. Twins live and die with their version directory, so
snapshot pinning and vacuum protection come for free: a pinned log version
pins its data directory, and the twins are just more files inside it.

Sampling is *universe* (correlated) sampling on the index's bucket-key
columns: a row is kept iff a salted remix of its key hash falls under
``fraction * 2^32``. Keep/drop is a pure function of the key VALUE, which
gives the three properties the approximate tier needs:

- **append-stable strata**: rows appended later make the same keep/drop
  decision as rows written at create time, so per-bucket sampling
  fractions stay on-target across build -> append -> compact without any
  re-balancing bookkeeping;
- **join-correlated**: two indexes bucketed by the same join key sample
  the same key universe, so a sampled join keeps matching pairs and the
  joined-row count scales by 1/p (not 1/p^2) — the unbiased-join property
  from the correlated-sampling literature;
- **bucket-decorrelated**: the remix is salted so the keep decision is
  independent of ``bucket_id = hash % num_buckets``; without it, sampling
  would keep whole buckets and starve others.

The mask is applied in row order, so the twin inherits the data file's
sort order and its footer min/max stats stay usable for row-group pruning.

Writes are bracketed by the ``approx.sample`` fault point; a crash between
data file and twins (or mid-tier-set) just leaves files without twins,
which the planner reads as "tier ineligible" — exact execution, never a
wrong answer.
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional, Sequence

import numpy as np

from ..columnar import io as cio
from ..columnar.table import ColumnBatch
from ..ops.bucketize import key_hash_words
from ..ops.hashing import _fmix32, hash32_np
from ..utils import env, faults

SAMPLE_PREFIX = "_sample."
_SAMPLE_NAME_RE = re.compile(r"^_sample\.r(\d{1,7})\.(?P<base>.+)$")
# per-file sample metadata (key NDV + per-tier kept rows), the NDV-clamp
# fallback when the PR-15 sketch sidecars (the better, whole-index NDV
# source) are not enabled. Shares the underscore-prefix invisibility.
SAMPLE_META_PREFIX = "_sample.meta."

# Decorrelates the keep decision from bucket assignment (which uses the
# unsalted hash); golden-ratio constant, same family as the hash finalizers.
_UNIVERSE_SALT = np.uint32(0x9E3779B1)


def approx_mode() -> str:
    """``HYPERSPACE_APPROX``: "0" (default, off) / "1" / "verify"."""
    v = env.env_str("HYPERSPACE_APPROX").strip().lower()
    if v == "verify":
        return "verify"
    if v in ("1", "true", "on"):
        return "1"
    return "0"


def approx_enabled() -> bool:
    return approx_mode() != "0"


def sample_fractions() -> tuple[float, ...]:
    """Configured sampling tiers, ascending, each in (0, 1)."""
    raw = env.env_str("HYPERSPACE_APPROX_FRACTIONS") or "0.01,0.1"
    out = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            f = float(part)
        except ValueError:
            continue
        if 0.0 < f < 1.0:
            out.append(f)
    return tuple(sorted(set(out)))


def fraction_ppm(fraction: float) -> int:
    return int(round(fraction * 1_000_000))


def sample_file_name(base_name: str, fraction: float) -> str:
    return f"{SAMPLE_PREFIX}r{fraction_ppm(fraction):06d}.{base_name}"


def sample_path(data_path: str, fraction: float) -> str:
    d, base = os.path.split(data_path)
    return os.path.join(d, sample_file_name(base, fraction))


def parse_sample_name(name: str) -> Optional[tuple[float, str]]:
    """``(fraction, base_data_file_name)`` if ``name`` is a sample twin."""
    m = _SAMPLE_NAME_RE.match(name)
    if m is None:
        return None
    return int(m.group(1)) / 1_000_000, m.group("base")


def strip_sample_prefix(name: str) -> str:
    """Base data-file name for a twin; any other name passes through."""
    parsed = parse_sample_name(name)
    return parsed[1] if parsed is not None else name


def derived_base(name: str) -> Optional[str]:
    """Base data-file name a sample twin or sample meta belongs to, or
    None for any other file. Vacuum's in-version-dir sweep uses this to
    keep derived files exactly as long as their data file is referenced."""
    if name.startswith(SAMPLE_META_PREFIX) and name.endswith(".json"):
        return name[len(SAMPLE_META_PREFIX):-len(".json")]
    parsed = parse_sample_name(name)
    return parsed[1] if parsed is not None else None


def sample_meta_path(data_path: str) -> str:
    d, base = os.path.split(data_path)
    return os.path.join(d, f"{SAMPLE_META_PREFIX}{base}.json")


def load_sample_meta(data_path: str) -> Optional[dict]:
    """The data file's sample meta (``rows``, ``key_ndv``, per-tier
    ``kept``), or None when absent/unreadable — absence reads as "no NDV
    floor evidence from this file", never as an error."""
    try:
        with open(sample_meta_path(data_path), encoding="utf-8") as f:
            return json.load(f)
    except Exception:
        return None


def _key_hash(batch: ColumnBatch, key_columns: Sequence[str]) -> np.ndarray:
    """Salted per-row key hash the keep decision thresholds against."""
    cols = [key_hash_words(batch.column(c)) for c in key_columns]
    h = hash32_np(cols)
    return _fmix32(h.astype(np.uint32) ^ _UNIVERSE_SALT, np).astype(np.uint64)


def keep_threshold(fraction: float) -> int:
    """A key survives tier ``fraction`` iff its salted hash < this."""
    return int(round(fraction * float(2**32)))


def universe_keep_mask(
    batch: ColumnBatch, key_columns: Sequence[str], fraction: float
) -> np.ndarray:
    """Boolean keep mask: salted remix of the row's key hash < fraction*2^32.

    Deterministic in the key value — the whole sampling design rides on
    this function being a pure function of ``key_columns`` row values.
    """
    return _key_hash(batch, key_columns) < np.uint64(keep_threshold(fraction))


def maybe_write_samples(
    batch: ColumnBatch,
    data_path: str,
    row_group_size: int,
    key_columns: Sequence[str],
) -> int:
    """Write sample twins for a just-written index data file.

    No-op (one env read) when the approximate tier is off, the file is not
    parquet, or the index has no key columns. Returns the number of twins
    written. All configured tiers are written unconditionally — tier
    *choice* (including the NDV-based minimum-keys clamp) happens on the
    read side, so a twin set is never partially stratified by data shape.
    """
    if not approx_enabled() or not data_path.endswith(".parquet"):
        return 0
    if not key_columns:
        return 0
    fractions = sample_fractions()
    if not fractions:
        return 0
    faults.fire("approx.sample")
    h = _key_hash(batch, key_columns)
    written = 0
    kept_rows: dict[str, int] = {}
    for fraction in fractions:
        keep = h < np.uint64(keep_threshold(fraction))
        cio.write_index_file(
            batch.filter(keep),
            sample_path(data_path, fraction),
            row_group_size=row_group_size,
        )
        kept_rows[str(fraction_ppm(fraction))] = int(np.count_nonzero(keep))
        written += 1
    # meta last, inside the fault bracket: a crash mid-set leaves twins
    # without meta, which the NDV clamp reads as "no floor evidence" and
    # the missing-twin check still catches partially-written sets
    # heavy clusters: keys owning an outsized share of this file's rows.
    # The read-side skew guard aggregates these across files and DECLINES
    # the sampled tier when a heavy key would be dropped at the requested
    # fraction — a sample that never sees a dominant cluster cannot bound
    # it, and an unhonest CI is worse than an exact answer. Recorded by
    # salted hash (the same value the keep decision thresholds), so the
    # guard needs no key values and works across join sides.
    uniq, counts = np.unique(h, return_counts=True)
    # the recording floor derives from the read-side guard threshold so
    # any configured HYPERSPACE_APPROX_MAX_KEY_SHARE can actually be
    # honored: record at half the threshold (margin for a key whose
    # share is diluted in this file but dominant index-wide), capped at
    # 1% of the file's rows and never below an absolute 8 rows (tiny
    # files would otherwise record noise). The entry cap is sized so no
    # key at or above the floor is ever truncated — shares sum to 1, so
    # at most 1/share_floor keys can qualify per file.
    max_share = env.env_float("HYPERSPACE_APPROX_MAX_KEY_SHARE")
    share_floor = min(0.01, max_share / 2.0) if max_share > 0 else 0.01
    floor = max(8, int(share_floor * batch.num_rows))
    cap = int(1.0 / share_floor) + 1
    big = counts >= floor
    order = np.argsort(counts[big])[::-1][:cap]
    heavy = {
        str(int(uniq[big][i])): int(counts[big][i]) for i in order
    }
    meta = {
        "rows": int(batch.num_rows),
        # hash-level distinct count ~= key NDV (32-bit collisions are
        # negligible at file scale); the read-side minimum-keys clamp
        # divides by this to refuse fractions too coarse for the key space
        "key_ndv": int(uniq.size),
        "kept": kept_rows,
        "heavy": heavy,
    }
    tmp = sample_meta_path(data_path) + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(meta, f)
    os.replace(tmp, sample_meta_path(data_path))
    faults.fire_after("approx.sample")
    from ..telemetry.metrics import REGISTRY

    REGISTRY.counter("approx.samples.written").inc(written)
    return written
