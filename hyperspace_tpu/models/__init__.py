"""Index implementations ("derived datasets").

Importing this package registers every index kind for polymorphic log-entry
deserialization and hooks the kind-specific rewrite rules into the
score-based optimizer.
"""

from .base import Index, IndexConfig, IndexerContext, UpdateMode
from .covering import CoveringIndex, CoveringIndexConfig
from .dataskipping import (
    BloomFilterSketch,
    DataSkippingIndex,
    DataSkippingIndexConfig,
    MinMaxSketch,
    ValueListSketch,
    ZRegionSketch,
)
from .zorder import ZOrderCoveringIndex, ZOrderCoveringIndexConfig

__all__ = [
    "Index",
    "IndexConfig",
    "IndexerContext",
    "UpdateMode",
    "CoveringIndex",
    "CoveringIndexConfig",
    "DataSkippingIndex",
    "DataSkippingIndexConfig",
    "MinMaxSketch",
    "BloomFilterSketch",
    "ValueListSketch",
    "ZRegionSketch",
    "ZOrderCoveringIndex",
    "ZOrderCoveringIndexConfig",
]
