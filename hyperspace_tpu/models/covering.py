"""CoveringIndex — kind "CI".

Reference parity: index/covering/CoveringIndex.scala:33-193 (vertical slice of
indexed+included columns, hash-bucketed by indexed columns and sorted within
buckets; createIndexData's lineage column via input_file_name + id map
:140-192), CoveringIndexTrait.scala:32-135 (refreshIncremental/refreshFull/
optimize/canHandleDeletedFiles), CoveringIndexConfig.

TPU-first write path: bucket placement comes from ops/hashing (same hash at
build and query time), rows are exchanged to bucket shards via
parallel/exchange on a device mesh when one is active, and each bucket is
written as one sorted parquet file whose name encodes the bucket id (the
analogue of Spark's BucketingUtils filename contract, which OptimizeAction
relies on to group files bucket-wise).
"""

from __future__ import annotations

import logging
import os
import re
from typing import TYPE_CHECKING, Optional, Sequence

import numpy as np

from .base import Index, IndexConfig, IndexerContext, UpdateMode, register_index_kind, validate_column_names
from .. import constants as C
from ..columnar import io as cio
from ..columnar.table import Column, ColumnBatch, Schema
from ..exceptions import HyperspaceError
from ..meta.entry import FileInfo
from ..ops.bucketize import bucket_ids_for_batch, sort_indices_within
from ..plan.nodes import FileScan

if TYPE_CHECKING:
    from ..plan.dataframe import DataFrame

# optional run suffix: "-<seq>" for streaming file-group runs, with an
# "s<slice>" tail when a hierarchical mesh wrote one run per slice — its
# own namespace, so host-fallback runs of the same seq can never collide
_BUCKET_FILE_RE = re.compile(
    r"^part-(\d+)-b(\d{5})(?:-\d+(?:s\d+)?)?\.(?:parquet|arrow)$"
)

# Row-group granularity for index data writes: fine enough that sorted
# buckets prune precisely, coarse enough to amortize metadata.
INDEX_ROW_GROUP_SIZE = 16384


def index_row_group_size(n_rows: int) -> int:
    """~64 row groups per file, floored at INDEX_ROW_GROUP_SIZE: small files
    keep fine-grained stats for range pruning; multi-million-row buckets
    stop paying per-group encode overhead (the 50M-build regression)."""
    return max(INDEX_ROW_GROUP_SIZE, min(1 << 20, n_rows // 64))


def bucket_file_name(
    version: int, bucket: int, seq: "int | str | None" = None, ext: str = ".parquet"
) -> str:
    suffix = f"-{seq}" if seq is not None else ""
    return f"part-{version}-b{bucket:05d}{suffix}{ext}"


def _session_index_ext(session) -> str:
    return cio.index_file_ext(
        session.conf.index_format if session is not None else "parquet"
    )


def index_write_opts(session, clustered_cols) -> dict:
    """Parquet write options for index data files from session conf: stats
    scoped to the clustered (sort/z-order) columns — the only ones whose
    row-group min/max actually prune — and the index codec. See
    INDEX_STATS_COLUMNS / INDEX_COMPRESSION in constants.py."""
    if session is None:
        return {}
    conf = session.conf
    return {
        "stats_columns": (
            list(clustered_cols)
            if conf.index_stats_columns == "clustered"
            else None
        ),
        "compression": conf.index_compression,
    }


def bucket_id_from_filename(name: str) -> Optional[int]:
    # Sample twins (_sample.r<ppm>.part-...) carry their base file's bucket
    # id, so bucketed-join grouping and prune keep-checks work transparently
    # on sampled plans (models/sample_store.py).
    base = os.path.basename(name)
    if base.startswith("_sample."):
        from .sample_store import strip_sample_prefix

        base = strip_sample_prefix(base)
    m = _BUCKET_FILE_RE.match(base)
    return int(m.group(2)) if m else None


def resolve_columns(schema: Schema, names: Sequence[str]) -> list[str]:
    """Case-insensitive column resolution; a bare dotted path resolves to
    its flattened nested column (ref: ResolverUtils.ResolvedColumn with the
    __hs_nested. prefix; create-path nested block CreateAction.scala:50-81)."""
    by_lower = {f.name.lower(): f.name for f in schema}
    out = []
    for n in names:
        r = by_lower.get(n.lower())
        if r is None:
            r = by_lower.get((C.NESTED_FIELD_PREFIX + n).lower())
        if r is None:
            raise HyperspaceError(
                f"Column {n!r} could not be resolved; available: {schema.names}"
            )
        out.append(r)
    return out


class CoveringIndex(Index):
    kind = "CI"
    kind_abbr = "CI"

    def __init__(
        self,
        indexed_columns: list[str],
        included_columns: list[str],
        schema: list[dict],
        num_buckets: int,
        properties: dict[str, str] | None = None,
    ):
        self._indexed = list(indexed_columns)
        self._included = list(included_columns)
        self._schema = list(schema)
        self.num_buckets = num_buckets
        self._properties = dict(properties or {})

    # --- metadata ---
    def indexed_columns(self) -> list[str]:
        return list(self._indexed)

    def referenced_columns(self) -> list[str]:
        return self._indexed + self._included

    def included_columns(self) -> list[str]:
        return list(self._included)

    def schema(self) -> Schema:
        return Schema.from_list(self._schema)

    def properties(self) -> dict[str, str]:
        return dict(self._properties)

    def has_lineage(self) -> bool:
        return self._properties.get("lineage", "false") == "true"

    def can_handle_deleted_files(self) -> bool:
        return self.has_lineage()

    def statistics(self) -> dict[str, object]:
        return {
            "numBuckets": self.num_buckets,
            "includedColumns": ",".join(self._included),
        }

    # --- data construction ---
    @staticmethod
    def create_index_data(
        ctx: IndexerContext,
        df: "DataFrame",
        indexed: list[str],
        included: list[str],
        lineage: bool,
    ) -> ColumnBatch:
        """Project the vertical slice; with lineage, each row carries the
        stable id of its source file (ref: CoveringIndex.createIndexData
        :140-192 — input_file_name() joined to a broadcast file-id map; here
        ids attach at per-file scan granularity, no join needed)."""
        cols = indexed + [c for c in included if c not in indexed]
        if not lineage:
            return df.select(*cols).collect()
        scan = _single_file_scan(df)
        fast = _lineage_fast_path(ctx, df, scan, cols)
        if fast is not None:
            return fast
        fids, batches = read_source_files_parallel(ctx, df, scan, cols)
        batches = [
            b.with_column(
                C.DATA_FILE_NAME_ID,
                Column(np.full(b.num_rows, fid, dtype=np.int64), "int64"),
            )
            for fid, b in zip(fids, batches)
        ]
        return ColumnBatch.concat(batches)

    # --- maintenance ---
    def write(self, ctx: IndexerContext, index_data: ColumnBatch) -> None:
        write_bucketed(
            index_data,
            ctx.index_data_path,
            self._indexed,
            self.num_buckets,
            session=ctx.session,
        )

    def optimize(self, ctx: IndexerContext, files_to_optimize: list[FileInfo]) -> None:
        """Compact many small per-bucket files into one per bucket
        (ref: CoveringIndexTrait.optimize:130-134). Buckets compact
        independently — rows already carry their bucket in the filename, so
        no re-hash is needed; concurrency is capped so in-flight buckets
        stay within the in-memory build budget."""
        from ..utils.workers import io_pool

        by_bucket: dict[Optional[int], list[FileInfo]] = {}
        for f in files_to_optimize:
            by_bucket.setdefault(bucket_id_from_filename(f.name), []).append(f)
        if None in by_bucket:
            # unknown layout: full re-bucketing path
            batch = cio.read_parquet([f.name for f in files_to_optimize])
            write_bucketed(
                batch, ctx.index_data_path, self._indexed, self.num_buckets,
                session=ctx.session,
            )
            return

        ext = _session_index_ext(ctx.session)
        write_opts = index_write_opts(ctx.session, self._indexed)

        def compact(item):
            b, files = item
            batch = cio.read_parquet([f.name for f in files])
            part = batch.take(sort_indices_within(batch, self._indexed))
            out_path = os.path.join(
                ctx.index_data_path, bucket_file_name(0, b, ext=ext)
            )
            cio.write_index_file(
                part,
                out_path,
                row_group_size=INDEX_ROW_GROUP_SIZE,
                **write_opts,
            )
            # "merge" of the input runs' per-row-group sketches: the compacted
            # file has NEW row groups (re-sorted), so the merged sidecar is a
            # rebuild over the merged batch — exact by construction, and
            # skipping keeps working on compacted output
            _write_sketch_sidecar(part, out_path, INDEX_ROW_GROUP_SIZE, self._indexed)
            # re-stratification at compaction is just a rewrite of the twins
            # over the merged batch: the universe mask is a pure function of
            # the key value, so strata stay on-target by construction
            _write_sample_runs(part, out_path, INDEX_ROW_GROUP_SIZE, self._indexed)

        from ..utils.workers import io_worker_count

        biggest = max(
            (sum(f.size for f in files) for files in by_bucket.values()),
            default=1,
        )
        budget = ctx.session.conf.build_max_bytes_in_memory
        # HYPERSPACE_IO_THREADS governs the width like every other IO pool,
        # further clamped so in-flight buckets stay within the build budget
        workers = io_worker_count(
            len(by_bucket), cap=max(1, budget // max(1, biggest))
        )
        with io_pool(workers, "hs-compact") as pool:
            list(pool.map(compact, by_bucket.items()))

    def ingest_delta(
        self, ctx: IndexerContext, delta_df: "DataFrame", version: int
    ) -> int:
        """Log-structured ingest: bucketize ONLY the delta rows and write
        them as append-only per-bucket runs into the staged version
        directory — cost proportional to the batch, never a rebuild. The
        filename's version field is the ingest data version (the run lives
        in its own namespace next to the streaming-build ``-<seq>`` and
        mesh ``s<slice>`` runs), so delta runs from successive batches can
        never collide however their version dirs are later merged. Buckets
        accumulate one extra sorted run per batch; readers already handle
        multi-run buckets (the streaming-build layout) and compaction
        (``ingest/actions.IngestCompactAction``) re-sorts them into single
        files so row-group skipping stays precise. Returns rows written."""
        data = CoveringIndex.create_index_data(
            ctx, delta_df, self._indexed, self._included, self.has_lineage()
        )
        write_bucketed(
            data,
            ctx.index_data_path,
            self._indexed,
            self.num_buckets,
            version=version,
            session=ctx.session,
        )
        return data.num_rows

    def refresh_incremental(
        self,
        ctx: IndexerContext,
        appended_df: "DataFrame | None",
        deleted_files: list[FileInfo],
        index_content_files: list[FileInfo],
    ) -> tuple["CoveringIndex", UpdateMode]:
        """Index appended rows; drop rows of deleted source files via the
        lineage column (ref: CoveringIndexTrait.refreshIncremental:57-106).
        Above the in-memory budget BOTH slices stream: appended source files
        go through the file-group writer, and each old bucketed index file
        rewrites as its own run after the lineage anti-filter."""
        new_index = CoveringIndex(
            self._indexed, self._included, self._schema, self.num_buckets, self._properties
        )
        limit = ctx.session.conf.build_max_bytes_in_memory
        appended_scan = (
            _single_file_scan(appended_df) if appended_df is not None else None
        )
        appended_bytes = (
            sum(f.size for f in appended_scan.files) if appended_scan else 0
        )
        old_bytes = (
            sum(f.size for f in index_content_files) if deleted_files else 0
        )
        n_pieces = (len(appended_scan.files) if appended_scan else 0) + (
            len(index_content_files) if deleted_files else 0
        )
        streaming = (appended_bytes + old_bytes) > limit and n_pieces > 1

        if deleted_files and not self.has_lineage():
            raise HyperspaceError(
                "Index has no lineage column; cannot handle deleted source files"
            )

        if streaming:
            seq = 0
            if appended_scan is not None:
                _, seq = write_streaming_groups(
                    ctx, appended_df, appended_scan, self._indexed,
                    self._included, self.has_lineage(), self.num_buckets, limit,
                )
            if not deleted_files:
                return new_index, UpdateMode.MERGE
            deleted_ids = np.array([f.id for f in deleted_files], dtype=np.int64)
            for f in index_content_files:
                b = cio.read_parquet([f.name])
                keep = ~np.isin(b.column(C.DATA_FILE_NAME_ID).data, deleted_ids)
                if keep.any():
                    kept = b.filter(keep)
                    bucket = bucket_id_from_filename(f.name)
                    if bucket is None:
                        write_bucketed(
                            kept, ctx.index_data_path, self._indexed,
                            self.num_buckets, seq=seq, session=ctx.session,
                        )
                    else:
                        out_path = os.path.join(
                            ctx.index_data_path,
                            bucket_file_name(
                                0, bucket, seq, _session_index_ext(ctx.session)
                            ),
                        )
                        cio.write_index_file(
                            kept,
                            out_path,
                            row_group_size=INDEX_ROW_GROUP_SIZE,
                            **index_write_opts(ctx.session, self._indexed),
                        )
                        _write_sketch_sidecar(
                            kept, out_path, INDEX_ROW_GROUP_SIZE, self._indexed
                        )
                        _write_sample_runs(
                            kept, out_path, INDEX_ROW_GROUP_SIZE, self._indexed
                        )
                seq += 1
            return new_index, UpdateMode.OVERWRITE

        parts: list[ColumnBatch] = []
        if appended_df is not None:
            parts.append(
                CoveringIndex.create_index_data(
                    ctx, appended_df, self._indexed, self._included, self.has_lineage()
                )
            )
        if deleted_files:
            deleted_ids = np.array([f.id for f in deleted_files], dtype=np.int64)
            old = cio.read_parquet([f.name for f in index_content_files])
            keep = ~np.isin(old.column(C.DATA_FILE_NAME_ID).data, deleted_ids)
            parts.append(old.filter(keep))
            mode = UpdateMode.OVERWRITE
        else:
            mode = UpdateMode.MERGE
        merged = ColumnBatch.concat(parts)
        new_index.write(ctx, merged)
        return new_index, mode

    def refresh_full(
        self, ctx: IndexerContext, df: "DataFrame"
    ) -> tuple["CoveringIndex", ColumnBatch | None]:
        """Full rebuild; sources above the in-memory budget stream through
        the bucketed writer in file groups (data already written -> None),
        the same bounded-memory path as large creates."""
        new_index = CoveringIndex(
            self._indexed, self._included, self._schema, self.num_buckets, self._properties
        )
        scan = _single_file_scan(df)
        total_bytes = sum(f.size for f in scan.files)
        limit = ctx.session.conf.build_max_bytes_in_memory
        if total_bytes > limit and len(scan.files) > 1:
            write_streaming_groups(
                ctx, df, scan, self._indexed, self._included,
                self.has_lineage(), self.num_buckets, limit,
            )
            return new_index, None
        data = CoveringIndex.create_index_data(
            ctx, df, self._indexed, self._included, self.has_lineage()
        )
        return new_index, data

    # --- serialization ---
    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "properties": {
                "columns": {"indexed": self._indexed, "included": self._included},
                "schema": self._schema,
                "numBuckets": self.num_buckets,
                "properties": self._properties,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CoveringIndex":
        p = d["properties"]
        return cls(
            p["columns"]["indexed"],
            p["columns"]["included"],
            p["schema"],
            p["numBuckets"],
            p.get("properties", {}),
        )


register_index_kind(CoveringIndex.kind, CoveringIndex.from_dict)


def _write_sketch_sidecar(
    batch: ColumnBatch, data_path: str, row_group_size: int,
    key_columns: Sequence[str],
) -> None:
    """Per-row-group sketch sidecar next to a just-written index data file
    (models/dataskipping/sketch_store.py). Gated on HYPERSPACE_SKETCHES —
    disabled (the default) this is one env read. Import is lazy: the
    dataskipping package's __init__ pulls its index module, which imports
    back into this one."""
    from .dataskipping import sketch_store

    sketch_store.maybe_write_sidecar(batch, data_path, row_group_size, key_columns)


def _write_sample_runs(
    batch: ColumnBatch, data_path: str, row_group_size: int,
    key_columns: Sequence[str],
) -> None:
    """Sample twins for the approximate tier next to a just-written index
    data file (models/sample_store.py). Gated on HYPERSPACE_APPROX —
    disabled (the default) this is one env read. Rides the same three write
    hooks as the sketch sidecar, so creates, streaming builds, ingest_delta
    runs, incremental refreshes, and compaction all keep their twins."""
    from . import sample_store

    sample_store.maybe_write_samples(batch, data_path, row_group_size, key_columns)


def _file_groups(files: list[FileInfo], max_bytes: int) -> list[list[FileInfo]]:
    """Greedy grouping of source files under a byte budget (>=1 file/group)."""
    groups: list[list[FileInfo]] = []
    cur: list[FileInfo] = []
    size = 0
    for f in files:
        if cur and size + f.size > max_bytes:
            groups.append(cur)
            cur, size = [], 0
        cur.append(f)
        size += f.size
    if cur:
        groups.append(cur)
    return groups


def _lineage_fast_path(
    ctx: IndexerContext, df: "DataFrame", scan: FileScan, cols: list[str]
) -> ColumnBatch | None:
    """Lineage via ONE multi-file read + np.repeat of per-file row counts —
    skips the per-file read/concat entirely (and rides the file-set-level
    source-column cache in columnar.io). Only sound when rows arrive in
    scan.files order with no row-count-changing operators: the plan must be
    pure Project-over-Scan with no partition columns and no pushed filter."""
    from ..plan.dataframe import DataFrame as DF
    from ..plan.nodes import Project
    from ..rules.apply import with_hyperspace_rule_disabled

    if scan.partition_columns or scan.pushed_filter is not None:
        return None
    if not all(isinstance(n, (FileScan, Project)) for n in df.plan.preorder()):
        return None
    try:
        counts = [cio.file_num_rows(f.name) for f in scan.files]
    except Exception:
        return None
    fids = [
        ctx.file_id_tracker.add_file(f.name, f.size, f.modified_time)
        for f in scan.files
    ]
    with with_hyperspace_rule_disabled():
        batch = DF(ctx.session, df.plan).select(*cols).collect()
    if batch.num_rows != sum(counts):
        return None  # files changed underfoot: the per-file path re-reads
    lineage = np.repeat(np.asarray(fids, dtype=np.int64), counts)
    return batch.with_column(C.DATA_FILE_NAME_ID, Column(lineage, "int64"))


def read_source_files_parallel(
    ctx: IndexerContext, df: "DataFrame", scan: FileScan, cols: list[str]
) -> tuple[list[int], list[ColumnBatch]]:
    """Per-source-file reads for index builds: ids assigned serially (the
    tracker is not thread-safe), reads on a thread pool. Each worker
    re-enters the rewrite-disable guard — the guard is thread-local, and a
    maintenance read served THROUGH an index would corrupt per-file data
    (and at minimum re-read the index log per file)."""
    from ..plan.dataframe import DataFrame as DF
    from ..rules.apply import with_hyperspace_rule_disabled

    fids = [
        ctx.file_id_tracker.add_file(f.name, f.size, f.modified_time)
        for f in scan.files
    ]

    def read_one(f):
        with with_hyperspace_rule_disabled():
            sub = df.plan.transform_up(
                lambda n: n.copy(files=[f]) if n is scan else n
            )
            return DF(ctx.session, sub).select(*cols).collect()

    from ..utils.workers import io_pool, io_worker_count

    with io_pool(io_worker_count(len(scan.files)), "hs-build-read") as pool:
        batches = list(pool.map(read_one, scan.files))
    return fids, batches


def _single_file_scan(df: "DataFrame") -> FileScan:
    scans = [n for n in df.plan.preorder() if isinstance(n, FileScan)]
    if len(scans) != 1:
        raise HyperspaceError(
            f"Index source must contain exactly one file relation, found {len(scans)}"
        )
    return scans[0]


def write_bucketed(
    batch: ColumnBatch,
    path: str,
    bucket_columns: list[str],
    num_buckets: int,
    version: int = 0,
    seq: int | None = None,
    session=None,
) -> list[str]:
    """Partition rows by hash(bucket_columns) % num_buckets, sort each bucket
    by the bucket columns, and write one parquet file per non-empty bucket
    with the bucket id in the filename (the TPU-side replacement for
    DataFrameWriterExtensions.saveWithBuckets:50-68).

    When the session has an active device mesh, the partition — hash,
    placement, exchange — runs on the mesh (parallel.exchange
    .partition_batch_mesh); the bucket layout is bit-identical to the host
    path by the shared-hash contract, so host- and mesh-built indexes are
    interchangeable on disk. On a hierarchical (dcn, ici) mesh the source
    rows split across the slices and each slice exchanges independently on
    its own submesh — the bucket all_to_all never crosses DCN — producing
    one sorted run per slice per bucket (the same multi-run layout as
    streaming builds; readers re-sort multi-file buckets)."""
    from ..columnar.table import sort_key_values
    from ..ops.bucketize import partition_batch
    from ..utils.workers import io_pool

    ext = _session_index_ext(session)
    write_opts = index_write_opts(session, bucket_columns)
    # full-batch sort keys computed ONCE; each bucket gathers only its key
    # slice for the argsort and then gathers the output columns a single
    # time (the old take -> sort -> take shape paid two full-column copies)
    full_keys = [
        sort_key_values(batch.column(c), True) for c in reversed(bucket_columns)
    ]

    def write_bucket(args):
        bucket, rows, seq_val = args
        if len(full_keys) == 1:
            from ..ops.bucketize import stable_argsort

            order = stable_argsort(full_keys[0][rows])
        else:
            order = np.lexsort([k[rows] for k in full_keys])
        part = batch.take(rows[order])
        fname = bucket_file_name(version, bucket, seq_val, ext)
        # row groups sized for ~64 per file (floor INDEX_ROW_GROUP_SIZE):
        # sorted buckets + parquet min/max stats keep near-exact range
        # pruning while large buckets avoid encode overhead
        rgs = index_row_group_size(part.num_rows)
        full_path = os.path.join(path, fname)
        cio.write_index_file(
            part,
            full_path,
            row_group_size=rgs,
            **write_opts,
        )
        # per-row-group sketch sidecar (bloom/value-list/z-region on the
        # non-key columns): one hook covers creates, streaming builds, AND
        # ingest_delta runs — a live index's delta runs skip from the
        # moment they publish
        _write_sketch_sidecar(part, full_path, rgs, bucket_columns)
        # sample twins (approximate tier): same hook coverage as sketches
        _write_sample_runs(part, full_path, rgs, bucket_columns)
        return fname

    work: list[tuple] | None = None
    if session is not None:
        from ..parallel.mesh import active_mesh, is_hierarchical, slice_submeshes

        mesh = active_mesh(session)
        if mesh is not None:
            from ..parallel.exchange import partition_batch_mesh

            if is_hierarchical(mesh):
                subs = slice_submeshes(mesh)
                n_slices = len(subs)
                bounds = np.linspace(0, batch.num_rows, n_slices + 1).astype(
                    np.int64
                )

                def exchange_slice(si_sub):
                    si, sub = si_sub
                    start, stop = int(bounds[si]), int(bounds[si + 1])
                    if start == stop:
                        return si, start, []
                    return si, start, partition_batch_mesh(
                        batch.slice(start, stop), bucket_columns, num_buckets, sub
                    )

                # slice 0 runs alone first so the first-call compilation
                # happens once (not raced across slices on backends whose
                # compile path is untested under concurrency); the remaining
                # slices — disjoint device sets hitting the now-warm
                # executable cache — dispatch concurrently so none idles
                results = [exchange_slice((0, subs[0]))]
                if n_slices > 1 and results[0][2] is not None:
                    with io_pool(n_slices - 1, "hs-exchange") as xpool:
                        results += list(
                            xpool.map(exchange_slice, list(enumerate(subs))[1:])
                        )
                if len(results) == n_slices and all(
                    p is not None for _si, _st, p in results
                ):
                    runs: list[tuple] = []
                    for si, start, p in results:
                        # per-slice runs live in an "s<slice>" sub-namespace
                        # of the caller's seq so a host-fallback run with
                        # the same seq can never collide on a filename
                        seq_val = f"{seq if seq is not None else 0}s{si}"
                        runs += [(b, rows + start, seq_val) for b, rows in p]
                    work = runs
                else:
                    # a declining slice silently discarding the others'
                    # device work must be VISIBLE (multi-slice regressions
                    # otherwise look like a slow host build)
                    logging.getLogger(__name__).warning(
                        "hierarchical mesh exchange fell back to the host "
                        "partitioner (a slice declined); %d slices affected",
                        n_slices,
                    )
            else:
                p = partition_batch_mesh(batch, bucket_columns, num_buckets, mesh)
                if p is not None:
                    work = [(b, rows, seq) for b, rows in p]
    if work is None:
        work = [
            (b, rows, seq)
            for b, rows in partition_batch(batch, bucket_columns, num_buckets)
        ]
    # concurrent bucket writes (pyarrow releases the GIL; the analogue of the
    # reference's parallel executor-side write tasks). Capped by real cores:
    # the numpy half holds the GIL, so extra threads only add lock churn.
    from ..utils.workers import io_pool, io_worker_count

    workers = io_worker_count(max(1, len(work)), cap=os.cpu_count() or 1)
    with io_pool(workers, "hs-build-write") as pool:
        return list(pool.map(write_bucket, work))


def write_streaming_groups(
    ctx: IndexerContext,
    df: "DataFrame",
    scan: FileScan,
    indexed: list[str],
    included: list[str],
    lineage: bool,
    num_buckets: int,
    limit: int,
    start_seq: int = 0,
) -> tuple[list[dict] | None, int]:
    """Bounded-memory bucketed build (the reference leans on Spark's shuffle
    spill; here source files stream through in groups sized by
    hyperspace.tpu.build.maxBytesInMemory): each group bucketizes, sorts,
    and appends one run per bucket (seq suffix in the filename). Buckets
    then hold multiple sorted runs — queries handle that, and Optimize
    compacts them into single files. Used by large creates, full refreshes,
    and the appended slice of incremental refreshes. Returns
    (index schema list, next free seq)."""
    from ..plan.dataframe import DataFrame as DF

    groups = _file_groups(scan.files, limit)
    schema_list: list[dict] | None = None
    seq = start_seq
    for group in groups:
        sub = df.plan.transform_up(
            lambda n: n.copy(files=group) if n is scan else n
        )
        data = CoveringIndex.create_index_data(
            ctx, DF(ctx.session, sub), indexed, included, lineage
        )
        if schema_list is None:
            schema_list = data.schema.to_list()
        write_bucketed(
            data, ctx.index_data_path, indexed, num_buckets, seq=seq,
            session=ctx.session,
        )
        seq += 1
    return schema_list, seq


class CoveringIndexConfig(IndexConfig):
    """ref: CoveringIndexConfig / CoveringIndexConfigTrait."""

    def __init__(
        self,
        index_name: str,
        indexed_columns: Sequence[str],
        included_columns: Sequence[str] = (),
    ):
        if not index_name:
            raise HyperspaceError("Index name must not be empty")
        self._name = index_name
        self._indexed = validate_column_names(indexed_columns, "indexed")
        self._included = validate_column_names(included_columns, "included")
        overlap = {c.lower() for c in self._indexed} & {c.lower() for c in self._included}
        if overlap:
            raise HyperspaceError(f"Columns in both indexed and included: {overlap}")

    @property
    def index_name(self) -> str:
        return self._name

    def referenced_columns(self) -> list[str]:
        return self._indexed + self._included

    def create_index(
        self, ctx: IndexerContext, df: "DataFrame", properties: dict[str, str]
    ) -> tuple[CoveringIndex, ColumnBatch | None]:
        indexed = resolve_columns(df.schema, self._indexed)
        included = resolve_columns(df.schema, self._included)
        lineage = properties.get("lineage", "false") == "true"
        num_buckets = ctx.session.conf.num_buckets
        scan = _single_file_scan(df)
        total_bytes = sum(f.size for f in scan.files)
        limit = ctx.session.conf.build_max_bytes_in_memory
        if total_bytes > limit and len(scan.files) > 1:
            # out-of-core build: returns (index, None) — data already written
            index = self._create_streaming(
                ctx, df, scan, indexed, included, lineage, num_buckets, limit, properties
            )
            return index, None
        data = CoveringIndex.create_index_data(ctx, df, indexed, included, lineage)
        index = CoveringIndex(
            indexed,
            included,
            data.schema.to_list(),
            num_buckets,
            properties,
        )
        return index, data

    def _create_streaming(
        self,
        ctx: IndexerContext,
        df: "DataFrame",
        scan: FileScan,
        indexed: list[str],
        included: list[str],
        lineage: bool,
        num_buckets: int,
        limit: int,
        properties: dict[str, str],
    ) -> CoveringIndex:
        schema_list, _ = write_streaming_groups(
            ctx, df, scan, indexed, included, lineage, num_buckets, limit
        )
        return CoveringIndex(indexed, included, schema_list or [], num_buckets, properties)
