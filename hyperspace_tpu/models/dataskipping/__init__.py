from .index import DataSkippingIndex, DataSkippingIndexConfig
from .sketches import (
    BloomFilterSketch,
    MinMaxSketch,
    Sketch,
    ValueListSketch,
    ZRegionSketch,
)
from . import rule  # noqa: F401  (registers ApplyDataSkippingIndex)

__all__ = [
    "DataSkippingIndex",
    "DataSkippingIndexConfig",
    "BloomFilterSketch",
    "MinMaxSketch",
    "Sketch",
    "ValueListSketch",
    "ZRegionSketch",
]
