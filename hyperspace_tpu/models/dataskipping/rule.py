"""ApplyDataSkippingIndex — prune source files before the scan.

Reference parity: index/dataskipping/rules/ApplyDataSkippingIndex.scala:33-105
(on Filter→Scan, translate the predicate against the index's sketches and
drop skippable files at listing time via DataSkippingFileIndex; score 1 so it
always loses to covering rewrites) + rules filters (FilterPlanNodeFilter DS
variant, FilterConditionFilter, DataSkippingIndexRanker).

Here pruning edits the FileScan's resolved file list directly — the pruned
files never produce host IO or device transfers.
"""

from __future__ import annotations

from ..base import Index
from ...columnar import io as cio
from ...meta.entry import IndexLogEntry
from ...plan.nodes import FileScan, Filter, LogicalPlan
from ...rules.base import (
    HyperspaceRule,
    IndexRankFilter,
    QueryPlanIndexFilter,
    index_type_filter,
    reason,
)
from ...rules.filter_rule import match_filter_pattern
from ...rules.rule_utils import log_index_usage
from ...rules.score_optimizer import register_rule
from ...telemetry import trace
from ...telemetry.metrics import REGISTRY

TAG_DS_PREDICATE = "DATASKIPPING_INDEX_PREDICATE"


class DSFilterPlanNodeFilter(QueryPlanIndexFilter):
    def apply(self, plan, candidates):
        m = match_filter_pattern(plan)
        if m is None:
            return {}
        _, scan = m
        ds = index_type_filter("DS")(candidates.get(scan.plan_id, []))
        return {scan.plan_id: ds} if ds else {}


class DSFilterConditionFilter(QueryPlanIndexFilter):
    """Translate + tag the predicate (ref: FilterConditionFilter)."""

    def apply(self, plan, candidates):
        m = match_filter_pattern(plan)
        if m is None:
            return {}
        filter_node, scan = m
        out = []
        for e in candidates.get(scan.plan_id, []):
            translated = e.derived_dataset.translate_filter(filter_node.condition)
            if self.tag_reason_if(
                translated is not None,
                plan,
                e,
                reason(
                    "NO_CONVERTIBLE_PREDICATE",
                    "No sketch can bound any part of the filter condition.",
                ),
            ):
                e.set_tag(scan.plan_id, TAG_DS_PREDICATE, translated)
                self.tag_applicable_rule(plan, e, "ApplyDataSkippingIndex")
                out.append(e)
        return {scan.plan_id: out} if out else {}


class DataSkippingIndexRanker(IndexRankFilter):
    def apply(self, plan, candidates):
        # more sketch columns = tighter pruning potential
        out = {}
        for leaf_id, entries in candidates.items():
            if entries:
                out[leaf_id] = max(
                    entries,
                    key=lambda e: (len(e.derived_dataset.sketches), e.name),
                )
        return out


class ApplyDataSkippingIndex(HyperspaceRule):
    @property
    def filters(self):
        return [
            DSFilterPlanNodeFilter(self.session),
            DSFilterConditionFilter(self.session),
        ]

    @property
    def rank_filter(self):
        return DataSkippingIndexRanker(self.session)

    def apply_index(self, plan: LogicalPlan, chosen) -> LogicalPlan:
        out = plan
        for leaf_id, entry in chosen.items():
            out = _prune_scan(self.session, out, leaf_id, entry)
        return out

    def score(self, plan, chosen) -> int:
        # ref: score 1 — any covering rewrite wins over skipping (:76-83)
        return 1 if chosen else 0


def _prune_scan(session, plan: LogicalPlan, leaf_id: int, entry: IndexLogEntry) -> LogicalPlan:
    from ...rules.rule_utils import find_scan_by_id

    leaf = find_scan_by_id(plan, leaf_id)
    predicate = entry.get_tag(leaf_id, TAG_DS_PREDICATE)
    if leaf is None or predicate is None:
        return plan
    # sketch table cached per entry (repeat planning of the same query — the
    # bench loop pattern — must not re-read + re-decode every time)
    files = tuple(entry.content.files())
    cached = getattr(entry, "_sketch_table_cache", None)
    if cached is not None and cached[0] == files:
        sketch_table = cached[1]
    else:
        sketch_table = cio.read_parquet(list(files))
        entry._sketch_table_cache = (files, sketch_table)
    keep_mask = predicate(sketch_table)
    from .index import FILE_ID_COLUMN

    keep_ids = set(sketch_table.column(FILE_ID_COLUMN).data[keep_mask].tolist())
    # map file -> id via the entry's recorded source files (stable ids)
    id_by_key = {
        (f.name, f.size, f.modified_time): f.id for f in entry.source_file_infos()
    }
    kept_files = []
    for f in leaf.files:
        fid = id_by_key.get((f.name, f.size, f.modified_time))
        if fid is None or fid in keep_ids:
            kept_files.append(f)  # unknown files are never skipped (safety)
    n_pruned = len(leaf.files) - len(kept_files)
    bytes_pruned = sum(f.size for f in leaf.files) - sum(
        f.size for f in kept_files
    )
    # skip/hit statistics are the primary data-skipping tuning signal
    # (arXiv:2009.08150): record the effect even when nothing pruned
    REGISTRY.counter("dataskipping.files_scanned").inc(len(kept_files))
    REGISTRY.counter("dataskipping.files_pruned").inc(n_pruned)
    REGISTRY.counter("dataskipping.bytes_pruned").inc(bytes_pruned)
    if trace.enabled():
        trace.add_event(
            "dataskipping",
            index=entry.name,
            files_total=len(leaf.files),
            files_pruned=n_pruned,
            bytes_pruned=bytes_pruned,
        )
    # uniform usage-event contract: every successful rewrite emits, with the
    # chosen index name — a 0-file prune still consulted (used) the index
    log_index_usage(
        session,
        "ApplyDataSkippingIndex",
        [entry.name],
        f"Data skipping applied: {n_pruned} of {len(leaf.files)} files pruned",
    )
    if not n_pruned:
        return plan  # nothing pruned; leave the plan untouched
    pruned = leaf.copy(files=kept_files)
    from ...plan.nodes import IndexScanInfo

    pruned.index_info = IndexScanInfo(entry.name, "DS", entry.id)
    return plan.transform_up(lambda n: pruned if n is leaf else n)


register_rule(ApplyDataSkippingIndex)
