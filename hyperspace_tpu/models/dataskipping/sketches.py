"""Sketches: per-source-file summaries powering data skipping.

Reference parity: index/dataskipping/sketches/ — Sketch trait (Sketch.scala:
36-119: expressions, aggregate functions, convertPredicate single-node
contract), MinMaxSketch (MinMaxSketch.scala:37-101: Eq/EqNullSafe/Lt/Le/Gt/
Ge/In conversions), BloomFilterSketch (BloomFilterSketch.scala:47-87:
Eq/In via might-contain probes), SingleExprSketch (name parsing/resolution).

TPU-first: sketch *construction* is a segment reduce over rows grouped by
source file (ops/sketch.py kernels); predicate *conversion* produces a small
host closure over the per-file sketch table (thousands of rows at most) —
pruning happens before any device load, which is the whole point.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np

from ... import constants as C
from ...columnar.table import Column, ColumnBatch, STRING
from ...exceptions import HyperspaceError
from ...ops.sketch import BloomFilter, segment_min_max_np
from ...plan import expr as X
from ...plan.expr import Expr

# A predicate over the sketch table: batch (one row per file) -> bool keep mask
SketchPredicate = Callable[[ColumnBatch], np.ndarray]

from ...staticcheck.concurrency import guarded_by

SKETCH_REGISTRY: dict = guarded_by(
    {}, None, name="models.dataskipping.SKETCH_REGISTRY",
    note="populated only by module-level register_sketch calls at import",
)


def register_sketch(kind: str, loader: Callable[[dict], "Sketch"]) -> None:
    SKETCH_REGISTRY[kind] = loader


class Sketch:
    kind = "?"

    @property
    def expr(self) -> str:
        """Source column this sketch summarizes."""
        raise NotImplementedError

    def indexed_columns(self) -> list[str]:
        return [self.expr]

    def referenced_columns(self) -> list[str]:
        return [self.expr]

    def output_columns(self) -> list[str]:
        """Column names this sketch contributes to the sketch table."""
        raise NotImplementedError

    def aggregate(
        self, values: Column, segment_ids: np.ndarray, num_segments: int
    ) -> dict[str, Column]:
        """Per-file aggregation (the build-time segment reduce)."""
        raise NotImplementedError

    def aggregate_batch(
        self, batch: ColumnBatch, segment_ids: np.ndarray, num_segments: int
    ) -> dict[str, Column]:
        """Batch-level aggregation entry point (the per-row-group sketch
        store's build path). Single-column sketches delegate to
        :meth:`aggregate`; multi-column sketches (ZRegionSketch) override."""
        return self.aggregate(batch.column(self.expr), segment_ids, num_segments)

    def convert_predicate(self, pred: Expr) -> Optional[SketchPredicate]:
        """Translate one predicate leaf into a keep-mask over the sketch
        table; None = this sketch cannot bound the predicate (single-node
        contract; tree recursion handled by the index, ref Sketch.scala:72-110)."""
        raise NotImplementedError

    def to_dict(self) -> dict:
        raise NotImplementedError

    def __eq__(self, other):
        return type(self) is type(other) and self.to_dict() == other.to_dict()

    def __hash__(self):
        return hash((self.kind, self.expr))


def _is_col_lit(pred: Expr, col_name: str) -> Optional[tuple[type, Any]]:
    """Match `col <op> literal` / `literal <op> col` (normalized); returns
    (op type, literal value)."""
    flip = {X.Lt: X.Gt, X.Le: X.Ge, X.Gt: X.Lt, X.Ge: X.Le, X.Eq: X.Eq, X.Ne: X.Ne}
    if isinstance(pred, tuple(flip)):
        left, right = pred.left, pred.right
        if isinstance(left, X.Col) and isinstance(right, X.Lit) and left.name.lower() == col_name.lower():
            return type(pred), right.value
        if isinstance(right, X.Col) and isinstance(left, X.Lit) and right.name.lower() == col_name.lower():
            return flip[type(pred)], left.value
    return None


class MinMaxSketch(Sketch):
    """ref: MinMaxSketch.scala:37-101."""

    kind = "MinMaxSketch"

    def __init__(self, expr: str):
        self._expr = expr

    @property
    def expr(self) -> str:
        return self._expr

    def output_columns(self) -> list[str]:
        return [f"{self._expr}__min", f"{self._expr}__max"]

    def aggregate(self, values, segment_ids, num_segments):
        if values.dtype == STRING:
            # order-correct codes against a sorted vocab, then decode extremes
            vals = np.asarray(values.decode(), dtype=object)
            valid = values.validity if values.validity is not None else np.ones(len(vals), bool)
            vals = np.where(valid, vals, "").astype(str)
            vocab, codes = np.unique(vals, return_inverse=True)
            mins, maxs = segment_min_max_np(codes.astype(np.int64), segment_ids, num_segments)
            mn = Column(mins.astype(np.int32), STRING, None, list(vocab))
            mx = Column(maxs.astype(np.int32), STRING, None, list(vocab))
        else:
            mins, maxs = segment_min_max_np(values.data, segment_ids, num_segments)
            mn = Column(mins, values.dtype)
            mx = Column(maxs, values.dtype)
        lo, hi = self.output_columns()
        return {lo: mn, hi: mx}

    def convert_predicate(self, pred: Expr) -> Optional[SketchPredicate]:
        lo_name, hi_name = self.output_columns()

        def cols(batch):
            lo = batch.column(lo_name)
            hi = batch.column(hi_name)
            if lo.dtype == STRING:
                return (
                    np.asarray(lo.decode(), dtype=object).astype(str),
                    np.asarray(hi.decode(), dtype=object).astype(str),
                )
            return lo.data, hi.data

        m = _is_col_lit(pred, self._expr)
        if m is not None:
            op, v = m
            if op is X.Eq:
                return lambda b: (lambda lo, hi: (lo <= v) & (hi >= v))(*cols(b))
            if op is X.Ne:
                # only an all-equal file can be skipped
                return lambda b: (lambda lo, hi: ~((lo == v) & (hi == v)))(*cols(b))
            if op is X.Lt:
                return lambda b: cols(b)[0] < v
            if op is X.Le:
                return lambda b: cols(b)[0] <= v
            if op is X.Gt:
                return lambda b: cols(b)[1] > v
            if op is X.Ge:
                return lambda b: cols(b)[1] >= v
        if (
            isinstance(pred, X.In)
            and isinstance(pred.child, X.Col)
            and pred.child.name.lower() == self._expr.lower()
        ):
            values = sorted(pred.values)

            def in_mask(b):
                lo, hi = cols(b)
                # a sorted-array bound check per file (ref: SortedArrayLowerBound)
                arr = np.asarray(values)
                idx = np.searchsorted(arr, lo, side="left")
                idx = np.clip(idx, 0, len(arr) - 1)
                return (arr[idx] >= lo) & (arr[idx] <= hi)

            return in_mask
        if isinstance(pred, X.IsNotNull) and isinstance(pred.child, X.Col):
            return None  # cannot bound without null counts
        return None

    def to_dict(self) -> dict:
        return {"kind": self.kind, "expr": self._expr}

    @classmethod
    def from_dict(cls, d: dict) -> "MinMaxSketch":
        return cls(d["expr"])

    def __repr__(self):
        return f"MinMax({self._expr})"


class BloomFilterSketch(Sketch):
    """ref: BloomFilterSketch.scala:47-87; aggregation wraps ops/sketch
    BloomFilter the way BloomFilterAgg wraps Spark's (expressions/
    BloomFilterAgg.scala:29-82)."""

    kind = "BloomFilterSketch"

    def __init__(self, expr: str, expected_distinct: int = 10000, fpp: float = 0.01):
        self._expr = expr
        self.expected_distinct = int(expected_distinct)
        self.fpp = float(fpp)

    @property
    def expr(self) -> str:
        return self._expr

    def output_columns(self) -> list[str]:
        return [f"{self._expr}__bloom"]

    @staticmethod
    def _canonical_words(col: Column) -> list[np.ndarray]:
        """Hash words independent of storage width: build and probe may see
        the same logical values as int32 vs int64 (or float32 vs float64), so
        integers/dates/bools widen to int64 and floats to float64 before
        decomposition; strings hash by value."""
        from ...ops.bucketize import key_hash_words

        if col.dtype == STRING:
            return [key_hash_words(col)]
        if col.data.dtype.kind == "f":
            return [col.data.astype(np.float64)]
        return [col.data.astype(np.int64)]

    def aggregate(self, values, segment_ids, num_segments):
        import json

        blooms = []
        order = np.argsort(segment_ids, kind="stable")
        sorted_ids = segment_ids[order]
        bounds = np.searchsorted(sorted_ids, np.arange(num_segments + 1))
        for s in range(num_segments):
            rows = order[bounds[s]: bounds[s + 1]]
            bf = BloomFilter.create(self.expected_distinct, self.fpp)
            if len(rows):
                bf.add_words(self._canonical_words(values.take(rows)))
            blooms.append(json.dumps(bf.to_dict()))
        return {self.output_columns()[0]: Column.from_values(blooms)}

    def _decoded_filters(self, batch: ColumnBatch) -> list[BloomFilter]:
        """Per-file filters, decoded once per sketch-table batch (cached on
        the batch: json+base64 decode is the hot cost of repeated planning)."""
        import json

        cache = batch.__dict__.setdefault("_bloom_cache", {})
        name = self.output_columns()[0]
        filters = cache.get(name)
        if filters is None:
            filters = [
                BloomFilter.from_dict(json.loads(blob))
                for blob in batch.column(name).decode()
            ]
            cache[name] = filters
        return filters

    def _probe(self, batch: ColumnBatch, values: list[Any]) -> np.ndarray:
        probe_col = Column.from_values(values)
        words = self._canonical_words(probe_col)
        filters = self._decoded_filters(batch)
        out = np.zeros(len(filters), dtype=bool)
        for i, bf in enumerate(filters):
            out[i] = bool(bf.might_contain_words(words).any())
        return out

    def convert_predicate(self, pred: Expr) -> Optional[SketchPredicate]:
        m = _is_col_lit(pred, self._expr)
        if m is not None and m[0] is X.Eq:
            v = m[1]
            return lambda b: self._probe(b, [v])
        if (
            isinstance(pred, X.In)
            and isinstance(pred.child, X.Col)
            and pred.child.name.lower() == self._expr.lower()
        ):
            values = list(pred.values)
            return lambda b: self._probe(b, values)
        return None

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "expr": self._expr,
            "expectedDistinctCountPerFile": self.expected_distinct,
            "fpp": self.fpp,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BloomFilterSketch":
        return cls(d["expr"], d.get("expectedDistinctCountPerFile", 10000), d.get("fpp", 0.01))

    def __repr__(self):
        return f"BloomFilter({self._expr})"


class ValueListSketch(Sketch):
    """Distinct values per file — exact membership skipping for
    low-cardinality columns (the reference roadmap's ValueListSketch;
    complements MinMax for sparse domains)."""

    kind = "ValueListSketch"
    MAX_VALUES = 256

    def __init__(self, expr: str):
        self._expr = expr

    @property
    def expr(self) -> str:
        return self._expr

    def output_columns(self) -> list[str]:
        return [f"{self._expr}__values"]

    def aggregate(self, values, segment_ids, num_segments):
        import json

        decoded = values.decode() if values.dtype == STRING else values.data
        # one argsort, then contiguous per-segment slices (O(N log N) instead
        # of a full-array scan per file)
        order = np.argsort(segment_ids, kind="stable")
        sorted_ids = segment_ids[order]
        bounds = np.searchsorted(sorted_ids, np.arange(num_segments + 1))
        out = []
        for s in range(num_segments):
            vals = decoded[order[bounds[s]: bounds[s + 1]]]
            uniq = np.unique(np.asarray(vals, dtype=object).astype(str) if values.dtype == STRING else vals)
            if len(uniq) > self.MAX_VALUES:
                out.append("")  # too many: sketch is unbounded for this file
            else:
                out.append(json.dumps([v.item() if hasattr(v, "item") else v for v in uniq]))
        return {self.output_columns()[0]: Column.from_values(out)}

    def convert_predicate(self, pred: Expr) -> Optional[SketchPredicate]:
        import json

        name = self.output_columns()[0]

        def match(b: ColumnBatch, values: list) -> np.ndarray:
            col = b.column(name).decode()
            out = np.ones(len(col), dtype=bool)
            for i, blob in enumerate(col):
                if not blob:
                    continue  # unbounded file: cannot skip
                file_vals = set(json.loads(blob))
                out[i] = any(v in file_vals for v in values)
            return out

        m = _is_col_lit(pred, self._expr)
        if m is not None and m[0] is X.Eq:
            return lambda b: match(b, [m[1]])
        if (
            isinstance(pred, X.In)
            and isinstance(pred.child, X.Col)
            and pred.child.name.lower() == self._expr.lower()
        ):
            return lambda b: match(b, list(pred.values))
        return None

    def to_dict(self) -> dict:
        return {"kind": self.kind, "expr": self._expr}

    @classmethod
    def from_dict(cls, d: dict) -> "ValueListSketch":
        return cls(d["expr"])

    def __repr__(self):
        return f"ValueList({self._expr})"


class PartitionSketch(Sketch):
    """Per-file partition value (constant within a file) — auto-added for
    partitioned sources so disjunctions over partition + indexed columns
    still skip (ref: PartitionSketch.scala:38-74, agg FirstNullSafe)."""

    kind = "PartitionSketch"

    def __init__(self, expr: str):
        self._expr = expr

    @property
    def expr(self) -> str:
        return self._expr

    def output_columns(self) -> list[str]:
        return [f"{self._expr}__part"]

    def aggregate(self, values, segment_ids, num_segments):
        # first value per segment (constant per file for partition columns);
        # empty segments yield NULL rather than stealing a neighbor's value
        from ...columnar.table import Column

        if len(values) == 0:
            # every file empty: all-null sketch values
            data = np.zeros(num_segments, dtype=values.data.dtype)
            return {
                self.output_columns()[0]: Column(
                    data, values.dtype, np.zeros(num_segments, bool), values.dictionary
                )
            }
        order = np.argsort(segment_ids, kind="stable")
        sorted_ids = segment_ids[order]
        bounds = np.searchsorted(sorted_ids, np.arange(num_segments + 1))
        non_empty = bounds[1:] > bounds[:-1]
        idx = np.where(non_empty, np.clip(bounds[:-1], 0, len(order) - 1), 0)
        firsts = values.take(order[idx])
        if not non_empty.all():
            firsts = Column(firsts.data, firsts.dtype, non_empty, firsts.dictionary)
        return {self.output_columns()[0]: firsts}

    def convert_predicate(self, pred: Expr) -> Optional[SketchPredicate]:
        name = self.output_columns()[0]

        def vals(b: ColumnBatch):
            c = b.column(name)
            if c.dtype == STRING:
                return np.asarray(c.decode(), dtype=object).astype(str)
            return c.data

        m = _is_col_lit(pred, self._expr)
        if m is not None:
            op, v = m
            fns = {
                X.Eq: lambda a: a == v,
                X.Ne: lambda a: a != v,
                X.Lt: lambda a: a < v,
                X.Le: lambda a: a <= v,
                X.Gt: lambda a: a > v,
                X.Ge: lambda a: a >= v,
            }
            f = fns.get(op)
            if f is not None:
                return lambda b: np.asarray(f(vals(b)), dtype=bool)
        if (
            isinstance(pred, X.In)
            and isinstance(pred.child, X.Col)
            and pred.child.name.lower() == self._expr.lower()
        ):
            values = list(pred.values)
            return lambda b: np.isin(vals(b), np.asarray(values))
        return None

    def to_dict(self) -> dict:
        return {"kind": self.kind, "expr": self._expr}

    @classmethod
    def from_dict(cls, d: dict) -> "PartitionSketch":
        return cls(d["expr"])

    def __repr__(self):
        return f"Partition({self._expr})"


class ZRegionSketch(Sketch):
    """Per-segment bounding box over SEVERAL columns — the value-space
    z-region of a row group. Covering-index buckets sort by the key
    columns, so columns correlated with the sort order (ingest time,
    monotone ids, derived dimensions) cluster into narrow per-row-group
    boxes; a multi-column range conjunction keeps a group only when the
    query hyper-rectangle intersects its box. Numeric/date columns only
    (string regions would be vocab-dependent)."""

    kind = "ZRegionSketch"

    def __init__(self, exprs: Sequence[str]):
        if not exprs:
            raise HyperspaceError("ZRegionSketch requires at least one column")
        self._exprs = [str(e) for e in exprs]

    @property
    def expr(self) -> str:
        return ",".join(self._exprs)

    def indexed_columns(self) -> list[str]:
        return list(self._exprs)

    def referenced_columns(self) -> list[str]:
        return list(self._exprs)

    def output_columns(self) -> list[str]:
        out = []
        for c in self._exprs:
            out += [f"{c}__rlo", f"{c}__rhi"]
        return out

    def aggregate(self, values, segment_ids, num_segments):
        raise HyperspaceError(
            "ZRegionSketch aggregates whole batches (aggregate_batch); it is "
            "not usable as a single-column DataSkippingIndex sketch"
        )

    def aggregate_batch(self, batch, segment_ids, num_segments):
        out: dict[str, Column] = {}
        for c in self._exprs:
            col = batch.column(c)
            if col.dtype == STRING:
                raise HyperspaceError(
                    f"ZRegionSketch column {c!r} is a string column"
                )
            # null rows carry the storage fill value; including it can only
            # WIDEN the box (extra keeps, never a false drop)
            mins, maxs = segment_min_max_np(col.data, segment_ids, num_segments)
            out[f"{c}__rlo"] = Column(mins, col.dtype)
            out[f"{c}__rhi"] = Column(maxs, col.dtype)
        return out

    def convert_predicate(self, pred: Expr) -> Optional[SketchPredicate]:
        for c in self._exprs:
            lo_name, hi_name = f"{c}__rlo", f"{c}__rhi"

            def cols(batch, lo_name=lo_name, hi_name=hi_name):
                return batch.column(lo_name).data, batch.column(hi_name).data

            m = _is_col_lit(pred, c)
            if m is not None:
                op, v = m
                if isinstance(v, str):
                    return None  # string literal vs numeric box: cannot bound
                if op is X.Eq:
                    return lambda b, v=v, cols=cols: (
                        lambda lo, hi: (lo <= v) & (hi >= v)
                    )(*cols(b))
                if op is X.Lt:
                    return lambda b, v=v, cols=cols: cols(b)[0] < v
                if op is X.Le:
                    return lambda b, v=v, cols=cols: cols(b)[0] <= v
                if op is X.Gt:
                    return lambda b, v=v, cols=cols: cols(b)[1] > v
                if op is X.Ge:
                    return lambda b, v=v, cols=cols: cols(b)[1] >= v
                return None
            if (
                isinstance(pred, X.In)
                and isinstance(pred.child, X.Col)
                and pred.child.name.lower() == c.lower()
            ):
                if any(isinstance(v, str) for v in pred.values):
                    return None
                values = sorted(pred.values)

                def in_mask(b, values=values, cols=cols):
                    lo, hi = cols(b)
                    arr = np.asarray(values)
                    idx = np.searchsorted(arr, lo, side="left")
                    idx = np.clip(idx, 0, len(arr) - 1)
                    return (arr[idx] >= lo) & (arr[idx] <= hi)

                return in_mask
        return None

    def to_dict(self) -> dict:
        return {"kind": self.kind, "exprs": list(self._exprs)}

    @classmethod
    def from_dict(cls, d: dict) -> "ZRegionSketch":
        return cls(d["exprs"])

    def __repr__(self):
        return f"ZRegion({self.expr})"


register_sketch(MinMaxSketch.kind, MinMaxSketch.from_dict)
register_sketch(ZRegionSketch.kind, ZRegionSketch.from_dict)
register_sketch(BloomFilterSketch.kind, BloomFilterSketch.from_dict)
register_sketch(ValueListSketch.kind, ValueListSketch.from_dict)
register_sketch(PartitionSketch.kind, PartitionSketch.from_dict)


def sketch_from_dict(d: dict) -> Sketch:
    loader = SKETCH_REGISTRY.get(d.get("kind"))
    if loader is None:
        raise HyperspaceError(f"Unknown sketch kind: {d.get('kind')!r}")
    return loader(d)
