"""DataSkippingIndex — kind "DS".

Reference parity: index/dataskipping/DataSkippingIndex.scala:44-336 —
createIndexData :291-317 (groupBy(input_file_name()).agg(sketch aggs) +
file-id join), translateFilterCondition :143-185 (NNF walk, per-sketch
convertPredicate, And/Or composition with constant folding), writeImpl
:187-206, refreshIncremental :79-110 (sketch appended files, anti-join
deleted ids), DataSkippingIndexConfig.scala:39-95.

The sketch table is tiny (one row per source file); it stays host-resident
and prunes the file list before anything reaches HBM.
"""

from __future__ import annotations

import os
from typing import TYPE_CHECKING, Optional, Sequence

import numpy as np

from ..base import Index, IndexConfig, IndexerContext, UpdateMode, register_index_kind
from ... import constants as C
from ...columnar import io as cio
from ...columnar.table import Column, ColumnBatch
from ...exceptions import HyperspaceError
from ...meta.entry import FileInfo
from ...plan import expr as X
from ...plan.expr import Expr, to_nnf
from .sketches import Sketch, SketchPredicate, sketch_from_dict

if TYPE_CHECKING:
    from ...plan.dataframe import DataFrame

FILE_ID_COLUMN = C.DATA_FILE_NAME_ID


class DataSkippingIndex(Index):
    kind = "DS"
    kind_abbr = "DS"

    def __init__(self, sketches: Sequence[Sketch], properties: dict[str, str] | None = None):
        if not sketches:
            raise HyperspaceError("DataSkippingIndex requires at least one sketch")
        self.sketches = list(sketches)
        self._properties = dict(properties or {})

    # --- metadata ---
    def indexed_columns(self) -> list[str]:
        out = []
        for s in self.sketches:
            out.extend(s.indexed_columns())
        return sorted(set(out))

    def referenced_columns(self) -> list[str]:
        out = []
        for s in self.sketches:
            out.extend(s.referenced_columns())
        return sorted(set(out))

    def properties(self) -> dict[str, str]:
        return dict(self._properties)

    def statistics(self) -> dict[str, object]:
        return {"sketches": [repr(s) for s in self.sketches]}

    def can_handle_deleted_files(self) -> bool:
        return True  # rows are keyed by file id; deletes drop rows

    # --- build ---
    @staticmethod
    def build_sketch_table(
        ctx: IndexerContext, df: "DataFrame", sketches: Sequence[Sketch]
    ) -> ColumnBatch:
        """Per-file segment reduce (the analogue of
        groupBy(input_file_name()).agg(...) :291-317)."""
        from ..covering import _single_file_scan
        from ...plan.dataframe import DataFrame as DF

        from ..covering import read_source_files_parallel

        scan = _single_file_scan(df)
        needed = sorted({c for s in sketches for c in s.referenced_columns()})
        file_ids, parts = read_source_files_parallel(ctx, df, scan, needed)
        seg_ids = [
            np.full(b.num_rows, seg, dtype=np.int64) for seg, b in enumerate(parts)
        ]
        all_rows = ColumnBatch.concat(parts)
        segments = np.concatenate(seg_ids) if seg_ids else np.empty(0, np.int64)
        num_files = len(scan.files)

        cols: dict[str, Column] = {
            FILE_ID_COLUMN: Column(np.asarray(file_ids, dtype=np.int64), "int64")
        }
        for sketch in sketches:
            values = all_rows.column(sketch.expr)
            cols.update(sketch.aggregate(values, segments, num_files))
        return ColumnBatch(cols)

    def write(self, ctx: IndexerContext, index_data: ColumnBatch) -> None:
        cio.write_parquet(
            index_data,
            os.path.join(ctx.index_data_path, "sketches-0.parquet"),
            compression=ctx.session.conf.index_compression,
            keep_dictionary=True,  # engine-owned: skip the plain-string cast
        )

    # --- refresh ---
    def refresh_incremental(
        self,
        ctx: IndexerContext,
        appended_df: "DataFrame | None",
        deleted_files: list[FileInfo],
        index_content_files: list[FileInfo],
    ) -> tuple["DataSkippingIndex", UpdateMode]:
        old = cio.read_parquet([f.name for f in index_content_files])
        parts = []
        if deleted_files:
            deleted_ids = np.asarray([f.id for f in deleted_files], dtype=np.int64)
            keep = ~np.isin(old.column(FILE_ID_COLUMN).data, deleted_ids)
            parts.append(old.filter(keep))
        else:
            parts.append(old)
        if appended_df is not None:
            parts.append(
                DataSkippingIndex.build_sketch_table(ctx, appended_df, self.sketches)
            )
        merged = ColumnBatch.concat([p.select(parts[0].schema.names) for p in parts])
        new_index = DataSkippingIndex(self.sketches, self._properties)
        new_index.write(ctx, merged)
        return new_index, UpdateMode.OVERWRITE

    def refresh_full(
        self, ctx: IndexerContext, df: "DataFrame"
    ) -> tuple["DataSkippingIndex", ColumnBatch]:
        return (
            DataSkippingIndex(self.sketches, self._properties),
            DataSkippingIndex.build_sketch_table(ctx, df, self.sketches),
        )

    # --- query-time translation (ref: translateFilterCondition :143-185) ---
    def translate_filter(self, condition: Expr) -> Optional[SketchPredicate]:
        """Predicate -> keep-mask closure over the sketch table; None if no
        part of the condition can be bounded."""
        return _translate(to_nnf(condition), self.sketches)

    # --- serialization ---
    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "properties": {
                "sketches": [s.to_dict() for s in self.sketches],
                "properties": self._properties,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DataSkippingIndex":
        p = d["properties"]
        return cls(
            [sketch_from_dict(s) for s in p["sketches"]], p.get("properties", {})
        )


register_index_kind(DataSkippingIndex.kind, DataSkippingIndex.from_dict)


def _translate(pred: Expr, sketches: Sequence[Sketch]) -> Optional[SketchPredicate]:
    """NNF tree recursion with And/Or composition and constant folding
    (unknown And-branch folds to the known side; unknown Or-branch makes the
    whole Or unknown — ref :154-177)."""
    if isinstance(pred, X.And):
        left = _translate(pred.left, sketches)
        right = _translate(pred.right, sketches)
        if left is None:
            return right
        if right is None:
            return left
        return lambda b: left(b) & right(b)
    if isinstance(pred, X.Or):
        left = _translate(pred.left, sketches)
        right = _translate(pred.right, sketches)
        if left is None or right is None:
            return None
        return lambda b: left(b) | right(b)
    for sketch in sketches:
        converted = sketch.convert_predicate(pred)
        if converted is not None:
            return converted
    return None


class DataSkippingIndexConfig(IndexConfig):
    """ref: DataSkippingIndexConfig.scala:39-95 (duplicate-sketch check;
    auto partition sketch arrives with partitioned sources)."""

    def __init__(self, index_name: str, sketches: Sequence[Sketch]):
        if not index_name:
            raise HyperspaceError("Index name must not be empty")
        if not sketches:
            raise HyperspaceError("At least one sketch is required")
        seen = set()
        for s in sketches:
            key = (s.kind, s.expr.lower())
            if key in seen:
                raise HyperspaceError(f"Duplicate sketch: {s!r}")
            seen.add(key)
        self._name = index_name
        self.sketches = list(sketches)

    @property
    def index_name(self) -> str:
        return self._name

    def referenced_columns(self) -> list[str]:
        out = []
        for s in self.sketches:
            out.extend(s.referenced_columns())
        return sorted(set(out))

    def create_index(
        self, ctx: IndexerContext, df: "DataFrame", properties: dict[str, str]
    ) -> tuple[DataSkippingIndex, ColumnBatch]:
        from ..covering import resolve_columns, _single_file_scan
        from .sketches import PartitionSketch

        resolve_columns(df.schema, self.referenced_columns())
        sketches = list(self.sketches)
        # auto partition sketch for partitioned sources (ref:
        # DataSkippingIndexConfig.createIndex:56-70)
        if ctx.session.conf.dataskipping_auto_partition_sketch:
            scan = _single_file_scan(df)
            have = {(s.kind, s.expr.lower()) for s in sketches}
            for pcol in scan.partition_columns:
                if (PartitionSketch.kind, pcol.lower()) not in have:
                    sketches.append(PartitionSketch(pcol))
        index = DataSkippingIndex(sketches, properties)
        data = DataSkippingIndex.build_sketch_table(ctx, df, sketches)
        return index, data
