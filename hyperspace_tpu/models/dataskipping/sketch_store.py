"""Per-row-group sketch store for covering indexes.

PR-4 row-group skipping evaluates parquet footer min/max statistics, which
only bound predicates on the SORT columns — an Eq/In on any other column
reads every row group. This module generalizes it along the "Extensible
Data Skipping" blueprint: a pluggable registry of per-row-group sketches
(bloom filters for high-NDV equality/IN, exact value lists for low-NDV
columns, value-space z-region boxes for multi-column ranges) written as a
**sidecar** next to every parquet index data file:

    v__=3/part-0-b00001.parquet
    v__=3/_sketch.part-0-b00001.parquet.json   <- this module

The underscore prefix keeps sidecars out of every index content listing
(``actions/create.content_of_version_dir`` filters ``_``/``.`` basenames),
so they are invisible to scans, the plan verifier's content check, vacuum
refcounts, and the chaos gate's debris audit — they live and die with
their version directory.

Lifecycle: every engine write path that produces a parquet index data
file (``models/covering.write_bucketed`` — creates, streaming builds,
``Index.ingest_delta`` delta runs — plus ``CoveringIndex.optimize``'s
compaction rewrite and the incremental-refresh lineage rewrite) calls
:func:`maybe_write_sidecar` with the exact batch and ``row_group_size``
it handed the parquet writer, so the per-group sketch segments match the
physical row groups one to one. A compaction re-sorts runs into new row
groups, so its "merge" of the input runs' sketches is a rebuild over the
merged batch — exact by construction. Skipping therefore keeps working on
a live, appending index: a fresh delta run carries its own sidecar from
the moment it is published.

Soundness: a sketch may only vote **definite miss** — a file with no
sidecar, a sidecar missing the needed sketch, a stale sidecar (row-group
count or data size drift), or an unreadable sidecar keeps every group.
Bloom false positives keep extra groups (slow, never wrong);
``HYPERSPACE_PRUNE=verify`` re-reads the full file set and raises on any
post-filter divergence, which is exactly how a corrupted sidecar
surfaces.

Everything is gated on ``HYPERSPACE_SKETCHES`` (default off: zero
sidecars, zero prune-path changes, bit-identical engine). Decoded
sidecars are cached in a byte-bounded LRU (``cache.sketch.*``,
``HYPERSPACE_SKETCH_CACHE_MB``) following the footer-stats cache
discipline — repeat point lookups cost a dict hit, not a JSON+base64
decode per query.
"""

from __future__ import annotations

import json
import os
from typing import TYPE_CHECKING, Optional, Sequence

import numpy as np

from ... import constants as C
from ...columnar import io as cio
from ...columnar.table import Column, ColumnBatch, STRING, numpy_dtype
from ...utils import env
from .sketches import (
    BloomFilterSketch,
    Sketch,
    ValueListSketch,
    ZRegionSketch,
    sketch_from_dict,
)

if TYPE_CHECKING:
    from ...columnar.table import Schema

SIDECAR_PREFIX = "_sketch."
SIDECAR_SUFFIX = ".json"
SIDECAR_VERSION = 1

# per-file NDV at or below which the exact value list replaces the bloom
# filter (ValueListSketch.MAX_VALUES is the per-GROUP bound it degrades at)
VALUELIST_NDV_MAX = 256

_ALL_KINDS = ("bloom", "valuelist", "zregion")


def sketches_enabled() -> bool:
    return bool(enabled_kinds())


def enabled_kinds() -> frozenset:
    """Kinds enabled by ``HYPERSPACE_SKETCHES``: unset/"0"/"off" disables
    everything (the default — the engine is bit-identical to pre-sketch),
    "1"/"all" enables every kind, otherwise a comma list drawn from
    bloom/valuelist/zregion (unknown names are ignored, not fatal — a
    typo'd kind must not take down planning)."""
    raw = (env.env_str("HYPERSPACE_SKETCHES") or "").strip().lower()
    if raw in ("", "0", "false", "off"):
        return frozenset()
    if raw in ("1", "all", "true", "on"):
        return frozenset(_ALL_KINDS)
    return frozenset(k.strip() for k in raw.split(",")) & frozenset(_ALL_KINDS)


def bloom_fpp() -> float:
    return env.env_float("HYPERSPACE_SKETCH_BLOOM_FPP")


def bloom_ndv_cap() -> int:
    return env.env_int("HYPERSPACE_SKETCH_BLOOM_NDV")


def sidecar_path(data_path: str) -> str:
    d, base = os.path.split(data_path)
    return os.path.join(d, f"{SIDECAR_PREFIX}{base}{SIDECAR_SUFFIX}")


def eligible_columns(schema: "Schema", key_columns: Sequence[str]) -> list[str]:
    """Columns a sketch may summarize: everything except the bucket-key /
    sort columns (footer min/max already bounds those) and the lineage id
    (an internal bookkeeping column no user predicate references)."""
    keys = {c.lower() for c in key_columns}
    return [
        f.name
        for f in schema
        if f.name.lower() not in keys and f.name != C.DATA_FILE_NAME_ID
    ]


def declared_capability(
    schema: "Schema", key_columns: Sequence[str]
) -> tuple:
    """The (kind, columns) pairs this layout MAY carry sketches for under
    the current config — the upper bound the planner and the plan verifier
    share. Deterministic in (schema, keys, env): the plan-time decision to
    route a conjunct through the sketch path must re-derive identically
    inside the verifier. Per-file sidecars hold a SUBSET (e.g. the
    bloom-vs-valuelist choice is per-file NDV-driven); a file missing a
    declared sketch simply keeps all its groups."""
    kinds = enabled_kinds()
    if not kinds:
        return ()
    cols = eligible_columns(schema, key_columns)
    if not cols:
        return ()
    cap = []
    for c in cols:
        if "bloom" in kinds:
            cap.append(("bloom", (c,)))
        if "valuelist" in kinds:
            cap.append(("valuelist", (c,)))
    if "zregion" in kinds:
        numeric = [
            c for c in cols if schema.field(c).dtype != STRING
        ]
        if numeric:
            cap.append(("zregion", tuple(numeric)))
    return tuple(cap)


def capability_sketches(capability: Sequence) -> list[Sketch]:
    """Sketch instances for a declared capability — used for plan-time
    convertibility checks and the verifier's re-derivation. Bloom params
    do not affect convertibility, so defaults are fine here."""
    out: list[Sketch] = []
    for kind, cols in capability:
        if kind == "bloom":
            out.append(BloomFilterSketch(cols[0]))
        elif kind == "valuelist":
            out.append(ValueListSketch(cols[0]))
        elif kind == "zregion":
            out.append(ZRegionSketch(list(cols)))
    return out


def convertible(sketches: Sequence[Sketch], pred) -> bool:
    """True when any sketch can bound ``pred`` (single-node contract)."""
    for s in sketches:
        try:
            if s.convert_predicate(pred) is not None:
                return True
        except Exception:
            continue
    return False


def condition_sketchable(condition, schema: "Schema",
                         key_columns: Sequence[str]) -> bool:
    """True when at least one conjunct of ``condition`` is boundable by a
    declared sketch — FilterColumnFilter's relaxed admission: with
    sketches enabled, a covering index can serve a filter that never
    touches the leading indexed column, because the sidecar sketches skip
    row groups on the non-sort columns instead."""
    if condition is None or not sketches_enabled():
        return False
    capability = declared_capability(schema, key_columns)
    if not capability:
        return False
    from ...plan.expr import split_conjunction

    sketches = capability_sketches(capability)
    return any(convertible(sketches, c) for c in split_conjunction(condition))


# ---------------------------------------------------------------------------
# build + write (the index write paths' hook)
# ---------------------------------------------------------------------------

def _column_ndv(col: Column) -> int:
    """Exact distinct count (dictionary codes for strings — in-repo
    constructors guarantee unique vocabs, so codes biject onto values)."""
    if len(col) == 0:
        return 0
    return int(len(np.unique(col.data)))


def _column_to_json(col: Column) -> dict:
    if col.dtype == STRING:
        return {
            "dtype": STRING,
            "values": [str(v) for v in np.asarray(col.decode(), dtype=object)],
        }
    return {"dtype": col.dtype, "values": col.data.tolist()}


def _column_from_json(d: dict) -> Column:
    if d["dtype"] == STRING:
        return Column.from_values([str(v) for v in d["values"]])
    return Column(
        np.asarray(d["values"], dtype=numpy_dtype(d["dtype"])), d["dtype"]
    )


def plan_sketches(
    batch: ColumnBatch, key_columns: Sequence[str],
    row_group_size: int = 1 << 30,
) -> list[Sketch]:
    """The sketch set for one data file, from the enabled kinds and the
    batch's own NDV profile: low-NDV columns get the exact value list,
    high-NDV columns the bloom filter (sized for the per-row-group
    expected distinct count — a group holds at most ``row_group_size``
    distinct values — capped by ``HYPERSPACE_SKETCH_BLOOM_NDV``), and
    the numeric non-key columns share one z-region box sketch."""
    kinds = enabled_kinds()
    if not kinds:
        return []
    cols = eligible_columns(batch.schema, key_columns)
    out: list[Sketch] = []
    zregion_cols: list[str] = []
    for c in cols:
        col = batch.column(c)
        ndv = _column_ndv(col)
        if "valuelist" in kinds and 0 < ndv <= VALUELIST_NDV_MAX:
            out.append(ValueListSketch(c))
        elif "bloom" in kinds and ndv > 0:
            expected = max(16, min(ndv, row_group_size, bloom_ndv_cap()))
            out.append(BloomFilterSketch(c, expected, bloom_fpp()))
        if "zregion" in kinds and col.dtype != STRING:
            zregion_cols.append(c)
    if zregion_cols:
        out.append(ZRegionSketch(zregion_cols))
    return out


def build_sidecar(
    batch: ColumnBatch, row_group_size: int, key_columns: Sequence[str]
) -> Optional[dict]:
    """The serialized per-row-group sketch table for one data file about to
    be written with ``row_group_size`` (pyarrow slices the table into
    consecutive groups of exactly that many rows, so segment ids are
    ``row // row_group_size``). None when nothing is enabled/eligible."""
    n = batch.num_rows
    if n == 0 or row_group_size <= 0:
        return None
    sketches = plan_sketches(batch, key_columns, row_group_size)
    if not sketches:
        return None
    num_groups = (n + row_group_size - 1) // row_group_size
    segment_ids = np.arange(n, dtype=np.int64) // row_group_size
    columns: dict[str, dict] = {}
    built: list[dict] = []
    for s in sketches:
        try:
            aggs = s.aggregate_batch(batch, segment_ids, num_groups)
        except Exception:
            continue  # an unbuildable sketch costs coverage, never the write
        for name, col in aggs.items():
            columns[name] = _column_to_json(col)
        built.append(s.to_dict())
    if not built:
        return None
    ndv = {
        c: _column_ndv(batch.column(c))
        for c in eligible_columns(batch.schema, key_columns)
    }
    return {
        "version": SIDECAR_VERSION,
        "num_row_groups": int(num_groups),
        "row_group_size": int(row_group_size),
        "data_rows": int(n),
        "ndv": ndv,
        "sketches": built,
        "columns": columns,
    }


def maybe_write_sidecar(
    batch: ColumnBatch,
    data_path: str,
    row_group_size: int,
    key_columns: Sequence[str],
) -> bool:
    """Write the sketch sidecar for a just-written parquet index data
    file. No-op (one env read) when sketches are disabled, the file is not
    parquet (arrow IPC has no row groups), or nothing is eligible.
    Returns True when a sidecar was written."""
    if not sketches_enabled() or not data_path.endswith(".parquet"):
        return False
    side = build_sidecar(batch, row_group_size, key_columns)
    if side is None:
        return False
    # stamp the data file's size so a rewrite that skips the sidecar can
    # never be interpreted through stale sketches
    try:
        side["data_size"] = os.path.getsize(data_path)
    except OSError:
        return False
    with open(sidecar_path(data_path), "w", encoding="utf-8") as f:
        json.dump(side, f)
    from ...telemetry.metrics import REGISTRY

    REGISTRY.counter("pruning.sketch.sidecars_written").inc()
    REGISTRY.counter("pruning.sketch.sketches_built").inc(
        len(side["sketches"])
    )
    from ...telemetry import workload

    workload.charge_sketch_write()
    return True


# ---------------------------------------------------------------------------
# load + evaluate (the exec-time prune path)
# ---------------------------------------------------------------------------

class Sidecar:
    """One decoded sidecar: the sketch instances plus their per-row-group
    table (one row per group). Cached whole, so bloom bitsets decode once
    per (file, cache lifetime), not once per query."""

    __slots__ = ("sketches", "batch", "num_row_groups", "ndv",
                 "row_group_size", "data_size", "nbytes")

    def __init__(self, sketches: list[Sketch], batch: ColumnBatch,
                 num_row_groups: int, ndv: dict, row_group_size: int,
                 data_size: int, nbytes: int):
        self.sketches = sketches
        self.batch = batch
        self.num_row_groups = num_row_groups
        self.ndv = ndv
        self.row_group_size = row_group_size
        self.data_size = data_size  # data file size stamped at write time
        self.nbytes = nbytes

    def keep_mask(self, conjuncts: Sequence) -> Optional[np.ndarray]:
        """AND of every conjunct's sketch vote over this file's groups;
        None when no conjunct is evaluable here (caller keeps the file).
        A conjunct with no matching sketch contributes keep-all — a
        missing sketch narrows coverage, never correctness."""
        mask = None
        for pred in conjuncts:
            fn = None
            for s in self.sketches:
                try:
                    fn = s.convert_predicate(pred)
                except Exception:
                    fn = None
                if fn is not None:
                    break
            if fn is None:
                continue
            try:
                vote = np.asarray(fn(self.batch), dtype=bool)
            except Exception:
                continue  # an unevaluable sketch keeps every group
            if vote.shape != (self.num_row_groups,):
                continue
            mask = vote if mask is None else (mask & vote)
        return mask


_SIDECAR_CACHE = cio._BytesBoundedLRU(
    env.env_int("HYPERSPACE_SKETCH_CACHE_MB") * 1024 * 1024,
    metric_name="sketch",
)


def _decode_sidecar(raw: dict, nbytes: int) -> Optional[Sidecar]:
    try:
        if raw.get("version") != SIDECAR_VERSION:
            return None
        sketches = [sketch_from_dict(d) for d in raw["sketches"]]
        batch = ColumnBatch(
            {name: _column_from_json(d) for name, d in raw["columns"].items()}
        )
        n = int(raw["num_row_groups"])
        if batch.num_rows != n:
            return None
        return Sidecar(
            sketches, batch, n, dict(raw.get("ndv", {})),
            int(raw.get("row_group_size", 0)),
            int(raw.get("data_size", -1)), nbytes,
        )
    except Exception:
        return None  # malformed sidecar == no sidecar (keep everything)


def load_sidecar(data_path: str) -> Optional[Sidecar]:
    """The decoded sidecar for an index data file, or None when absent,
    unreadable, malformed, or stale (recorded data size no longer matches
    the file — e.g. a rewrite that bypassed the sketch hook). Served from
    the bounded ``cache.sketch`` LRU keyed by the sidecar's stat identity."""
    spath = sidecar_path(data_path)
    try:
        st = os.stat(spath)
    except OSError:
        return None
    key = (spath, st.st_mtime_ns, st.st_ino, st.st_size)

    def _parse():
        with open(spath, encoding="utf-8") as f:
            text = f.read()
        raw = json.loads(text)
        sc = _decode_sidecar(raw, len(text))
        if sc is None:
            raise _BadSidecar
        return sc, len(text)

    try:
        if _SIDECAR_CACHE.max_bytes > 0:
            sc = _SIDECAR_CACHE.get_or_put(key, _parse)
        else:
            sc = _parse()[0]
    except _BadSidecar:
        return None
    except Exception:
        return None  # unreadable sidecar == no sidecar
    try:
        data_size = os.path.getsize(data_path)
    except OSError:
        return None
    # staleness guard: the sidecar was stamped with the data file's size at
    # write time; drift means the data was rewritten without its sketches
    if sc.data_size >= 0 and sc.data_size != data_size:
        from ...telemetry.metrics import REGISTRY

        REGISTRY.counter("pruning.sketch.stale").inc()
        return None
    return sc


class _BadSidecar(Exception):
    """Sidecar parsed but failed validation — never cached as good."""


# ---------------------------------------------------------------------------
# planner/ranker support
# ---------------------------------------------------------------------------

def index_ndv_stats(entry) -> Optional[tuple[dict, int]]:
    """(per-column NDV map, rows per row group) sampled from the first
    content file that carries a sidecar — the dictionary/NDV statistics
    the FilterIndexRanker's scan-fraction estimate consumes. Bounded probe
    (first 8 parquet files) so a sketchless index costs 8 stats at most;
    hits ride the sidecar cache."""
    try:
        files = entry.content.file_infos()
    except Exception:
        return None
    probed = 0
    for f in files:
        if not f.name.endswith(".parquet"):
            continue
        sc = load_sidecar(f.name)
        if sc is not None and sc.ndv:
            return dict(sc.ndv), max(1, sc.row_group_size)
        probed += 1
        if probed >= 8:
            break
    return None
