"""ZOrderCoveringIndex — kind "ZCI".

Reference parity: index/zordercovering/ZOrderCoveringIndex.scala:32-190 —
covering index laid out along a z-order curve instead of hash buckets
(bucketSpec=None :40); stats collection per indexed column (:50-95,
min/max or approx quantiles); write = z-address column + range partition +
sort-within (:97-154); a single indexed column degenerates to a plain
range-partitioned sort (:104-113); partition count = source bytes /
targetSourceBytesPerPartition (default 1 GB).

TPU note: the z-address computation is the vectorized bit interleave in
ops/zorder (device variant available for <=32-bit addresses); the range
partition is a histogram split of the computed addresses.
"""

from __future__ import annotations

import os
from typing import TYPE_CHECKING, Sequence

import numpy as np

from ..base import Index, IndexConfig, IndexerContext, UpdateMode, register_index_kind, validate_column_names
from ..covering import CoveringIndex, index_write_opts, resolve_columns
from ... import constants as C
from ...columnar import io as cio
from ...columnar.table import Column, ColumnBatch, Schema
from ...exceptions import HyperspaceError
from ...meta.entry import FileInfo
from ...ops.zorder import interleave_bits
from .fields import ZOrderField, build_field

if TYPE_CHECKING:
    from ...plan.dataframe import DataFrame


class ZOrderCoveringIndex(Index):
    kind = "ZCI"
    kind_abbr = "ZCI"

    def __init__(
        self,
        indexed_columns: list[str],
        included_columns: list[str],
        schema: list[dict],
        fields: Sequence[ZOrderField],
        properties: dict[str, str] | None = None,
    ):
        self._indexed = list(indexed_columns)
        self._included = list(included_columns)
        self._schema = list(schema)
        self.fields = list(fields)
        self._properties = dict(properties or {})

    # --- metadata ---
    def indexed_columns(self) -> list[str]:
        return list(self._indexed)

    def included_columns(self) -> list[str]:
        return list(self._included)

    def referenced_columns(self) -> list[str]:
        return self._indexed + self._included

    def schema(self) -> Schema:
        return Schema.from_list(self._schema)

    def properties(self) -> dict[str, str]:
        return dict(self._properties)

    def has_lineage(self) -> bool:
        return self._properties.get("lineage", "false") == "true"

    def can_handle_deleted_files(self) -> bool:
        return self.has_lineage()

    def statistics(self) -> dict[str, object]:
        return {
            "zOrderFields": [f.to_dict() for f in self.fields],
            "includedColumns": ",".join(self._included),
        }

    # --- write path ---
    def write(self, ctx: IndexerContext, index_data: ColumnBatch) -> None:
        target_bytes = ctx.session.conf.zorder_target_source_bytes_per_partition
        write_zordered(
            index_data, ctx.index_data_path, self._indexed, self.fields,
            target_bytes, ext=cio.index_file_ext(ctx.session.conf.index_format),
            session=ctx.session,
        )

    def optimize(self, ctx: IndexerContext, files_to_optimize: list[FileInfo]) -> None:
        batch = cio.read_parquet([f.name for f in files_to_optimize])
        self.write(ctx, batch)

    def refresh_incremental(
        self,
        ctx: IndexerContext,
        appended_df: "DataFrame | None",
        deleted_files: list[FileInfo],
        index_content_files: list[FileInfo],
    ) -> tuple["ZOrderCoveringIndex", UpdateMode]:
        parts: list[ColumnBatch] = []
        if appended_df is not None:
            parts.append(
                CoveringIndex.create_index_data(
                    ctx, appended_df, self._indexed, self._included, self.has_lineage()
                )
            )
        if deleted_files:
            if not self.has_lineage():
                raise HyperspaceError(
                    "Index has no lineage column; cannot handle deleted source files"
                )
            deleted_ids = np.asarray([f.id for f in deleted_files], dtype=np.int64)
            old = cio.read_parquet([f.name for f in index_content_files])
            keep = ~np.isin(old.column(C.DATA_FILE_NAME_ID).data, deleted_ids)
            parts.append(old.filter(keep))
            mode = UpdateMode.OVERWRITE
        else:
            mode = UpdateMode.MERGE
        merged = ColumnBatch.concat([p.select(parts[0].schema.names) for p in parts])
        new_index = ZOrderCoveringIndex(
            self._indexed, self._included, self._schema, self.fields, self._properties
        )
        new_index.write(ctx, merged)
        return new_index, mode

    def refresh_full(
        self, ctx: IndexerContext, df: "DataFrame"
    ) -> tuple["ZOrderCoveringIndex", ColumnBatch]:
        data = CoveringIndex.create_index_data(
            ctx, df, self._indexed, self._included, self.has_lineage()
        )
        fields = [
            build_field(c, data.column(c), ctx.session.conf.zorder_quantile_enabled)
            for c in self._indexed
        ]
        return (
            ZOrderCoveringIndex(
                self._indexed, self._included, self._schema, fields, self._properties
            ),
            data,
        )

    # --- serialization ---
    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "properties": {
                "columns": {"indexed": self._indexed, "included": self._included},
                "schema": self._schema,
                "zOrderFields": [f.to_dict() for f in self.fields],
                "properties": self._properties,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ZOrderCoveringIndex":
        p = d["properties"]
        return cls(
            p["columns"]["indexed"],
            p["columns"]["included"],
            p["schema"],
            [ZOrderField.from_dict(f) for f in p["zOrderFields"]],
            p.get("properties", {}),
        )


register_index_kind(ZOrderCoveringIndex.kind, ZOrderCoveringIndex.from_dict)


def compute_zaddresses(
    batch: ColumnBatch, indexed: list[str], fields: Sequence[ZOrderField]
) -> np.ndarray:
    pairs = []
    by_name = {f.name: f for f in fields}
    for c in indexed:
        f = by_name[c]
        pairs.append((f.codes(batch.column(c)), f.nbits))
    return interleave_bits(pairs)


def write_zordered(
    batch: ColumnBatch,
    path: str,
    indexed: list[str],
    fields: Sequence[ZOrderField],
    target_bytes_per_partition: int,
    version: int = 0,
    ext: str = ".parquet",
    session=None,
) -> list[str]:
    """Sort rows by z-address (single column: plain range sort, ref :104-113)
    and split into roughly-equal partitions; one index data file each."""
    n = batch.num_rows
    if n == 0:
        os.makedirs(path, exist_ok=True)
        return []
    from ...ops.bucketize import stable_argsort

    if len(indexed) == 1:
        from ...columnar.table import sort_key_values

        order = stable_argsort(sort_key_values(batch.column(indexed[0]), True))
    else:
        z = compute_zaddresses(batch, indexed, fields)
        order = stable_argsort(z)
    sorted_batch = batch.take(order)
    # partition count from data size (ref: numPartitions = bytes/target)
    approx_bytes = sum(
        c.data.nbytes + (0 if c.dictionary is None else 64 * len(c.dictionary))
        for c in batch.columns.values()
    )
    num_parts = max(1, int(np.ceil(approx_bytes / max(1, target_bytes_per_partition))))
    num_parts = min(num_parts, n)
    from ...utils.workers import io_pool
    from ..covering import INDEX_ROW_GROUP_SIZE

    bounds = np.linspace(0, n, num_parts + 1).astype(np.int64)

    # z-ordering clusters every indexed field, so all of them keep stats
    write_opts = index_write_opts(session, indexed)

    def write_part(i: int) -> str | None:
        # zero-copy view: one full gather happened above; partition writes
        # must not re-copy the whole sorted batch a second time
        part = sorted_batch.slice(int(bounds[i]), int(bounds[i + 1]))
        if part.num_rows == 0:
            return None
        fname = f"part-{version}-z{i:05d}{ext}"
        cio.write_index_file(
            part,
            os.path.join(path, fname),
            row_group_size=INDEX_ROW_GROUP_SIZE,
            **write_opts,
        )
        return fname

    # concurrent partition writes (pyarrow releases the GIL), bounded so
    # in-flight partition copies stay under ~1 GB of extra memory
    per_part_bytes = max(1, approx_bytes // num_parts)
    mem_bound = max(1, (1 << 30) // per_part_bytes)
    with io_pool(min(8, num_parts, mem_bound), "hs-zorder") as pool:
        return [f for f in pool.map(write_part, range(num_parts)) if f]


def streaming_zorder_build(
    ctx: IndexerContext,
    df: "DataFrame",
    scan,
    indexed: list[str],
    included: list[str],
    lineage: bool,
    quantile_enabled: bool,
    target_bytes: int,
    limit: int,
    sample_rows: int = 200_000,
) -> tuple[list[ZOrderField], list[dict]] | None:
    """Bounded-memory z-order build, two passes over limit-sized file
    groups (the reference leans on Spark's repartitionByRange sampling +
    shuffle spill; ZOrderCoveringIndex.scala:97-154):

    pass 1 streams the groups to collect exact per-column extremes and a
    uniform row sample; fields build from the sample (extremes appended so
    min-max scaling is exact); range cut points come from sample z-address
    quantiles. pass 2 re-streams each group, assigns rows to z ranges, and
    appends one sorted run per (range, group) — files cover narrow z ranges,
    which is the layout contract the rule's pruning relies on.

    Returns (fields, schema_list); None when a string indexed column makes
    streaming inapplicable (caller materializes instead)."""
    from ...utils.workers import io_pool
    from ...columnar.table import STRING
    from ..covering import INDEX_ROW_GROUP_SIZE, _file_groups
    from ...plan.dataframe import DataFrame as DF

    groups = _file_groups(scan.files, limit)
    rng = np.random.default_rng(0)
    per_group = max(1, sample_rows // max(1, len(groups)))
    samples: dict[str, list[np.ndarray]] = {c: [] for c in indexed}
    schema_list: list[dict] | None = None

    def group_df(group):
        sub = df.plan.transform_up(
            lambda nd: nd.copy(files=group) if nd is scan else nd
        )
        return DF(ctx.session, sub)

    # ---- pass 1: extremes + sample (indexed columns only — included
    # columns are read once, in pass 2; partition count comes from SOURCE
    # bytes like the reference's numPartitions = sourceBytes/target) -------
    validity_samples: dict[str, list[np.ndarray]] = {c: [] for c in indexed}
    dtype_labels: dict[str, str] = {}
    for group in groups:
        data = CoveringIndex.create_index_data(
            ctx, group_df(group), indexed, [], lineage=False
        )
        if not dtype_labels:
            if any(data.column(c).dtype == STRING for c in indexed):
                return None
            dtype_labels = {c: data.schema.field(c).dtype for c in indexed}
        n = data.num_rows
        if n == 0:
            continue
        # the SAME sampled rows for every column (per-column null dropping
        # would produce ragged sample columns); nulls ride along as validity
        take = rng.choice(n, size=min(per_group, n), replace=False)
        for c in indexed:
            col = data.column(c)
            vals = col.data[take]
            vmask = (
                np.ones(len(take), dtype=bool)
                if col.validity is None
                else col.validity[take]
            )
            # exact extremes ride along so min-max scaling never clips
            valid_all = (
                col.data if col.validity is None else col.data[col.validity]
            )
            if len(valid_all):
                vals = np.concatenate(
                    [vals, [valid_all.min(), valid_all.max()]]
                )
                vmask = np.concatenate([vmask, [True, True]])
            samples[c].append(vals)
            validity_samples[c].append(vmask)

    # pass 1 used the indexed slice only; the index schema comes from the
    # first pass-2 group (which carries included columns + lineage)
    fields = []
    sample_cols = {}
    for c in indexed:
        if samples[c]:
            arr = np.concatenate(samples[c])
            vmask = np.concatenate(validity_samples[c])
        else:
            arr, vmask = np.zeros(1, np.int64), np.ones(1, dtype=bool)
        col = Column(
            arr,
            dtype_labels.get(c, str(arr.dtype)),
            None if vmask.all() else vmask,
        )
        sample_cols[c] = col
        fields.append(build_field(c, col, quantile_enabled))

    # ---- range cuts from sample z quantiles ------------------------------
    total_bytes = sum(f.size for f in scan.files)
    num_parts = max(1, int(np.ceil(total_bytes / max(1, target_bytes))))
    sample_batch = ColumnBatch(sample_cols)
    if len(indexed) == 1:
        z_sample = fields[0].codes(sample_cols[indexed[0]]).astype(np.uint64)
    else:
        z_sample = compute_zaddresses(sample_batch, indexed, fields)
    cuts = np.unique(
        np.quantile(
            z_sample.astype(np.float64),
            [i / num_parts for i in range(1, num_parts)],
        ).astype(np.uint64)
    ) if num_parts > 1 else np.empty(0, np.uint64)

    # ---- pass 2: assign, sort, append runs -------------------------------
    os.makedirs(ctx.index_data_path, exist_ok=True)
    for seq, group in enumerate(groups):
        data = CoveringIndex.create_index_data(
            ctx, group_df(group), indexed, included, lineage
        )
        if schema_list is None:
            schema_list = data.schema.to_list()
        if data.num_rows == 0:
            continue
        if len(indexed) == 1:
            z = fields[0].codes(data.column(indexed[0])).astype(np.uint64)
        else:
            z = compute_zaddresses(data, indexed, fields)
        part_ids = np.searchsorted(cuts, z, side="right")
        order = np.lexsort((z, part_ids))
        z_sorted = z[order]
        p_sorted = part_ids[order]
        bounds = np.searchsorted(p_sorted, np.arange(len(cuts) + 2))

        zext = cio.index_file_ext(ctx.session.conf.index_format)
        write_opts = index_write_opts(ctx.session, indexed)

        def write_run(p: int):
            rows = order[bounds[p]: bounds[p + 1]]
            if not len(rows):
                return
            part = data.take(rows)
            cio.write_index_file(
                part,
                os.path.join(
                    ctx.index_data_path, f"part-0-z{p:05d}-{seq}{zext}"
                ),
                row_group_size=INDEX_ROW_GROUP_SIZE,
                **write_opts,
            )

        with io_pool(8, "hs-zorder") as pool:
            list(pool.map(write_run, range(len(cuts) + 1)))
    return fields, schema_list or []


class ZOrderCoveringIndexConfig(IndexConfig):
    """ref: ZOrderCoveringIndexConfig (user API parity with the reference's
    python binding IndexConfig family)."""

    def __init__(
        self,
        index_name: str,
        indexed_columns: Sequence[str],
        included_columns: Sequence[str] = (),
    ):
        if not index_name:
            raise HyperspaceError("Index name must not be empty")
        self._name = index_name
        self._indexed = validate_column_names(indexed_columns, "indexed")
        self._included = validate_column_names(included_columns, "included")
        overlap = {c.lower() for c in self._indexed} & {c.lower() for c in self._included}
        if overlap:
            raise HyperspaceError(f"Columns in both indexed and included: {overlap}")

    @property
    def index_name(self) -> str:
        return self._name

    def referenced_columns(self) -> list[str]:
        return self._indexed + self._included

    def create_index(
        self, ctx: IndexerContext, df: "DataFrame", properties: dict[str, str]
    ) -> tuple[ZOrderCoveringIndex, "ColumnBatch | None"]:
        from ..covering import _single_file_scan

        indexed = resolve_columns(df.schema, self._indexed)
        included = resolve_columns(df.schema, self._included)
        lineage = properties.get("lineage", "false") == "true"
        scan = _single_file_scan(df)
        total_bytes = sum(f.size for f in scan.files)
        limit = ctx.session.conf.build_max_bytes_in_memory
        if total_bytes > limit and len(scan.files) > 1:
            out = streaming_zorder_build(
                ctx, df, scan, indexed, included, lineage,
                ctx.session.conf.zorder_quantile_enabled,
                ctx.session.conf.zorder_target_source_bytes_per_partition,
                limit,
            )
            if out is not None:
                fields, schema_list = out
                return (
                    ZOrderCoveringIndex(
                        indexed, included, schema_list, fields, properties
                    ),
                    None,
                )
        data = CoveringIndex.create_index_data(ctx, df, indexed, included, lineage)
        # stats collection over the built data (ref: collectStats :50-95)
        fields = [
            build_field(c, data.column(c), ctx.session.conf.zorder_quantile_enabled)
            for c in indexed
        ]
        index = ZOrderCoveringIndex(
            indexed, included, data.schema.to_list(), fields, properties
        )
        return index, data
