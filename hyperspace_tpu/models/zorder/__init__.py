from .index import ZOrderCoveringIndex, ZOrderCoveringIndexConfig
from .fields import MinMaxZOrderField, PercentileZOrderField, ZOrderField
from . import rule  # noqa: F401  (registers ZOrderFilterIndexRule)

__all__ = [
    "ZOrderCoveringIndex",
    "ZOrderCoveringIndexConfig",
    "MinMaxZOrderField",
    "PercentileZOrderField",
    "ZOrderField",
]
