"""ZOrderField — per-type mapping of column values to z-address bit codes.

Reference parity: index/zordercovering/ZOrderField.scala:26-570 — min-max
scaled variants for Long/Int/Short/Byte/Timestamp/Date/Boolean (:350-407),
percentile-bucket variants to fight skew (:227-287), string prefix mapping,
factory build(:474-564).

Vectorized, not per-row: each field yields an (codes uint64, nbits) pair for
ops/zorder.interleave_bits.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ...columnar.table import Column, STRING
from ...exceptions import HyperspaceError
from ...ops.zorder import scale_min_max, scale_percentile

DEFAULT_BITS = 16


class ZOrderField:
    kind = "?"

    def __init__(self, name: str, nbits: int = DEFAULT_BITS):
        self.name = name
        self.nbits = int(nbits)

    def codes(self, col: Column) -> np.ndarray:
        raise NotImplementedError

    def to_dict(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict) -> "ZOrderField":
        kind = d.get("kind")
        cls = _FIELD_KINDS.get(kind)
        if cls is None:
            raise HyperspaceError(f"Unknown z-order field kind {kind!r}")
        return cls._from_dict(d)


class MinMaxZOrderField(ZOrderField):
    """Linear min-max scaling (ref: the *MinMaxZOrderField family :350-407).
    Covers ints, floats, dates, bools; strings scale by sorted-code rank."""

    kind = "minmax"

    def __init__(self, name: str, vmin: float, vmax: float, nbits: int = DEFAULT_BITS):
        super().__init__(name, nbits)
        self.vmin = vmin
        self.vmax = vmax

    def codes(self, col: Column) -> np.ndarray:
        vals = _numeric_values(col)
        return scale_min_max(vals, self.vmin, self.vmax, self.nbits)

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "name": self.name,
            "min": self.vmin,
            "max": self.vmax,
            "nbits": self.nbits,
        }

    @classmethod
    def _from_dict(cls, d: dict) -> "MinMaxZOrderField":
        return cls(d["name"], d["min"], d["max"], d.get("nbits", DEFAULT_BITS))

    @staticmethod
    def from_column(name: str, col: Column, nbits: int = DEFAULT_BITS) -> "MinMaxZOrderField":
        vals = _numeric_values(col)
        if len(vals) == 0:
            return MinMaxZOrderField(name, 0.0, 0.0, nbits)
        return MinMaxZOrderField(name, float(vals.min()), float(vals.max()), nbits)


class PercentileZOrderField(ZOrderField):
    """Quantile-bucket scaling for skewed columns (ref: percentile variants
    :227-287; enabled by hyperspace.index.zorder.quantile.enabled)."""

    kind = "percentile"

    def __init__(self, name: str, boundaries: list[float], nbits: int = DEFAULT_BITS):
        super().__init__(name, nbits)
        self.boundaries = list(boundaries)

    def codes(self, col: Column) -> np.ndarray:
        vals = _numeric_values(col)
        return scale_percentile(vals, np.asarray(self.boundaries), self.nbits)

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "name": self.name,
            "boundaries": self.boundaries,
            "nbits": self.nbits,
        }

    @classmethod
    def _from_dict(cls, d: dict) -> "PercentileZOrderField":
        return cls(d["name"], d["boundaries"], d.get("nbits", DEFAULT_BITS))

    @staticmethod
    def from_column(name: str, col: Column, nbits: int = DEFAULT_BITS) -> "PercentileZOrderField":
        vals = _numeric_values(col)
        n_bounds = (1 << nbits) - 1
        if len(vals) == 0:
            return PercentileZOrderField(name, [0.0] * n_bounds, nbits)
        qs = np.linspace(0, 1, n_bounds + 2)[1:-1]
        bounds = np.quantile(vals.astype(np.float64), qs)
        return PercentileZOrderField(name, [float(b) for b in bounds], nbits)


_FIELD_KINDS = {
    MinMaxZOrderField.kind: MinMaxZOrderField,
    PercentileZOrderField.kind: PercentileZOrderField,
}


def _numeric_values(col: Column) -> np.ndarray:
    """Order-preserving numeric view of any supported column type."""
    if col.dtype == STRING:
        # rank against the sorted vocabulary: preserves lexicographic order
        vals = np.asarray(col.decode(), dtype=object)
        if col.validity is not None:
            vals = vals.copy()
            vals[~col.validity] = ""
        vocab, codes = np.unique(vals.astype(str), return_inverse=True)
        return codes.astype(np.float64)
    if col.dtype == "bool":
        return col.data.astype(np.float64)
    data = col.data.astype(np.float64)
    if col.validity is not None:
        data = np.where(col.validity, data, np.nan)
        data = np.nan_to_num(data, nan=float(np.nanmin(data)) if np.isfinite(np.nanmin(data)) else 0.0)
    return data


def build_field(
    name: str,
    col: Column,
    use_percentile: bool,
    nbits: int = DEFAULT_BITS,
) -> ZOrderField:
    """Factory (ref: ZOrderField.build:474-564): percentile for skew-prone
    numeric columns when enabled, else min-max."""
    if use_percentile and col.dtype != STRING and col.dtype != "bool":
        # cap boundary count: 2^nbits - 1 boundaries is too many for high
        # nbits; percentile fields quantize to at most 8 bits
        return PercentileZOrderField.from_column(name, col, min(nbits, 8))
    return MinMaxZOrderField.from_column(name, col, nbits)
