"""ZOrderFilterIndexRule.

Reference parity: index/zordercovering/ZOrderFilterIndexRule.scala — like
FilterIndexRule but *any* indexed column appearing in the predicate
qualifies (ZOrderFilterColumnFilter:36-80) because the z-curve clusters
every indexed dimension; the ranker prefers indexes with fewer untouched
indexed columns, then smaller size (ZOrderFilterRankFilter:82+).
"""

from __future__ import annotations

from ...plan.nodes import LogicalPlan
from ...rules.base import (
    HyperspaceRule,
    IndexRankFilter,
    MISSING_INDEXED_COL,
    MISSING_REQUIRED_COL,
    QueryPlanIndexFilter,
    index_type_filter,
    reason,
)
from ...rules.filter_rule import match_filter_pattern
from ...rules.rule_utils import (
    common_bytes_ratio,
    subtree_required_columns,
    find_scan_by_id,
    log_index_usage,
    transform_plan_to_use_index,
)
from ...rules.score_optimizer import register_rule


class ZOrderFilterColumnFilter(QueryPlanIndexFilter):
    def apply(self, plan, candidates):
        m = match_filter_pattern(plan)
        if m is None:
            return {}
        filter_node, scan = m
        filter_refs = {c.lower() for c in filter_node.condition.references()}
        required = {c.lower() for c in subtree_required_columns(plan)} | filter_refs
        out = []
        for e in index_type_filter("ZCI")(candidates.get(scan.plan_id, [])):
            indexed = {c.lower() for c in e.derived_dataset.indexed_columns()}
            covered = {c.lower() for c in e.derived_dataset.referenced_columns()}
            # ANY indexed column in the predicate unlocks the z-layout
            if not self.tag_reason_if(
                bool(indexed & filter_refs),
                plan,
                e,
                reason(
                    MISSING_INDEXED_COL,
                    "No indexed column appears in the filter condition.",
                    indexed=sorted(indexed),
                ),
            ):
                continue
            if not self.tag_reason_if(
                required <= covered,
                plan,
                e,
                reason(
                    MISSING_REQUIRED_COL,
                    "The index does not cover all required columns.",
                    missing=sorted(required - covered),
                ),
            ):
                continue
            self.tag_applicable_rule(plan, e, "ZOrderFilterIndexRule")
            out.append(e)
        return {scan.plan_id: out} if out else {}


class ZOrderFilterRankFilter(IndexRankFilter):
    def apply(self, plan, candidates):
        m = match_filter_pattern(plan)
        filter_refs = (
            {c.lower() for c in m[0].condition.references()} if m else set()
        )
        out = {}
        for leaf_id, entries in candidates.items():
            if not entries:
                continue

            def key(e):
                indexed = {c.lower() for c in e.derived_dataset.indexed_columns()}
                untouched = len(indexed - filter_refs)
                return (untouched, e.index_data_size_in_bytes(), e.name)

            out[leaf_id] = min(entries, key=key)
        return out


class ZOrderFilterIndexRule(HyperspaceRule):
    @property
    def filters(self):
        return [ZOrderFilterColumnFilter(self.session)]

    @property
    def rank_filter(self):
        return ZOrderFilterRankFilter(self.session)

    def apply_index(self, plan: LogicalPlan, chosen) -> LogicalPlan:
        out = plan
        for leaf_id, entry in chosen.items():
            # z-order data has no bucket spec (ref: bucketSpec=None :40)
            out = transform_plan_to_use_index(
                self.session, entry, out, leaf_id, False, False
            )
            log_index_usage(
                self.session,
                "ZOrderFilterIndexRule",
                [entry.name],
                f"Z-order index applied: {entry.name}",
            )
        return out

    def score(self, plan, chosen) -> int:
        total = 0.0
        for leaf_id, entry in chosen.items():
            scan = find_scan_by_id(plan, leaf_id)
            total += 50 * common_bytes_ratio(entry, scan)
        return int(total)


register_rule(ZOrderFilterIndexRule)
