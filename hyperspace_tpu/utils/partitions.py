"""Hive-style partition path handling (`.../key=value/...` directories).

The reference gets partitioned-relation handling from Spark's datasource
layer (partition base paths and column derivation; see
DefaultFileBasedRelation's partition base path logic :129-192). Here the
helpers are pure functions of the file path so no state can drift from the
scan's file list.
"""

from __future__ import annotations

import os
from typing import Optional

from ..columnar.table import Field


def _relative_dir_components(path: str, roots: list[str]) -> list[str]:
    """Directory components of `path` strictly below its read root (the file
    basename and everything above the root are excluded, so a '=' in an
    unrelated ancestor directory or filename never fabricates a column)."""
    apath = os.path.abspath(path)
    for root in sorted((os.path.abspath(r) for r in roots), key=len, reverse=True):
        if apath == root:
            return []
        if apath.startswith(root.rstrip(os.sep) + os.sep):
            rel = os.path.relpath(os.path.dirname(apath), root)
            return [] if rel == "." else rel.split(os.sep)
    return []


def parse_partition_values(path: str, roots: list[str] | None = None) -> dict[str, str]:
    """key=value directory components below the read root, in order."""
    comps = (
        _relative_dir_components(path, roots)
        if roots
        else [c for c in path.split(os.sep)][:-1]
    )
    out: dict[str, str] = {}
    for comp in comps:
        if "=" in comp and not comp.startswith("="):
            k, _, v = comp.partition("=")
            if k and not k.startswith(("_", ".")):
                out[k] = v
    return out


def infer_partition_fields(file_paths: list[str], roots: list[str] | None = None) -> list[Field]:
    """Partition columns shared by every file, typed int64 when every value
    parses as an integer, else string. Empty when files disagree on keys."""
    if not file_paths:
        return []
    per_file = [parse_partition_values(p, roots) for p in file_paths]
    keys = list(per_file[0].keys())
    for pv in per_file[1:]:
        if list(pv.keys()) != keys:
            return []
    fields = []
    for k in keys:
        values = [pv[k] for pv in per_file]
        try:
            [int(v) for v in values]
            dtype = "int64"
        except ValueError:
            dtype = "string"
        fields.append(Field(k, dtype))
    return fields


def partition_key(path: str, keys: list[str], roots: list[str] | None = None) -> tuple:
    pv = parse_partition_values(path, roots)
    return tuple(pv.get(k, "") for k in keys)
