"""Typed registry of every ``HYPERSPACE_*`` environment knob.

Before this module, knob reads were scattered ``os.environ.get`` calls with
the name, type, and default repeated at each site — a drifted default or a
typo'd name only surfaced as a knob that silently did nothing. This registry
is the single source of truth: every knob declares its name, type, default,
and docstring here, every read goes through the typed accessors below
(hslint HS301 enforces it), and the env-knob table in docs/performance.md is
generated from it (``python -m hyperspace_tpu.utils.env --update-docs``).

Read semantics are deliberately conservative: accessors parse the raw
string exactly the way the historical call sites did (``int(s)``,
``float(s)``, ``s == "1"``), so centralizing the reads cannot change any
observable behavior. Call-site-specific fallbacks (e.g. the IO pool's
"unparseable means serial") stay at the call site, built on ``read_raw``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass(frozen=True)
class EnvKnob:
    """One environment knob: its contract, not its current value."""

    name: str
    kind: str  # "int" | "float" | "str" | "bool" | "mode"
    default: object  # default VALUE (None = unset); shown in the docs table
    doc: str
    owner: str  # module that consumes the knob (docs table column)
    choices: tuple = ()  # for kind="mode": the accepted values

    def raw(self, default: "str | None" = None):
        return os.environ.get(self.name, default)


# mutated only by the module-level _register calls below at import time;
# env.py sits under staticcheck/concurrency in the import graph, so it
# cannot use guarded_by without a cycle
_REGISTRY: dict[str, EnvKnob] = {}  # hslint: HS305 — import-time only


def _register(name, kind, default, doc, owner, choices=()) -> EnvKnob:
    knob = EnvKnob(name, kind, default, doc, owner, tuple(choices))
    _REGISTRY[name] = knob
    return knob


def knob(name: str) -> EnvKnob:
    """The registered knob — KeyError for unregistered names, because the
    registry IS the catalog (an unregistered read is a lint violation)."""
    return _REGISTRY[name]


def all_knobs() -> list[EnvKnob]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


# --- typed accessors (the only sanctioned os.environ read path) -------------
#
# A name NOT in the registry is accepted only when the caller supplies an
# explicit default (ad-hoc knobs: parameterized test caches). A registered
# name with no explicit default falls back to the registry default.

def _raw(name: str) -> "str | None":
    k = _REGISTRY.get(name)
    if k is not None:
        return k.raw()
    return os.environ.get(name)


def _default(name: str, explicit):
    if explicit is not None:
        return explicit
    return _REGISTRY[name].default  # KeyError: unregistered AND no default


def read_raw(name: str, default: "str | None" = None) -> "str | None":
    """Raw string read (sites with bespoke parsing/fallback semantics)."""
    v = _raw(name)
    return v if v is not None else default


def env_str(name: str, default: "str | None" = None) -> "str | None":
    v = _raw(name)
    return v if v is not None else _default(name, default)


def env_int(name: str, default: "int | None" = None) -> int:
    v = _raw(name)
    return int(v) if v is not None else _default(name, default)


def env_float(name: str, default: "float | None" = None) -> float:
    v = _raw(name)
    return float(v) if v is not None else _default(name, default)


def env_bool(name: str) -> bool:
    """Historical convention: only the literal string "1" enables."""
    return _raw(name) == "1"


# ---------------------------------------------------------------------------
# the catalog — grouped by subsystem, alphabetical within a group
# ---------------------------------------------------------------------------

# IO / caches (columnar/io.py, utils/device_cache.py, utils/workers.py)
_register(
    "HYPERSPACE_BUILD_CACHE_MB", "int", 2048,
    "Byte budget (MB) of the maintenance source-column cache.",
    "columnar/io.py",
)
_register(
    "HYPERSPACE_DEVICE_CACHE_MB", "float", 6144,
    "Byte budget (MB) of device-resident column arrays; 0 disables.",
    "utils/device_cache.py",
)
_register(
    "HYPERSPACE_HOST_DERIVED_CACHE_MB", "float", 512,
    "Byte budget (MB) of host-derived device arrays (group ids, masks).",
    "utils/device_cache.py",
)
_register(
    "HYPERSPACE_INDEX_CACHE_MB", "int", 1024,
    "Byte budget (MB) of the decoded index-chunk cache.",
    "columnar/io.py",
)
_register(
    "HYPERSPACE_IO_BUDGET_MB", "float", 512,
    "Read-ahead byte budget (MB) of the streaming readers (scan chunks and "
    "bucket-pair loads in flight).",
    "columnar/io.py",
)
_register(
    "HYPERSPACE_IO_THREADS", "int", None,
    "Width of every IO-bound thread pool (parallel parquet decode, bucket "
    "loaders, compaction). Default min(8, nproc); <=1 or unparseable means "
    "serial.",
    "utils/workers.py",
)
_register(
    "HYPERSPACE_SKETCH_CACHE_MB", "int", 64,
    "Byte budget (MB) of the decoded per-row-group sketch sidecar cache "
    "(cache.sketch.*); 0 disables caching (sidecars re-parse per query).",
    "models/dataskipping/sketch_store.py",
)
_register(
    "HYPERSPACE_STATS_CACHE_MB", "int", 64,
    "Byte budget (MB) of the parquet footer row-group stats cache.",
    "columnar/io.py",
)
_register(
    "HYPERSPACE_STREAM_CHUNK_MB", "float", 64,
    "Target chunk size (MB) of the pipelined scan streamer's file groups.",
    "columnar/io.py",
)

# execution (plan/tpu_exec.py, plan/device_join.py, plan/pruning.py)
_register(
    "HYPERSPACE_ADAPTIVE", "mode", "0",
    "Mid-query adaptive re-optimization: 0 = off (default; bit-identical "
    "static plans), 1 = on (per-bucket join re-planning from observed "
    "build bytes, observed-selectivity conjunct reordering, scan "
    "abort-and-replan on pruning underdelivery), verify = adapt AND "
    "re-execute the static plan, raising on any result divergence (debug).",
    "plan/adaptive.py", choices=("0", "1", "verify"),
)
_register(
    "HYPERSPACE_ADAPTIVE_ABORT_FACTOR", "float", 4.0,
    "Actual-over-predicted kept-data ratio at which an under-delivering "
    "index scan aborts at a chunk boundary and re-enters the ranker "
    "(raw scan or next-best candidate) against the same pinned snapshot.",
    "plan/adaptive.py",
)
_register(
    "HYPERSPACE_ADAPTIVE_WARMUP_CHUNKS", "int", 2,
    "Chunks (scan abort) / observed bucket pairs (join re-plan) / chunk "
    "rows batches (conjunct reorder) the adaptive executor observes before "
    "it is allowed to switch anything.",
    "plan/adaptive.py",
)
_register(
    "HYPERSPACE_FORCE_PALLAS", "bool", False,
    "Force the Pallas kernel route off-TPU (interpret mode; testing).",
    "plan/tpu_exec.py",
)
_register(
    "HYPERSPACE_JOIN_BROADCAST_ROWS", "int", 4096,
    "Estimated build-side row count at or below which a bucket pair takes "
    "the broadcast strategy (whole pair in one band item, never split).",
    "plan/join_memory.py",
)
_register(
    "HYPERSPACE_JOIN_SPLIT_ROWS", "int", 1 << 18,
    "Left-side row count above which a bucket splits into probe chunks "
    "(only where partials fold exactly). Explicitly set, it OVERRIDES the "
    "grant-derived adaptive split row count (docs/performance.md "
    "\"Bucketed joins\"); unset, the device-memory grant decides.",
    "plan/device_join.py",
)
_register(
    "HYPERSPACE_PARK_WAIT_MS", "float", 50,
    "Bounded wait (ms) a parked join wave spends on the device ledger's "
    "release condition — after its own waves are spilled — for OTHER "
    "queries' reservations to drain before taking the zero-holder force "
    "grant past the limit.",
    "plan/join_memory.py",
)
_register(
    "HYPERSPACE_PIPELINE", "mode", "1",
    "Streaming executor mode: 1 = pipelined (default), serial = staged "
    "without overlap (debug), 0 = monolithic barrier path.",
    "plan/tpu_exec.py", choices=("1", "serial", "0"),
)
_register(
    "HYPERSPACE_PIPELINE_DEPTH", "int", 2,
    "Dispatch window of the chunk streamer (uploads in flight ahead of the "
    "device).",
    "plan/tpu_exec.py",
)
_register(
    "HYPERSPACE_PRUNE", "mode", "1",
    "Predicate-driven index pruning: 1 = on (default), 0 = off, verify = "
    "prune AND read full, raise on post-filter divergence (debug).",
    "plan/pruning.py", choices=("1", "0", "verify"),
)
_register(
    "HYPERSPACE_APPROX", "mode", "0",
    "Approximate query tier: 0 = off (default; exact execution, "
    "bit-identical results), 1 = on (sample twins written at index build / "
    "append / compact; eligible Count/Sum aggregates may execute against "
    "sampled runs with CLT confidence intervals when requested or when QoS "
    "degrades a predicted deadline miss), verify = sample AND run exact "
    "alongside, raising if any reported 95% CI fails to cover the exact "
    "answer (debug).",
    "plan/sampling.py", choices=("0", "1", "verify"),
)
_register(
    "HYPERSPACE_APPROX_FRACTIONS", "str", "0.01,0.1",
    "Comma list of sampling fractions (strata tiers) maintained as sample "
    "twin files next to index data and available to the sampled execution "
    "tier. Changing this only affects newly written index versions.",
    "models/sample_store.py",
)
_register(
    "HYPERSPACE_APPROX_CI_SAFETY", "float", 2.0,
    "Multiplier applied to CLT 95% half-widths from the sampled tier. "
    "The variance estimate is cluster-level (universe sampling keeps "
    "whole keys) but still sample-based; the safety factor absorbs "
    "small-sample effects, keeping reported intervals conservative.",
    "plan/sampling.py",
)
_register(
    "HYPERSPACE_APPROX_MAX_KEY_SHARE", "float", 0.05,
    "Skew guard for the sampled tier: if a single key owns at least this "
    "share of an index's rows (from the heavy-cluster entries in the "
    "per-file sample metas) AND the universe hash drops that key at the "
    "requested fraction, the planner declines the tier "
    "(approx.ineligible.hot-key) and falls back to exact — a sample that "
    "never sees a dominant cluster cannot honestly bound it. The write "
    "side derives its per-file heavy-cluster recording floor from this "
    "knob (half the threshold, capped at 1% of the file's rows, at least "
    "8 rows), so lower how-hot-counts-as-hot settings take effect on "
    "index versions written after the change.",
    "plan/sampling.py",
)
_register(
    "HYPERSPACE_APPROX_MIN_KEYS", "int", 8,
    "Minimum expected distinct sampled keys (fraction x sidecar NDV) for a "
    "sampling tier to be considered viable for an index scan; below it the "
    "planner declines the tier and falls back to a coarser fraction or "
    "exact execution.",
    "plan/sampling.py",
)
_register(
    "HYPERSPACE_SKETCHES", "str", None,
    "Per-row-group sketch store for covering indexes: unset/0 = off (the "
    "default; no sidecars, prune path unchanged), 1/all = every kind, or "
    "a comma list of bloom,valuelist,zregion. Enabled, index writes emit "
    "per-row-group sketch sidecars and Eq/In/range predicates on NON-sort "
    "columns skip row groups at scan time.",
    "models/dataskipping/sketch_store.py",
)
_register(
    "HYPERSPACE_SKETCH_BLOOM_FPP", "float", 0.01,
    "Target false-positive probability of per-row-group bloom filter "
    "sketches (sizing only; false positives keep extra groups, never drop).",
    "models/dataskipping/sketch_store.py",
)
_register(
    "HYPERSPACE_SKETCH_BLOOM_NDV", "int", 8192,
    "Cap on the expected-distinct-count a per-row-group bloom filter is "
    "sized for (bounds sidecar bytes on very-high-NDV columns).",
    "models/dataskipping/sketch_store.py",
)

# mesh scale-out (parallel/placement.py, parallel/mesh.py)
_register(
    "HYPERSPACE_MESH", "bool", False,
    "Mesh-sharded scale-out execution: bucketed-join band waves and "
    "streaming scan/agg chunks fan out across every visible device via the "
    "skew-aware placer (largest-first bin packing by predicted decoded "
    "bytes; round-robin when footer stats are missing). Results stay "
    "bit-identical to single-device execution; off (default) keeps every "
    "dispatch on the default device.",
    "parallel/placement.py",
)
_register(
    "HYPERSPACE_MESH_DEVICES", "int", 0,
    "Cap on the devices the mesh placer targets (0 = all visible; values "
    "above the visible count clamp down).",
    "parallel/placement.py",
)

# result cache / incremental views (cache/)
_register(
    "HYPERSPACE_RESULT_CACHE", "mode", "0",
    "Cross-query result cache keyed by (plan fingerprint, pinned snapshot "
    "version): 1 = on, 0 = off (default; correctness gates pin per-run "
    "execution effects, so serving deployments opt in), verify = on AND "
    "every hit/fold recomputes from scratch, raising on divergence.",
    "cache/result_cache.py", choices=("1", "0", "verify"),
)
_register(
    "HYPERSPACE_RESULT_CACHE_FOLD_DEPTH", "int", 32,
    "Successive delta folds a cached aggregate may accumulate before the "
    "next miss recomputes from scratch to re-anchor the entry.",
    "cache/view_maintenance.py",
)
_register(
    "HYPERSPACE_RESULT_CACHE_MB", "float", 256,
    "Byte budget (MB) of the cross-query result cache (LRU past it).",
    "cache/result_cache.py",
)

# serving (serve/)
_register(
    "HYPERSPACE_QOS_COST_MBPS", "float", 256,
    "Byte-cost normalization of the weighted-fair virtual clock: a "
    "finished query's attributed bytes (scan io + device transfers) are "
    "charged as bytes / (this many MB per second) on top of its run wall "
    "time.",
    "serve/qos.py",
)
_register(
    "HYPERSPACE_SERVE_AGING_MS", "float", 0,
    "Queue-wait aging interval (ms): a queued query's effective priority "
    "grows by one level per interval waited, bounded by "
    "HYPERSPACE_SERVE_AGING_CAP, so priority-0 queries cannot starve "
    "under a sustained high-priority flood. 0 (default) disables aging "
    "and preserves exact static-priority dispatch order.",
    "serve/qos.py",
)
_register(
    "HYPERSPACE_SERVE_AGING_CAP", "int", 100,
    "Upper bound on the aging priority boost (levels) a queued query can "
    "accumulate when HYPERSPACE_SERVE_AGING_MS is enabled.",
    "serve/qos.py",
)
_register(
    "HYPERSPACE_TENANTS", "str", None,
    "Tenant QoS bootstrap spec parsed at registry construction: "
    "name:key=value,...;name2:... with keys weight, rate_qps, burst, "
    "max_in_flight, max_active, budget_fraction (e.g. "
    "gold:weight=4,rate_qps=50;bulk:weight=1,max_active=1). Malformed "
    "specs raise TenantSpecError.",
    "serve/tenant.py",
)
_register(
    "HYPERSPACE_DEVICE_BUDGET_MB", "float", 4096,
    "Byte budget (MB) of the DEVICE-resident ledger bucketed-join band "
    "waves reserve their padded upload footprint through before dispatch; "
    "over-budget waves park/spill instead of declining to the host tier. "
    "0 disables the ledger (fixed-threshold pre-adaptive behavior).",
    "serve/budget.py",
)
_register(
    "HYPERSPACE_GLOBAL_BUDGET_MB", "float", 1024,
    "Byte budget (MB) of the GLOBAL read-ahead ledger every streaming "
    "consumer (scan chunks, join pair loads, across all concurrent "
    "queries) reserves through. Unset, an explicitly-set legacy "
    "HYPERSPACE_IO_BUDGET_MB carries over as the global limit.",
    "serve/budget.py",
)
_register(
    "HYPERSPACE_MAX_CONCURRENT_QUERIES", "int", 4,
    "Queries the scheduler runs concurrently (admission-controlled; the "
    "rest wait in the bounded run queue).",
    "serve/scheduler.py",
)
_register(
    "HYPERSPACE_SERVE_DEFAULT_PRIORITY", "int", 0,
    "Priority of queries submitted without an explicit one (higher runs "
    "first; FIFO within a priority).",
    "serve/scheduler.py",
)
_register(
    "HYPERSPACE_SERVE_QUEUE_DEPTH", "int", 32,
    "Bound of the scheduler's run queue; submissions past it are rejected "
    "at admission (load shedding) instead of queueing unboundedly.",
    "serve/scheduler.py",
)

# backend / device tier (utils/backend.py)
_register(
    "HYPERSPACE_BACKEND_TIMEOUT", "float", 30,
    "Seconds the backend probe waits for a device grant before the host "
    "tier takes over.",
    "utils/backend.py",
)
_register(
    "HYPERSPACE_BREAKER_COOLDOWN", "float", 30,
    "Seconds the device breaker stays open after a transient device "
    "failure before a half-open recovery probe is allowed (doubles per "
    "consecutive reopen, capped at 16x).",
    "utils/backend.py",
)
_register(
    "HYPERSPACE_DEVICE_STRICT", "bool", False,
    "Device failures raise instead of falling back to the host tier "
    "(CI/differential gates).",
    "utils/backend.py",
)

# ingestion / index maintenance (ingest/)
_register(
    "HYPERSPACE_COMPACT_RUNS", "int", 8,
    "Delta runs (files) a bucket accumulates before it becomes a "
    "compaction candidate; appends past the threshold schedule a "
    "background compaction on the shared IO pool.",
    "ingest/compaction.py",
)
_register(
    "HYPERSPACE_VACUUM_GRACE_S", "float", 0,
    "Seconds a superseded (unreferenced-by-latest) index data version must "
    "stay observed before vacuum may retire it, on top of its snapshot "
    "refcount draining; 0 = refcount-only.",
    "ingest/compaction.py",
)

# robustness / fault tolerance (utils/faults.py, utils/retry.py, actions/)
_register(
    "HYPERSPACE_ACTION_RETRIES", "int", 3,
    "Total attempts an index-mutating action makes when it loses the "
    "optimistic-concurrency race (ConcurrentWriteError re-reads the log "
    "and re-runs the transaction).",
    "actions/base.py",
)
_register(
    "HYPERSPACE_FAULTS", "str", None,
    "Deterministic fault-injection spec (point:kind:trigger[;...]) armed "
    "at import; unset = disarmed, zero overhead. Grammar in "
    "docs/robustness.md.",
    "utils/faults.py",
)
_register(
    "HYPERSPACE_IO_RETRIES", "int", 3,
    "Total attempts per per-file decode / footer-stats read unit for "
    "transient IO errors (bounded exponential backoff, deterministic "
    "jitter); 1 disables retrying.",
    "utils/retry.py",
)
_register(
    "HYPERSPACE_STALE_TX_S", "float", 3600,
    "Age (seconds) past which a transient log entry counts as a dead "
    "transaction: the auto recovery pass rolls back/fixes forward only "
    "entries older than this (explicit recover(force=True) ignores age).",
    "index_manager.py",
)

# telemetry (telemetry/trace.py, telemetry/exporter.py, telemetry/attribution.py)
_register(
    "HYPERSPACE_ESTIMATOR_FEEDBACK", "bool", False,
    "Estimator feedback: FilterIndexRanker and the join memory planner "
    "multiply their estimates by the accuracy ledger's observed "
    "correction factor per (index, predicate shape). Off (default) the "
    "ledger is observe-only and planning is bit-identical.",
    "telemetry/plan_stats.py",
)
_register(
    "HYPERSPACE_PLAN_STATS", "bool", False,
    "Collect per-plan-node runtime statistics (rows/wall/route/bytes + "
    "estimator q-errors) on every collect(), not just under "
    "explain_analyze; annotations ride exec spans when tracing is on "
    "(tools/trace_report.py --plan-stats).",
    "telemetry/plan_stats.py",
)
_register(
    "HYPERSPACE_METRICS_PORT", "int", None,
    "TCP port of the opt-in metrics exporter (Prometheus /metrics, JSON "
    "/snapshot, /healthz) started with the first query scheduler; 0 binds "
    "an ephemeral port (tests); unset = no exporter thread, no socket.",
    "telemetry/exporter.py",
)
_register(
    "HYPERSPACE_QUERY_LOG_WINDOW", "int", 256,
    "Finished serving queries kept in the rolling in-memory query log "
    "(hs.profile, /snapshot, tools/hs_top.py).",
    "telemetry/attribution.py",
)
_register(
    "HYPERSPACE_SLOW_QUERY_FILE", "str", None,
    "JSONL path the slow-query log appends finished query records to; "
    "unset disables the log.",
    "telemetry/attribution.py",
)
_register(
    "HYPERSPACE_SLOW_QUERY_MS", "float", 0,
    "Minimum total latency (ms) a finished serving query must exceed to "
    "enter the slow-query log (0 = log every query once the file is set).",
    "telemetry/attribution.py",
)
_register(
    "HYPERSPACE_SNAPSHOT_FILE", "str", None,
    "JSONL path the periodic snapshot sink appends full registry + "
    "serving-state snapshots to (headless runs); unset disables the sink.",
    "telemetry/exporter.py",
)
_register(
    "HYPERSPACE_SNAPSHOT_INTERVAL_S", "float", 10,
    "Seconds between periodic JSONL snapshots when the snapshot sink is "
    "enabled.",
    "telemetry/exporter.py",
)
_register(
    "HYPERSPACE_WORKLOAD_DIR", "str", None,
    "Directory for the durable workload-intelligence plane: the size-"
    "rotated JSONL query journal plus the persisted per-index utility "
    "ledger. Unset (default) the whole plane is off — zero writes, zero "
    "notes, bit-identical results.",
    "telemetry/workload.py",
)
_register(
    "HYPERSPACE_WORKLOAD_ROTATE_MB", "float", 64,
    "Workload-journal rotation bound (MB): the current workload.jsonl "
    "rotates to a numbered segment once it reaches this size.",
    "telemetry/workload.py",
)
_register(
    "HYPERSPACE_WORKLOAD_RETAIN", "int", 8,
    "Rotated workload-journal segments kept; older segments are deleted "
    "at rotation (the current file is always kept on top).",
    "telemetry/workload.py",
)
_register(
    "HYPERSPACE_WORKLOAD_WINDOW", "int", 64,
    "Rolling-window size (samples) the drift detector compares against "
    "the frozen baseline, per query label and per estimator.",
    "telemetry/workload.py",
)
_register(
    "HYPERSPACE_WORKLOAD_BASELINE", "int", 64,
    "Samples frozen as the drift baseline: the FIRST N observations of "
    "each series; everything after feeds the rolling window.",
    "telemetry/workload.py",
)
_register(
    "HYPERSPACE_WORKLOAD_DRIFT_FACTOR", "float", 2.0,
    "Drift threshold: a regression fires when the rolling window's median "
    "latency (or geomean q-error) exceeds the baseline by this factor.",
    "telemetry/workload.py",
)
_register(
    "HYPERSPACE_WORKLOAD_DRIFT_MIN", "int", 8,
    "Minimum samples required on BOTH sides (baseline and window) before "
    "the drift detector will compare a series.",
    "telemetry/workload.py",
)
_register(
    "HYPERSPACE_WORKLOAD_DRIFT_ABS_MS", "float", 1.0,
    "Absolute floor for latency drift: on top of the ratio, the window "
    "median must exceed the baseline median by at least this many "
    "milliseconds (guards microsecond-scale series against scheduler "
    "jitter).",
    "telemetry/workload.py",
)
_register(
    "HYPERSPACE_TRACE", "bool", False,
    "Force-enable query tracing at import (the traced tier-1 run).",
    "telemetry/trace.py",
)
_register(
    "HYPERSPACE_TRACE_FILE", "str", None,
    "JSONL sink path attached when tracing is force-enabled.",
    "telemetry/trace.py",
)

# static analysis (staticcheck/)
_register(
    "HYPERSPACE_LOCK_AUDIT", "bool", False,
    "Audit every TrackedLock acquisition: record per-thread held-sets into "
    "the global acquisition-order graph and raise LockOrderError (naming "
    "the cycle and both stack sites) when a nesting closes a cycle.",
    "staticcheck/concurrency.py",
)
_register(
    "HYPERSPACE_KERNEL_AUDIT", "bool", False,
    "Audit every kernel-cache miss: trace the jaxpr on the kernel's first "
    "call and scan it for hazards (host callbacks, implicit f64 promotion, "
    "non-deterministic primitives).",
    "staticcheck/kernel_audit.py",
)
_register(
    "HYPERSPACE_RETRACE_WARN", "int", 8,
    "Retrace watchdog threshold: distinct fingerprints of one kernel kind "
    "with identical dtype signatures before a churn warning fires.",
    "staticcheck/kernel_audit.py",
)
_register(
    "HYPERSPACE_VERIFY_PLAN", "bool", False,
    "Run the plan invariant verifier on every optimized plan (raises "
    "PlanInvariantError naming the node path on violation).",
    "staticcheck/plan_verifier.py",
)
_register(
    "HYPERSPACE_LIFECYCLE_AUDIT", "bool", False,
    "Audit resource lifecycles: record owner + acquire call chain for "
    "every live handle (snapshot pins, budget streams, ledger waves, "
    "attribution scopes, cache in-flight markers) so check_quiescent() "
    "can raise ResourceLeakError naming every leaked handle.",
    "staticcheck/lifecycle.py",
)


# ---------------------------------------------------------------------------
# docs table generation
# ---------------------------------------------------------------------------

_DOCS_BEGIN = "<!-- env-knob-table:begin (generated by hyperspace_tpu.utils.env; do not edit by hand) -->"
_DOCS_END = "<!-- env-knob-table:end -->"


def markdown_table() -> str:
    """The docs/performance.md env-knob table, generated from the registry."""
    rows = [
        "| Variable | Type | Default | Owner | Effect |",
        "|---|---|---|---|---|",
    ]
    for k in all_knobs():
        if k.kind == "bool":
            default = "1" if k.default else "unset"
        elif k.default is None:
            default = "unset"
        else:
            default = str(k.default)
        kind = k.kind if not k.choices else "/".join(k.choices)
        rows.append(
            f"| `{k.name}` | {kind} | {default} | `{k.owner}` | {k.doc} |"
        )
    return "\n".join(rows)


def render_docs_section() -> str:
    return f"{_DOCS_BEGIN}\n\n{markdown_table()}\n\n{_DOCS_END}"


def update_docs(path: str, check_only: bool = False) -> bool:
    """Replace the marked table section in ``path`` with the generated one.
    Returns True when the file already matched (or was updated)."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    start = text.find(_DOCS_BEGIN)
    end = text.find(_DOCS_END)
    if start < 0 or end < 0:
        raise ValueError(f"{path} has no env-knob-table markers")
    new = text[:start] + render_docs_section() + text[end + len(_DOCS_END):]
    if new == text:
        return True
    if check_only:
        return False
    with open(path, "w", encoding="utf-8") as f:
        f.write(new)
    return True


if __name__ == "__main__":  # pragma: no cover - tooling entry
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--update-docs", metavar="PATH", nargs="?",
                    const="docs/performance.md")
    ap.add_argument("--check", action="store_true",
                    help="with --update-docs: fail instead of rewriting")
    args = ap.parse_args()
    if args.update_docs:
        ok = update_docs(args.update_docs, check_only=args.check)
        if not ok:
            print(f"{args.update_docs}: env-knob table is stale "
                  f"(run python -m hyperspace_tpu.utils.env --update-docs)")
            raise SystemExit(1)
        print(f"{args.update_docs}: env-knob table up to date")
    else:
        print(markdown_table())
