"""Small bounded LRU map: recency updates on BOTH get and set, so hot
entries survive churn (a FIFO bound would evict the hottest item first).
Thread-safe: per-bucket executors hit the kernel caches from pool workers."""

from __future__ import annotations

import threading
from collections import OrderedDict


class BoundedLRU:
    def __init__(self, maxlen: int):
        self.maxlen = maxlen
        self._d: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key, default=None):
        with self._lock:
            try:
                value = self._d[key]
            except KeyError:
                return default
            self._d.move_to_end(key)
            return value

    def set(self, key, value) -> None:
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.maxlen:
                self._d.popitem(last=False)

    def pop(self, key, default=None):
        with self._lock:
            return self._d.pop(key, default)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._d

    def __iter__(self):
        return iter(self._d)
