"""Small bounded LRU map: recency updates on BOTH get and set, so hot
entries survive churn (a FIFO bound would evict the hottest item first).
Thread-safe: per-bucket executors hit the kernel caches from pool workers.

``get_or_put`` closes the check-then-insert atomicity gap the separate
get()/set() scopes left open: two threads missing on the same key used to
double-compute the value (and double-pay any eviction accounting). The
implementation is single-flight — the first missing thread builds while
the key is marked in-flight, later threads wait on its event and then
re-read; the factory never runs under the map lock (an expensive or
lock-acquiring factory must not serialize unrelated keys or create
nesting edges), and a failed build wakes the waiters so one of them takes
over instead of deadlocking on a value that will never arrive.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from ..staticcheck.concurrency import TrackedLock


class BoundedLRU:
    def __init__(self, maxlen: int, name: str = "lru"):
        self.maxlen = maxlen
        self._d: OrderedDict = OrderedDict()
        self._lock = TrackedLock(f"lru.{name}")
        self._inflight: dict = {}

    def get(self, key, default=None):
        with self._lock:
            try:
                value = self._d[key]
            except KeyError:
                return default
            self._d.move_to_end(key)
            return value

    def set(self, key, value) -> None:
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.maxlen:
                self._d.popitem(last=False)

    def get_or_put(self, key, factory):
        """The cached value for ``key``, building it with ``factory()``
        exactly once across concurrently missing threads (single-flight)."""
        while True:
            with self._lock:
                try:
                    value = self._d[key]
                    self._d.move_to_end(key)
                    return value
                except KeyError:
                    pass
                event = self._inflight.get(key)
                if event is None:
                    event = self._inflight[key] = threading.Event()
                    building = True
                else:
                    building = False
            if not building:
                # another thread is building this key: wait, then re-check
                # (its build may have failed — the loop lets us take over)
                event.wait()
                continue
            try:
                value = factory()
            except BaseException:
                with self._lock:
                    self._inflight.pop(key, None)
                event.set()
                raise
            with self._lock:
                self._d[key] = value
                self._d.move_to_end(key)
                while len(self._d) > self.maxlen:
                    self._d.popitem(last=False)
                self._inflight.pop(key, None)
            event.set()
            return value

    def pop(self, key, default=None):
        with self._lock:
            return self._d.pop(key, default)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._d

    def __iter__(self):
        return iter(self._d)
