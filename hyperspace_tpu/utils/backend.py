"""Watchdog-guarded jax backend access.

In some environments the first backend touch (``jax.devices()`` / any jnp
op) blocks indefinitely — e.g. a remote-TPU PJRT plugin waiting for a device
grant. A user query must degrade to the host executor instead of freezing,
so every backend touch on the library's query/build paths goes through
``safe_backend()`` / ``safe_device_count()``: the first call probes backend
init in a daemon thread with a timeout; the outcome is memoized
process-wide, and while a probe is still hanging later calls return
immediately (host path) rather than re-waiting.

The timeout is ``HYPERSPACE_BACKEND_TIMEOUT`` seconds (default 30). A probe
that eventually completes flips later calls to the real backend.
"""

from __future__ import annotations

from typing import Optional

from ..staticcheck.concurrency import TrackedLock, guarded_by
from . import env
from .workers import spawn_thread

_lock = TrackedLock("backend.state")
_state: dict = guarded_by(
    {"status": "unprobed", "backend": None, "thread": None, "waited": False},
    _lock,
    name="utils.backend._state",
)


def _default_timeout() -> float:
    return env.env_float("HYPERSPACE_BACKEND_TIMEOUT")


def _probe_target() -> None:
    try:
        import jax

        b = jax.default_backend()
        with _lock:
            _state["backend"] = b
            _state["status"] = "ready"
    except Exception:
        with _lock:
            _state["status"] = "failed"


def safe_backend(timeout_s: Optional[float] = None) -> Optional[str]:
    """The jax backend platform name, or None if init hangs/fails."""
    timeout = _default_timeout() if timeout_s is None else timeout_s
    with _lock:
        if _state["status"] == "ready":
            return _state["backend"]
        if _state["status"] == "failed":
            return None
        if _state["status"] == "unprobed":
            # named + daemon via the workers chokepoint: the probe may hang
            # on a dead tunnel forever and must never block shutdown
            t = spawn_thread(_probe_target, name="hs-backend-probe")
            _state.update(status="probing", thread=t)
        t = _state["thread"]
        # only the first caller pays the full timeout; once it has elapsed a
        # hung probe must not re-stall every subsequent query
        wait = timeout if not _state["waited"] else 0.05
    t.join(wait)
    with _lock:
        _state["waited"] = True
        if _state["status"] == "ready":
            return _state["backend"]
        return None


def safe_device_count(timeout_s: Optional[float] = None) -> int:
    """len(jax.devices()), or 0 when the backend is unavailable."""
    if safe_backend(timeout_s) is None:
        return 0
    import jax

    return len(jax.devices())


def _reset_for_testing() -> None:
    with _lock:
        _state.update(status="unprobed", backend=None, thread=None, waited=False)
    global _device_healthy
    _device_healthy = True


# ---------------------------------------------------------------------------
# device-execution circuit breaker
# ---------------------------------------------------------------------------
# The query rewrite is fail-open in the reference (ApplyHyperspace.scala:60-64);
# the device tier extends that to EXECUTION: if a device kernel fails mid-query
# (e.g. a remote-TPU tunnel drops), the query falls back to the host executor
# and the device tier latches off for the rest of the process instead of
# failing every subsequent query. HYPERSPACE_DEVICE_STRICT=1 re-raises instead
# (set by the test harness so CI surfaces device bugs rather than masking
# them with silent host fallbacks).

import logging

_logger = logging.getLogger(__name__)
_device_healthy = True


def device_healthy() -> bool:
    return _device_healthy


def device_strict() -> bool:
    return env.env_bool("HYPERSPACE_DEVICE_STRICT")


def record_device_failure(err: BaseException) -> None:
    global _device_healthy
    if device_strict():
        raise err
    if _device_healthy:
        _logger.warning(
            "device execution failed (%s); host paths take over for this process",
            err,
        )
    _device_healthy = False
