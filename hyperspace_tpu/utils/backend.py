"""Watchdog-guarded jax backend access.

In some environments the first backend touch (``jax.devices()`` / any jnp
op) blocks indefinitely — e.g. a remote-TPU PJRT plugin waiting for a device
grant. A user query must degrade to the host executor instead of freezing,
so every backend touch on the library's query/build paths goes through
``safe_backend()`` / ``safe_device_count()``: the first call probes backend
init in a daemon thread with a timeout; the outcome is memoized
process-wide, and while a probe is still hanging later calls return
immediately (host path) rather than re-waiting.

The timeout is ``HYPERSPACE_BACKEND_TIMEOUT`` seconds (default 30). A probe
that eventually completes flips later calls to the real backend.
"""

from __future__ import annotations

import time
from typing import Optional

from ..staticcheck.concurrency import TrackedLock, guarded_by
from . import env
from .workers import spawn_thread

_lock = TrackedLock("backend.state")
_state: dict = guarded_by(
    {"status": "unprobed", "backend": None, "thread": None, "waited": False},
    _lock,
    name="utils.backend._state",
)


def _default_timeout() -> float:
    return env.env_float("HYPERSPACE_BACKEND_TIMEOUT")


def _probe_target() -> None:
    try:
        import jax

        b = jax.default_backend()
        with _lock:
            _state["backend"] = b
            _state["status"] = "ready"
    except Exception:
        with _lock:
            _state["status"] = "failed"


def safe_backend(timeout_s: Optional[float] = None) -> Optional[str]:
    """The jax backend platform name, or None if init hangs/fails."""
    timeout = _default_timeout() if timeout_s is None else timeout_s
    with _lock:
        if _state["status"] == "ready":
            return _state["backend"]
        if _state["status"] == "failed":
            return None
        if _state["status"] == "unprobed":
            # named + daemon via the workers chokepoint: the probe may hang
            # on a dead tunnel forever and must never block shutdown
            t = spawn_thread(_probe_target, name="hs-backend-probe")
            _state.update(status="probing", thread=t)
        t = _state["thread"]
        # only the first caller pays the full timeout; once it has elapsed a
        # hung probe must not re-stall every subsequent query
        wait = timeout if not _state["waited"] else 0.05
    t.join(wait)
    with _lock:
        _state["waited"] = True
        if _state["status"] == "ready":
            return _state["backend"]
        return None


def safe_device_count(timeout_s: Optional[float] = None) -> int:
    """len(jax.devices()), or 0 when the backend is unavailable."""
    if safe_backend(timeout_s) is None:
        return 0
    import jax

    return len(jax.devices())


def _reset_for_testing() -> None:
    with _lock:
        _state.update(status="unprobed", backend=None, thread=None, waited=False)
        _breaker.update(
            state=CLOSED, opened_at=0.0, cooldown=0.0, reopens=0, last_kind=None
        )
    _set_breaker_gauge(CLOSED)


# ---------------------------------------------------------------------------
# device-execution circuit breaker
# ---------------------------------------------------------------------------
# The query rewrite is fail-open in the reference (ApplyHyperspace.scala:60-64);
# the device tier extends that to EXECUTION: a device kernel failing mid-query
# (e.g. a dropped remote-TPU tunnel) degrades that query to the host executor
# instead of failing it. What happens NEXT depends on the failure kind:
#
#   permanent (compile/lowering/shape errors — deterministic, re-failing
#   forever)                  -> LATCHED: device tier off for the process,
#                                the original always-latch behavior
#   transient (tunnel drops, timeouts, RESOURCE_EXHAUSTED/OOM — the device
#   may come back)            -> OPEN: device tier off for a cooldown
#                                (HYPERSPACE_BREAKER_COOLDOWN, default 30 s),
#                                then ONE query probes it (HALF_OPEN); a
#                                probe success closes the breaker, a probe
#                                failure reopens with doubled cooldown
#                                (capped at 16x)
#
# HYPERSPACE_DEVICE_STRICT=1 re-raises instead (set by the test harness so
# CI surfaces device bugs rather than masking them with host fallbacks).
# State is surfaced through the `breaker.state` gauge, `breaker.*` counters,
# and `hs.profile`; the clock is injectable so tests drive cooldowns without
# sleeping.

import logging

_logger = logging.getLogger(__name__)

CLOSED, OPEN, HALF_OPEN, LATCHED = "closed", "open", "half_open", "latched"
_STATE_CODES = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2, LATCHED: 3}
_MAX_COOLDOWN_FACTOR = 16

_breaker: dict = guarded_by(
    {"state": CLOSED, "opened_at": 0.0, "cooldown": 0.0, "reopens": 0,
     "last_kind": None},
    _lock,
    name="utils.backend._breaker",
)

_clock = time.monotonic


def _set_clock_for_testing(fn) -> None:
    """Inject a fake monotonic clock (tests drive cooldown expiry)."""
    global _clock
    _clock = fn


def _count(event: str) -> None:
    from ..telemetry.metrics import REGISTRY

    REGISTRY.counter(f"breaker.{event}").inc()


def _set_breaker_gauge(state: str) -> None:
    from ..telemetry.metrics import REGISTRY

    REGISTRY.gauge("breaker.state").set(_STATE_CODES[state])


def classify_device_failure(err: BaseException) -> str:
    """"permanent" for deterministic compile/lowering/shape errors (retrying
    the same query re-fails forever — latch, exactly the old behavior);
    "transient" for runtime/transport errors that a healthy device would not
    produce (the tier deserves a recovery probe). Unknown exception types
    default to transient: an unclassified runtime error wrongly latching
    the tier off forever is the costlier mistake."""
    if isinstance(err, (TypeError, ValueError, NotImplementedError)):
        return "permanent"  # tracing/shape errors are deterministic
    if isinstance(err, (OSError, ConnectionError, TimeoutError, MemoryError)):
        return "transient"
    msg = str(err).lower()
    if any(
        s in msg
        for s in ("lowering", "compilation", "invalid argument",
                  "unimplemented", "tracer", "unsupported")
    ):
        return "permanent"
    return "transient"


def breaker_state() -> str:
    """Current breaker state WITHOUT side effects (reports, hs.profile)."""
    with _lock:
        return _breaker["state"]


def breaker_snapshot() -> dict:
    """Report block for bench/chaos artifacts."""
    from ..telemetry.metrics import REGISTRY

    def val(name: str) -> int:
        m = REGISTRY.get(name)
        return 0 if m is None else int(m.value)

    with _lock:
        state = _breaker["state"]
        kind = _breaker["last_kind"]
    return {
        "state": state,
        "last_failure_kind": kind,
        "opened": val("breaker.opened"),
        "reopened": val("breaker.reopened"),
        "probes": val("breaker.probes"),
        "recovered": val("breaker.recovered"),
        "latched": val("breaker.latched"),
    }


def device_healthy() -> bool:
    """Gate every device-tier entry point. CLOSED admits everything (the
    fast path is one unlocked dict read). OPEN admits nothing until the
    cooldown elapses, then flips to HALF_OPEN and admits exactly the
    flipping caller as the recovery probe; other callers stay on the host
    tier until the probe resolves via record_device_success/_failure."""
    if _breaker["state"] == CLOSED:  # racy read: worst case one extra lock
        return True
    with _lock:
        state = _breaker["state"]
        if state == CLOSED:
            return True
        if state in (LATCHED, HALF_OPEN):
            return False
        # OPEN: probe when the cooldown has elapsed
        if _clock() - _breaker["opened_at"] >= _breaker["cooldown"]:
            _breaker["state"] = HALF_OPEN
            _count("probes")
            _set_breaker_gauge(HALF_OPEN)
            return True
        return False


def device_strict() -> bool:
    return env.env_bool("HYPERSPACE_DEVICE_STRICT")


def record_device_success() -> None:
    """Signal one successful device execution: a HALF_OPEN probe succeeding
    closes the breaker and resets the cooldown ladder. No-op when CLOSED
    (the common case — one unlocked read)."""
    if _breaker["state"] == CLOSED:
        return
    with _lock:
        if _breaker["state"] != HALF_OPEN:
            return
        _breaker.update(state=CLOSED, reopens=0, cooldown=0.0, last_kind=None)
    _count("recovered")
    _set_breaker_gauge(CLOSED)
    _logger.warning("device tier recovered; breaker closed")


def record_device_failure(err: BaseException) -> None:
    if device_strict():
        raise err
    kind = classify_device_failure(err)
    # every degrade-to-host occurrence (not just state TRANSITIONS like
    # breaker.opened): the per-query attribution ledger charges this to the
    # query that hit the failure, and /healthz derives its rolling degrade
    # rate from it
    from ..telemetry.metrics import REGISTRY

    REGISTRY.counter("device.degrades").inc()
    with _lock:
        prev = _breaker["state"]
        _breaker["last_kind"] = kind
        if kind == "permanent":
            _breaker["state"] = LATCHED
        else:
            base = env.env_float("HYPERSPACE_BREAKER_COOLDOWN")
            reopens = _breaker["reopens"] + 1 if prev in (OPEN, HALF_OPEN) else 0
            factor = min(2 ** reopens, _MAX_COOLDOWN_FACTOR)
            _breaker.update(
                state=OPEN, opened_at=_clock(), cooldown=base * factor,
                reopens=reopens,
            )
        new = _breaker["state"]
    if new == LATCHED:
        _count("latched")
        if prev != LATCHED:
            _logger.warning(
                "device execution failed permanently (%s); host paths take "
                "over for this process", err,
            )
    else:
        _count("reopened" if prev in (OPEN, HALF_OPEN) else "opened")
        if prev == CLOSED:
            _logger.warning(
                "device execution failed (%s); breaker open, host paths "
                "take over until the cooldown probe", err,
            )
    _set_breaker_gauge(new)
