"""Hashing helpers (ref: util/HashingUtils.scala md5Hex)."""

import hashlib


def md5_hex(s: str) -> str:
    return hashlib.md5(s.encode("utf-8")).hexdigest()
