"""JSON (de)serialization for log entries (ref: util/JsonUtils.scala:33-60).

The reference uses Jackson with polymorphic-type info on the `Index` trait
(`@JsonTypeInfo`, index/Index.scala:31). Here every serializable object
implements to_dict()/from_dict(); polymorphic dispatch happens on a "type"
discriminator handled by the registries in meta.entry / models.base.
"""

import json
from typing import Any


def to_json(obj: Any, indent: int | None = 2) -> str:
    d = obj.to_dict() if hasattr(obj, "to_dict") else obj
    return json.dumps(d, indent=indent, sort_keys=False)


def from_json(s: str) -> Any:
    return json.loads(s)
