"""Deterministic fault injection at named engine chokepoints.

The engine's durability story (two-phase action FSM, atomic log CAS,
fail-open device tier) is only as good as its behavior when things actually
fail — and real failures are rare, racy, and unreproducible. This module
makes them cheap and deterministic: a handful of *named injection points*
are planted at the existing IO / device / log chokepoints, and a seeded
``HYPERSPACE_FAULTS`` spec arms typed failures at exactly chosen hits.
The chaos gate (tools/chaos_stress.py) and tests/test_robustness.py sweep
specs and assert the hardening layers (utils/retry.py backoff, the device
breaker, IndexManager.recover()) hold the "bit-identical or typed error,
never wrong answers" line.

Injection points (the catalog — adding one means adding it HERE):

    io.read_file     per-file parquet/csv/json decode (columnar/io.py)
    io.footer        parquet footer-stats parse (columnar/io.py)
    device.upload    host->device transfer (utils/rpc_meter.record_upload —
                     the metering funnel every real upload passes; cache
                     hits move no bytes and never fault)
    device.dispatch  device kernel dispatch (utils/rpc_meter.py — the
                     record_dispatch funnel every execution path calls)
    device.fetch     device->host result fetch (utils/rpc_meter.device_get)
    kernel.compile   kernel trace/compile on cache miss (plan/kernel_cache.py)
    log.write        transaction-log CAS commit (meta/log_manager.py)
    data.publish     staged index-data version publish (meta/data_manager.py)
    ingest.append    delta-run build of an ingest batch (ingest/actions.py),
                     bracketing stage -> write -> publish
    ingest.compact   delta-run compaction build (ingest/actions.py), same
                     bracket around the compacted version's stage/publish
    workload.journal workload-journal line append (telemetry/workload.py),
                     bracketing the payload write -> newline so crash_after
                     leaves the torn tail line load() must skip
    approx.sample    sample-twin publish next to an index data file
                     (models/sample_store.py), bracketing the tier loop so
                     crash_before leaves a data file with no twins and
                     crash_after a partially-written tier set — both must
                     read as "tier ineligible, exact answer" downstream

Spec grammar (``HYPERSPACE_FAULTS``, also ``arm()``):

    spec    = rule [";" rule ...]
    rule    = point ":" kind ":" trigger
    point   = exact name above, or a prefix wildcard like "device.*"
    kind    = "ioerror" | "oom" | "crash_before" | "crash_after"
    trigger = "n=K"                  fire on the K-th hit (1-based), once
            | "p=F[,seed=S]"         fire each hit with probability F,
                                     seeded (default seed 0) — deterministic
            | "always"               fire on every hit

Examples:
    HYPERSPACE_FAULTS="io.read_file:ioerror:n=1"
    HYPERSPACE_FAULTS="io.read_file:ioerror:p=0.05,seed=7;log.write:crash_after:n=2"

Kinds map to typed errors so failures stay attributable end to end:

- ``ioerror`` raises :class:`InjectedIOError` — an ``IOError`` (the retry
  classifier treats it as transient) that is ALSO a ``HyperspaceError``
  (an unabsorbed injection surfaces as a typed engine error, never a bare
  builtin).
- ``oom`` raises :class:`InjectedOOMError` — ``MemoryError``-shaped, the
  RESOURCE_EXHAUSTED analogue; the device breaker classifies it transient.
- ``crash_before`` / ``crash_after`` raise :class:`InjectedCrash` *before*
  or *after* the guarded operation. ``InjectedCrash`` derives from
  ``BaseException`` so no ``except Exception`` handler on the way out can
  absorb it — the process state it leaves behind (stranded transient log
  entries, unpublished staging dirs, published-but-unlogged versions) is
  what ``recover()`` must repair. (``finally`` blocks still run; artifacts
  that only a hard kill leaves — e.g. mkstemp temp files — are covered by
  planting them directly in recovery tests.)

Disarmed (``HYPERSPACE_FAULTS`` unset), every hook is a single global read
and an immediate return: zero counters, zero span events, zero behavior
change — the clean path stays bit-identical, which tests assert.

Observability: every injected failure increments ``faults.injected`` and
``faults.injected.<point>`` and emits a ``fault:<point>`` span event
carrying the kind and hit number, so injected failures are attributable in
any trace they surface in.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field

from ..exceptions import HyperspaceError
from . import env

KINDS = ("ioerror", "oom", "crash_before", "crash_after")

POINTS = (
    "io.read_file",
    "io.footer",
    "device.upload",
    "device.dispatch",
    "device.fetch",
    "kernel.compile",
    "log.write",
    "data.publish",
    "ingest.append",
    "ingest.compact",
    "workload.journal",
    "approx.sample",
)


class InjectedIOError(IOError, HyperspaceError):
    """Injected transient IO failure (retryable; typed)."""


class InjectedOOMError(MemoryError, HyperspaceError):
    """Injected allocation failure (RESOURCE_EXHAUSTED analogue; typed)."""


class InjectedCrash(BaseException):
    """Simulated process death at an injection point. BaseException so no
    ``except Exception`` on the unwind path can swallow it — only the
    harness that armed the fault catches it."""


class FaultSpecError(HyperspaceError):
    """Malformed ``HYPERSPACE_FAULTS`` spec string."""


@dataclass
class FaultRule:
    """One armed rule; hit/fire bookkeeping is mutated under ``_PLAN_LOCK``."""

    point: str  # exact name, or "prefix.*"
    kind: str
    nth: int | None = None  # fire on exactly this hit (1-based)
    p: float | None = None  # or fire each hit with this probability
    always: bool = False
    seed: int = 0
    hits: int = 0
    fired: int = 0
    rng: random.Random = field(default_factory=random.Random, repr=False)

    def matches(self, point: str) -> bool:
        if self.point.endswith(".*"):
            return point.startswith(self.point[:-1])
        return point == self.point

    def should_fire(self) -> bool:
        """Called with the hit already counted; deterministic per seed."""
        if self.always:
            return True
        if self.nth is not None:
            return self.hits == self.nth
        return self.rng.random() < (self.p or 0.0)


def parse_spec(spec: str) -> list[FaultRule]:
    """Parse a ``HYPERSPACE_FAULTS`` spec string into rules (see module
    docstring for the grammar); raises :class:`FaultSpecError` on any
    malformed rule so a typo'd spec fails loudly instead of silently
    injecting nothing."""
    rules: list[FaultRule] = []
    for chunk in spec.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        parts = chunk.split(":")
        if len(parts) != 3:
            raise FaultSpecError(
                f"fault rule {chunk!r} must be point:kind:trigger"
            )
        point, kind, trigger = (p.strip() for p in parts)
        base = point[:-2] if point.endswith(".*") else point
        if point.endswith(".*"):
            if not any(p.startswith(base + ".") or p == base for p in POINTS):
                raise FaultSpecError(f"unknown injection point {point!r}")
        elif point not in POINTS:
            raise FaultSpecError(
                f"unknown injection point {point!r}; known: {', '.join(POINTS)}"
            )
        if kind not in KINDS:
            raise FaultSpecError(
                f"unknown fault kind {kind!r}; known: {', '.join(KINDS)}"
            )
        rule = FaultRule(point=point, kind=kind)
        if trigger == "always":
            rule.always = True
        else:
            for kv in trigger.split(","):
                if "=" not in kv:
                    raise FaultSpecError(
                        f"fault trigger {trigger!r} must be n=K, p=F[,seed=S], "
                        f"or always"
                    )
                k, _, v = kv.partition("=")
                k, v = k.strip(), v.strip()
                try:
                    if k == "n":
                        rule.nth = int(v)
                    elif k == "p":
                        rule.p = float(v)
                    elif k == "seed":
                        rule.seed = int(v)
                    else:
                        raise FaultSpecError(
                            f"unknown trigger key {k!r} in {chunk!r}"
                        )
                except ValueError as e:
                    raise FaultSpecError(
                        f"bad trigger value {kv!r} in {chunk!r}"
                    ) from e
            if (rule.nth is None) == (rule.p is None):
                raise FaultSpecError(
                    f"fault rule {chunk!r} needs exactly one of n=K / p=F"
                )
            if rule.nth is not None and rule.nth < 1:
                raise FaultSpecError(f"n must be >= 1 in {chunk!r}")
            if rule.p is not None and not (0.0 <= rule.p <= 1.0):
                raise FaultSpecError(f"p must be in [0, 1] in {chunk!r}")
        rule.rng = random.Random(rule.seed)
        rules.append(rule)
    return rules


# armed plan: None = disarmed (the zero-overhead fast path reads only this).
# Hit counting mutates rule state, and injection points fire from IO-pool
# workers, so all bookkeeping runs under one leaf lock.
_PLAN: "list[FaultRule] | None" = None
_PLAN_LOCK = threading.Lock()  # leaf: never acquires another lock inside


def arm(spec: str) -> list[FaultRule]:
    """Arm a spec programmatically (tests / the chaos gate). Returns the
    live rules so callers can inspect hit/fire counts afterwards."""
    global _PLAN
    rules = parse_spec(spec)
    with _PLAN_LOCK:
        _PLAN = rules if rules else None
    return rules


def disarm() -> None:
    global _PLAN
    with _PLAN_LOCK:
        _PLAN = None


def armed() -> bool:
    return _PLAN is not None


def _fire_rule(rule: FaultRule, point: str, ctx: dict) -> None:
    from ..telemetry import trace
    from ..telemetry.metrics import REGISTRY

    REGISTRY.counter("faults.injected").inc()
    REGISTRY.counter(f"faults.injected.{point}").inc()
    trace.add_event(
        f"fault:{point}", kind=rule.kind, hit=rule.hits, **ctx
    )
    detail = ", ".join(f"{k}={v}" for k, v in ctx.items())
    msg = f"injected {rule.kind} at {point} (hit {rule.hits}{', ' + detail if detail else ''})"
    if rule.kind == "ioerror":
        raise InjectedIOError(msg)
    if rule.kind == "oom":
        raise InjectedOOMError(msg)
    raise InjectedCrash(msg)


def _select(point: str, phase: str) -> "tuple[FaultRule, dict] | None":
    """Count a hit on every matching rule; return the first that fires in
    this phase. ``before`` fires ioerror/oom/crash_before; ``after`` fires
    crash_after (the hit was already counted by the before call)."""
    with _PLAN_LOCK:
        plan = _PLAN
        if plan is None:
            return None
        for rule in plan:
            if not rule.matches(point):
                continue
            if phase == "before":
                rule.hits += 1
                if rule.kind != "crash_after" and rule.should_fire():
                    rule.fired += 1
                    return rule, {}
            else:
                if rule.kind == "crash_after" and rule.should_fire():
                    rule.fired += 1
                    return rule, {}
    return None


def fire(point: str, **ctx) -> None:
    """Hook placed BEFORE the guarded operation. Counts one hit per armed
    matching rule and raises the typed failure when one triggers
    (ioerror / oom / crash_before). No-op (one global read) when disarmed."""
    if _PLAN is None:
        return
    hit = _select(point, "before")
    if hit is not None:
        _fire_rule(hit[0], point, ctx)


def fire_after(point: str, **ctx) -> None:
    """Hook placed AFTER the guarded operation succeeded: the crash_after
    half of a crash pair (the op took effect; the process dies before any
    follow-up). Uses the hit counted by the paired ``fire`` call."""
    if _PLAN is None:
        return
    hit = _select(point, "after")
    if hit is not None:
        _fire_rule(hit[0], point, ctx)


def snapshot() -> list[dict]:
    """Armed-rule state for reports (chaos gate JSON, bench artifact)."""
    with _PLAN_LOCK:
        if _PLAN is None:
            return []
        return [
            {
                "point": r.point,
                "kind": r.kind,
                "trigger": (
                    "always" if r.always
                    else f"n={r.nth}" if r.nth is not None
                    else f"p={r.p},seed={r.seed}"
                ),
                "hits": r.hits,
                "fired": r.fired,
            }
            for r in _PLAN
        ]


# env arming at import: the registered knob is the production surface (the
# chaos gate's subprocesses and the verify recipe's faulted smoke set it);
# in-process tests use arm()/disarm().
_env_spec = env.read_raw("HYPERSPACE_FAULTS")
if _env_spec:
    arm(_env_spec)
