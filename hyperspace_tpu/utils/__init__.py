from .hashing import md5_hex
from .json_utils import to_json, from_json
from .workers import io_thread_cap, io_worker_count

__all__ = ["md5_hex", "to_json", "from_json", "io_thread_cap", "io_worker_count"]
