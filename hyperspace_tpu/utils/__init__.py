from .hashing import md5_hex
from .json_utils import to_json, from_json

__all__ = ["md5_hex", "to_json", "from_json"]
