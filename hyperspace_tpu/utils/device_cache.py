"""Device-resident array cache.

Repeated queries over the same index chunks re-shipped every column to the
device on every execution; on remote-TPU backends (the axon tunnel) that
costs ~10 ms per 16 MB plus a round trip, which dominates sub-second
queries. This cache keeps the device copy alive keyed by the *source numpy
array's object identity* — the columnar chunk cache (columnar/io.py) serves
shallow copies whose underlying ``.data`` buffers are shared and immutable,
so object identity is a sound content key.

Safety against id() reuse: each entry holds a weakref to the source array
and a lookup only hits when the weakref still resolves to the *same object*
(a dead or rebound ref is evicted). Mutated/derived arrays get fresh ids and
therefore fresh entries. Eviction is least-recently-used by device bytes
(``HYPERSPACE_DEVICE_CACHE_MB``, default 2048; 0 disables).
"""

from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Callable


from ..staticcheck.concurrency import TrackedLock
from . import env
from .rpc_meter import _tree_nbytes  # one canonical tree-size walker


def _budget_bytes(env_name: str, default_mb: str) -> int:
    return int(env.env_float(env_name, float(default_mb)) * 2**20)


def _cache_counter(name: str, event: str, n: int = 1) -> None:
    from ..telemetry.metrics import REGISTRY

    REGISTRY.counter(f"cache.{name}.{event}").inc(n)


def _cache_gauge(name: str, value: float) -> None:
    from ..telemetry.metrics import REGISTRY

    REGISTRY.gauge(f"cache.{name}.bytes").set(value)


class DeviceArrayCache:
    # default budget sized for a v5e chip (16 GB HBM): 6 GB of resident
    # columns keeps a 50M-row query working set (≈1.8 GB) plus the join
    # indexes hot without re-shipping over the tunnel every repeat
    def __init__(self, budget_env: str = "HYPERSPACE_DEVICE_CACHE_MB", default_mb: str = "6144") -> None:
        self._budget_env = budget_env
        self._default_mb = default_mb
        self._metric = "device" if budget_env == "HYPERSPACE_DEVICE_CACHE_MB" else "host_derived"
        self._d: OrderedDict = OrderedDict()
        self._bytes = 0
        self._lock = TrackedLock(f"device_cache.{self._metric}")
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.evicted_bytes = 0

    def get_or_put(self, src, key_extra, builder: Callable, meter: bool = True):
        """The device copy of ``src`` (a numpy array) under derivation
        ``key_extra``, built by ``builder()`` on miss. ``builder`` returns a
        device array or a tuple of device arrays."""
        return self.get_or_put_multi((src,), key_extra, builder, meter=meter)

    def get_or_put_multi(self, srcs, key_extra, builder: Callable, meter: bool = True):
        """Like get_or_put but keyed on SEVERAL source arrays at once (e.g. a
        stacked per-join upload derived from every bucket's key buffer): the
        entry hits only while EVERY weakref still resolves to its original
        object, so id reuse on any constituent invalidates the whole stack.
        ``meter=False`` for builders that only derive device-side state from
        arrays already in HBM (the pipeline's chunk concatenation) — device
        bytes without a host->device transfer."""
        budget = _budget_bytes(self._budget_env, self._default_mb)
        if budget <= 0:
            value = builder()
            if meter and self is DEVICE_CACHE:  # cache off: still uploads
                from .rpc_meter import METER

                METER.record_upload(_tree_nbytes(value))
            return value
        srcs = tuple(srcs)
        key = (tuple(id(s) for s in srcs), key_extra)
        with self._lock:
            entry = self._d.get(key)
            if entry is not None:
                refs, value, nbytes = entry
                if all(r() is s for r, s in zip(refs, srcs)):
                    self._d.move_to_end(key)
                    self.hits += 1
                    _cache_counter(self._metric, "hits")
                    return value
                # an id was reused by a different array — stale entry
                del self._d[key]
                self._bytes -= nbytes
            self.misses += 1
        _cache_counter(self._metric, "misses")
        value, nbytes = self._build(key_extra, builder, meter)
        if nbytes > budget:
            return value
        try:
            refs = tuple(weakref.ref(s) for s in srcs)
        except TypeError:  # un-weakref-able source: don't cache
            return value
        with self._lock:
            existing = self._d.get(key)
            if existing is not None:
                # lost a concurrent build race: serve the already-cached
                # object so every caller holds THE resident copy (downstream
                # caches key on buffer identity); our duplicate upload is
                # dropped. The entry's refs are live — we hold srcs, so
                # their ids cannot have been reused.
                value = existing[1]
            else:
                self._d[key] = (refs, value, nbytes)
                self._bytes += nbytes
            evicted_n = evicted_b = 0
            while self._bytes > budget and self._d:
                _, (_r, _v, nb) = self._d.popitem(last=False)
                self._bytes -= nb
                evicted_n += 1
                evicted_b += nb
            self.evictions += evicted_n
            self.evicted_bytes += evicted_b
            occupancy = self._bytes
        if evicted_n:
            _cache_counter(self._metric, "evictions", evicted_n)
            _cache_counter(self._metric, "evicted_bytes", evicted_b)
        _cache_gauge(self._metric, occupancy)
        return value

    def _build(self, key_extra, builder: Callable, meter: bool = True):
        """Run the builder; a DEVICE_CACHE miss IS a host->device transfer,
        so it meters an upload and (when tracing) lands in an `upload` span."""
        if self is not DEVICE_CACHE or not meter:
            value = builder()
            return value, _tree_nbytes(value)
        from ..telemetry import attribution, trace
        from .rpc_meter import METER

        with trace.span("upload", key=str(key_extra)), \
                attribution.phase("upload"):
            value = builder()
            nbytes = _tree_nbytes(value)
            METER.record_upload(nbytes)
            trace.add_attr("nbytes", nbytes)
        return value, nbytes

    def get_or_put_keyed(self, key, builder: Callable):
        """Budgeted LRU entry under an explicit hashable ``key`` (no source
        buffer to validate — for deterministic values like padded masks)."""
        budget = _budget_bytes(self._budget_env, self._default_mb)
        if budget <= 0:
            value = builder()
            if self is DEVICE_CACHE:
                from .rpc_meter import METER

                METER.record_upload(_tree_nbytes(value))
            return value
        full_key = ("keyed", key)
        with self._lock:
            entry = self._d.get(full_key)
            if entry is not None:
                self._d.move_to_end(full_key)
                self.hits += 1
                _cache_counter(self._metric, "hits")
                return entry[1]
            self.misses += 1
        _cache_counter(self._metric, "misses")
        value, nbytes = self._build(key, builder)
        if nbytes > budget:
            return value
        with self._lock:
            if full_key not in self._d:
                self._d[full_key] = (None, value, nbytes)
                self._bytes += nbytes
            evicted_n = evicted_b = 0
            while self._bytes > budget and self._d:
                _, (_r, _v, nb) = self._d.popitem(last=False)
                self._bytes -= nb
                evicted_n += 1
                evicted_b += nb
            self.evictions += evicted_n
            self.evicted_bytes += evicted_b
            occupancy = self._bytes
        if evicted_n:
            _cache_counter(self._metric, "evictions", evicted_n)
            _cache_counter(self._metric, "evicted_bytes", evicted_b)
        _cache_gauge(self._metric, occupancy)
        return value

    @property
    def occupancy_bytes(self) -> int:
        return self._bytes

    def check_consistency(self) -> bool:
        """Byte accounting invariant: the occupancy counter equals the sum
        of the resident entries' sizes (race-stress gate)."""
        with self._lock:
            return self._bytes == sum(e[2] for e in self._d.values())

    def clear(self) -> None:
        with self._lock:
            self._d.clear()
            self._bytes = 0
        _cache_gauge(self._metric, 0)


# process-wide caches shared by every executor path: device uploads charge
# the device budget; cheap-to-recompute host derivations (argsorts,
# factorize results) get their own budget so they cannot evict transfers
DEVICE_CACHE = DeviceArrayCache()
HOST_DERIVED_CACHE = DeviceArrayCache("HYPERSPACE_HOST_DERIVED_CACHE_MB", "512")
