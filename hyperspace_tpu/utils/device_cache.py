"""Device-resident array cache.

Repeated queries over the same index chunks re-shipped every column to the
device on every execution; on remote-TPU backends (the axon tunnel) that
costs ~10 ms per 16 MB plus a round trip, which dominates sub-second
queries. This cache keeps the device copy alive keyed by the *source numpy
array's object identity* — the columnar chunk cache (columnar/io.py) serves
shallow copies whose underlying ``.data`` buffers are shared and immutable,
so object identity is a sound content key.

Safety against id() reuse: each entry holds a weakref to the source array
and a lookup only hits when the weakref still resolves to the *same object*
(a dead or rebound ref is evicted). Mutated/derived arrays get fresh ids and
therefore fresh entries. Eviction is least-recently-used by device bytes
(``HYPERSPACE_DEVICE_CACHE_MB``, default 2048; 0 disables).
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import OrderedDict
from typing import Callable


def _budget_bytes(env: str, default_mb: str) -> int:
    return int(float(os.environ.get(env, default_mb)) * 2**20)


def _tree_nbytes(value) -> int:
    if isinstance(value, (tuple, list)):
        return sum(_tree_nbytes(v) for v in value)
    return getattr(value, "nbytes", 0)


class DeviceArrayCache:
    def __init__(self, budget_env: str = "HYPERSPACE_DEVICE_CACHE_MB", default_mb: str = "2048") -> None:
        self._budget_env = budget_env
        self._default_mb = default_mb
        self._d: OrderedDict = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get_or_put(self, src, key_extra, builder: Callable):
        """The device copy of ``src`` (a numpy array) under derivation
        ``key_extra``, built by ``builder()`` on miss. ``builder`` returns a
        device array or a tuple of device arrays."""
        budget = _budget_bytes(self._budget_env, self._default_mb)
        if budget <= 0:
            return builder()
        key = (id(src), key_extra)
        with self._lock:
            entry = self._d.get(key)
            if entry is not None:
                ref, value, nbytes = entry
                if ref() is src:
                    self._d.move_to_end(key)
                    self.hits += 1
                    return value
                # id was reused by a different array — stale entry
                del self._d[key]
                self._bytes -= nbytes
            self.misses += 1
        value = builder()
        nbytes = _tree_nbytes(value)
        if nbytes > budget:
            return value
        try:
            ref = weakref.ref(src)
        except TypeError:  # un-weakref-able source: don't cache
            return value
        with self._lock:
            if key not in self._d:
                self._d[key] = (ref, value, nbytes)
                self._bytes += nbytes
            while self._bytes > budget and self._d:
                _, (_r, _v, nb) = self._d.popitem(last=False)
                self._bytes -= nb
        return value

    def get_or_put_keyed(self, key, builder: Callable):
        """Budgeted LRU entry under an explicit hashable ``key`` (no source
        buffer to validate — for deterministic values like padded masks)."""
        budget = _budget_bytes(self._budget_env, self._default_mb)
        if budget <= 0:
            return builder()
        full_key = ("keyed", key)
        with self._lock:
            entry = self._d.get(full_key)
            if entry is not None:
                self._d.move_to_end(full_key)
                self.hits += 1
                return entry[1]
            self.misses += 1
        value = builder()
        nbytes = _tree_nbytes(value)
        if nbytes > budget:
            return value
        with self._lock:
            if full_key not in self._d:
                self._d[full_key] = (None, value, nbytes)
                self._bytes += nbytes
            while self._bytes > budget and self._d:
                _, (_r, _v, nb) = self._d.popitem(last=False)
                self._bytes -= nb
        return value

    def clear(self) -> None:
        with self._lock:
            self._d.clear()
            self._bytes = 0


# process-wide caches shared by every executor path: device uploads charge
# the device budget; cheap-to-recompute host derivations (argsorts,
# factorize results) get their own budget so they cannot evict transfers
DEVICE_CACHE = DeviceArrayCache()
HOST_DERIVED_CACHE = DeviceArrayCache("HYPERSPACE_HOST_DERIVED_CACHE_MB", "512")
