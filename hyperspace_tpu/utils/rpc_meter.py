"""Device-RPC accounting: dispatches, fetches, and transfer bytes.

On remote-tunnel backends (the axon TPU plugin) every jitted-kernel dispatch
and every blocking fetch pays a ~75 ms round trip, so the device tier's
economics are decided by COUNTS as much as bytes. The meter makes those
counts first-class: execution paths record each kernel dispatch, each
``device_get``, and each host->device transfer; benchmarks snapshot the
counters around a query and publish the deltas (VERDICT r3 item 1: "record
per-query RPC/transfer counts in the artifact so losses are attributable").

Thread-safe; negligible overhead (a lock + integer adds per event, against
milliseconds-scale device work).
"""

from __future__ import annotations

from ..staticcheck.concurrency import TrackedLock


def _tree_nbytes(value) -> int:
    if isinstance(value, (tuple, list)):
        return sum(_tree_nbytes(v) for v in value)
    if isinstance(value, dict):
        return sum(_tree_nbytes(v) for v in value.values())
    return getattr(value, "nbytes", 0)


class RpcMeter:
    def __init__(self) -> None:
        self._lock = TrackedLock("rpc_meter")
        self.dispatches = 0  # jitted kernel calls (async dispatch RPCs)
        self.fetches = 0  # blocking device_get round trips
        self.uploads = 0  # host->device array transfers
        self.upload_bytes = 0
        self.fetch_bytes = 0

    def record_dispatch(self, n: int = 1) -> None:
        # the one funnel every jitted-kernel dispatch passes through right
        # before the call — which makes it the `device.dispatch` injection
        # point: an armed fault raises here, inside the caller's
        # record_device_failure try block, exactly like a dead tunnel
        from . import faults

        faults.fire("device.dispatch")
        with self._lock:
            self.dispatches += n

    def record_upload(self, nbytes: int, n: int = 1) -> None:
        # `device.upload` injection point: every REAL host->device transfer
        # (monolithic, chunk-streamed, join, mesh) meters through here —
        # a device-cache hit moves no bytes, so it never faults either
        from . import faults

        faults.fire("device.upload")
        with self._lock:
            self.uploads += n
            self.upload_bytes += nbytes
        if self is METER:
            from ..telemetry.metrics import REGISTRY

            REGISTRY.counter("rpc.upload_bytes").inc(nbytes)

    def record_fetch(self, nbytes: int, n: int = 1) -> None:
        with self._lock:
            self.fetches += n
            self.fetch_bytes += nbytes
        if self is METER:
            from ..telemetry.metrics import REGISTRY

            REGISTRY.counter("rpc.fetch_bytes").inc(nbytes)

    def snapshot(self) -> dict:
        # all five counters read under the SAME lock acquisition the writers
        # hold, so a snapshot is a consistent point-in-time cut — reading the
        # public attributes directly can interleave with a concurrent
        # record_upload and pair a new `uploads` with an old `upload_bytes`
        with self._lock:
            return {
                "dispatches": self.dispatches,
                "fetches": self.fetches,
                "uploads": self.uploads,
                "upload_bytes": self.upload_bytes,
                "fetch_bytes": self.fetch_bytes,
            }

    @staticmethod
    def delta(before: dict, after: dict) -> dict:
        return {k: after[k] - before[k] for k in before}

    def delta_since(self, before: dict) -> dict:
        return self.delta(before, self.snapshot())

    def measure(self) -> "MeterDelta":
        """Context manager capturing the meter delta around a block:

            with METER.measure() as m:
                run_query()
            print(m.delta["dispatches"])

        Replaces the snapshot-subtract pattern each caller re-implemented.
        """
        return MeterDelta(self)


class MeterDelta:
    def __init__(self, meter: RpcMeter):
        self._meter = meter
        self._before: dict = {}
        self.delta: dict = {}

    def __enter__(self) -> "MeterDelta":
        self._before = self._meter.snapshot()
        return self

    def __exit__(self, *exc) -> bool:
        self.delta = self._meter.delta_since(self._before)
        return False


METER = RpcMeter()


def device_get(tree):
    """``jax.device_get`` with fetch accounting — use this in execution
    paths instead of calling jax directly so every blocking round trip
    lands in the meter (and, when tracing is on, in a `fetch` span). The
    one funnel every blocking fetch passes through, so it is also the
    serving query's "fetch" phase chokepoint."""
    import time

    import jax

    from ..telemetry import attribution, trace
    from . import faults

    with trace.span("fetch"):
        faults.fire("device.fetch")
        t0 = time.perf_counter()
        out = jax.device_get(tree)
        attribution.charge_phase("fetch", time.perf_counter() - t0)
        nbytes = _tree_nbytes(out)
        METER.record_fetch(nbytes)
        trace.add_attr("nbytes", nbytes)
    return out
