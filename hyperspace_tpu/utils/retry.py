"""Bounded retry with deterministic backoff for transient host-side failures.

A multi-file streamed scan dies today on a single transient IO error even
though the other 199 files decode fine — and transient errors are exactly
what network filesystems, overlay mounts, and the fault-injection harness
produce. This module is the one retry policy for host IO: bounded attempts,
exponential backoff with *deterministic* jitter (no RNG state, no
cross-test flake), and a transient/permanent classifier so structural
errors (missing file, bad schema) fail immediately instead of burning
retries.

Used by ``columnar/io.py`` around the per-file parquet/csv/json decode
units and the footer-stats parse — the chokepoints every scan path (the
monolithic readers, ``iter_chunks`` on the IO pool, the maintenance cache)
funnels through, so one wrap covers them all.

Observability: ``io.retry.attempts`` counts actual re-attempts (0 on a
clean run), ``io.retry.gave_up`` counts exhaustion; each re-attempt emits a
``retry:<what>`` span event naming the attempt and the error. The sleep is
injectable (``clock=``) so unit tests exercise full backoff schedules
without ever sleeping; hslint HS401 keeps ``time.sleep`` from leaking
anywhere else.

Knob: ``HYPERSPACE_IO_RETRIES`` — total attempts per unit (default 3);
``1`` disables retrying without touching the call sites.
"""

from __future__ import annotations

import time
import zlib
from typing import Callable

from . import env

# Backoff shape: attempt k (1-based re-attempt) sleeps
#   min(MAX_DELAY, BASE * 2**(k-1)) * (0.5 + 0.5 * jitter)
# where jitter in [0, 1) is a crc32 hash of (what, k) — deterministic for a
# given call site and attempt, decorrelated across sites.
BASE_DELAY_S = 0.05
MAX_DELAY_S = 2.0


class _Transient:
    """Marker mixin alternative: see is_transient."""


def is_transient(err: BaseException) -> bool:
    """Transient = worth re-attempting with the same inputs.

    - OS-level IO errors are transient (network FS hiccups, EINTR, the
      injected ``InjectedIOError``) EXCEPT the structural ones where a
      retry provably re-fails: missing paths, permissions, is-a-directory.
    - ``pyarrow``'s ``ArrowIOError`` subclasses ``IOError`` → transient;
      its parse/semantic errors (``ArrowInvalid`` etc.) do not → permanent.
    - Everything else (HyperspaceError, ValueError, MemoryError, crash
      injections) is permanent: retrying cannot change the outcome.
    """
    if isinstance(
        err,
        (
            FileNotFoundError,
            PermissionError,
            IsADirectoryError,
            NotADirectoryError,
        ),
    ):
        return False
    return isinstance(err, (OSError, ConnectionError, TimeoutError))


def _jitter(what: str, attempt: int) -> float:
    """Deterministic jitter in [0, 1): stable per (site, attempt), no RNG."""
    return (zlib.crc32(f"{what}:{attempt}".encode()) % 1000) / 1000.0


def backoff_delay(what: str, attempt: int) -> float:
    """Sleep before re-attempt ``attempt`` (1-based) of unit ``what``."""
    raw = min(MAX_DELAY_S, BASE_DELAY_S * (2 ** (attempt - 1)))
    return raw * (0.5 + 0.5 * _jitter(what, attempt))


def retry_attempts() -> int:
    try:
        return max(1, env.env_int("HYPERSPACE_IO_RETRIES"))
    except ValueError:
        return 3


def retry_call(
    fn: Callable,
    what: str,
    attempts: "int | None" = None,
    classify: Callable[[BaseException], bool] = is_transient,
    clock: "Callable[[float], None] | None" = None,
):
    """``fn()`` with up to ``attempts`` tries; re-attempts only on errors
    ``classify`` deems transient, sleeping ``backoff_delay`` between tries
    via ``clock`` (default ``time.sleep``; tests inject a fake). The final
    failure propagates unchanged — callers' error handling (ChunkReadError
    wrapping, footer keep-file semantics) sees exactly the error they
    always saw, just fewer of them."""
    total = retry_attempts() if attempts is None else max(1, attempts)
    sleep = time.sleep if clock is None else clock
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:
            attempt += 1
            if attempt >= total or not classify(e):
                if attempt > 1:
                    from ..telemetry.metrics import REGISTRY

                    REGISTRY.counter("io.retry.gave_up").inc()
                raise
            from ..telemetry import trace
            from ..telemetry.metrics import REGISTRY

            REGISTRY.counter("io.retry.attempts").inc()
            trace.add_event(
                f"retry:{what}", attempt=attempt, error=type(e).__name__
            )
            sleep(backoff_delay(what, attempt))
