"""Shared thread-pool sizing for IO-bound fan-out.

Every IO-bound pool in the engine — the parallel parquet reader
(columnar/io.py), the bucket-pair loaders of the co-partitioned join
(plan/bucket_join.py), and the index-maintenance compaction/read pools
(models/covering.py) — sizes itself through this one helper, so
``HYPERSPACE_IO_THREADS`` governs them all uniformly. pyarrow releases the
GIL during decode, which is why a small pool scales near-linearly; values
``<= 1`` mean fully serial execution (the pipeline's debug fallback).
"""

from __future__ import annotations

import os

from . import env


def io_thread_cap(default_cap: int = 8) -> int:
    """Configured pool width: ``HYPERSPACE_IO_THREADS``, default
    ``min(default_cap, nproc)``. Unparseable values mean serial (1)."""
    try:
        return int(
            env.read_raw(
                "HYPERSPACE_IO_THREADS", str(min(default_cap, os.cpu_count() or 1))
            )
        )
    except ValueError:
        return 1


def io_worker_count(n_items: int, cap: int | None = None) -> int:
    """Pool width for ``n_items`` IO-bound tasks: the configured cap,
    clamped by the item count and an optional caller cap (e.g. a memory
    budget or a real-core bound), never below 1 — ThreadPoolExecutor
    requires a positive width even for empty work lists."""
    width = io_thread_cap()
    if cap is not None:
        width = min(width, cap)
    return max(1, min(width, n_items))
