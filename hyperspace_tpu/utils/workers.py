"""Shared thread-pool sizing AND creation for IO-bound fan-out.

Every IO-bound pool in the engine — the parallel parquet reader
(columnar/io.py), the bucket-pair loaders of the co-partitioned join
(plan/bucket_join.py), and the index-maintenance compaction/read pools
(models/covering.py) — sizes itself through ``io_worker_count`` and
constructs itself through ``io_pool``, so ``HYPERSPACE_IO_THREADS``
governs them all uniformly and every worker thread carries an ``hs-*``
name (thread dumps and the lock-order audit attribute work to a
subsystem). pyarrow releases the GIL during decode, which is why a small
pool scales near-linearly; values ``<= 1`` mean fully serial execution
(the pipeline's debug fallback).

This module (plus the backend prober in utils/backend.py) is the only
sanctioned thread/pool creation site — hslint HS304 flags
``threading.Thread`` / ``ThreadPoolExecutor`` construction anywhere else
in the package, so stray unnamed threads can't appear outside the audited
chokepoints.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor

from . import env
from ..staticcheck.concurrency import TrackedLock


def io_thread_cap(default_cap: int = 8) -> int:
    """Configured pool width: ``HYPERSPACE_IO_THREADS``, default
    ``min(default_cap, nproc)``. Unparseable values mean serial (1)."""
    try:
        return int(
            env.read_raw(
                "HYPERSPACE_IO_THREADS", str(min(default_cap, os.cpu_count() or 1))
            )
        )
    except ValueError:
        return 1


def io_worker_count(n_items: int, cap: int | None = None) -> int:
    """Pool width for ``n_items`` IO-bound tasks: the configured cap,
    clamped by the item count and an optional caller cap (e.g. a memory
    budget or a real-core bound), never below 1 — ThreadPoolExecutor
    requires a positive width even for empty work lists."""
    width = io_thread_cap()
    if cap is not None:
        width = min(width, cap)
    return max(1, min(width, n_items))


def io_pool(max_workers: int, thread_name_prefix: str = "hs-io") -> ThreadPoolExecutor:
    """The engine's ThreadPoolExecutor constructor (hslint HS304 chokepoint):
    every pool gets an ``hs-*`` thread-name prefix so stack dumps, the trace
    layer, and the lock-order audit can attribute worker activity."""
    return ThreadPoolExecutor(
        max_workers=max_workers, thread_name_prefix=thread_name_prefix
    )


_SHARED_POOL: "ThreadPoolExecutor | None" = None
_shared_pool_lock = TrackedLock("workers.shared_pool")  # singleton swap


def shared_io_pool() -> ThreadPoolExecutor:
    """The process-wide decode pool serving-layer streams share. Under the
    query scheduler, the per-iterator pools of the scan/join streamers
    would multiply to ``queries x HYPERSPACE_IO_THREADS`` threads; the
    shared pool instead bounds TOTAL decode parallelism at
    ``io_thread_cap()`` so N concurrent queries interleave their chunk
    decodes as tasks on one engine pool (query A's dispatch overlaps
    query B's decode on the same workers).

    Only top-level read-ahead units may run here: a shared-pool task that
    blocked on another shared-pool task could starve the pool (the nested
    per-file fan-out in ``_pmap_ordered`` keeps its own short-lived pools
    for exactly that reason). Never shut down — read-ahead futures are
    cancelled by their stream's ``finally``, so exit stays prompt."""
    global _SHARED_POOL
    with _shared_pool_lock:
        if _SHARED_POOL is None:
            _SHARED_POOL = ThreadPoolExecutor(
                max_workers=io_thread_cap(), thread_name_prefix="hs-engine-io"
            )
        return _SHARED_POOL


def spawn_thread(target, name: str, daemon: bool = True, args: tuple = ()) -> threading.Thread:
    """Create AND start a named thread (hslint HS304 chokepoint). Daemon by
    default: engine background threads (the backend prober) must never block
    interpreter shutdown."""
    t = threading.Thread(target=target, name=name, daemon=daemon, args=args)
    t.start()
    return t
