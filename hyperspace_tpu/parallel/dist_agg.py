"""Distributed filter-aggregate over a device mesh.

The scaled form of the fused query kernel: columns live sharded across the
mesh (one shard per device, ICI within a slice / DCN across slices — jax
inserts the collectives either way), each shard runs the fused
filter+aggregate locally, and a `psum` tree combines the partials. This is
what an accelerated Q6 looks like when the index chunks exceed one chip's
HBM — the analogue of Spark's partial→final aggregation over executors,
minus the shuffle (only scalars cross the interconnect).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import mesh_row_axes, shard_map
from ..ops.intsum import int_chunk_sums


def _row_axis(mesh: Mesh, axis):
    """Resolve the data axis: explicit, or every axis of the mesh. On a
    hierarchical (dcn, ici) mesh the collectives run over the axis TUPLE —
    XLA lowers psum(('dcn','ici')) as an intra-slice ICI reduction followed
    by a cross-slice DCN combine of the already-reduced partials, so row
    data never crosses DCN."""
    if axis is not None:
        return axis
    return mesh_row_axes(mesh)


def distributed_filter_aggregate(
    mesh: Mesh,
    cols: dict[str, jnp.ndarray],
    mask: jnp.ndarray,
    pred_fn: Callable[[dict[str, jnp.ndarray]], jnp.ndarray],
    agg_fns: dict[str, Callable[[dict[str, jnp.ndarray], jnp.ndarray], jnp.ndarray]],
    axis: "str | tuple[str, ...] | None" = None,
) -> dict[str, jnp.ndarray]:
    """Run pred_fn + per-shard reductions under shard_map, psum the results.

    cols/mask: arrays sharded on the leading dim over `axis`;
    pred_fn(cols) -> bool array; agg_fns: name -> fn(cols, final_mask) ->
    scalar partial (summed across shards).
    Returns {name: replicated scalar}.
    """
    axis = _row_axis(mesh, axis)

    def body(cols_shard, mask_shard):
        m = mask_shard & pred_fn(cols_shard)
        out = {}
        for name, fn in agg_fns.items():
            out[name] = jax.lax.psum(fn(cols_shard, m), axis)
        return out

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis), cols), P(axis)),
        out_specs=jax.tree.map(lambda _: P(), dict(agg_fns)),
        check_vma=False,
    )
    from ..telemetry import trace
    from ..utils.rpc_meter import METER

    with trace.span("kernel:dist_filter_agg", aggs=len(agg_fns)):
        METER.record_dispatch()
        # Utility API keyed by caller-supplied closures (pred_fn/agg_fns):
        # no sound automatic fingerprint exists, so this jits per call.
        # The query path caches its mesh kernels via KERNEL_CACHE instead
        # (tpu_exec mesh route + build_distributed_grouped_kernel below).
        # hslint: HS201 — per-call closures; no cacheable fingerprint
        return jax.jit(fn)(cols, mask)


def build_distributed_grouped_kernel(
    mesh: Mesh,
    pred_fn: Callable | None,
    agg_list: list[tuple[str, Callable]],
    seg_pad: int,
    axis: "str | tuple[str, ...] | None" = None,
):
    """Build (and jit once — callers cache) a mesh kernel for grouped
    aggregation: every shard segment-reduces its rows (group ids are global,
    factorized host-side), then a psum/pmin/pmax tree combines per-group
    partials — only [seg_pad]-sized vectors cross the interconnect, never
    rows. Global aggregates are the seg_pad-with-one-group special case.

    agg_list: (kind, value_fn(cols)->vals) with kind in
    sum/count/min/max/avg. Kernel returns (counts, first_masked,
    tuple(outputs)), replicated — first_masked is the GLOBAL row index of
    each group's first predicate-passing row (pmin over shard-local
    minima), so assembly orders output rows exactly like the host tier."""
    axis = _row_axis(mesh, axis)
    axes = axis if isinstance(axis, (tuple, list)) else (axis,)
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

    def body(cols_shard, gids_shard, mask_shard):
        m = mask_shard
        if pred_fn is not None:
            m = m & pred_fn(cols_shard)
        g = jnp.where(m, gids_shard, seg_pad - 1)
        counts = jax.lax.psum(
            jax.ops.segment_sum(jnp.ones_like(g, dtype=jnp.int32), g, num_segments=seg_pad),
            axis,
        )
        # global row index = linear shard index * shard length + local row
        shard_idx = jnp.int32(0)
        for a in axes:
            shard_idx = shard_idx * axis_sizes[a] + jax.lax.axis_index(a)
        local_idx = jnp.arange(g.shape[0], dtype=jnp.int32)
        global_idx = shard_idx * jnp.int32(g.shape[0]) + local_idx
        first_masked = jax.lax.pmin(
            jax.ops.segment_min(
                jnp.where(m, global_idx, jnp.int32(2**31 - 1)),
                g,
                num_segments=seg_pad,
            ),
            axis,
        )
        out = []
        for kind, fn in agg_list:
            if kind == "count":
                out.append(counts)
                continue
            vals = fn(cols_shard)
            int_vals = jnp.issubdtype(vals.dtype, jnp.integer)
            if kind == "sum":
                if int_vals:
                    # exact int accumulation: psum each 8-bit chunk's
                    # per-shard segment sums; the caller's global row cap
                    # keeps every psum total within int32, and the host
                    # recombines into int64 exactly (tiers must agree)
                    out.append(
                        tuple(
                            jax.lax.psum(c, axis)
                            for c in int_chunk_sums(vals, g, seg_pad)
                        )
                    )
                else:
                    out.append(
                        jax.lax.psum(jax.ops.segment_sum(vals, g, num_segments=seg_pad), axis)
                    )
            elif kind == "min":
                out.append(
                    jax.lax.pmin(jax.ops.segment_min(vals, g, num_segments=seg_pad), axis)
                )
            elif kind == "max":
                out.append(
                    jax.lax.pmax(jax.ops.segment_max(vals, g, num_segments=seg_pad), axis)
                )
            elif kind == "avg":
                if int_vals:  # exact chunked sums; the host divides
                    out.append(
                        tuple(
                            jax.lax.psum(c, axis)
                            for c in int_chunk_sums(vals, g, seg_pad)
                        )
                    )
                else:
                    s = jax.lax.psum(jax.ops.segment_sum(vals, g, num_segments=seg_pad), axis)
                    out.append(s / jnp.maximum(counts, 1))
        return counts, first_masked, tuple(out)

    def wrapper(cols, gids, mask):
        inner = shard_map(
            body,
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(axis), cols), P(axis), P(axis)),
            out_specs=(P(), P(), tuple(P() for _ in agg_list)),
            check_vma=False,
        )
        return inner(cols, gids, mask)

    # hslint: HS201 — builder runs via KERNEL_CACHE.get_or_build (tpu_exec)
    return jax.jit(wrapper)


def shard_columns(
    mesh: Mesh, cols: dict, axis: "str | tuple[str, ...] | None" = None
) -> tuple[dict, "jnp.ndarray"]:
    """Pad to a multiple of the mesh size and place each column sharded on
    the leading dimension. Returns (cols, mask)."""
    import numpy as np

    from .mesh import num_shards

    axis = _row_axis(mesh, axis)
    n = len(next(iter(cols.values())))
    d = num_shards(mesh, axis)
    padded = ((n + d - 1) // d) * d
    sharding = NamedSharding(mesh, P(axis))
    from ..utils.rpc_meter import METER

    out = {}
    nbytes = 0
    for name, arr in cols.items():
        a = np.asarray(arr)
        if padded != n:
            a = np.pad(a, (0, padded - n))
        out[name] = jax.device_put(jnp.asarray(a), sharding)
        nbytes += a.nbytes
    mask = jax.device_put(
        jnp.asarray(np.arange(padded) < n), sharding
    )
    METER.record_upload(nbytes + mask.nbytes, n=len(out) + 1)
    return out, mask
