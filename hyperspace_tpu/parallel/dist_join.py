"""Distributed co-partitioned merge join over a device mesh.

The scaled form of the Exchange-free sort-merge join that covering join
indexes buy (ref: covering/JoinIndexRule.scala:635-720 + Spark's bucketed
SMJ, execution/BucketUnionExec.scala:52-121): both sides are pre-bucketed on
the join key, bucket b lives on shard b % n, so every device probes ITS
buckets against ITS buckets with ZERO inter-chip communication — the
sharding already is the shuffle. One shard_map call serves a whole wave of
buckets; no collective appears in the body because co-partitioning makes
the join embarrassingly shard-local (the ICI stays idle by design, unlike
the raw-table join whose all_to_all it replaces).

The probe phase (per-left-row lower bound + match count over the sorted
right keys) is static-shaped and runs on device; run expansion to pair
indices is dynamic-sized and stays on the host, exactly like the
single-device plain join (plan/device_join.py), so results are
bit-identical to the host merge join.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import SHARD_AXIS, shard_map
from ..plan.kernel_cache import MESH_CACHE, mesh_probe_fingerprint

# alias kept for tests/tools poking cache state directly
_PROBE_CACHE = MESH_CACHE


def _build_probe(mesh: Mesh, axis: str):
    def body(lk, rk, n_r):
        # [1, padL] / [1, padR] / [1] per shard — purely local, no psum
        lo = jnp.searchsorted(rk[0], lk[0], side="left")
        hi = jnp.searchsorted(rk[0], lk[0], side="right")
        n = n_r[0]
        lo = jnp.minimum(lo, n)
        hi = jnp.minimum(hi, n)
        return lo[None, :].astype(jnp.int32), (hi - lo)[None, :].astype(jnp.int32)

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P(axis)),
        check_vma=False,
    )
    return jax.jit(fn)  # hslint: HS201 — builder runs via MESH_CACHE.get_or_build


def mesh_join_probe(
    mesh: Mesh,
    lk_stack: np.ndarray,
    rk_stack: np.ndarray,
    n_r: np.ndarray,
    axis: str = SHARD_AXIS,
) -> tuple[np.ndarray, np.ndarray]:
    """Probe a wave of co-partitioned buckets, one per mesh shard.

    lk_stack: [S, padL] sorted left keys per bucket (padded with the dtype
    maximum); rk_stack: [S, padR] sorted right keys; n_r: [S] real right
    row counts. Returns host (starts [S, padL], counts [S, padL]) int64.
    """
    key = mesh_probe_fingerprint(
        id(mesh), axis, lk_stack.shape, rk_stack.shape, str(lk_stack.dtype)
    )
    fn = MESH_CACHE.get_or_build(
        key, lambda: _build_probe(mesh, axis), "mesh_probe"
    )
    shard = NamedSharding(mesh, P(axis))
    from ..telemetry import trace
    from ..utils.rpc_meter import METER, device_get as metered_get

    with trace.span(
        "kernel:mesh_join_probe", buckets=int(lk_stack.shape[0])
    ):
        METER.record_upload(lk_stack.nbytes + rk_stack.nbytes + n_r.nbytes, n=3)
        METER.record_dispatch()
        lo, cnt = metered_get(
            fn(
                jax.device_put(jnp.asarray(lk_stack), shard),
                jax.device_put(jnp.asarray(rk_stack), shard),
                jax.device_put(jnp.asarray(n_r.astype(np.int32)), shard),
            )
        )
    return np.asarray(lo).astype(np.int64), np.asarray(cnt).astype(np.int64)
