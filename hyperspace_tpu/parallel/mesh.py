"""Device-mesh helpers.

The sharding model (per the public scaling-book recipe): pick a Mesh, annotate
shardings with NamedSharding/PartitionSpec, let XLA insert collectives over
ICI (intra-slice) / DCN (multi-slice). Hyperspace workloads shard on one data
axis — rows/buckets — so the default mesh is 1-D ("shards"); index builds map
bucket b to shard b % n.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shards"


def device_mesh(num_devices: int | None = None, axis: str = SHARD_AXIS) -> Mesh:
    devices = jax.devices()
    n = num_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"Requested {n} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n]), (axis,))


def shard_rows(mesh: Mesh, axis: str = SHARD_AXIS) -> NamedSharding:
    """Rows sharded along the leading dim."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def num_shards(mesh: Mesh, axis: str = SHARD_AXIS) -> int:
    return mesh.shape[axis]


def active_mesh(session) -> Mesh | None:
    """The execution mesh requested by `hyperspace.tpu.exec.meshDevices`
    when that many devices actually exist; None otherwise. Device discovery
    goes through the watchdog-guarded probe so a hung backend degrades to
    the host/single-device path instead of freezing the caller."""
    if session is None:
        return None
    n = session.conf.exec_mesh_devices
    if n <= 1:
        return None
    from ..utils.backend import safe_device_count

    if safe_device_count() < n:
        return None
    return device_mesh(n)
