"""Device-mesh helpers.

The sharding model (per the public scaling-book recipe): pick a Mesh, annotate
shardings with NamedSharding/PartitionSpec, let XLA insert collectives over
ICI (intra-slice) / DCN (multi-slice). Hyperspace workloads shard on one data
axis — rows/buckets — so the default mesh is 1-D ("shards"); index builds map
bucket b to shard b % n.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level with a `check_vma` kwarg
    from jax import shard_map as _shard_map_impl

    _SHARD_MAP_KWARG = "check_vma"
except ImportError:  # older jax: experimental module, `check_rep` spelling
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _SHARD_MAP_KWARG = "check_rep"


def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
    """Version-guarded ``jax.shard_map``. Callers write the current
    (top-level, ``check_vma``) API; this shim translates for jax releases
    that only ship ``jax.experimental.shard_map.shard_map(check_rep=...)``."""
    kwargs = {_SHARD_MAP_KWARG: check_vma}
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


SHARD_AXIS = "shards"

# Hierarchical (multi-slice) axis names: "ici" is the fast intra-slice
# interconnect, "dcn" the slower cross-slice network. Shardings put the
# row dimension over BOTH axes so every chip holds a shard; collectives
# over ("dcn", "ici") lower hierarchically — XLA reduces within each slice
# over ICI first and only per-group partials cross DCN (the scaling-book
# recipe for multi-host reductions).
AXIS_DCN = "dcn"
AXIS_ICI = "ici"
HIER_AXES = (AXIS_DCN, AXIS_ICI)

def device_mesh(num_devices: int | None = None, axis: str = SHARD_AXIS) -> Mesh:
    devices = jax.devices()
    n = num_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"Requested {n} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n]), (axis,))


def hierarchical_mesh(num_slices: int, devices_per_slice: int) -> Mesh:
    """A 2-D (dcn, ici) mesh for multi-slice deployments: row i of the
    device grid is one slice (ICI-connected); slices talk over DCN. On a
    single host this still runs (axes are logical), which is how the CPU
    harness exercises the multi-slice code path."""
    devices = jax.devices()
    n = num_slices * devices_per_slice
    if n > len(devices):
        raise ValueError(f"Requested {n} devices, have {len(devices)}")
    grid = np.array(devices[:n]).reshape(num_slices, devices_per_slice)
    return Mesh(grid, HIER_AXES)


def is_hierarchical(mesh: Mesh) -> bool:
    """True for multi-axis (multi-slice) meshes. Row-moving paths (build
    exchange, mesh join probe) check this and stay intra-slice only."""
    return len(mesh.axis_names) != 1


def slice_submeshes(mesh: Mesh) -> list[Mesh]:
    """One flat 1-D mesh per slice of a hierarchical mesh: row i of the
    (dcn, ici) device grid becomes an independent ("shards",) mesh whose
    collectives ride that slice's ICI only. Multi-slice index builds
    partition their source rows across these submeshes so the bucket
    all_to_all never crosses DCN."""
    if not is_hierarchical(mesh):
        return [mesh]
    return [Mesh(row, (SHARD_AXIS,)) for row in mesh.devices]


def mesh_row_axes(mesh: Mesh):
    """The axis spec that shards the row dimension over every device of
    this mesh: the single data axis on a 1-D mesh, the (dcn, ici) pair on
    a hierarchical one."""
    names = tuple(mesh.axis_names)
    return names[0] if len(names) == 1 else names


def shard_rows(mesh: Mesh, axis: "str | tuple[str, ...] | None" = None) -> NamedSharding:
    """Rows sharded along the leading dim (over every mesh axis by default)."""
    return NamedSharding(mesh, P(axis if axis is not None else mesh_row_axes(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def num_shards(mesh: Mesh, axis: "str | tuple[str, ...] | None" = None) -> int:
    if axis is None:
        axis = mesh_row_axes(mesh)
    if isinstance(axis, tuple):
        out = 1
        for a in axis:
            out *= mesh.shape[a]
        return out
    return mesh.shape[axis]


def visible_devices(cap: int = 0) -> list:
    """The device list scale-out placement may target, resolved through the
    watchdog-guarded probe (``utils.backend.safe_device_count``) so a hung
    backend yields ``[]`` instead of freezing the caller. ``cap`` > 0 clamps
    the list (``HYPERSPACE_MESH_DEVICES``); the order is ``jax.devices()``
    order, which is stable for a process lifetime — placement determinism
    leans on that."""
    from ..utils.backend import safe_device_count

    n = safe_device_count()
    if n <= 0:
        return []
    devices = jax.devices()[:n]
    if cap > 0:
        devices = devices[:cap]
    return list(devices)


def active_mesh(session) -> Mesh | None:
    """The execution mesh requested by `hyperspace.tpu.exec.meshDevices`
    when that many devices actually exist; None otherwise. Device discovery
    goes through the watchdog-guarded probe so a hung backend degrades to
    the host/single-device path instead of freezing the caller."""
    if session is None:
        return None
    n = session.conf.exec_mesh_devices
    if n <= 1:
        return None
    from ..utils.backend import safe_device_count

    if safe_device_count() < n:
        return None
    slices = session.conf.exec_mesh_slices
    if slices > 1:
        return hierarchical_mesh(slices, n // slices)
    return device_mesh(n)
