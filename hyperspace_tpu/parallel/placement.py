"""Skew-aware bucket→device placement for mesh-sharded execution.

The covering indexes bucketize both join sides identically, so bucket work
units (band-wave items in ``plan/device_join``, streamed chunks in
``plan/tpu_exec``) are independent: any device may compute any unit and the
host-side fold reassembles results in bucket/chunk order, bit-identical to
single-device execution. That independence is what this module exploits —
it only decides *where* each unit runs, never *what* runs.

Placement policy (JSPIM-style skew awareness): the join memory planner's
per-bucket footer-stat estimates predict each bucket's decoded bytes. A
bucket predicted to exceed the per-device fair share is split into as many
ranges as shares it covers (its probe chunks then rotate through those
ranges), and all ranges are largest-first bin packed onto the least-loaded
device. Buckets with no stats fall back to deterministic round-robin —
counted in ``mesh.placement.fallbacks`` so a stats-starved workload is
visible. Everything is a pure function of the estimates dict and the
device count, so placement is deterministic for a fixed dataset.

Default-off behind ``HYPERSPACE_MESH``; locally the path is driven with
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` on CPU, where
placement, balance, and bit-identity are all provable at nproc=1.
"""

from __future__ import annotations

import math
from typing import Optional

from ..telemetry import trace
from ..telemetry.metrics import REGISTRY
from ..utils import env


def mesh_enabled() -> bool:
    """``HYPERSPACE_MESH=1`` — the scale-out placement master switch."""
    return env.env_bool("HYPERSPACE_MESH")


def mesh_devices() -> list:
    """The devices placement may target: ``[]`` when the knob is off or
    fewer than two devices are visible (a 1-device mesh is just the default
    device with extra bookkeeping)."""
    if not mesh_enabled():
        return []
    try:
        cap = env.env_int("HYPERSPACE_MESH_DEVICES")
    except ValueError:
        cap = 0
    from .mesh import visible_devices

    devices = visible_devices(cap)
    return devices if len(devices) >= 2 else []


def mesh_size() -> int:
    return len(mesh_devices())


def _query_offset() -> int:
    """The serving scheduler's home-device assignment for the current
    query (tenant-weighted occupancy argmin) — placement rotates its
    round-robin and tie-breaks from here so concurrent queries spread
    instead of all packing from ordinal 0."""
    from ..serve.context import current_query

    q = current_query()
    home = getattr(q, "device_home", None) if q is not None else None
    return int(home) if home is not None else 0


class Placement:
    """An immutable bucket→device assignment. ``chunk`` indexes a split
    bucket's probe chunks: a bucket planned into k ranges rotates its
    chunks through the k packed ordinals; unplanned buckets round-robin
    deterministically from the query's home offset."""

    __slots__ = ("devices", "_units", "_offset")

    def __init__(self, devices: list, units: dict, offset: int):
        self.devices = devices
        self._units = units  # bucket -> tuple[ordinal, ...] in range order
        self._offset = offset

    def ordinal_for(self, bucket: int, chunk: int = 0) -> int:
        ords = self._units.get(bucket)
        if ords is None:
            REGISTRY.counter("mesh.placement.fallbacks").inc()
            return (bucket + chunk + self._offset) % len(self.devices)
        return ords[chunk % len(ords)]

    def device_for(self, bucket: int, chunk: int = 0):
        return self.devices[self.ordinal_for(bucket, chunk)]

    def slot_for(self, bucket: int, chunk: int = 0) -> tuple:
        """The ``(ordinal, device)`` pair band schedulers thread through
        ``_BandScheduler.add`` — hashable, so it doubles as the wave
        grouping key."""
        o = self.ordinal_for(bucket, chunk)
        return o, self.devices[o]


def plan_bucket_placement(
    estimates: dict, devices: "list | None" = None, offset: int = 0
) -> Optional[Placement]:
    """Largest-first bin packing of predicted per-bucket decoded bytes
    onto the mesh. ``estimates`` maps bucket -> predicted bytes (buckets
    absent from it take the round-robin fallback at lookup time). None
    when no mesh is on."""
    if devices is None:
        devices = mesh_devices()
    ndev = len(devices)
    if ndev < 2:
        return None
    loads = [0.0] * ndev
    units: dict[int, tuple] = {}
    total = float(sum(estimates.values()))
    if estimates and total > 0:
        share = total / ndev
        # one work unit per fair share the bucket covers: a skewed bucket
        # becomes several ranges its split chunks rotate through, so ONE
        # hot bucket can no longer pin the balance to a single device
        work = []
        for b in sorted(estimates):
            nbytes = float(estimates[b])
            k = max(1, min(ndev, math.ceil(nbytes / share))) if nbytes > 0 else 1
            for i in range(k):
                work.append((nbytes / k, int(b), i))
        work.sort(key=lambda u: (-u[0], u[1], u[2]))
        placed: dict[int, list] = {}
        for nbytes, b, i in work:
            o = min(
                range(ndev), key=lambda d: (loads[d], (d - offset) % ndev)
            )
            loads[o] += nbytes
            placed.setdefault(b, []).append((i, o))
        units = {
            b: tuple(o for _i, o in sorted(pairs)) for b, pairs in placed.items()
        }
    REGISTRY.counter("mesh.placement.buckets").inc(len(estimates))
    used = [l for l in loads if l > 0]
    ratio = (max(used) / (sum(used) / len(used))) if used else 1.0
    if estimates:
        REGISTRY.gauge("mesh.placement.devices_used").set(len(used))
        REGISTRY.gauge("mesh.placement.bytes_imbalance_ratio").set(ratio)
    if trace.enabled():
        # zero-width marker carrying the packing outcome (join:resume idiom)
        with trace.span(
            "mesh:place", buckets=len(estimates), devices=ndev,
            devices_used=len(used), imbalance=round(ratio, 3),
        ):
            pass
    return Placement(devices, units, offset)


def plan_for_strategy(strategy) -> Optional[Placement]:
    """A Placement for one bucketed join, driven by the memory planner's
    footer-stat estimates (a stable read-only map — ``observe_actual``
    writes a separate observed-actuals ledger)."""
    devices = mesh_devices()
    if len(devices) < 2:
        return None
    estimates = {}
    if strategy is not None:
        estimates = {b: est[1] for b, est in strategy.estimates.items()}
    return plan_bucket_placement(estimates, devices, _query_offset())


class ChunkPlacer:
    """Greedy online least-loaded placement for streamed scan/agg chunks,
    where per-chunk sizes are only known as chunks decode. Deterministic
    in chunk arrival order (which the streaming executor fixes), so the
    same query places the same way every run."""

    __slots__ = ("devices", "_loads", "_offset")

    def __init__(self, devices: list, offset: int = 0):
        self.devices = devices
        self._loads = [0] * len(devices)
        self._offset = offset

    def next(self, nbytes: int):
        """(ordinal, device) for the next chunk; charges its bytes."""
        n = len(self.devices)
        o = min(range(n), key=lambda d: (self._loads[d], (d - self._offset) % n))
        self._loads[o] += max(int(nbytes), 1)
        return o, self.devices[o]


def chunk_placer() -> Optional[ChunkPlacer]:
    """A fresh ChunkPlacer when the mesh is on; None otherwise."""
    devices = mesh_devices()
    if len(devices) < 2:
        return None
    return ChunkPlacer(devices, _query_offset())
