"""all_to_all bucket exchange — the TPU-native replacement for Spark's hash
shuffle in bucketed index builds.

Reference behavior replaced: `repartition(numBuckets, indexedCols)` +
bucketed sorted write (covering/CoveringIndex.scala:56-71,
DataFrameWriterExtensions.scala:50-68) ran as a full JVM shuffle through
Spark's block manager. Here every device holds a row chunk, computes
destination shards from the shared hash (ops/hashing.py), and one
`lax.all_to_all` over the mesh axis moves rows across ICI (or DCN when the
mesh spans hosts); a per-device segmented sort finishes the bucket layout.

Static-shape contract (XLA requires fixed shapes): each device sends at most
`capacity` rows to each destination, padding with a validity mask. The kernel
also returns the true per-(src,dst) max count so the host can detect overflow
and re-launch with a larger capacity (size-class recompilation, one cache
entry per power-of-two capacity).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import SHARD_AXIS, shard_map


def _exchange_body(axis: str, n_dest: int, capacity: int, cols, dest):
    """Per-device body under shard_map. cols: pytree of [N] arrays;
    dest: [N] int32 in [0, n_dest). Returns (pytree of [n_dest*capacity],
    valid mask, overflow_max)."""
    n = dest.shape[0]
    order = jnp.argsort(dest)
    dest_sorted = dest[order]
    counts = jnp.bincount(dest_sorted, length=n_dest)
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    max_count = counts.max()

    # slot (d, m) <- sorted row at offsets[d] + m when m < counts[d]
    d_idx = jax.lax.broadcasted_iota(jnp.int32, (n_dest, capacity), 0)
    m_idx = jax.lax.broadcasted_iota(jnp.int32, (n_dest, capacity), 1)
    src_pos = offsets[d_idx] + m_idx
    valid = m_idx < counts[d_idx]
    src_pos = jnp.clip(src_pos, 0, n - 1)

    def build_send(col):
        return col[order][src_pos]  # [n_dest, capacity]

    send = jax.tree.map(build_send, cols)
    recv = jax.tree.map(
        lambda s: jax.lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=True),
        send,
    )
    valid_recv = jax.lax.all_to_all(valid, axis, split_axis=0, concat_axis=0, tiled=True)
    flat = jax.tree.map(lambda r: r.reshape(n_dest * capacity), recv)
    # overflow signal: global max of per-device max count
    overflow = jax.lax.pmax(max_count, axis)
    return flat, valid_recv.reshape(n_dest * capacity), overflow


def bucket_exchange(
    mesh: Mesh,
    cols: Any,
    dest: jnp.ndarray,
    capacity: int,
    axis: str = SHARD_AXIS,
):
    """Exchange rows so all rows with dest==d land on shard d.

    cols: pytree of arrays with leading dim = total rows (sharded over mesh);
    dest: int32 array aligned with cols (values in [0, num_shards));
    capacity: static per-(src,dst) row budget.

    Returns (cols_out, valid, overflow) where cols_out arrays have
    num_shards*capacity rows per shard (padded; valid marks real rows) and
    overflow is the true max per-(src,dst) count — if overflow > capacity the
    result is truncated and the caller must retry with a larger capacity.
    """
    n_dest = mesh.shape[axis]
    body = partial(_exchange_body, axis, n_dest, capacity)
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis), cols), P(axis)),
        out_specs=(jax.tree.map(lambda _: P(axis), cols), P(axis), P()),
        check_vma=False,
    )
    return fn(cols, dest)


def exchange_with_retry(mesh, cols, dest, rows_per_shard: int, axis: str = SHARD_AXIS):
    """Host wrapper: start from a balanced-capacity guess, grow by powers of
    two on overflow (skewed buckets). Each capacity is a separate compile
    cache entry."""
    from ..telemetry import trace
    from ..utils.rpc_meter import METER

    n = mesh.shape[axis]
    capacity = max(64, int(2 ** np.ceil(np.log2(max(1, 2 * rows_per_shard // n)))))
    while True:
        with trace.span("kernel:bucket_exchange", capacity=capacity):
            METER.record_dispatch()
            out, valid, overflow = bucket_exchange(mesh, cols, dest, capacity, axis)
            overflow = int(overflow)  # blocking read inside the span
        if overflow <= capacity:
            return out, valid
        capacity = int(2 ** np.ceil(np.log2(overflow)))


def partition_batch_mesh(batch, bucket_columns, num_buckets: int, mesh: Mesh, axis: str = SHARD_AXIS):
    """Bucket partition of a production index build, computed ON the mesh:
    key words shard across devices, the bucket hash runs on device with the
    exact arithmetic of the host path (ops/hashing), and one all_to_all
    moves (bucket, row-id) pairs so shard s owns every bucket ≡ s (mod D).

    Returns the same structure as ops.bucketize.partition_batch — per-bucket
    row indices in original row order, so downstream sort+write produce a
    bit-identical bucket layout — or None when the batch cannot shard
    (fewer rows than devices) and the host path should take over.

    Ref: the Spark hash shuffle behind repartition(numBuckets, cols)
    (covering/CoveringIndex.scala:56-71); here the shuffle decision — hash,
    placement, exchange — runs on the device mesh, and the host materializes
    each bucket's rows for the parquet write.
    """
    from jax.sharding import NamedSharding

    from ..ops.bucketize import key_hash_words
    from ..ops.hashing import _words_np, bucket_ids_jnp

    from .mesh import is_hierarchical

    if is_hierarchical(mesh):
        # build row-exchange is intra-slice by design: all_to_all must ride
        # ICI, never DCN (rows are the big payload). On a hierarchical mesh
        # the host partitioner takes over; multi-slice builds partition
        # sources per slice upstream.
        return None
    D = mesh.shape[axis]
    n = batch.num_rows
    if n < D:
        return None
    padded = ((n + D - 1) // D) * D

    def pad32(a: np.ndarray) -> np.ndarray:
        out = np.zeros(padded, np.int32)
        out[:n] = a.view(np.int32) if a.dtype == np.uint32 else a.astype(np.int32)
        return out

    # decompose keys into uint32 words exactly as the host hash does (int64
    # and float64 split into two words; strings hash by value host-side and
    # ship their word), transported as int32 (no x64 on device)
    words: list[np.ndarray] = []
    for c in bucket_columns:
        for w in _words_np(np.asarray(key_hash_words(batch.column(c)))):
            words.append(pad32(w))
    row_id = np.full(padded, -1, np.int32)
    row_id[:n] = np.arange(n, dtype=np.int32)

    from ..telemetry import trace
    from ..utils.rpc_meter import METER

    with trace.span("kernel:mesh_partition", rows=n, buckets=num_buckets) as sp:
        shard = NamedSharding(mesh, P(axis))
        METER.record_upload(
            sum(w.nbytes for w in words) + row_id.nbytes, n=len(words) + 1
        )
        words_d = [jax.device_put(jnp.asarray(w), shard) for w in words]
        row_d = jax.device_put(jnp.asarray(row_id), shard)
        # each transported word is one single-word hash column; mixing order
        # matches hash32_np's word order, so placement is bit-identical
        bucket_d = bucket_ids_jnp(words_d, num_buckets)
        dest_d = bucket_d % jnp.int32(D)
        out, valid = exchange_with_retry(
            mesh, {"b": bucket_d, "r": row_d}, dest_d, padded // D, axis
        )

    b_np = np.asarray(out["b"])
    r_np = np.asarray(out["r"])
    sel = np.asarray(valid) & (r_np >= 0)
    if int(sel.sum()) != n:
        return None  # lost rows would corrupt the index: host path instead
    b_sel, r_sel = b_np[sel], r_np[sel]
    # stable by bucket: rows arrive shard-major / source-major, which is the
    # original row order within each bucket (same contract as the host
    # counting-sort partition)
    order = np.argsort(b_sel, kind="stable")
    b_sorted, r_sorted = b_sel[order], r_sel[order]
    bounds = np.searchsorted(b_sorted, np.arange(num_buckets + 1))
    return [
        (b, r_sorted[bounds[b]: bounds[b + 1]])
        for b in range(num_buckets)
        if bounds[b + 1] > bounds[b]
    ]
