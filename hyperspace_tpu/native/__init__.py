"""ctypes bindings for the native host kernels (native/hs_native.cpp).

Loads a prebuilt libhs_native.so next to this package, or builds it once
with the system compiler on first use; every entry point has a numpy
fallback so the framework works without a toolchain. Hash outputs are
bit-identical to ops/hashing.py (covered by a parity test) — bucket layout
is an on-disk contract.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

import numpy as np

from ..staticcheck.concurrency import TrackedLock

logger = logging.getLogger(__name__)

_LIB_NAME = "libhs_native.so"
_ABI_VERSION = 4

# named so the one-time compile/load critical section participates in the
# lock-order graph (it subprocesses the compiler while held — nothing else
# may nest inside it)
_lock = TrackedLock("native.load")
_lib: ctypes.CDLL | None = None
_tried = False


def _source_path() -> str:
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo_root, "native", "hs_native.cpp")


def _lib_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), _LIB_NAME)


# the exact flags the .so was (or would be) built with — bench artifacts
# record these so host-tier numbers are reproducible
BUILD_FLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC"]
COMPILER = "g++"


def build_facts() -> dict:
    """Self-description for benchmark artifacts: compiler, flags, and
    whether the native library is CURRENTLY loaded (vs numpy fallbacks).
    Reads load state without triggering a build — callers that want the
    library pay for it on their own hot path, not while collecting facts."""
    facts = {"compiler": COMPILER, "flags": list(BUILD_FLAGS), "abi": _ABI_VERSION}
    try:
        out = subprocess.run(
            [COMPILER, "--version"], capture_output=True, text=True, timeout=10
        )
        facts["compiler_version"] = out.stdout.splitlines()[0] if out.stdout else None
    except Exception:
        facts["compiler_version"] = None
    facts["loaded"] = _lib is not None
    return facts


def _build() -> bool:
    src = _source_path()
    if not os.path.exists(src):
        return False
    out = _lib_path()
    cmd = [COMPILER, *BUILD_FLAGS, src, "-o", out]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception as e:  # missing compiler, sandbox, ... -> numpy fallback
        logger.info("native build skipped (%s); using numpy fallbacks", e)
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = _lib_path()
        if not os.path.exists(path) and not _build():
            return None
        try:
            lib = ctypes.CDLL(path)
            if lib.hs_native_abi_version() != _ABI_VERSION:
                logger.warning("stale %s (ABI mismatch); rebuilding", _LIB_NAME)
                os.unlink(path)
                if not _build():
                    return None
                lib = ctypes.CDLL(path)
            _configure(lib)
            _lib = lib
        except OSError as e:
            # corrupt or foreign-arch artifact: rebuild once from source
            logger.info("native load failed (%s); rebuilding", e)
            try:
                os.unlink(path)
            except OSError:
                pass  # hslint: HS402 — best-effort removal; the rebuild overwrites anyway
            if _build():
                try:
                    lib = ctypes.CDLL(path)
                    _configure(lib)
                    _lib = lib
                except OSError:
                    logger.info("native rebuild failed; using numpy fallbacks")
        return _lib


def _configure(lib: ctypes.CDLL) -> None:
    u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    lib.hs_hash32_i64.argtypes = [i64p, ctypes.c_int64, u32p]
    lib.hs_hash32_i32.argtypes = [i32p, ctypes.c_int64, u32p]
    lib.hs_hash32_words.argtypes = [u32p, ctypes.c_int64, ctypes.c_int32, u32p]
    lib.hs_bucket_partition.argtypes = [
        u32p, ctypes.c_int64, ctypes.c_int32, i32p, i64p, i64p,
    ]
    lib.hs_join_i64.argtypes = [
        i64p, ctypes.c_int64, i64p, ctypes.c_int64, i64p, i64p, ctypes.c_int64,
    ]
    lib.hs_join_i64.restype = ctypes.c_int64
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    lib.hs_probe_agg_i64.argtypes = [
        i64p, ctypes.c_int64, i64p, ctypes.c_int64,
        f64p, ctypes.c_int32, i64p, f64p,
    ]
    lib.hs_probe_agg_i64.restype = ctypes.c_int64
    lib.hs_radix_argsort_i64.argtypes = [i64p, ctypes.c_int64, i64p]
    lib.hs_radix_argsort_i32.argtypes = [i32p, ctypes.c_int64, i64p]


def radix_argsort(keys: np.ndarray) -> np.ndarray | None:
    """Stable O(n)-per-digit argsort for int64/int32 keys (index-build
    bucket sorts); None -> numpy stable argsort fallback."""
    lib = _load()
    if lib is None or len(keys) < 4096:  # numpy wins at tiny sizes
        return None
    out = np.empty(len(keys), dtype=np.int64)
    if keys.dtype == np.int64:
        lib.hs_radix_argsort_i64(np.ascontiguousarray(keys), len(keys), out)
        return out
    if keys.dtype == np.int32:
        lib.hs_radix_argsort_i32(np.ascontiguousarray(keys), len(keys), out)
        return out
    return None


def available() -> bool:
    return _load() is not None


def hash32(keys: np.ndarray) -> np.ndarray | None:
    """Native single-column hash for int32/int64 keys; None -> caller falls
    back to the numpy implementation."""
    lib = _load()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys)
    out = np.empty(len(keys), dtype=np.uint32)
    if keys.dtype == np.int64:
        lib.hs_hash32_i64(keys, len(keys), out)
        return out
    if keys.dtype == np.int32:
        lib.hs_hash32_i32(keys, len(keys), out)
        return out
    return None


def hash32_words(words: list[np.ndarray]) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    n = len(words[0])
    stacked = np.ascontiguousarray(
        np.concatenate([np.ascontiguousarray(w, dtype=np.uint32) for w in words])
    )
    out = np.empty(n, dtype=np.uint32)
    lib.hs_hash32_words(stacked, n, len(words), out)
    return out


def bucket_partition(hashes: np.ndarray, num_buckets: int):
    """(bucket_ids, order, offsets) via counting sort; None on no native lib."""
    lib = _load()
    if lib is None:
        return None
    hashes = np.ascontiguousarray(hashes, dtype=np.uint32)
    n = len(hashes)
    bucket_ids = np.empty(n, dtype=np.int32)
    order = np.empty(n, dtype=np.int64)
    offsets = np.empty(num_buckets + 1, dtype=np.int64)
    lib.hs_bucket_partition(hashes, n, num_buckets, bucket_ids, order, offsets)
    return bucket_ids, order, offsets


def join_i64(lcodes: np.ndarray, rcodes: np.ndarray) -> "tuple[np.ndarray, np.ndarray] | None":
    """Native inner hash join of factorized int64 code arrays (negative
    codes never match). Pair order matches the numpy sort+searchsorted path
    (left-major, ascending right within a key). None -> numpy fallback."""
    lib = _load()
    if lib is None:
        return None
    lcodes = np.ascontiguousarray(lcodes, dtype=np.int64)
    rcodes = np.ascontiguousarray(rcodes, dtype=np.int64)
    cap = max(len(lcodes), len(rcodes), 1)
    while True:
        li = np.empty(cap, dtype=np.int64)
        ri = np.empty(cap, dtype=np.int64)
        total = lib.hs_join_i64(lcodes, len(lcodes), rcodes, len(rcodes), li, ri, cap)
        if total <= cap:
            return li[:total], ri[:total]
        cap = int(total)


def probe_agg_i64(lk: np.ndarray, rk_sorted: np.ndarray, weights: "list[np.ndarray]"):
    """Fused probe + per-key accumulation: counts[nr] and one float64 sum
    vector per weight array, over a sorted unique int64 right side.
    None -> numpy fallback."""
    lib = _load()
    if lib is None:
        return None
    lk = np.ascontiguousarray(lk, dtype=np.int64)
    rk = np.ascontiguousarray(rk_sorted, dtype=np.int64)
    w = len(weights)
    stacked = np.ascontiguousarray(
        np.stack([np.ascontiguousarray(x, dtype=np.float64) for x in weights])
        if w
        else np.zeros((0, len(lk)))
    ).reshape(-1)
    counts = np.empty(len(rk), dtype=np.int64)
    sums = np.empty((w, len(rk)), dtype=np.float64)
    lib.hs_probe_agg_i64(lk, len(lk), rk, len(rk), stacked, w, counts, sums.reshape(-1))
    return counts, [sums[i] for i in range(w)]
