"""Reference-compatible API aliases.

Reference parity: python/hyperspace/hyperspace.py:9-260 and
indexconfig.py:1-31 — camelCase method names on the Hyperspace handle and the
IndexConfig alias, so reference users' scripts port by changing imports only.
"""

from __future__ import annotations

from .hyperspace import Hyperspace as _Hyperspace
from .models.covering import CoveringIndexConfig
from .models.zorder import ZOrderCoveringIndexConfig

# reference python binding names
IndexConfig = CoveringIndexConfig
ZOrderIndexConfig = ZOrderCoveringIndexConfig


class Hyperspace(_Hyperspace):
    """Hyperspace handle with the reference's camelCase surface."""

    def createIndex(self, df, config) -> None:  # noqa: N802
        self.create_index(df, config)

    def deleteIndex(self, name: str) -> None:  # noqa: N802
        self.delete_index(name)

    def restoreIndex(self, name: str) -> None:  # noqa: N802
        self.restore_index(name)

    def vacuumIndex(self, name: str) -> None:  # noqa: N802
        self.vacuum_index(name)

    def refreshIndex(self, name: str, mode: str = "full") -> None:  # noqa: N802
        self.refresh_index(name, mode)

    def optimizeIndex(self, name: str, mode: str = "quick") -> None:  # noqa: N802
        self.optimize_index(name, mode)

    def whyNot(self, df, indexName: str = "", extended: bool = False, redirectFunc=None):  # noqa: N802
        return self.why_not(df, indexName, extended, redirectFunc)


def enableHyperspace(session):  # noqa: N802
    """ref: Implicits.enableHyperspace (package.scala:40-44)."""
    return session.enable_hyperspace()


def disableHyperspace(session):  # noqa: N802
    return session.disable_hyperspace()


def isHyperspaceEnabled(session) -> bool:  # noqa: N802
    return session.is_hyperspace_enabled()
