// hs_native — host-side hot loops of the index build, in C++.
//
// The reference's equivalents run inside Spark's JVM codegen (hash
// partitioning + sort for bucketed writes); the XLA path covers the
// device side, and this library covers the host-resident case: one pass
// computes the murmur-style hash (bit-identical to ops/hashing.py — the
// bucket layout is an on-disk contract), and a counting-sort partition
// replaces the O(n log n) stable argsort with O(n + buckets).
//
// Build: g++ -O3 -std=c++17 -shared -fPIC native/hs_native.cpp -o libhs_native.so
// Exposed via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>

namespace {

constexpr uint32_t C1 = 0xCC9E2D51u;
constexpr uint32_t C2 = 0x1B873593u;
constexpr uint32_t SEED = 42u;

inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

inline uint32_t mix_round(uint32_t h, uint32_t k) {
  k *= C1;
  k = rotl32(k, 15);
  k *= C2;
  h ^= k;
  h = rotl32(h, 13);
  h = h * 5u + 0xE6546B64u;
  return h;
}

inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  h ^= h >> 16;
  return h;
}

}  // namespace

extern "C" {

// hash of a single int64 key column: words (lo, hi), matching
// ops/hashing.hash32_np's int64 decomposition
void hs_hash32_i64(const int64_t* keys, int64_t n, uint32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    uint64_t bits;
    std::memcpy(&bits, &keys[i], 8);
    uint32_t h = SEED;
    h = mix_round(h, static_cast<uint32_t>(bits & 0xFFFFFFFFull));
    h = mix_round(h, static_cast<uint32_t>(bits >> 32));
    out[i] = fmix32(h);
  }
}

// hash of a single int32 key column (one word)
void hs_hash32_i32(const int32_t* keys, int64_t n, uint32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    uint32_t h = SEED;
    h = mix_round(h, static_cast<uint32_t>(keys[i]));
    out[i] = fmix32(h);
  }
}

// hash of pre-extracted uint32 words, w columns laid out column-major
// (words[c*n + i]): the generic multi-column path
void hs_hash32_words(const uint32_t* words, int64_t n, int32_t w, uint32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    uint32_t h = SEED;
    for (int32_t c = 0; c < w; ++c) {
      h = mix_round(h, words[static_cast<int64_t>(c) * n + i]);
    }
    out[i] = fmix32(h);
  }
}

// stable counting-sort partition by bucket = hash % num_buckets.
// Outputs: bucket_ids[n], order[n] (row indices grouped by bucket, stable
// within bucket), offsets[num_buckets+1] (bucket boundaries in order).
void hs_bucket_partition(const uint32_t* hashes, int64_t n, int32_t num_buckets,
                         int32_t* bucket_ids, int64_t* order,
                         int64_t* offsets) {
  for (int32_t b = 0; b <= num_buckets; ++b) offsets[b] = 0;
  for (int64_t i = 0; i < n; ++i) {
    int32_t b = static_cast<int32_t>(hashes[i] % static_cast<uint32_t>(num_buckets));
    bucket_ids[i] = b;
    offsets[b + 1] += 1;
  }
  for (int32_t b = 0; b < num_buckets; ++b) offsets[b + 1] += offsets[b];
  // scatter (stable): cursor per bucket
  int64_t* cursor = new int64_t[num_buckets];
  for (int32_t b = 0; b < num_buckets; ++b) cursor[b] = offsets[b];
  for (int64_t i = 0; i < n; ++i) {
    order[cursor[bucket_ids[i]]++] = i;
  }
  delete[] cursor;
}

// Inner hash join of two int64 code arrays (pre-factorized join keys;
// negative codes are NULL sentinels that never match). Chained hash table
// over the RIGHT side, probe from the LEFT, preserving left-major then
// right-original pair order (the order np.repeat+expand_runs produces, so
// results are interchangeable with the numpy path).
//
// Writes up to `cap` pairs into li_out/ri_out and returns the TOTAL pair
// count; if the return value exceeds cap the caller must retry with a
// larger buffer (the table build is O(nr), so a retry is cheap).
int64_t hs_join_i64(const int64_t* lcodes, int64_t nl, const int64_t* rcodes,
                    int64_t nr, int64_t* li_out, int64_t* ri_out,
                    int64_t cap) {
  // power-of-two table, ~2x right rows
  int64_t tbits = 1;
  while ((int64_t(1) << tbits) < nr * 2) ++tbits;
  const int64_t tsize = int64_t(1) << tbits;
  const uint64_t mask = static_cast<uint64_t>(tsize - 1);
  int64_t* head = new int64_t[tsize];
  int64_t* next = new int64_t[nr > 0 ? nr : 1];
  for (int64_t i = 0; i < tsize; ++i) head[i] = -1;
  // insert right rows in REVERSE so chain traversal yields ascending
  // original right order per key
  for (int64_t j = nr - 1; j >= 0; --j) {
    int64_t c = rcodes[j];
    if (c < 0) { next[j] = -1; continue; }
    uint64_t h = static_cast<uint64_t>(c) * 0x9E3779B97F4A7C15ull;
    uint64_t slot = (h >> 17) & mask;
    next[j] = head[slot];
    head[slot] = j;
  }
  int64_t total = 0;
  for (int64_t i = 0; i < nl; ++i) {
    int64_t c = lcodes[i];
    if (c < 0) continue;
    uint64_t h = static_cast<uint64_t>(c) * 0x9E3779B97F4A7C15ull;
    for (int64_t j = head[(h >> 17) & mask]; j != -1; j = next[j]) {
      if (rcodes[j] == c) {
        if (total < cap) {
          li_out[total] = i;
          ri_out[total] = j;
        }
        ++total;
      }
    }
  }
  delete[] head;
  delete[] next;
  return total;
}

// Fused probe + per-key accumulation for the co-partitioned join+aggregate
// hot shape (one int64 equi-key, sorted unique right side, aggregate inputs
// from the left side): for each left row, one binary search finds its right
// key slot; counts and W weighted sums accumulate per slot in a single
// pass — no match-index materialization, no intermediate mask arrays.
// weights is column-major [w][nl]; sums_out is [w][nr]; counts_out is [nr].
// float64 accumulation matches the numpy bincount path bit-for-bit in
// exactness class. Returns the number of matched left rows.
int64_t hs_probe_agg_i64(const int64_t* lk, int64_t nl,
                         const int64_t* rk_sorted, int64_t nr,
                         const double* weights, int32_t w,
                         int64_t* counts_out, double* sums_out) {
  for (int64_t j = 0; j < nr; ++j) counts_out[j] = 0;
  for (int64_t j = 0; j < static_cast<int64_t>(w) * nr; ++j) sums_out[j] = 0.0;
  int64_t matched = 0;
  for (int64_t i = 0; i < nl; ++i) {
    const int64_t key = lk[i];
    int64_t lo = 0, hi = nr;
    while (lo < hi) {
      const int64_t mid = (lo + hi) >> 1;
      if (rk_sorted[mid] < key) lo = mid + 1; else hi = mid;
    }
    if (lo >= nr || rk_sorted[lo] != key) continue;
    ++matched;
    counts_out[lo] += 1;
    for (int32_t c = 0; c < w; ++c) {
      sums_out[static_cast<int64_t>(c) * nr + lo] +=
          weights[static_cast<int64_t>(c) * nl + i];
    }
  }
  return matched;
}

// Stable LSD radix argsort on int64 keys (index-build bucket sort: numpy's
// stable argsort for int64 is a comparison sort; radix is O(n) per digit
// with uniform-digit passes skipped — key ranges rarely span all 8 bytes).
void hs_radix_argsort_i64(const int64_t* keys, int64_t n, int64_t* order) {
  // bias to unsigned so negatives order before non-negatives
  static constexpr uint64_t BIAS = 0x8000000000000000ull;
  auto hist = new int64_t[8][256]();
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t k = static_cast<uint64_t>(keys[i]) ^ BIAS;
    for (int d = 0; d < 8; ++d) ++hist[d][(k >> (d * 8)) & 0xFF];
  }
  for (int64_t i = 0; i < n; ++i) order[i] = i;
  int64_t* tmp = new int64_t[n > 0 ? n : 1];
  int64_t* src = order;
  int64_t* dst = tmp;
  for (int d = 0; d < 8; ++d) {
    const int64_t* h = hist[d];
    int nonzero = 0;
    for (int b = 0; b < 256 && nonzero < 2; ++b) nonzero += h[b] != 0;
    if (nonzero < 2) continue;  // uniform digit: pass is the identity
    int64_t offs[256];
    int64_t run = 0;
    for (int b = 0; b < 256; ++b) {
      offs[b] = run;
      run += h[b];
    }
    const int shift = d * 8;
    for (int64_t i = 0; i < n; ++i) {
      const int64_t idx = src[i];
      const uint64_t k = static_cast<uint64_t>(keys[idx]) ^ BIAS;
      dst[offs[(k >> shift) & 0xFF]++] = idx;
    }
    int64_t* t = src;
    src = dst;
    dst = t;
  }
  if (src != order) std::memcpy(order, src, static_cast<size_t>(n) * 8);
  delete[] tmp;
  delete[] hist;
}

// int32 variant (dates, dictionary codes): 4 digit passes
void hs_radix_argsort_i32(const int32_t* keys, int64_t n, int64_t* order) {
  static constexpr uint32_t BIAS = 0x80000000u;
  auto hist = new int64_t[4][256]();
  for (int64_t i = 0; i < n; ++i) {
    const uint32_t k = static_cast<uint32_t>(keys[i]) ^ BIAS;
    for (int d = 0; d < 4; ++d) ++hist[d][(k >> (d * 8)) & 0xFF];
  }
  for (int64_t i = 0; i < n; ++i) order[i] = i;
  int64_t* tmp = new int64_t[n > 0 ? n : 1];
  int64_t* src = order;
  int64_t* dst = tmp;
  for (int d = 0; d < 4; ++d) {
    const int64_t* h = hist[d];
    int nonzero = 0;
    for (int b = 0; b < 256 && nonzero < 2; ++b) nonzero += h[b] != 0;
    if (nonzero < 2) continue;
    int64_t offs[256];
    int64_t run = 0;
    for (int b = 0; b < 256; ++b) {
      offs[b] = run;
      run += h[b];
    }
    const int shift = d * 8;
    for (int64_t i = 0; i < n; ++i) {
      const int64_t idx = src[i];
      const uint32_t k = static_cast<uint32_t>(keys[idx]) ^ BIAS;
      dst[offs[(k >> shift) & 0xFF]++] = idx;
    }
    int64_t* t = src;
    src = dst;
    dst = t;
  }
  if (src != order) std::memcpy(order, src, static_cast<size_t>(n) * 8);
  delete[] tmp;
  delete[] hist;
}

int32_t hs_native_abi_version() { return 4; }

}  // extern "C"
