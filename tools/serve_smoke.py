#!/usr/bin/env python
"""Serving-layer gate: N concurrent clients through the query scheduler
must produce per-query results bit-identical to serial runs, with zero
lock-order violations, consistent cache byte accounting, and a fully
drained global budget at quiescence.

A serial pass runs every TPC-H query once (the reference bits, also
warming the shared caches); then ``SMOKE_CLIENTS`` client threads
(default 8) each submit the whole mixed query set ``SMOKE_REPEATS``
times (default 2, client-rotated order) to ONE shared ``QueryScheduler``
(``SMOKE_CONCURRENT`` workers, default 4) and compare every result to
the reference at ``float.hex()`` bit precision. A cancellation exercise
then submits queries and cancels them mid-flight, asserting the
scheduler stays healthy and the budget ledger returns to zero.

Asserted invariants (exit 0 iff all hold):

- every served result matches the serial reference bit for bit;
- ``staticcheck.lock.violations`` stays 0 with the acquisition-order
  audit forced on (``SMOKE_LOCK_AUDIT=0`` opts out);
- every bounded cache's ``check_consistency()`` holds at quiescence;
- the global budget ledger is consistent AND drained (held_bytes == 0);
- the scheduler reaches a quiescent state (nothing active or queued).

    timeout 300 env JAX_PLATFORMS=cpu python tools/serve_smoke.py

Env: SMOKE_CLIENTS (8), SMOKE_CONCURRENT (4), SMOKE_REPEATS (2),
SMOKE_ROWS (60000).
"""

import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


def main() -> int:
    os.environ.setdefault("HYPERSPACE_DEVICE_STRICT", "1")
    # small chunks so the streaming executor engages even at smoke row counts
    os.environ.setdefault("HYPERSPACE_STREAM_CHUNK_MB", "0.5")
    # force a real IO pool width: on a 1-core container the default
    # (min(8, nproc)) collapses to serial decode and the shared pool /
    # global-budget read-ahead machinery under test would never engage
    os.environ.setdefault("HYPERSPACE_IO_THREADS", "4")
    # a small global budget so backpressure (stalls/force grants) actually
    # fires during the smoke rather than only on production-sized scans
    os.environ.setdefault("HYPERSPACE_GLOBAL_BUDGET_MB", "8")
    if os.environ.get("SMOKE_LOCK_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LOCK_AUDIT", "1")
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import tempfile

    from hyperspace_tpu import Hyperspace, HyperspaceSession, serve
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.plan import kernel_cache as kc
    from hyperspace_tpu.staticcheck import concurrency as cc
    from hyperspace_tpu.telemetry.metrics import REGISTRY
    from hyperspace_tpu.utils import device_cache as dc

    clients = int(os.environ.get("SMOKE_CLIENTS", 8))
    concurrent = int(os.environ.get("SMOKE_CONCURRENT", 4))
    repeats = int(os.environ.get("SMOKE_REPEATS", 2))
    rows = int(os.environ.get("SMOKE_ROWS", 60_000))

    ws = tempfile.mkdtemp(prefix="hs_serve_smoke_")
    generate_tpch(ws, rows_lineitem=rows, seed=23)
    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, ws)
    session.enable_hyperspace()

    names = list(TPCH_QUERIES)

    # serial reference (also warms every shared cache)
    serial = {
        name: _bits(TPCH_QUERIES[name](session, ws).to_pydict())
        for name in names
    }

    sched = serve.QueryScheduler(
        max_concurrent=concurrent,
        queue_depth=max(64, clients * len(names)),
    )
    mismatches: list = []
    errors: list = []
    barrier = threading.Barrier(clients)

    def client(tid: int) -> None:
        try:
            barrier.wait()  # maximal admission contention
            for r in range(repeats):
                off = (tid + r) % len(names)
                order = names[off:] + names[:off]
                for name in order:
                    # closed loop: next submit waits for this result
                    h = sched.submit_query(
                        TPCH_QUERIES[name](session, ws),
                        label=f"c{tid}:{name}",
                        priority=tid % 3,
                    )
                    got = _bits(h.result(timeout=300).to_pydict())
                    if got != serial[name]:
                        mismatches.append((tid, name))
        except Exception as e:  # noqa: BLE001 - reported via the gate
            errors.append((tid, repr(e)))

    from hyperspace_tpu.utils.workers import spawn_thread

    threads = [
        spawn_thread(client, name=f"hs-smoke-client-{i}", daemon=False, args=(i,))
        for i in range(clients)
    ]
    for t in threads:
        t.join()
    sched.drain(timeout=60)

    # --- cancellation exercise: cancel mid-flight, ledger must drain ------
    cancel_ok = True
    cancelled_any = 0
    try:
        handles = [
            sched.submit_query(
                TPCH_QUERIES[name](session, ws), label=f"cancel:{name}"
            )
            for name in names
        ] * 1
        for h in handles:
            h.cancel()
        for h in handles:
            try:
                h.result(timeout=300)
            except serve.QueryCancelledError:
                cancelled_any += 1
            except Exception as e:  # noqa: BLE001 - reported via the gate
                errors.append(("cancel", repr(e)))
        sched.drain(timeout=60)
    except Exception as e:  # noqa: BLE001 - reported via the gate
        cancel_ok = False
        errors.append(("cancel-exercise", repr(e)))

    state = sched.state()
    budget = serve.global_budget()
    quiescent = not state["active"] and not state["queued"]
    budget_drained = budget.held_bytes() == 0 and budget.check_consistency()
    sched.shutdown(wait=True)

    consistency = {
        "io.index_chunk": cio._INDEX_CHUNK_CACHE.check_consistency(),
        "io.source_col": cio._SOURCE_COL_CACHE.check_consistency(),
        "io.rowgroup_stats": cio._ROWGROUP_STATS_CACHE.check_consistency(),
        "device": dc.DEVICE_CACHE.check_consistency(),
        "host_derived": dc.HOST_DERIVED_CACHE.check_consistency(),
        "kernel": kc.KERNEL_CACHE.check_consistency(),
        "kernel_join": kc.JOIN_CACHE.check_consistency(),
        "kernel_topk": kc.TOPK_CACHE.check_consistency(),
        "kernel_sort": kc.SORT_CACHE.check_consistency(),
    }

    lock_report = cc.report()

    def val(n: str) -> int:
        m = REGISTRY.get(n)
        return 0 if m is None else int(m.value)

    violations = val("staticcheck.lock.violations")
    ok = (
        not mismatches
        and not errors
        and cancel_ok
        and violations == 0
        and all(consistency.values())
        and budget_drained
        and quiescent
        # the machinery under test must actually have engaged: read-ahead
        # reserved through the global ledger (not the serial fallback)
        and val("serve.budget.reservations") > 0
    )
    out = {
        "rows": rows,
        "clients": clients,
        "max_concurrent": concurrent,
        "repeats": repeats,
        "queries": names,
        "served_runs": clients * repeats * len(names),
        "bit_identical": not mismatches and not errors,
        "mismatches": mismatches[:10],
        "errors": errors[:10],
        "cancelled_resolved": cancelled_any,
        "scheduler_totals": state["totals"],
        "scheduler_quiescent": quiescent,
        "budget_drained": budget_drained,
        "queue_wait_ms": (REGISTRY.get("serve.queue_wait_ms").value
                          if REGISTRY.get("serve.queue_wait_ms") else {}),
        "budget_counters": {
            n: val(f"serve.budget.{n}")
            for n in ("reservations", "stalls", "force_grants")
        },
        "lock_audit": lock_report["audit_enabled"],
        "lock_acquisitions": val("staticcheck.lock.acquisitions"),
        "lock_violations": violations,
        "cache_consistency": consistency,
        "ok": ok,
    }
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
