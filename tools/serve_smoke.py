#!/usr/bin/env python
"""Serving-layer gate: N concurrent clients through the query scheduler
must produce per-query results bit-identical to serial runs, with zero
lock-order violations, consistent cache byte accounting, a fully drained
global budget at quiescence — and, since the telemetry plane landed, a
conserved per-query attribution ledger under a live metrics exporter.

A serial pass runs every TPC-H query once (the reference bits, also
warming the shared caches); then ``SMOKE_CLIENTS`` client threads
(default 8) each submit the whole mixed query set ``SMOKE_REPEATS``
times (default 2, client-rotated order) to ONE shared ``QueryScheduler``
(``SMOKE_CONCURRENT`` workers, default 4) and compare every result to
the reference at ``float.hex()`` bit precision. A cancellation exercise
then submits queries and cancels them mid-flight, asserting the
scheduler stays healthy and the budget ledger returns to zero. The
metrics exporter runs on an ephemeral port for the whole serving phase
and a scraper thread hits /metrics + /snapshot + /healthz continuously.

Asserted invariants (exit 0 iff all hold):

- every served result matches the serial reference bit for bit;
- attribution conservation: for every ``io.* / cache.* / rpc.* /
  pipeline.* / pruning.* / serve.budget.*`` counter, the sum over
  per-query ledger entries equals the global counter's delta across the
  serving window (every increment was charged to exactly one query);
- every /metrics scrape parses as Prometheus text with internally
  consistent histograms (cumulative buckets, +Inf == _count) and every
  /snapshot parses as JSON — while serving is in full flight;
- ``staticcheck.lock.violations`` stays 0 with the acquisition-order
  audit forced on (``SMOKE_LOCK_AUDIT=0`` opts out);
- every bounded cache's ``check_consistency()`` holds at quiescence;
- the global budget ledger is consistent AND drained (held_bytes == 0);
- the scheduler reaches a quiescent state (nothing active or queued).

    timeout 300 env JAX_PLATFORMS=cpu python tools/serve_smoke.py

Env: SMOKE_CLIENTS (8), SMOKE_CONCURRENT (4), SMOKE_REPEATS (2),
SMOKE_ROWS (60000), SMOKE_EXPORTER=0 to skip the exporter/scrape leg.
"""

import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# counters charged exclusively inside query execution: the conservation
# set. (serve.*, exporter.*, staticcheck.* also increment on scheduler /
# scrape / auditor threads that serve no single query, so they are
# legitimately global-only.)
CONSERVED_PREFIXES = (
    "io.", "cache.", "rpc.", "pipeline.", "pruning.", "serve.budget.",
)


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


def _parse_prometheus(text: str) -> list:
    """Parse-and-validate a /metrics body; returns a list of violation
    strings (empty == consistent). Checks the text-format grammar plus
    the per-metric consistency cut: cumulative non-decreasing buckets
    and +Inf bucket == _count for every histogram."""
    errors = []
    buckets: dict[str, list] = {}
    counts: dict[str, float] = {}
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        parts = ln.rsplit(" ", 1)
        if len(parts) != 2:
            errors.append(f"unparseable line: {ln!r}")
            continue
        series, raw = parts
        try:
            val = float(raw)
        except ValueError:
            errors.append(f"non-numeric value: {ln!r}")
            continue
        if '{le="' in series:
            name = series.split("{", 1)[0]
            le = series.split('le="', 1)[1].split('"', 1)[0]
            buckets.setdefault(name, []).append((le, val))
        elif series.endswith("_count"):
            counts[series[: -len("_count")]] = val
    for name, bs in buckets.items():
        cum = [v for _le, v in bs]
        if any(later < earlier for earlier, later in zip(cum, cum[1:])):
            errors.append(f"{name}: buckets not cumulative: {bs}")
        base = name[: -len("_bucket")] if name.endswith("_bucket") else name
        if not bs or bs[-1][0] != "+Inf":
            errors.append(f"{name}: missing +Inf bucket")
        elif counts.get(base) != bs[-1][1]:
            errors.append(
                f"{name}: +Inf ({bs[-1][1]}) != _count ({counts.get(base)})"
            )
    return errors


def main() -> int:
    os.environ.setdefault("HYPERSPACE_DEVICE_STRICT", "1")
    # small chunks so the streaming executor engages even at smoke row counts
    os.environ.setdefault("HYPERSPACE_STREAM_CHUNK_MB", "0.5")
    # force a real IO pool width: on a 1-core container the default
    # (min(8, nproc)) collapses to serial decode and the shared pool /
    # global-budget read-ahead machinery under test would never engage
    os.environ.setdefault("HYPERSPACE_IO_THREADS", "4")
    # a small global budget so backpressure (stalls/force grants) actually
    # fires during the smoke rather than only on production-sized scans
    os.environ.setdefault("HYPERSPACE_GLOBAL_BUDGET_MB", "8")
    # every served query must stay in the ledger window or the
    # conservation sum would lose evicted entries' charges
    os.environ.setdefault("HYPERSPACE_QUERY_LOG_WINDOW", "4096")
    exporter_on = os.environ.get("SMOKE_EXPORTER", "1") == "1"
    if exporter_on:
        # ephemeral port: the scheduler's knob-gated autostart is exactly
        # the path under test
        os.environ.setdefault("HYPERSPACE_METRICS_PORT", "0")
    if os.environ.get("SMOKE_LOCK_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LOCK_AUDIT", "1")
    if os.environ.get("SMOKE_LIFECYCLE_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LIFECYCLE_AUDIT", "1")
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import tempfile

    from hyperspace_tpu import Hyperspace, HyperspaceSession, serve
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.plan import kernel_cache as kc
    from hyperspace_tpu.staticcheck import concurrency as cc
    from hyperspace_tpu.staticcheck import lifecycle as lc
    from hyperspace_tpu.telemetry import exporter as texp
    from hyperspace_tpu.telemetry.attribution import LEDGER
    from hyperspace_tpu.telemetry.metrics import REGISTRY
    from hyperspace_tpu.utils import device_cache as dc

    clients = int(os.environ.get("SMOKE_CLIENTS", 8))
    concurrent = int(os.environ.get("SMOKE_CONCURRENT", 4))
    repeats = int(os.environ.get("SMOKE_REPEATS", 2))
    rows = int(os.environ.get("SMOKE_ROWS", 60_000))

    ws = tempfile.mkdtemp(prefix="hs_serve_smoke_")
    generate_tpch(ws, rows_lineitem=rows, seed=23)
    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, ws)
    session.enable_hyperspace()

    names = list(TPCH_QUERIES)

    # serial reference (also warms every shared cache)
    serial = {
        name: _bits(TPCH_QUERIES[name](session, ws).to_pydict())
        for name in names
    }

    sched = serve.QueryScheduler(
        max_concurrent=concurrent,
        queue_depth=max(64, clients * len(names)),
    )
    mismatches: list = []
    errors: list = []
    barrier = threading.Barrier(clients)

    # --- live scraper: /metrics + /snapshot + /healthz during serving -----
    exp = texp.get_exporter() if exporter_on else None
    scrape_errors: list = []
    scrapes = {"metrics": 0, "snapshot": 0, "healthz": 0}
    scrape_stop = threading.Event()

    def scraper() -> None:
        while not scrape_stop.is_set():
            try:
                with urllib.request.urlopen(exp.url + "/metrics", timeout=10) as r:
                    scrape_errors.extend(
                        _parse_prometheus(r.read().decode("utf-8"))
                    )
                scrapes["metrics"] += 1
                with urllib.request.urlopen(exp.url + "/snapshot", timeout=10) as r:
                    snap = json.loads(r.read().decode("utf-8"))
                for key in ("ts", "metrics", "serving", "breaker", "queries"):
                    if key not in snap:
                        scrape_errors.append(f"/snapshot missing {key!r}")
                scrapes["snapshot"] += 1
                try:
                    with urllib.request.urlopen(exp.url + "/healthz", timeout=10) as r:
                        json.loads(r.read().decode("utf-8"))
                except urllib.error.HTTPError as he:
                    # 503 (degraded) is a VALID healthz answer; body must parse
                    json.loads(he.read().decode("utf-8"))
                scrapes["healthz"] += 1
            except Exception as e:  # noqa: BLE001 - reported via the gate
                scrape_errors.append(repr(e))
            scrape_stop.wait(0.05)

    # --- conservation baseline (after warmup, before any served query) ----
    def _conserved_counters() -> dict:
        return {
            name: value
            for name, kind, value in REGISTRY.export()
            if kind == "counter" and name.startswith(CONSERVED_PREFIXES)
        }

    g0 = _conserved_counters()
    l0 = {
        k: v
        for k, v in LEDGER.aggregate_counters().items()
        if k.startswith(CONSERVED_PREFIXES)
    }

    from hyperspace_tpu.utils.workers import spawn_thread

    scraper_thread = None
    if exp is not None:
        scraper_thread = spawn_thread(scraper, name="hs-smoke-scraper")

    def client(tid: int) -> None:
        try:
            barrier.wait()  # maximal admission contention
            for r in range(repeats):
                off = (tid + r) % len(names)
                order = names[off:] + names[:off]
                for name in order:
                    # closed loop: next submit waits for this result. The
                    # whole query (plan construction included) runs inside
                    # the submitted closure, so every increment lands
                    # under the query's attribution scope
                    h = sched.submit(
                        (lambda n=name: TPCH_QUERIES[n](session, ws)
                         .collect()),
                        label=f"c{tid}:{name}",
                        priority=tid % 3,
                    )
                    got = _bits(h.result(timeout=300).to_pydict())
                    if got != serial[name]:
                        mismatches.append((tid, name))
        except Exception as e:  # noqa: BLE001 - reported via the gate
            errors.append((tid, repr(e)))

    threads = [
        spawn_thread(client, name=f"hs-smoke-client-{i}", daemon=False, args=(i,))
        for i in range(clients)
    ]
    for t in threads:
        t.join()
    sched.drain(timeout=60)

    # --- cancellation exercise: cancel mid-flight, ledger must drain ------
    cancel_ok = True
    cancelled_any = 0
    try:
        handles = [
            sched.submit(
                (lambda n=name: TPCH_QUERIES[n](session, ws).collect()),
                label=f"cancel:{name}",
            )
            for name in names
        ]
        for h in handles:
            h.cancel()
        for h in handles:
            try:
                h.result(timeout=300)
            except serve.QueryCancelledError:
                cancelled_any += 1
            except Exception as e:  # noqa: BLE001 - reported via the gate
                errors.append(("cancel", repr(e)))
        sched.drain(timeout=60)
    except Exception as e:  # noqa: BLE001 - reported via the gate
        cancel_ok = False
        errors.append(("cancel-exercise", repr(e)))

    # --- attribution conservation: per-query sums == global deltas --------
    # (retry briefly: bound read-ahead tasks may still be landing charges)
    def _conservation_mismatches() -> dict:
        g1 = _conserved_counters()
        deltas = {
            k: g1.get(k, 0) - g0.get(k, 0) for k in set(g0) | set(g1)
        }
        lsum = {
            k: v - l0.get(k, 0)
            for k, v in LEDGER.aggregate_counters().items()
            if k.startswith(CONSERVED_PREFIXES)
        }
        return {
            k: {"global_delta": deltas.get(k, 0), "ledger_sum": lsum.get(k, 0)}
            for k in set(deltas) | set(lsum)
            if deltas.get(k, 0) != lsum.get(k, 0)
        }

    conservation = _conservation_mismatches()
    for _ in range(40):
        if not conservation:
            break
        time.sleep(0.25)  # hslint: HS401 — gate tool, straggler-charge settle
        conservation = _conservation_mismatches()

    if scraper_thread is not None:
        scrape_stop.set()
        scraper_thread.join(timeout=30)

    state = sched.state()
    budget = serve.global_budget()
    quiescent = not state["active"] and not state["queued"]
    budget_drained = budget.held_bytes() == 0 and budget.check_consistency()
    sched.shutdown(wait=True)
    texp.stop_exporter()
    texp.stop_snapshot_sink()

    consistency = {
        "io.index_chunk": cio._INDEX_CHUNK_CACHE.check_consistency(),
        "io.source_col": cio._SOURCE_COL_CACHE.check_consistency(),
        "io.rowgroup_stats": cio._ROWGROUP_STATS_CACHE.check_consistency(),
        "device": dc.DEVICE_CACHE.check_consistency(),
        "host_derived": dc.HOST_DERIVED_CACHE.check_consistency(),
        "kernel": kc.KERNEL_CACHE.check_consistency(),
        "kernel_join": kc.JOIN_CACHE.check_consistency(),
        "kernel_topk": kc.TOPK_CACHE.check_consistency(),
        "kernel_sort": kc.SORT_CACHE.check_consistency(),
    }

    lock_report = cc.report()
    # quiescence: served, cancelled, and rejected queries alike must have
    # released every handle (budget streams, pins, scopes, cache markers)
    leaks = [h.describe() for h in lc.check_quiescent(raise_on_leak=False)]
    lifecycle = lc.report()

    def val(n: str) -> int:
        m = REGISTRY.get(n)
        return 0 if m is None else int(m.value)

    violations = val("staticcheck.lock.violations")
    scrape_ok = exp is None or (
        not scrape_errors and all(v > 0 for v in scrapes.values())
    )
    ok = (
        not mismatches
        and not errors
        and cancel_ok
        and violations == 0
        and all(consistency.values())
        and budget_drained
        and quiescent
        and not conservation
        and scrape_ok
        # the machinery under test must actually have engaged: read-ahead
        # reserved through the global ledger (not the serial fallback),
        # and the ledger actually recorded the served queries
        and val("serve.budget.reservations") > 0
        and val("serve.query.records") >= clients * repeats * len(names)
        and not leaks
    )
    out = {
        "rows": rows,
        "clients": clients,
        "max_concurrent": concurrent,
        "repeats": repeats,
        "queries": names,
        "served_runs": clients * repeats * len(names),
        "bit_identical": not mismatches and not errors,
        "mismatches": mismatches[:10],
        "errors": errors[:10],
        "cancelled_resolved": cancelled_any,
        "scheduler_totals": state["totals"],
        "scheduler_quiescent": quiescent,
        "budget_drained": budget_drained,
        "attribution_conserved": not conservation,
        "conservation_mismatches": dict(list(conservation.items())[:10]),
        "ledger_records": val("serve.query.records"),
        "exporter": None if exp is None else {
            "url": exp.url,
            "scrapes": scrapes,
            "scrape_errors": scrape_errors[:10],
            "ok": scrape_ok,
        },
        "queue_wait_ms": (REGISTRY.get("serve.queue_wait_ms").value
                          if REGISTRY.get("serve.queue_wait_ms") else {}),
        "budget_counters": {
            n: val(f"serve.budget.{n}")
            for n in ("reservations", "stalls", "force_grants")
        },
        "lock_audit": lock_report["audit_enabled"],
        "lock_acquisitions": val("staticcheck.lock.acquisitions"),
        "lock_violations": violations,
        "cache_consistency": consistency,
        "lifecycle_audit": lifecycle["audit_enabled"],
        "lifecycle_acquires": lifecycle["acquires"],
        "lifecycle_releases": lifecycle["releases"],
        "lifecycle_leaks": leaks[:10],
        "ok": ok,
    }
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
