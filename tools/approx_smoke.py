#!/usr/bin/env python
"""Approximate-tier gate: exact-mode bit-identity, deadline-driven
degradation with honest error bounds, unbiased correlated join sampling,
and lock-order cleanliness.

Four invariant groups (exit 0 iff all hold):

- **Exact-mode bit-identity**: with ``HYPERSPACE_APPROX=1`` but no
  requested fraction (no ``approx_scope``, no QoS degrade), and again
  with approximation disabled entirely, every query result matches the
  pre-approx serial reference bit for bit — the tier is invisible until
  something asks for it.
- **CI honesty**: every sampled aggregate's 95% confidence interval
  covers the exact answer, in explicit ``approx_scope`` runs AND in
  ``HYPERSPACE_APPROX=verify`` mode (which executes exact alongside and
  raises on any miss).
- **Deadline degrade**: after the cost model learns an expensive label,
  a submit with an unmeetable deadline and ``allow_approx=True`` is NOT
  rejected — it runs sampled (``qos:admit`` decision "degraded"), its
  query-log record carries the ``approx`` block, the sampled wall beats
  the exact expectation, and the estimates' CIs cover exact. The same
  submit with ``allow_approx=False`` raises ``DeadlineUnmeetable`` and
  leaves an outcome="rejected" query-log record (the satellite bugfix:
  rejected queries used to vanish from the log entirely).
- **Honest under skew**: in a warehouse where one order key owns ~17% of
  lineitem rows, the sampled join either keeps the hot cluster whole
  (cluster-level variance sees it; CI must cover exact) or the skew
  guard declines the tier entirely (``approx.ineligible.hot-key``) and
  the answer is bit-exact. Never a quietly-wrong estimate.
- ``staticcheck.lock.violations`` stays 0 with ``HYPERSPACE_LOCK_AUDIT=1``
  (SMOKE_LOCK_AUDIT=0 opts out).

    timeout 300 env JAX_PLATFORMS=cpu python tools/approx_smoke.py

Env: SMOKE_ROWS (40000), SMOKE_FRACTION (0.1).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


def main() -> int:
    os.environ["HYPERSPACE_APPROX"] = "1"
    os.environ.setdefault("HYPERSPACE_QUERY_LOG_WINDOW", "4096")
    if os.environ.get("SMOKE_LOCK_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LOCK_AUDIT", "1")
    if os.environ.get("SMOKE_LIFECYCLE_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LIFECYCLE_AUDIT", "1")
    import tempfile

    os.environ.setdefault(
        "HYPERSPACE_WORKLOAD_DIR", tempfile.mkdtemp(prefix="hs_approx_wl_")
    )
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import glob
    import json
    import time

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu import Hyperspace, HyperspaceSession, serve
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import generate_tpch, tpch_indexes
    from hyperspace_tpu.models.covering import CoveringIndexConfig
    from hyperspace_tpu.plan import sampling
    from hyperspace_tpu.plan.expr import Count, Sum, col, lit
    from hyperspace_tpu.serve.scheduler import DeadlineUnmeetable
    from hyperspace_tpu.staticcheck import lifecycle as lc
    from hyperspace_tpu.telemetry import plan_stats
    from hyperspace_tpu.telemetry.attribution import LEDGER
    from hyperspace_tpu.telemetry.metrics import REGISTRY
    from hyperspace_tpu.serve import qos

    rows = int(os.environ.get("SMOKE_ROWS", 40_000))
    frac = float(os.environ.get("SMOKE_FRACTION", 0.1))
    failures: list[str] = []

    def check(ok: bool, msg: str) -> None:
        print(("PASS " if ok else "FAIL ") + msg)
        if not ok:
            failures.append(msg)

    ws = tempfile.mkdtemp(prefix="hs_approx_smoke_")
    generate_tpch(ws, rows_lineitem=rows, seed=31)

    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, ws)
    session.enable_hyperspace()

    twins = glob.glob(
        os.path.join(ws, "indexes", "**", "_sample.r*"), recursive=True
    )
    check(len(twins) > 0, f"sample twins written at create ({len(twins)})")

    def qjoin(date_cut: int = 9000):
        li = session.read.parquet(os.path.join(ws, "lineitem"))
        od = session.read.parquet(os.path.join(ws, "orders"))
        return (
            li.select("l_orderkey", "l_extendedprice")
            .join(
                od.select("o_orderkey", "o_orderdate"),
                col("l_orderkey") == col("o_orderkey"),
            )
            .filter(col("o_orderdate") < date_cut)
            .agg(
                Sum(col("l_extendedprice")).alias("rev"),
                Count(lit(1)).alias("n"),
            )
        )

    # --- 1) exact-mode bit-identity ------------------------------------
    ref = _bits(qjoin().to_pydict())
    check(
        _bits(qjoin().to_pydict()) == ref,
        "HYPERSPACE_APPROX=1 without a requested fraction is bit-identical",
    )
    os.environ["HYPERSPACE_APPROX"] = "0"
    with sampling.approx_scope(frac):
        got = _bits(qjoin().to_pydict())
    check(got == ref, "HYPERSPACE_APPROX=0 ignores approx_scope (bit-identical)")
    os.environ["HYPERSPACE_APPROX"] = "1"

    # --- 2) CI honesty (scope + verify mode) ---------------------------
    exact = qjoin().to_pydict()
    with plan_stats.collect_scope() as cap:
        with sampling.approx_scope(frac):
            approx = qjoin().to_pydict()
    info = (cap.summary() or {}).get("approx") or {}
    outs = info.get("outputs") or {}
    engaged = bool(outs)
    check(engaged, "sampled tier engaged under approx_scope")
    if engaged:
        for name in ("rev", "n"):
            ci = outs[name]["ci95_max"]
            diff = abs(float(approx[name][0]) - float(exact[name][0]))
            check(
                diff <= ci,
                f"CI covers exact for {name} (|err|={diff:.4g} <= ci={ci:.4g})",
            )
    os.environ["HYPERSPACE_APPROX"] = "verify"
    try:
        with sampling.approx_scope(frac):
            qjoin().collect()
        check(True, "verify mode: exact-alongside coverage check passed")
    except sampling.ApproxVerifyError as e:
        check(False, f"verify mode raised: {e}")
    os.environ["HYPERSPACE_APPROX"] = "1"

    # --- 3) hot-key skew: honest answer either way ---------------------
    # a separate warehouse where one order key owns ~17% of lineitem rows.
    # Universe sampling keeps or drops that cluster WHOLE: if the hash
    # keeps it, the cluster-level variance companion sees it and the CI
    # must cover exact; if the hash drops it, the sample is blind to a
    # dominant cluster and the skew guard must DECLINE the tier (the
    # result is then bit-exact). Either way, never a quietly-wrong answer.
    ws2 = tempfile.mkdtemp(prefix="hs_approx_hot_")
    generate_tpch(ws2, rows_lineitem=rows, seed=31)
    hot_n = rows // 5
    rng = np.random.default_rng(77)
    pq.write_table(
        pa.table(
            {
                "l_orderkey": np.full(hot_n, 17, dtype=np.int64),
                "l_partkey": rng.integers(0, rows // 30, hot_n),
                "l_suppkey": rng.integers(0, rows // 120, hot_n),
                "l_quantity": rng.integers(1, 51, hot_n).astype(np.float64),
                "l_extendedprice": rng.uniform(900, 105_000, hot_n),
                "l_discount": np.round(rng.uniform(0.0, 0.1, hot_n), 2),
                "l_tax": np.round(rng.uniform(0.0, 0.08, hot_n), 2),
                "l_returnflag": rng.choice(["A", "N", "R"], hot_n),
                "l_linestatus": rng.choice(["O", "F"], hot_n),
                "l_shipdate": rng.integers(8035, 10590, hot_n).astype(np.int32),
            }
        ),
        os.path.join(ws2, "lineitem", "part-hot.parquet"),
    )
    session2 = HyperspaceSession(warehouse_dir=ws2)
    session2.set_conf(C.INDEX_NUM_BUCKETS, 8)
    tpch_indexes(session2, Hyperspace(session2), ws2)
    session2.enable_hyperspace()

    def qhot():
        li = session2.read.parquet(os.path.join(ws2, "lineitem"))
        od = session2.read.parquet(os.path.join(ws2, "orders"))
        return (
            li.select("l_orderkey", "l_extendedprice")
            .join(
                od.select("o_orderkey", "o_orderdate"),
                col("l_orderkey") == col("o_orderkey"),
            )
            .agg(
                Sum(col("l_extendedprice")).alias("rev"),
                Count(lit(1)).alias("n"),
            )
        )

    e2 = qhot().to_pydict()
    with plan_stats.collect_scope() as cap2:
        with sampling.approx_scope(frac):
            a2 = qhot().to_pydict()
    sum2 = cap2.summary() or {}
    ap2 = sum2.get("approx") or {}
    outs2 = ap2.get("outputs") or {}
    if outs2:
        for name in ("rev", "n"):
            ci = outs2[name]["ci95_max"]
            diff = abs(float(a2[name][0]) - float(e2[name][0]))
            check(
                diff <= ci,
                f"hot-key CI covers exact for {name} "
                f"(|err|={diff:.4g} <= ci={ci:.4g})",
            )
    else:
        check(
            ap2.get("reason") == "hot-key",
            f"skew guard declined the tier (reason={ap2.get('reason')!r})",
        )
        check(
            _bits(a2) == _bits(e2),
            "declined hot-key query fell back to a bit-exact answer",
        )

    # --- 4) deadline degrade through the scheduler ---------------------
    sched = serve.QueryScheduler(max_concurrent=2, queue_depth=64)
    label = "approx-smoke-join"
    walls = []
    for _ in range(3):  # teach the cost model the exact-tier wall
        t0 = time.perf_counter()
        sched.submit(lambda: qjoin().collect(), label=label).result(timeout=120)
        walls.append(time.perf_counter() - t0)
    exact_mean = sum(walls) / len(walls)
    deadline = max(0.001, qos.COST_MODEL.predict(label) * 0.05)

    # allow_approx=False: typed rejection + outcome="rejected" in the log
    try:
        sched.submit(
            lambda: qjoin().collect(), label=label, deadline_s=deadline,
            allow_approx=False,
        )
        check(False, "allow_approx=False with unmeetable deadline raises")
    except DeadlineUnmeetable:
        check(True, "allow_approx=False with unmeetable deadline raises")
    rec = next(
        (
            r
            for r in reversed(LEDGER.recent_records())
            if r.get("outcome") == "rejected"
        ),
        None,
    )
    check(
        rec is not None,
        "deadline rejection leaves an outcome=rejected query-log record",
    )

    # allow_approx=True: degraded admit, sampled run, CI covers exact
    t0 = time.perf_counter()
    h = sched.submit(
        lambda: qjoin().collect(), label=label, deadline_s=deadline,
    )
    out = h.result(timeout=120)
    degraded_wall = time.perf_counter() - t0
    check(
        h.ctx.approx_fraction is not None,
        f"deadline miss degraded to sampled tier "
        f"(f={h.ctx.approx_fraction})",
    )
    drec = next(
        (
            r
            for r in reversed(LEDGER.recent_records())
            if (r.get("approx") or {}).get("degraded")
        ),
        None,
    )
    check(drec is not None, "degraded query-log record carries approx block")
    if drec is not None:
        check(
            bool((drec.get("approx") or {}).get("engaged")),
            "degraded query actually served from the sampled tier",
        )
        ap = drec.get("approx") or {}
        od = (ap.get("outputs") or {}).get("rev") or {}
        if od:
            diff = abs(float(out.to_pydict()["rev"][0]) - float(exact["rev"][0]))
            check(
                diff <= od.get("ci95_max", 0.0),
                "degraded run's CI covers the exact answer",
            )
    # at smoke scale fixed planning overhead dominates walls measured in
    # milliseconds, so this is a sanity bound only (absolute floor guards
    # against timer jitter); the >=5x latency win is asserted by the
    # approx_tier bench section at benchmark scale
    bound = max(5 * exact_mean, 0.5)
    check(
        degraded_wall < bound,
        f"degraded wall {degraded_wall:.3f}s within sanity bound "
        f"{bound:.3f}s (exact mean {exact_mean:.3f}s)",
    )
    sched.shutdown()

    # workload journal carries the approx block (flush first: appends are
    # async on the journal's writer thread, so reading the files right
    # after shutdown() races the last record)
    import hyperspace_tpu.telemetry.workload as workload

    workload.JOURNAL.flush()
    jrec = None
    for path in sorted(
        glob.glob(os.path.join(os.environ["HYPERSPACE_WORKLOAD_DIR"], "*.jsonl"))
    ):
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    r = json.loads(line)
                except ValueError:
                    continue
                if (r.get("approx") or {}).get("degraded"):
                    jrec = r
    check(
        jrec is not None or not workload.enabled(),
        "workload journal records the degrade decision",
    )

    # --- 5) lock audit --------------------------------------------------
    if os.environ.get("HYPERSPACE_LOCK_AUDIT") == "1":
        viol = next(
            (
                v
                for n, kind, v in REGISTRY.export()
                if n == "staticcheck.lock.violations" and kind == "counter"
            ),
            0,
        )
        check(viol == 0, f"0 lock-order violations under audit (got {viol})")

    # --- 6) lifecycle quiescence ----------------------------------------
    # the degraded/sampled paths, the scheduler rejections, and the verify
    # runs must all have released every handle they acquired
    leaks = [h.describe() for h in lc.check_quiescent(raise_on_leak=False)]
    lifecycle = lc.report()
    check(
        not leaks,
        "lifecycle quiescent (acquires="
        f"{lifecycle['acquires']} releases={lifecycle['releases']} "
        f"leaks={leaks[:5]})",
    )

    snap = sampling.APPROX.snapshot()
    print(f"approx telemetry: {snap}")
    if failures:
        print(f"\n{len(failures)} FAILURE(S)")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("\nALL PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
