#!/usr/bin/env python
"""hslint — AST lint enforcing hyperspace_tpu's codebase invariants.

Four PRs of rewriting left correctness resting on conventions nothing
checked: kernels compile only through the kernel cache, optimizer rules
always explain their rejections, env knobs live in one registry, shared
cache state mutates only under its lock. Each convention is a rule with a
stable code:

    HS1xx — plan / optimizer rules
      HS101  an IndexFilter subclass implements apply() without ever
             routing a rejection through tag_reason_if
      HS102  a module defines a HyperspaceRule with apply_index() but
             never emits usage events via rule_utils.log_index_usage

    HS2xx — kernels / device code
      HS201  bare jax.jit / pjit reference outside plan/kernel_cache.py
             (kernels must compile through a KernelCache so fingerprints,
             compile spans, and the retrace watchdog see them)

    HS3xx — concurrency / environment
      HS301  os.environ / os.getenv read outside utils/env.py (knob reads
             go through the typed registry)
      HS302  mutation of lock-guarded container state (an attribute
             initialised as dict/OrderedDict/set/list in a class that owns
             a threading/Tracked lock) outside a `with self.<lock>:` block
      HS303  wall-clock time.time() inside a `with trace.span(...)` block
             (span timing uses perf_counter; wall-clock there is a smell)
      HS304  threading.Thread / ThreadPoolExecutor construction outside
             utils/workers.py + utils/backend.py (threads come from the
             named, daemon-disciplined chokepoints so the lock-order audit
             and stack dumps can attribute them)
      HS305  module-level mutable container mutated from function scope
             with no guarded_by(...) declaration (the staticcheck
             concurrency registry) — shared state can't ship unguarded
      HS306  lexically nested lock acquisition (`with <lockA>:` containing
             `with <lockB>:`) without a declared order edge — declare the
             pair in staticcheck/concurrency.py DECLARED_EDGES, in a
             module-local DECLARED_EDGES, or justify a suppression

    HS4xx — robustness / failure handling
      HS401  time.sleep outside utils/retry.py + utils/backend.py (backoff
             goes through the one bounded, observable, fake-clockable
             retry policy — ad-hoc sleeps hide latency and flake)
      HS402  except-and-swallow: a broad handler (bare `except:`,
             Exception, BaseException, or OSError) whose body is only
             `pass` — swallowing errors silently hides real failures AND
             would absorb injected faults; justify with `# hslint: HS402`
             on the `pass` line when best-effort really is the contract

    HS5xx — resource release paths (staticcheck/lifecycle.py's static half)
      HS501  a call to a registered acquire function (stream, pin,
             protect_version, tracked_resource) whose release is not
             lexically guaranteed: no try/finally around it, not a with
             context, not returned/stored/handed off — the handle dies
             with the first BaseException unwind
      HS502  a try whose body acquires a registered resource and whose
             only cleanup sits under `except Exception` — invisible to
             the BaseException cancellation/crash contract
             (QueryCancelledError / InjectedCrash never enter it); move
             the release to a finally
      HS503  a finally that can itself raise before releasing: two or
             more release-ish statements without individual guards, so
             the first one failing skips the rest

Suppression: append `# hslint: HS201` (optionally with a justification
after the code) to the offending line or the line directly above it.

Baseline: `tools/hslint_baseline.txt` lists pre-existing debt as
`path::CODE::scope::detail` keys (no line numbers, so unrelated edits
don't churn it). Baselined findings print as notes; only NEW violations
fail the run. Regenerate deliberately with --write-baseline.

Usage:
    python tools/hslint.py                  # lint hyperspace_tpu/
    python tools/hslint.py path [path ...]  # explicit targets
    python tools/hslint.py --write-baseline # rewrite the baseline file
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from dataclasses import dataclass

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_TARGET = os.path.join(REPO_ROOT, "hyperspace_tpu")
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "hslint_baseline.txt")

# files exempt from specific rules (the rule's own chokepoint)
KERNEL_CACHE_FILE = os.path.join("plan", "kernel_cache.py")
ENV_REGISTRY_FILE = os.path.join("utils", "env.py")
THREAD_CHOKEPOINTS = (
    os.path.join("utils", "workers.py"),
    os.path.join("utils", "backend.py"),
)
SLEEP_CHOKEPOINTS = (
    os.path.join("utils", "retry.py"),
    os.path.join("utils", "backend.py"),
)
_BROAD_EXCEPTIONS = {"Exception", "BaseException", "OSError"}
CONCURRENCY_FILE = os.path.join(
    REPO_ROOT, "hyperspace_tpu", "staticcheck", "concurrency.py"
)

_FILTER_BASES = {
    "IndexFilter",
    "SourcePlanIndexFilter",
    "QueryPlanIndexFilter",
    "IndexRankFilter",
}
_CONTAINER_CTORS = {"dict", "OrderedDict", "set", "list", "deque", "defaultdict"}
_LOCK_CTORS = {"Lock", "RLock", "TrackedLock"}
_THREAD_CTORS = {"Thread", "ThreadPoolExecutor", "ProcessPoolExecutor"}
_MUTATORS = {
    "clear", "pop", "popitem", "move_to_end", "setdefault", "update",
    "append", "extend", "add", "discard", "remove", "insert",
}

# HS5xx: the acquire/release vocabulary of staticcheck/lifecycle.py's
# instrumented chokepoints. Acquire calls return (or register) a live
# handle; release-ish calls retire one.
_ACQUIRE_NAMES = {"stream", "pin", "protect_version", "tracked_resource"}
_RELEASE_NAMES = {
    "close", "release", "release_resource", "unprotect_version", "shutdown",
}
# statements in a finally that can raise before a later release runs
# (HS503): any release-ish call plus future cancellation
_FINALLY_RISKY_NAMES = _RELEASE_NAMES | {"cancel"}

_SUPPRESS_RE = re.compile(r"#\s*hslint:\s*([A-Z0-9, ]+)")


def _parse_declared_edges(tree: ast.AST) -> set:
    """``DECLARED_EDGES = {("outer", "inner"), ...}`` assignments in a
    module: the static mirror of the runtime lock-order declarations."""
    edges: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "DECLARED_EDGES"
            for t in node.targets
        ):
            continue
        value = node.value
        elts = getattr(value, "elts", [])
        for e in elts:
            if isinstance(e, (ast.Tuple, ast.List)) and len(e.elts) == 2:
                pair = tuple(
                    x.value for x in e.elts
                    if isinstance(x, ast.Constant) and isinstance(x.value, str)
                )
                if len(pair) == 2:
                    edges.add(pair)
    return edges


_GLOBAL_EDGES: "set | None" = None


def global_declared_edges() -> set:
    """Edges declared in staticcheck/concurrency.py (cached per run)."""
    global _GLOBAL_EDGES
    if _GLOBAL_EDGES is None:
        _GLOBAL_EDGES = set()
        if os.path.exists(CONCURRENCY_FILE):
            try:
                with open(CONCURRENCY_FILE, encoding="utf-8") as f:
                    _GLOBAL_EDGES = _parse_declared_edges(ast.parse(f.read()))
            except SyntaxError:
                pass
    return _GLOBAL_EDGES


def _static_lock_name(expr: ast.AST) -> "str | None":
    """The static spelling of a lock-ish with-item (``self._lock``,
    ``_roots_lock``, ``cache._lock``), or None when the expression does not
    look like a lock acquisition. Lock-ish = the terminal identifier
    contains "lock" (TrackedLock attributes and module lock globals both
    follow the convention)."""
    node = expr
    if isinstance(node, ast.Call):  # with lock.acquire_timeout(...) style
        node = node.func
    if isinstance(node, ast.Attribute):
        if "lock" not in node.attr.lower():
            return None
        base = node.value
        prefix = base.id if isinstance(base, ast.Name) else (
            base.attr if isinstance(base, ast.Attribute) else "?"
        )
        return f"{prefix}.{node.attr}"
    if isinstance(node, ast.Name):
        return node.id if "lock" in node.id.lower() else None
    return None


@dataclass(frozen=True)
class Finding:
    path: str  # repo-relative, forward slashes
    line: int
    code: str
    scope: str  # Class.method | function | <module>
    detail: str
    message: str

    @property
    def key(self) -> str:
        return f"{self.path}::{self.code}::{self.scope}::{self.detail}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message} [{self.scope}]"


def _last_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Call):
        return _last_name(node.func)
    return None


def _is_self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _FileLinter:
    def __init__(self, abspath: str, relpath: str, source: str):
        self.abspath = abspath
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.findings: list[Finding] = []
        self.suppressed: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
                self.suppressed[i] = codes
        self.scope: list[str] = []

    # --- plumbing ---
    def _scope_name(self) -> str:
        return ".".join(self.scope) if self.scope else "<module>"

    def emit(self, node: ast.AST, code: str, detail: str, message: str) -> None:
        line = getattr(node, "lineno", 0)
        for probe in (line, line - 1):
            if code in self.suppressed.get(probe, ()):
                return
        self.findings.append(
            Finding(self.relpath, line, code, self._scope_name(), detail, message)
        )

    def run(self) -> list[Finding]:
        try:
            tree = ast.parse(self.source, filename=self.abspath)
        except SyntaxError as e:
            self.findings.append(
                Finding(self.relpath, e.lineno or 0, "HS000", "<module>",
                        "syntax-error", f"file does not parse: {e.msg}")
            )
            return self.findings
        self.declared_edges = global_declared_edges() | _parse_declared_edges(tree)
        self._module_rules(tree)
        self._shared_state_rules(tree)
        self._walk(tree, span_depth=0)
        return self.findings

    # --- module-granularity rules (HS101 / HS102) ---
    def _module_rules(self, tree: ast.Module) -> None:
        calls_log_usage = any(
            isinstance(n, ast.Call) and _last_name(n.func) == "log_index_usage"
            for n in ast.walk(tree)
        )
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            self.scope.append(node.name)
            self._class_module_rules(node, calls_log_usage)
            self.scope.pop()

    def _class_module_rules(self, node: ast.ClassDef, calls_log_usage: bool) -> None:
        base_names = { _last_name(b) for b in node.bases }
        # HS101: filter subclass with apply() but no tag_reason_if
        if base_names & _FILTER_BASES:
            apply_def = next(
                (m for m in node.body
                 if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                 and m.name == "apply"),
                None,
            )
            if apply_def is not None and not self._is_abstract(apply_def):
                tags = any(
                    _last_name(n) == "tag_reason_if"
                    for n in ast.walk(node)
                    if isinstance(n, ast.Attribute)
                )
                if not tags:
                    self.emit(
                        apply_def, "HS101", node.name,
                        f"{node.name}.apply() never routes a rejection "
                        f"through tag_reason_if",
                    )
        # HS102: concrete rule with apply_index, module never logs usage
        apply_index = next(
            (m for m in node.body
             if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
             and m.name == "apply_index"),
            None,
        )
        if apply_index is not None and not self._is_abstract(apply_index):
            if not calls_log_usage:
                self.emit(
                    apply_index, "HS102", node.name,
                    f"{node.name}.apply_index() rewrites plans but the "
                    f"module never calls rule_utils.log_index_usage",
                )

    # --- HS305: module-level shared mutable state needs a declared guard ---
    def _shared_state_rules(self, tree: ast.Module) -> None:
        containers: dict[str, ast.AST] = {}
        guarded: set[str] = set()
        for node in tree.body:
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target = node.target
            if target is not None and isinstance(target, ast.Name):
                name = target.id
                v = node.value
                if isinstance(v, ast.Call) and _last_name(v.func) == "guarded_by":
                    guarded.add(name)  # X = guarded_by(<init>, lock, ...)
                    continue
                ctor = _last_name(v) if isinstance(v, ast.Call) else None
                if ctor in _CONTAINER_CTORS or isinstance(
                    v, (ast.Dict, ast.List, ast.Set)
                ):
                    containers[name] = node
            elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                call = node.value
                if (
                    _last_name(call.func) == "guarded_by"
                    and call.args
                    and isinstance(call.args[0], ast.Name)
                ):
                    guarded.add(call.args[0].id)  # guarded_by(X, lock, ...)
        if not containers:
            return
        mutated: dict[str, int] = {}
        for node in tree.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                continue
            for name, line in self._function_scope_mutations(node, containers):
                mutated.setdefault(name, line)
        for name, line in sorted(mutated.items(), key=lambda kv: kv[1]):
            if name in guarded:
                continue
            node = containers[name]
            self.emit(
                node, "HS305", name,
                f"module-level mutable container {name!r} is mutated from "
                f"function scope with no registered guard — declare "
                f"guarded_by({name}, <lock>) (staticcheck.concurrency) or "
                f"justify a suppression",
            )

    @staticmethod
    def _function_scope_mutations(scope: ast.AST, containers: dict):
        """(name, line) for every mutation of a module container inside
        function bodies under ``scope``: subscript/attr-slice stores,
        mutator method calls, augmented assigns, del, and `global` rebinds."""
        names = set(containers)
        for fn in ast.walk(scope):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            declared_global: set = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    declared_global.update(n for n in node.names if n in names)
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if isinstance(t, ast.Subscript) and isinstance(
                            t.value, ast.Name
                        ) and t.value.id in names:
                            yield t.value.id, node.lineno
                        elif isinstance(t, ast.Name) and t.id in declared_global:
                            yield t.id, node.lineno
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript) and isinstance(
                            t.value, ast.Name
                        ) and t.value.id in names:
                            yield t.value.id, node.lineno
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ):
                    f = node.func
                    if f.attr in _MUTATORS and isinstance(
                        f.value, ast.Name
                    ) and f.value.id in names:
                        yield f.value.id, node.lineno

    @staticmethod
    def _is_abstract(fn: ast.AST) -> bool:
        body = [
            s for s in fn.body
            if not (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant))
        ]
        return len(body) == 1 and isinstance(body[0], (ast.Raise, ast.Pass))

    # --- recursive walk carrying lexical context ---
    def _walk(self, node: ast.AST, span_depth: int, cls: "_ClassInfo | None" = None,
              lock_depth: int = 0, held: tuple = ()) -> None:
        for child in ast.iter_child_nodes(node):
            self._visit(child, span_depth, cls, lock_depth, held)

    def _visit(self, node: ast.AST, span_depth: int, cls: "_ClassInfo | None",
               lock_depth: int, held: tuple = ()) -> None:
        if isinstance(node, ast.ClassDef):
            info = _ClassInfo.collect(node)
            self.scope.append(node.name)
            self._walk(node, span_depth, info, 0)
            self.scope.pop()
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.scope.append(node.name)
            self._hs501_function(node)
            in_init = cls is not None and node.name == "__init__"
            # decorator_list is among iter_child_nodes, so one walk covers
            # both the decorators and the body. Lexical lock context does
            # NOT cross the function boundary: a nested def runs later,
            # not under the enclosing with-block.
            self._walk(
                node, span_depth,
                None if in_init else cls,  # __init__ builds state pre-publication
                0,
                (),
            )
            self.scope.pop()
            return
        if isinstance(node, ast.With):
            spans = any(self._is_span_call(i.context_expr) for i in node.items)
            locks = cls is not None and any(
                (_is_self_attr(i.context_expr) or "") in cls.lock_attrs
                for i in node.items
            )
            new_held = held
            for i in node.items:
                lock_name = _static_lock_name(i.context_expr)
                if lock_name is not None:
                    # HS306: acquiring a second, different lock inside one
                    # already lexically held needs a declared order edge
                    if new_held and new_held[-1] != lock_name:
                        edge = (new_held[-1], lock_name)
                        if edge not in self.declared_edges:
                            self.emit(
                                i.context_expr, "HS306",
                                f"{edge[0]}->{edge[1]}",
                                f"nested lock acquisition {edge[0]} -> "
                                f"{edge[1]} without a declared order edge — "
                                f"add it to DECLARED_EDGES "
                                f"(staticcheck/concurrency.py or this "
                                f"module) or justify a suppression",
                            )
                    new_held = new_held + (lock_name,)
            for i in node.items:
                self._visit(i.context_expr, span_depth, cls, lock_depth, held)
            for stmt in node.body:
                self._visit(
                    stmt,
                    span_depth + (1 if spans else 0),
                    cls,
                    lock_depth + (1 if locks else 0),
                    new_held,
                )
            return

        self._expr_rules(node, span_depth, cls, lock_depth)
        self._walk(node, span_depth, cls, lock_depth, held)

    @staticmethod
    def _is_span_call(expr: ast.AST) -> bool:
        if not isinstance(expr, ast.Call):
            return False
        name = _last_name(expr.func)
        return name == "span"

    # --- expression/statement rules ---
    def _expr_rules(self, node: ast.AST, span_depth: int,
                    cls: "_ClassInfo | None", lock_depth: int) -> None:
        # HS201: bare jax.jit / pjit reference
        if (
            isinstance(node, ast.Attribute)
            and node.attr in ("jit", "pjit")
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax"
            and not self.relpath.endswith(KERNEL_CACHE_FILE.replace(os.sep, "/"))
        ):
            self.emit(
                node, "HS201", f"jax.{node.attr}",
                f"bare jax.{node.attr} outside plan/kernel_cache.py — compile "
                f"through a KernelCache (fingerprints, compile spans, audit)",
            )
        if (
            isinstance(node, ast.Name)
            and node.id == "pjit"
            and isinstance(getattr(node, "ctx", None), ast.Load)
            and not self.relpath.endswith(KERNEL_CACHE_FILE.replace(os.sep, "/"))
        ):
            self.emit(
                node, "HS201", "pjit",
                "bare pjit outside plan/kernel_cache.py — compile through a "
                "KernelCache",
            )

        # HS301: os.environ / os.getenv reads
        if not self.relpath.endswith(ENV_REGISTRY_FILE.replace(os.sep, "/")):
            self._env_rules(node)

        # HS304: thread / pool construction outside the workers chokepoints
        if (
            isinstance(node, ast.Call)
            and _last_name(node.func) in _THREAD_CTORS
            and not any(
                self.relpath.endswith(p.replace(os.sep, "/"))
                for p in THREAD_CHOKEPOINTS
            )
        ):
            ctor = _last_name(node.func)
            self.emit(
                node, "HS304", ctor,
                f"{ctor} constructed outside utils/workers.py — create "
                f"threads via workers.spawn_thread / pools via "
                f"workers.io_pool (named, daemon-disciplined, auditable)",
            )

        # HS302: lock-guarded container mutated outside the lock
        if cls is not None and cls.lock_attrs and lock_depth == 0:
            attr = self._mutated_attr(node, cls)
            if attr is not None:
                self.emit(
                    node, "HS302", f"self.{attr}",
                    f"self.{attr} is lock-guarded shared state; mutate it "
                    f"inside `with self.{sorted(cls.lock_attrs)[0]}:`",
                )

        # HS401: ad-hoc sleep outside the retry/backoff chokepoints
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "sleep"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "time"
            and not any(
                self.relpath.endswith(p.replace(os.sep, "/"))
                for p in SLEEP_CHOKEPOINTS
            )
        ):
            self.emit(
                node, "HS401", "time.sleep",
                "time.sleep outside utils/retry.py — backoff goes through "
                "retry_call (bounded, observable, fake-clockable)",
            )

        # HS402: broad except handler that only swallows
        if isinstance(node, ast.ExceptHandler) and self._is_broad_swallow(node):
            kinds = self._handler_kinds(node)
            # anchor on the `pass` so the justification comment sits where
            # the swallowing actually happens
            self.emit(
                node.body[0], "HS402", kinds,
                f"`except {kinds}: pass` swallows failures silently — "
                f"handle, narrow the type, or justify with `# hslint: "
                f"HS402 — <why best-effort is the contract>`",
            )

        # HS303: wall clock inside a telemetry span
        if (
            span_depth > 0
            and isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "time"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "time"
        ):
            self.emit(
                node, "HS303", "time.time",
                "wall-clock time.time() inside a telemetry span — use "
                "time.perf_counter() (span timing already does)",
            )

        # HS502 / HS503: release-path soundness of try statements
        if isinstance(node, ast.Try):
            self._hs502_try(node)
            self._hs503_finally(node)

    # --- HS5xx: resource release paths ------------------------------------
    def _hs501_function(self, fn: ast.AST) -> None:
        """A registered acquire call must have a lexically guaranteed
        release: an enclosing try/finally, with-item or return position, or
        an ownership handoff (stored to an attribute/container, passed on,
        released in some finally). The acquire chokepoints themselves and
        ``__enter__`` (whose release lives in ``__exit__``) are exempt."""
        if fn.name == "__enter__" or fn.name in _ACQUIRE_NAMES:
            return
        parents: dict = {}
        for p in ast.walk(fn):
            for c in ast.iter_child_nodes(p):
                parents[c] = p
        # names that escape the function's responsibility: mentioned in any
        # finally, used as a with context, returned/yielded, stored into an
        # attribute/subscript, or handed to another call
        escaped: set = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Try) and n.finalbody:
                for s in n.finalbody:
                    escaped.update(
                        m.id for m in ast.walk(s) if isinstance(m, ast.Name)
                    )
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                escaped.update(
                    i.context_expr.id for i in n.items
                    if isinstance(i.context_expr, ast.Name)
                )
            elif isinstance(n, (ast.Return, ast.Yield)) and n.value is not None:
                escaped.update(
                    m.id for m in ast.walk(n.value) if isinstance(m, ast.Name)
                )
            elif isinstance(n, ast.Assign) and any(
                isinstance(t, (ast.Attribute, ast.Subscript)) for t in n.targets
            ):
                escaped.update(
                    m.id for m in ast.walk(n.value) if isinstance(m, ast.Name)
                )
            elif isinstance(n, ast.Call):
                for a in list(n.args) + [kw.value for kw in n.keywords]:
                    escaped.update(
                        m.id for m in ast.walk(a) if isinstance(m, ast.Name)
                    )
        for call in ast.walk(fn):
            if not (
                isinstance(call, ast.Call)
                and _last_name(call.func) in _ACQUIRE_NAMES
            ):
                continue
            # attribute the call to its NEAREST enclosing def: nested
            # functions are visited (and checked) on their own
            anc = parents.get(call)
            while anc is not None and not isinstance(
                anc, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                anc = parents.get(anc)
            if anc is not fn:
                continue
            acquire = _last_name(call.func) or "?"
            guarded = False
            target_name = None
            p = parents.get(call)
            while p is not None and p is not fn:
                if isinstance(p, ast.Try) and p.finalbody:
                    guarded = True
                    break
                if isinstance(p, (ast.withitem, ast.Return)):
                    guarded = True  # with-context / ownership to caller
                    break
                if isinstance(p, ast.Call) and p is not call:
                    guarded = True  # handed to another call
                    break
                if isinstance(p, ast.Assign):
                    if any(
                        isinstance(t, (ast.Attribute, ast.Subscript))
                        for t in p.targets
                    ):
                        guarded = True  # stored: the owner releases
                    elif len(p.targets) == 1 and isinstance(
                        p.targets[0], ast.Name
                    ):
                        target_name = p.targets[0].id
                    break
                p = parents.get(p)
            if guarded or (target_name is not None and target_name in escaped):
                continue
            self.emit(
                call, "HS501", acquire,
                f"{acquire}() acquires a tracked resource but its release "
                f"is not lexically guaranteed — wrap in try/finally, use a "
                f"with block, or hand the handle to an owner",
            )

    def _hs502_try(self, node: ast.Try) -> None:
        """A try whose body acquires a resource, has no finally, and
        releases only under ``except Exception`` — the cleanup never runs
        on the BaseException cancellation/crash unwind."""
        if node.finalbody:
            return
        acquires = any(
            isinstance(n, ast.Call) and _last_name(n.func) in _ACQUIRE_NAMES
            for s in node.body
            for n in ast.walk(s)
        )
        if not acquires:
            return
        for h in node.handlers:
            t = h.type
            names = (
                [_last_name(e) for e in t.elts] if isinstance(t, ast.Tuple)
                else [] if t is None else [_last_name(t)]
            )
            if "Exception" not in names:
                continue  # bare / BaseException handlers DO see the unwind
            releases = any(
                isinstance(n, ast.Call)
                and _last_name(n.func) in _RELEASE_NAMES
                for s in h.body
                for n in ast.walk(s)
            )
            if releases:
                self.emit(
                    h, "HS502", "Exception",
                    "resource cleanup sits under `except Exception` — "
                    "QueryCancelledError/InjectedCrash are BaseExceptions "
                    "and never enter it; release in a finally instead",
                )
                return

    def _hs503_finally(self, node: ast.Try) -> None:
        """A finally whose top-level statements hold two or more
        release-ish calls without individual guards: the first one raising
        skips the rest, leaking what they would have released."""
        if not node.finalbody:
            return
        risky = [
            s for s in node.finalbody
            if not isinstance(s, ast.Try)  # individually guarded
            and any(
                isinstance(n, ast.Call)
                and _last_name(n.func) in _FINALLY_RISKY_NAMES
                for n in ast.walk(s)
            )
        ]
        if len(risky) >= 2:
            names = sorted({
                _last_name(n.func) or "?"
                for s in risky
                for n in ast.walk(s)
                if isinstance(n, ast.Call)
                and _last_name(n.func) in _FINALLY_RISKY_NAMES
            })
            self.emit(
                risky[1], "HS503", ",".join(names),
                f"finally runs {len(risky)} unguarded release statements "
                f"({', '.join(names)}) — an earlier one raising skips the "
                f"later releases; guard each (nested try/finally)",
            )

    @staticmethod
    def _handler_kinds(handler: ast.ExceptHandler) -> str:
        if handler.type is None:
            return "<bare>"
        if isinstance(handler.type, ast.Tuple):
            return ", ".join(
                _last_name(e) or "?" for e in handler.type.elts
            )
        return _last_name(handler.type) or "?"

    @staticmethod
    def _is_broad_swallow(handler: ast.ExceptHandler) -> bool:
        if not (len(handler.body) == 1 and isinstance(handler.body[0], ast.Pass)):
            return False
        t = handler.type
        if t is None:
            return True
        names = (
            [_last_name(e) for e in t.elts] if isinstance(t, ast.Tuple)
            else [_last_name(t)]
        )
        return any(n in _BROAD_EXCEPTIONS for n in names)

    def _env_rules(self, node: ast.AST) -> None:
        def env_key(call: ast.Call) -> str:
            if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
                call.args[0].value, str
            ):
                return call.args[0].value
            return "<dynamic>"

        if isinstance(node, ast.Call):
            f = node.func
            # os.getenv(...)
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "getenv"
                and isinstance(f.value, ast.Name)
                and f.value.id == "os"
            ):
                self.emit(
                    node, "HS301", env_key(node),
                    f"os.getenv({env_key(node)!r}) — read knobs through "
                    f"utils/env.py",
                )
            # os.environ.get(...)
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "get"
                and self._is_os_environ(f.value)
            ):
                self.emit(
                    node, "HS301", env_key(node),
                    f"os.environ.get({env_key(node)!r}) — read knobs through "
                    f"utils/env.py",
                )
        # os.environ[...] read
        if (
            isinstance(node, ast.Subscript)
            and self._is_os_environ(node.value)
            and isinstance(node.ctx, ast.Load)
        ):
            key = "<dynamic>"
            if isinstance(node.slice, ast.Constant) and isinstance(
                node.slice.value, str
            ):
                key = node.slice.value
            self.emit(
                node, "HS301", key,
                f"os.environ[{key!r}] — read knobs through utils/env.py",
            )

    @staticmethod
    def _is_os_environ(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os"
        )

    def _mutated_attr(self, node: ast.AST, cls: "_ClassInfo") -> str | None:
        guarded = cls.container_attrs
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript):
                    attr = _is_self_attr(t.value)
                    if attr in guarded:
                        return attr
                attr = _is_self_attr(t)
                if attr in guarded:
                    return attr
        if isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    attr = _is_self_attr(t.value)
                    if attr in guarded:
                        return attr
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            f = node.value.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                attr = _is_self_attr(f.value)
                if attr in guarded:
                    return attr
        return None


@dataclass
class _ClassInfo:
    lock_attrs: set
    container_attrs: set

    @staticmethod
    def collect(node: ast.ClassDef) -> "_ClassInfo":
        locks: set = set()
        containers: set = set()
        for m in node.body:
            if not (
                isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                and m.name == "__init__"
            ):
                continue
            for stmt in ast.walk(m):
                if not isinstance(stmt, ast.Assign):
                    continue
                for t in stmt.targets:
                    attr = _is_self_attr(t)
                    if attr is None:
                        continue
                    v = stmt.value
                    name = _last_name(v) if isinstance(v, ast.Call) else None
                    if name in _LOCK_CTORS:
                        locks.add(attr)
                    elif name in _CONTAINER_CTORS or isinstance(
                        v, (ast.Dict, ast.List, ast.Set)
                    ):
                        containers.add(attr)
        return _ClassInfo(locks, containers)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def iter_py_files(targets: list[str]):
    for target in targets:
        if os.path.isfile(target):
            yield target
            continue
        for dirpath, dirnames, names in os.walk(target):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for n in sorted(names):
                if n.endswith(".py"):
                    yield os.path.join(dirpath, n)


def lint_paths(targets: list[str]) -> list[Finding]:
    findings: list[Finding] = []
    for path in iter_py_files(targets):
        ab = os.path.abspath(path)
        rel = os.path.relpath(ab, REPO_ROOT)
        with open(ab, encoding="utf-8") as f:
            source = f.read()
        findings.extend(_FileLinter(ab, rel, source).run())
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


def load_baseline(path: str) -> set:
    if not os.path.exists(path):
        return set()
    out = set()
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                out.add(line)
    return out


def write_baseline(path: str, findings: list[Finding]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(
            "# hslint baseline — pre-existing debt, one path::CODE::scope::"
            "detail key per line.\n"
            "# New code must be clean; remove entries as debt is paid down.\n"
            "# Regenerate deliberately with: python tools/hslint.py "
            "--write-baseline\n"
        )
        for key in sorted({fi.key for fi in findings}):
            f.write(key + "\n")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="hyperspace_tpu invariant linter (see module docstring)"
    )
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding as a failure")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current findings")
    args = ap.parse_args(argv)

    targets = args.paths or [DEFAULT_TARGET]
    findings = lint_paths(targets)

    if args.write_baseline:
        write_baseline(args.baseline, findings)
        print(f"hslint: wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    baseline = set() if args.no_baseline else load_baseline(args.baseline)
    new = [f for f in findings if f.key not in baseline]
    old = [f for f in findings if f.key in baseline]
    stale = baseline - {f.key for f in findings}

    for f in old:
        print(f"note (baselined): {f.render()}")
    for key in sorted(stale):
        print(f"note (stale baseline entry — debt paid, remove it): {key}")
    for f in new:
        print(f.render())

    print(
        f"hslint: {len(new)} new violation(s), {len(old)} baselined, "
        f"{len(stale)} stale baseline entr(ies) over {len(list(iter_py_files(targets)))} files"
    )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
