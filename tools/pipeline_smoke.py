#!/usr/bin/env python
"""Pipelined-vs-serial smoke: run the TPC-H bench queries with the chunk
streamer ON (HYPERSPACE_PIPELINE=1) and OFF (=0, the monolithic serial
path) on the same generated dataset and assert the results are
bit-identical. Prints one JSON line; exit 0 iff every query matches and
the pipelined run actually streamed chunks.

    timeout 300 env JAX_PLATFORMS=cpu python tools/pipeline_smoke.py

Env: SMOKE_ROWS (lineitem rows, default 120000), HYPERSPACE_STREAM_CHUNK_MB
is forced small so the multi-file lineitem splits into several chunks.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    os.environ.setdefault("HYPERSPACE_DEVICE_STRICT", "1")
    os.environ.setdefault("HYPERSPACE_STREAM_CHUNK_MB", "0.5")
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import tempfile

    from hyperspace_tpu import HyperspaceSession
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch
    from hyperspace_tpu.telemetry.metrics import REGISTRY

    rows = int(os.environ.get("SMOKE_ROWS", 120_000))
    ws = tempfile.mkdtemp(prefix="hs_pipe_smoke_")
    # several lineitem files so the streamer has chunks to overlap
    import numpy as np  # noqa: F401 - generate_tpch needs numpy present

    generate_tpch(ws, rows_lineitem=rows, seed=7)
    # re-split lineitem into more files than generate_tpch's 500k/file rule
    _resplit(ws, "lineitem", parts=6)

    def run(pipeline: str) -> dict:
        os.environ["HYPERSPACE_PIPELINE"] = pipeline
        session = HyperspaceSession(warehouse_dir=ws)
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = {}
        for name, q in TPCH_QUERIES.items():
            out[name] = q(session, ws).to_pydict()
        return out

    chunks0 = REGISTRY.counter("pipeline.chunks").value
    on = run("1")
    streamed = REGISTRY.counter("pipeline.chunks").value - chunks0
    off = run("0")

    def bits(d):
        return repr(
            {
                k: [x.hex() if isinstance(x, float) else x for x in v]
                for k, v in d.items()
            }
        )

    mismatches = [name for name in on if bits(on[name]) != bits(off[name])]
    result = {
        "rows": rows,
        "queries": len(on),
        "chunks_streamed": streamed,
        "bit_identical": not mismatches,
        "mismatches": mismatches,
        "pipeline_counters": {
            k: v
            for k, v in REGISTRY.snapshot().items()
            if k.startswith("pipeline.") and not isinstance(v, dict)
        },
    }
    print(json.dumps(result))
    return 0 if not mismatches and streamed > 0 else 1


def _resplit(ws: str, table: str, parts: int) -> None:
    """Split a table dir's single parquet into `parts` row slices so chunk
    streaming has multiple files to overlap even at smoke scale."""
    import glob

    import numpy as np

    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch

    files = sorted(glob.glob(os.path.join(ws, table, "*.parquet")))
    batch = cio.read_parquet(files)
    n = batch.num_rows
    if len(files) >= parts or n < parts:
        return
    for f in files:
        os.remove(f)
    bounds = np.linspace(0, n, parts + 1).astype(int)
    for i in range(parts):
        part = batch.take(np.arange(bounds[i], bounds[i + 1]))
        cio.write_parquet(
            part, os.path.join(ws, table, f"part-{i:04d}.parquet")
        )


if __name__ == "__main__":
    sys.exit(main())
