#!/usr/bin/env python
"""Pruned-vs-full smoke: run the TPC-H bench queries plus point-lookup /
range / IN sections over covering indexes with predicate-driven pruning ON
(default) and OFF (HYPERSPACE_PRUNE=0) and assert the results are
bit-identical AND that pruning demonstrably fired (files kept < files
total on the point and range sections, row groups skipped on range).
Prints one JSON line; exit 0 iff every query matches and pruning fired.

    timeout 300 env JAX_PLATFORMS=cpu python tools/prune_smoke.py

Env: SMOKE_ROWS (lineitem rows, default 120000). The point/range/IN
sections run over an "events" table whose key is clustered across source
files and whose index builds under a small memory budget — the multi-run
bucket layout where range predicates drop whole sorted runs.

The whole smoke runs with the per-row-group sketch store enabled
(HYPERSPACE_SKETCHES=1), so every section's pruned-vs-full comparison
also covers the sketch kinds. Dedicated sections assert the new
predicate class fires: ``sketch_eq`` / ``sketch_in`` hit NON-sort
columns (bloom / value-list / z-region sidecars must skip row groups),
and their ``*_live`` twins re-run after two ``hs.append`` batches and a
compaction — skipping must keep working on a live, appending index.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


def _prune_delta(fn):
    """(result, pruning.* counter deltas incl. the plan stage) for one run."""
    from hyperspace_tpu.telemetry.metrics import REGISTRY

    def snap():
        return {
            k: v
            for k, v in REGISTRY.snapshot().items()
            if k.startswith("pruning.") and isinstance(v, (int, float))
        }

    before = snap()
    out = fn()
    after = snap()
    return out, {k: after[k] - before.get(k, 0) for k in after}


def main() -> int:
    os.environ.setdefault("HYPERSPACE_DEVICE_STRICT", "1")
    os.environ.setdefault("HYPERSPACE_STREAM_CHUNK_MB", "0.5")
    os.environ.setdefault("HYPERSPACE_SKETCHES", "1")
    os.environ.pop("HYPERSPACE_PRUNE", None)
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import tempfile

    import numpy as np

    from hyperspace_tpu import (
        CoveringIndexConfig,
        Hyperspace,
        HyperspaceSession,
    )
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.plan import Count, Max, Min, Sum, col, lit

    rows = int(os.environ.get("SMOKE_ROWS", 120_000))
    ws = tempfile.mkdtemp(prefix="hs_prune_smoke_")
    generate_tpch(ws, rows_lineitem=rows, seed=7)

    # events: key clustered across files (ingest order), so the streaming
    # multi-run index build yields runs that cover disjoint key ranges
    rng = np.random.default_rng(3)
    n_ev = max(rows, 80_000)
    n_files = 8
    per = n_ev // n_files

    def events_batch(i: int, base: int) -> ColumnBatch:
        k = np.arange(per, dtype=np.int64) + base
        return ColumnBatch.from_pydict(
            {
                "ev_k": k.tolist(),
                "ev_q": rng.integers(1, 50, per).tolist(),
                "ev_v": rng.uniform(0, 100, per).tolist(),
                "ev_s": rng.choice(["a", "b", "c"], per).tolist(),
                # sketch-section columns, clustered with the sort key the
                # way ingest-ordered attributes are in practice: a
                # high-NDV monotone id (bloom) and a low-NDV time-bucket
                # dimension (value list / z-region)
                "ev_id": (k + 10_000_000).tolist(),
                "ev_cat": (k // 2500).tolist(),
            }
        )

    for i in range(n_files):
        cio.write_parquet(
            events_batch(i, i * per),
            os.path.join(ws, "events", f"part-{i:02d}.parquet"),
        )

    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, ws)
    # small budget: the events index streams in file groups -> multi-run buckets
    session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, 1 * 1024 * 1024)
    hs.create_index(
        session.read.parquet(os.path.join(ws, "events")),
        CoveringIndexConfig(
            "ev_k_idx", ["ev_k"], ["ev_q", "ev_v", "ev_s", "ev_id", "ev_cat"]
        ),
    )
    session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, C.BUILD_MAX_BYTES_IN_MEMORY_DEFAULT)
    session.enable_hyperspace()

    ev = lambda: session.read.parquet(os.path.join(ws, "events"))
    k_point = int(n_ev * 5 // 8 + 17)
    lo, hi = int(n_ev // 8 + 100), int(n_ev // 8 + 2100)
    in_keys = [3, k_point, int(n_ev - 5), n_ev * 10]  # last one matches nothing
    sections = {
        "point": lambda: ev()
        .filter(col("ev_k") == k_point)
        .select("ev_k", "ev_q", "ev_v", "ev_s")
        .to_pydict(),
        "range": lambda: ev()
        .filter((col("ev_k") >= lo) & (col("ev_k") < hi))
        .select("ev_k", "ev_v")
        .to_pydict(),
        "in": lambda: ev()
        .filter(col("ev_k").isin(in_keys))
        .select("ev_k", "ev_q")
        .to_pydict(),
        # exact folds only (count/int-sum/min/max): bit-identical across the
        # pruned and full device paths regardless of padded array shape
        "range_agg": lambda: ev()
        .filter((col("ev_k") >= lo) & (col("ev_k") < hi * 3))
        .agg(
            Count(lit(1)).alias("n"),
            Sum(col("ev_q")).alias("sq"),
            Min(col("ev_k")).alias("mn"),
            Max(col("ev_k")).alias("mx"),
        )
        .to_pydict(),
        # NON-sort-column predicates: the sidecar sketch store is the only
        # evidence source (ev_k is unconstrained, so neither bucket pruning
        # nor footer min/max applies)
        "sketch_eq": lambda: ev()
        .filter(col("ev_id") == 10_000_000 + k_point)
        .select("ev_k", "ev_id", "ev_cat")
        .to_pydict(),
        "sketch_in": lambda: ev()
        .filter(col("ev_cat").isin([1, int(n_ev // 2500) - 2]))
        .select("ev_k", "ev_cat")
        .to_pydict(),
    }

    mismatches = []
    fired = {}
    results = {}

    def run_section(name, q):
        got, delta = _prune_delta(q)
        os.environ["HYPERSPACE_PRUNE"] = "0"
        expected = q()
        del os.environ["HYPERSPACE_PRUNE"]
        if _bits(got) != _bits(expected):
            mismatches.append(name)
        fired[name] = delta
        results[name] = len(next(iter(got.values()), []))

    for name, q in sections.items():
        run_section(name, q)

    # live leg: two ingest batches + a compaction, then the sketch sections
    # again — per-run sidecars and the compacted rewrite must keep skipping
    from hyperspace_tpu.exceptions import NoChangesError

    for j in range(2):
        cio.write_parquet(
            events_batch(10 + j, n_ev + j * per),
            os.path.join(ws, "events", f"part-a{j}.parquet"),
        )
        hs.append("ev_k_idx", session.read.parquet(os.path.join(ws, "events")))
    try:
        hs.compact_index("ev_k_idx", min_runs=2)
    except NoChangesError:
        pass  # background compaction beat us to it — equally live
    for name in ("sketch_eq", "sketch_in"):
        run_section(f"{name}_live", sections[name])

    for name, q in TPCH_QUERIES.items():
        got = q(session, ws).to_pydict()
        os.environ["HYPERSPACE_PRUNE"] = "0"
        expected = q(session, ws).to_pydict()
        del os.environ["HYPERSPACE_PRUNE"]
        if _bits(got) != _bits(expected):
            mismatches.append(name)

    def kept_lt_total(d):
        return d.get("pruning.files_kept", 0) < d.get("pruning.files_total", 0)

    def sketch_fired(d):
        return (
            d.get("pruning.sketch.rowgroups_skipped", 0) > 0
            and d.get("pruning.rowgroups_kept", 0)
            < d.get("pruning.rowgroups_total", 0)
        )

    pruning_fired = (
        kept_lt_total(fired["point"])
        and kept_lt_total(fired["range"])
        and kept_lt_total(fired["in"])
        and fired["range"].get("pruning.rowgroups_kept", 0)
        < fired["range"].get("pruning.rowgroups_total", 0)
        # non-sort-column skipping via the sketch store, cold AND live
        # (after 2 appends + a compaction)
        and all(
            sketch_fired(fired[s])
            for s in ("sketch_eq", "sketch_in", "sketch_eq_live", "sketch_in_live")
        )
    )
    out = {
        "rows": rows,
        "events_rows": n_ev,
        "sections": fired,
        "section_rows": results,
        "tpch_queries": len(TPCH_QUERIES),
        "bit_identical": not mismatches,
        "mismatches": mismatches,
        "pruning_fired": pruning_fired,
    }
    print(json.dumps(out))
    return 0 if not mismatches and pruning_fired else 1


if __name__ == "__main__":
    sys.exit(main())
