#!/usr/bin/env python
"""Observability-catalog drift linter: every metric name and trace-span
label the code can emit must appear in docs/observability.md.

The docs are the operator contract — dashboards, alerts, and the
trace_report tooling are written against the catalog tables and the span
taxonomy. A counter added in code but not in the catalog is invisible
drift: it ships, someone graphs it from a guess, and the next rename
breaks them silently. This linter closes the loop from the code side:

- **code vocabulary** — an AST walk over ``hyperspace_tpu/`` collects the
  first argument of every ``.counter(...)`` / ``.gauge(...)`` /
  ``.histogram(...)`` call and every ``trace.span(...)`` call. Constant
  strings are taken verbatim; f-strings keep their literal parts with
  each interpolation collapsed to a ``*`` wildcard (``f"rule:{name}"``
  becomes ``rule:*``). Non-literal names (a bare variable) are skipped —
  they are constructed from parts this linter already saw at their
  definition sites.
- **docs vocabulary** — every backtick-quoted token in
  docs/observability.md plus every label in the "Span taxonomy" block.
  Brace sets expand (``cache.result.{hits,misses}`` covers both) and
  ``<placeholder>`` segments become wildcards (``rules.<Rule>.applied``
  covers every rule).

A code name passes if any docs pattern covers it. New undocumented names
fail; intentional gaps go in ``tools/obslint_baseline.txt`` via
``--write-baseline`` (line-based: ``metric::<name>`` / ``span::<label>``),
so the failure mode is always "a NEW name appeared undocumented", never
silent baseline growth.

    python tools/obslint.py              # exit 1 on new undocumented names
    python tools/obslint.py --write-baseline
    python tools/obslint.py --no-baseline   # full report, ignore baseline

Run by the test suite (tests/test_lifecycle.py) so drift fails CI.
"""

import argparse
import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "hyperspace_tpu")
DOCS = os.path.join(REPO, "docs", "observability.md")
BASELINE = os.path.join(REPO, "tools", "obslint_baseline.txt")

_METRIC_METHODS = {"counter", "gauge", "histogram"}


# ---------------------------------------------------------------------------
# code vocabulary


def _name_of(node: ast.expr) -> str | None:
    """Literal str -> itself; f-string -> literal parts with every
    interpolation collapsed to '*'; anything else -> None (skip)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    return None


def collect_code(root: str = PACKAGE) -> dict[str, list]:
    """{'metric::<name>' | 'span::<label>': [path:line, ...]}."""
    found: dict[str, list] = {}
    for dirpath, _dirs, files in os.walk(root):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, REPO)
            with open(path, encoding="utf-8") as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError:
                    continue
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                func = node.func
                if not isinstance(func, ast.Attribute):
                    continue
                key = None
                if func.attr in _METRIC_METHODS:
                    key = "metric"
                elif func.attr == "span":
                    key = "span"
                if key is None:
                    continue
                name = _name_of(node.args[0])
                if name is None:
                    continue
                found.setdefault(f"{key}::{name}", []).append(
                    f"{rel}:{node.lineno}"
                )
    return found


# ---------------------------------------------------------------------------
# docs vocabulary

_BRACE = re.compile(r"\{([^{}]*,[^{}]*)\}")


def _expand_braces(pat: str) -> list:
    """cache.x.{hits,misses} -> [cache.x.hits, cache.x.misses]."""
    m = _BRACE.search(pat)
    if m is None:
        return [pat]
    out = []
    for alt in m.group(1).split(","):
        out.extend(
            _expand_braces(pat[: m.start()] + alt.strip() + pat[m.end():])
        )
    return out


def _to_pattern(tok: str) -> str:
    """<placeholder> segments become wildcards."""
    return re.sub(r"<[^<>]*>", "*", tok)


# a catalog-table row's name cell: later " / " alternates may be
# shorthand (`pruning.files_total` / `files_kept`) — reconstruct the full
# name by grafting the first token's leading segments onto the short one
_ROW_NAMES = re.compile(r"^\|\s*((?:`[^`]+`\s*/?\s*)+)\|")


def _row_alternates(text: str):
    for line in text.splitlines():
        m = _ROW_NAMES.match(line)
        if m is None:
            continue
        toks = re.findall(r"`([^`]+)`", m.group(1))
        if len(toks) < 2:
            continue
        first = toks[0].split(".")
        for tok in toks[1:]:
            parts = tok.split(".")
            if len(parts) < len(first):
                yield ".".join(first[: len(first) - len(parts)] + parts)


# a span-taxonomy label line: the label (possibly "a / b" alternates),
# then either end-of-line or >= 2 spaces before the description column.
# Wrapped description lines have single spaces between words and don't
# match.
_TAXONOMY_LABEL = re.compile(r"^\s*(\S+(?:\s/\s\S+)*)(?:\s{2,}.*)?$")


def collect_docs(path: str = DOCS) -> list:
    """Every backticked token + every span-taxonomy label, braces
    expanded and <placeholders> wildcarded."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    patterns: set = set()
    for tok in re.findall(r"`([^`\n]+)`", text):
        for t in re.split(r"\s*/\s*", tok.strip()):
            for e in _expand_braces(t):
                patterns.add(_to_pattern(e))
    for full in _row_alternates(text):
        for e in _expand_braces(full):
            patterns.add(_to_pattern(e))
    # span taxonomy: the fenced block right after its heading
    m = re.search(r"## Span taxonomy\s+```\n(.*?)```", text, re.DOTALL)
    if m:
        for ln in m.group(1).splitlines():
            lm = _TAXONOMY_LABEL.match(ln)
            if lm is None or not ln.strip():
                continue
            for t in lm.group(1).split(" / "):
                for e in _expand_braces(t):
                    patterns.add(_to_pattern(e))
    return sorted(patterns)


def _compat(a: str, b: str, _memo=None) -> bool:
    """Glob-intersection: can two '*'-wildcard patterns name a common
    string? A code-side f-string interpolation and a docs-side
    <placeholder> both mean "some concrete value here" — the code name is
    documented iff some instantiation of both coincides."""
    if _memo is None:
        _memo = {}
    key = (len(a), len(b))
    if key in _memo:
        return _memo[key]
    if not a and not b:
        out = True
    elif a and a[0] == "*":
        out = _compat(a[1:], b, _memo) or (bool(b) and _compat(a, b[1:], _memo))
    elif b and b[0] == "*":
        out = _compat(a, b[1:], _memo) or (bool(a) and _compat(a[1:], b, _memo))
    elif a and b and a[0] == b[0]:
        out = _compat(a[1:], b[1:], _memo)
    else:
        out = False
    _memo[key] = out
    return out


def covered(name: str, patterns: list) -> bool:
    """True if any docs pattern can name what the code name names."""
    return any(_compat(name, pat) for pat in patterns)


# ---------------------------------------------------------------------------


def load_baseline(path: str = BASELINE) -> set:
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        return {
            ln.strip()
            for ln in f
            if ln.strip() and not ln.startswith("#")
        }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--no-baseline", action="store_true")
    args = ap.parse_args(argv)

    code = collect_code()
    patterns = collect_docs()
    undocumented = {
        key: sites
        for key, sites in sorted(code.items())
        if not covered(key.split("::", 1)[1], patterns)
    }

    if args.write_baseline:
        with open(BASELINE, "w", encoding="utf-8") as f:
            f.write(
                "# obslint baseline: metric/span names intentionally "
                "absent from docs/observability.md.\n"
                "# Regenerate with: python tools/obslint.py "
                "--write-baseline\n"
            )
            for key in undocumented:
                f.write(key + "\n")
        print(f"obslint: baseline written ({len(undocumented)} entr(ies))")
        return 0

    baseline = set() if args.no_baseline else load_baseline()
    new = {k: v for k, v in undocumented.items() if k not in baseline}
    stale = sorted(baseline - set(undocumented))

    for key, sites in new.items():
        kind, name = key.split("::", 1)
        print(f"UNDOCUMENTED {kind} {name!r}  ({', '.join(sites[:3])})")
    for key in stale:
        print(f"stale baseline entry (now documented): {key}")
    print(
        f"obslint: {len(code)} names in code, {len(new)} undocumented, "
        f"{len(undocumented) - len(new)} baselined, {len(stale)} stale "
        f"baseline entr(ies)"
    )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
