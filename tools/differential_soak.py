#!/usr/bin/env python
"""Long-running differential soak: N random queries (host/device/mesh tiers,
hybrid-scan mix) must match raw results within the engine's float contract.

Run: python tools/differential_soak.py [N]
(2,500 seeds take ~95s on one CPU core; used as the round-2 release gate.)
"""

import os
import pathlib
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

os.environ["HYPERSPACE_DEVICE_STRICT"] = "1"  # device bugs must FAIL the gate

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")

from test_differential import canon, random_query, rows_close  # noqa: E402

from hyperspace_tpu import (  # noqa: E402
    CoveringIndexConfig,
    DataSkippingIndexConfig,
    Hyperspace,
    MinMaxSketch,
    ZOrderCoveringIndexConfig,
)
from hyperspace_tpu import constants as C  # noqa: E402
from hyperspace_tpu.columnar import io as cio  # noqa: E402
from hyperspace_tpu.columnar.table import ColumnBatch  # noqa: E402
from hyperspace_tpu.session import HyperspaceSession  # noqa: E402


def main(n_seeds: int = 2500) -> int:
    root = pathlib.Path(tempfile.mkdtemp(prefix="hs_soak_"))
    rng = np.random.default_rng(99)
    n = 5000
    for i in range(4):
        sl = n // 4
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "k": rng.integers(0, 200, sl).tolist(),
                    "d": rng.integers(i * 600, (i + 1) * 600, sl).tolist(),
                    "x": rng.uniform(0, 100, sl).tolist(),
                    "cat": rng.choice(["red", "green", "blue"], sl).tolist(),
                }
            ),
            str(root / "fact" / f"f{i}.parquet"),
        )
    cio.write_parquet(
        ColumnBatch.from_pydict(
            {"rk": list(range(200)), "w": rng.uniform(size=200).tolist()}
        ),
        str(root / "dim" / "d.parquet"),
    )
    session = HyperspaceSession(warehouse_dir=str(root))
    session.set_conf(C.INDEX_LINEAGE_ENABLED, True)
    hs = Hyperspace(session)
    fact = session.read.parquet(str(root / "fact"))
    dim = session.read.parquet(str(root / "dim"))
    hs.create_index(fact, CoveringIndexConfig("ci", ["k"], ["x", "cat", "d"]))
    hs.create_index(dim, CoveringIndexConfig("cd", ["rk"], ["w"]))
    hs.create_index(fact, ZOrderCoveringIndexConfig("z", ["d"], ["x", "k"]))
    hs.create_index(fact, DataSkippingIndexConfig("ds", [MinMaxSketch("d")]))

    # mutate the source AFTER the builds so hybrid-scan seeds actually
    # exercise hybrid plans (stale indexes + appended-file merge)
    cio.write_parquet(
        ColumnBatch.from_pydict(
            {"k": [5, 6], "d": [100, 2000], "x": [1.5, 2.5], "cat": ["red", "blue"]}
        ),
        str(root / "fact" / "appended.parquet"),
    )

    fails = 0
    t0 = time.time()
    for seed in range(n_seeds):
        r = np.random.default_rng(seed)
        tier = seed % 3
        session.set_conf(C.EXEC_TPU_ENABLED, tier >= 1)
        session.set_conf(C.EXEC_MESH_DEVICES, 8 if tier == 2 else 0)
        # half the mesh seeds run the hierarchical (2-slice) topology
        session.set_conf(C.EXEC_MESH_SLICES, 2 if tier == 2 and seed % 2 else 1)
        session.set_conf(C.HYBRID_SCAN_ENABLED, seed % 5 == 4)
        q = random_query(session, str(root), r)
        session.disable_hyperspace()
        expect = canon(q.to_pydict())
        session.enable_hyperspace()
        got = canon(q.to_pydict())
        session.disable_hyperspace()
        if not rows_close(got, expect):
            fails += 1
            print(f"MISMATCH seed {seed} tier {tier}")
            if fails > 3:
                break
    from hyperspace_tpu.utils.backend import device_healthy

    assert device_healthy(), "device tier latched off during the soak"
    print(
        f"soak done: {n_seeds} seeds x (host/device/mesh, hybrid mix), "
        f"{fails} mismatches, {round(time.time() - t0, 1)}s"
    )
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main(int(sys.argv[1]) if len(sys.argv) > 1 else 2500))
