#!/usr/bin/env python
"""Workload-intelligence gate: journal durability + attribution
conservation + drift detection, end to end with the plane ON.

A mixed run — direct collects, scheduler-served queries, and a
cancelled-while-queued query — executes against a table with TWO covering
indexes (one the queries use, one never applicable) under
``HYPERSPACE_WORKLOAD_DIR`` and the lock-order audit.

Asserted invariants (exit 0 iff all hold):

- every journal line parses and carries the full record schema — one
  uniform shape across done / cancelled outcomes, including the
  zero-filled ``phases_ms`` map over the whole phase vocabulary;
- per-index attribution conserves: the utility ledger's cross-index sums
  equal the global ``workload.index.*`` / ``workload.maintenance.*``
  counter deltas exactly (benefit bytes within per-increment rounding);
- ``hs.index_report()`` ranks the demonstrably-used index above the
  never-applied one, and the never-applied one is a cold candidate;
- the drift detector flags a deliberately slowed label (baseline fast,
  window slow) and stays SILENT on a stable label run the same way;
- results stay bit-identical to the no-index reference;
- ``staticcheck.lock.violations`` stays 0 with the acquisition-order
  audit forced on (``SMOKE_LOCK_AUDIT=0`` opts out).

    timeout 300 env JAX_PLATFORMS=cpu python tools/workload_smoke.py

Env: SMOKE_ROWS (40000), SMOKE_DRIFT_N (samples per drift side, 6).
"""

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bits(d: dict) -> str:
    # row-sorted canonical form: index scans may legitimately reorder rows
    cols = sorted(d)
    rows = sorted(zip(*(d[c] for c in cols))) if cols else []
    return repr(
        (cols, [[x.hex() if isinstance(x, float) else x for x in r]
                for r in rows])
    )


REQUIRED_KEYS = (
    "v", "seq", "query_id", "label", "tenant", "outcome", "started_s",
    "queue_wait_ms", "total_ms", "phases_ms", "bytes_read", "counters",
    "histograms", "workload",
)
WORKLOAD_KEYS = (
    "shapes", "join_keys", "columns", "candidates", "chosen", "pruned",
    "qerror_counts",
)


def main() -> int:
    drift_n = int(os.environ.get("SMOKE_DRIFT_N", 6))
    wdir = tempfile.mkdtemp(prefix="hs_workload_journal_")
    os.environ["HYPERSPACE_WORKLOAD_DIR"] = wdir
    os.environ.setdefault("HYPERSPACE_WORKLOAD_BASELINE", str(drift_n))
    os.environ.setdefault("HYPERSPACE_WORKLOAD_WINDOW", str(drift_n))
    os.environ.setdefault("HYPERSPACE_WORKLOAD_DRIFT_MIN", str(max(4, drift_n - 2)))
    os.environ.setdefault("HYPERSPACE_WORKLOAD_DRIFT_FACTOR", "2.0")
    os.environ.setdefault("HYPERSPACE_DEVICE_STRICT", "1")
    os.environ.setdefault("HYPERSPACE_SKETCHES", "1")
    # slow cost model => journaled benefit outweighs one-off index creation
    os.environ.setdefault("HYPERSPACE_QOS_COST_MBPS", "4")
    if os.environ.get("SMOKE_LOCK_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LOCK_AUDIT", "1")
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))

    import numpy as np

    from hyperspace_tpu import (
        CoveringIndexConfig,
        Hyperspace,
        HyperspaceSession,
        serve,
    )
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.plan import col
    from hyperspace_tpu.telemetry import DRIFT, JOURNAL, attribution
    from hyperspace_tpu.telemetry.attribution import PHASES
    from hyperspace_tpu.telemetry.index_ledger import INDEX_LEDGER
    from hyperspace_tpu.telemetry.metrics import REGISTRY

    rows = int(os.environ.get("SMOKE_ROWS", 40_000))
    ws = tempfile.mkdtemp(prefix="hs_workload_smoke_")
    rng = np.random.default_rng(11)
    n_files = 4
    per = rows // n_files
    for i in range(n_files):
        k = (np.arange(per, dtype=np.int64) + i * per)
        cio.write_parquet(
            ColumnBatch.from_pydict({
                "ev_k": k.tolist(),
                "ev_q": rng.integers(1, 50, per).tolist(),
                "ev_v": rng.uniform(0, 100, per).tolist(),
                "ev_s": rng.choice(["a", "b", "c"], per).tolist(),
            }),
            os.path.join(ws, "events", f"part-{i:02d}.parquet"),
        )

    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 4)
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    hs = Hyperspace(session)
    ev = lambda: session.read.parquet(os.path.join(ws, "events"))
    # the index the workload uses, and one no query can ever apply
    hs.create_index(
        ev(), CoveringIndexConfig("ev_used_idx", ["ev_k"], ["ev_q", "ev_v"])
    )
    hs.create_index(
        ev(), CoveringIndexConfig("ev_unused_idx", ["ev_s"], ["ev_q"])
    )

    k_point = rows // 2 + 7
    lo, hi = rows // 4, rows // 4 + 1500

    def q_point():
        return (
            ev().filter(col("ev_k") == k_point)
            .select("ev_k", "ev_q", "ev_v").to_pydict()
        )

    def q_range():
        return (
            ev().filter((col("ev_k") >= lo) & (col("ev_k") < hi))
            .select("ev_k", "ev_v").to_pydict()
        )

    session.disable_hyperspace()
    reference = {"point": _bits(q_point()), "range": _bits(q_range())}
    session.enable_hyperspace()

    failures: list[str] = []

    def check(cond: bool, msg: str) -> None:
        if not cond:
            failures.append(msg)

    # --- mixed run: direct collects -----------------------------------
    for _ in range(4):
        direct = {"point": _bits(q_point()), "range": _bits(q_range())}
        check(direct == reference, "direct results diverged from reference")

    # --- served + cancelled-while-queued ------------------------------
    sched = serve.QueryScheduler(max_concurrent=1, queue_depth=64)
    gate = threading.Event()
    try:
        blocker = sched.submit(lambda: gate.wait(30), label="blocker")
        victim = sched.submit(q_point, label="victim")
        sched.cancel(victim)
        gate.set()
        blocker.result(60)
        served = [
            sched.submit(q_point if i % 2 == 0 else q_range,
                         label="served").result(60)
            for i in range(4)
        ]
        check(
            all(_bits(s) == reference["point" if i % 2 == 0 else "range"]
                for i, s in enumerate(served)),
            "served results diverged from reference",
        )

        # --- drift: fast baseline, slow window, plus a stable control -
        fast, slow = (lambda: time.sleep(0.002) or 1), (lambda: time.sleep(0.05) or 1)
        for _ in range(drift_n):
            sched.submit(fast, label="drifting").result(30)
            sched.submit(fast, label="stable").result(30)
        for _ in range(drift_n):
            sched.submit(slow, label="drifting").result(30)
            sched.submit(fast, label="stable").result(30)
        sched.drain(60)
    finally:
        sched.shutdown()

    JOURNAL.flush()

    # --- journal schema: every line parses, one uniform record shape ---
    records = JOURNAL.load()
    recorded = attribution.LEDGER.snapshot()["totals"]["recorded"]
    check(len(records) == recorded,
          f"journal holds {len(records)} records, ledger recorded {recorded}")
    outcomes = set()
    for r in records:
        missing = [k for k in REQUIRED_KEYS if k not in r]
        check(not missing, f"record seq={r.get('seq')} missing keys {missing}")
        check(tuple(r.get("phases_ms", {})) == PHASES,
              f"record seq={r.get('seq')} phases_ms keys != PHASES")
        wl_missing = [k for k in WORKLOAD_KEYS if k not in (r.get("workload") or {})]
        check(not wl_missing,
              f"record seq={r.get('seq')} workload block missing {wl_missing}")
        outcomes.add(r.get("outcome"))
    check("done" in outcomes and "cancelled" in outcomes,
          f"expected done+cancelled outcomes in the journal, got {outcomes}")

    # --- conservation: ledger sums == global counter deltas ------------
    snap = REGISTRY.snapshot()
    totals = INDEX_LEDGER.totals()
    check(snap.get("workload.index.applied", 0) == totals["queries"],
          f"applied counter {snap.get('workload.index.applied', 0)} != "
          f"ledger queries {totals['queries']}")
    check(snap.get("workload.index.bytes_skipped", 0) == totals["bytes_skipped"],
          "bytes_skipped counter != ledger sum")
    check(
        snap.get("workload.index.rowgroups_skipped", 0)
        == totals["rowgroups_skipped"],
        "rowgroups_skipped counter != ledger sum",
    )
    check(
        abs(snap.get("workload.index.benefit_bytes", 0)
            - totals["benefit_bytes"]) <= 0.001 * max(1, totals["queries"]),
        f"benefit_bytes counter {snap.get('workload.index.benefit_bytes', 0)}"
        f" != ledger sum {totals['benefit_bytes']}",
    )
    check(
        snap.get("workload.maintenance.actions", 0)
        == totals["maintenance_actions"],
        f"maintenance counter {snap.get('workload.maintenance.actions', 0)} "
        f"!= ledger sum {totals['maintenance_actions']}",
    )
    check(totals["queries"] > 0, "no index application was ever charged")
    check(totals["maintenance_actions"] >= 2,
          "index creation was not charged as maintenance")

    # --- ranking: used index above the never-applied one ---------------
    report = INDEX_LEDGER.report()
    order = [r["name"] for r in report]
    check(
        "ev_used_idx" in order and "ev_unused_idx" in order
        and order.index("ev_used_idx") < order.index("ev_unused_idx"),
        f"index_report ranking wrong: {order}",
    )
    check("ev_unused_idx" in INDEX_LEDGER.cold_candidates(),
          "never-applied index not flagged as a cold candidate")
    used_row = next(r for r in report if r["name"] == "ev_used_idx")
    check(used_row["queries"] > 0, "used index shows zero query hits")

    # --- drift: planted regression fires, stable label stays silent ----
    regs = DRIFT.regressions()
    reg_keys = {(r["kind"], r["key"]) for r in regs}
    check(("latency", "drifting") in reg_keys,
          f"planted regression not flagged; regressions={regs}")
    check(("latency", "stable") not in reg_keys,
          "stable label wrongly flagged as drifting")
    check(snap.get("workload.drift.latency", 0) >= 1,
          "workload.drift.latency counter never fired")

    # --- hygiene -------------------------------------------------------
    check(snap.get("staticcheck.lock.violations", 0) == 0,
          "lock-order violations under audit")
    check(snap.get("workload.journal.errors", 0) == 0,
          "journal writes errored")

    out = {
        "journal_records": len(records),
        "journal_dir": wdir,
        "outcomes": sorted(outcomes),
        "ledger_totals": totals,
        "index_order": order,
        "cold": INDEX_LEDGER.cold_candidates(),
        "regressions": regs,
        "lock_violations": snap.get("staticcheck.lock.violations", 0),
        "failures": failures,
        "ok": not failures,
    }
    print(json.dumps(out, default=str))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
