#!/usr/bin/env python
"""Persistent TPU-grant prober.

Loops forever: every cycle it spawns a throwaway subprocess that tries to
initialize the JAX backend (a hung remote-TPU grant dies with the subprocess),
and appends one JSON line per attempt to the status file. The newest line is
the current tunnel state; the history is the evidence trail VERDICT r3 item 3
asked for ("periodic probe timestamps, not 3 attempts").

Usage: python tools/tpu_prober.py [status_path] [interval_s] [probe_timeout_s]
Default status path: /tmp/tpu_probe_status.jsonl
"""

import json
import os
import subprocess
import sys
import time


def probe_once(timeout_s: float) -> dict:
    t0 = time.time()
    info: dict = {"ts": round(t0, 1), "iso": time.strftime("%Y-%m-%dT%H:%M:%S")}
    try:
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; print('BACKEND=' + jax.default_backend()); "
                "print('NDEV=%d' % len(jax.devices()))",
            ],
            capture_output=True,
            timeout=timeout_s,
            text=True,
        )
        info["elapsed_s"] = round(time.time() - t0, 1)
        info["rc"] = out.returncode
        backend = None
        for line in out.stdout.splitlines():
            if line.startswith("BACKEND="):
                backend = line[8:].strip()
        info["backend"] = backend if out.returncode == 0 else None
        if out.returncode != 0:
            info["stderr_tail"] = out.stderr[-500:]
    except subprocess.TimeoutExpired:
        info["elapsed_s"] = round(time.time() - t0, 1)
        info["backend"] = None
        info["timeout"] = True
    return info


def main() -> None:
    status = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tpu_probe_status.jsonl"
    interval = float(sys.argv[2]) if len(sys.argv) > 2 else 60.0
    timeout = float(sys.argv[3]) if len(sys.argv) > 3 else 90.0
    while True:
        info = probe_once(timeout)
        with open(status, "a") as f:
            f.write(json.dumps(info) + "\n")
        # also maintain a "latest" file for cheap reads
        with open(status + ".latest", "w") as f:
            f.write(json.dumps(info))
        time.sleep(max(0.0, interval - info.get("elapsed_s", 0)))


if __name__ == "__main__":
    main()
