#!/usr/bin/env python
"""Pretty-print or aggregate a JSONL trace artifact.

A trace file is produced by `bench.py --profile`, by
``HYPERSPACE_TRACE=1 HYPERSPACE_TRACE_FILE=trace.jsonl``, or by any
`telemetry.trace.JsonlTraceSink`. One JSON span per line; parents follow
their children (spans are written on completion).

Usage:
    python tools/trace_report.py trace.jsonl             # span trees
    python tools/trace_report.py trace.jsonl --agg       # per-name rollup
    python tools/trace_report.py trace.jsonl --top 20    # slowest spans
    python tools/trace_report.py trace.jsonl --name kernel:   # filter trees
    python tools/trace_report.py trace.jsonl --query 17  # one serving query
    python tools/trace_report.py trace.jsonl --tenant gold # one tenant's queries
    python tools/trace_report.py trace.jsonl --plan-stats # annotated exec trees

``--query <id>`` extracts a single serving query's span tree from a mixed
multi-query trace: it keeps only the ``serve:query`` subtree(s) whose
``query_id`` attribute matches (plus that query's ``serve:admit`` span),
and composes with --agg/--top to aggregate just that query's spans.

See docs/observability.md for the span taxonomy.
"""

from __future__ import annotations

import argparse
import sys
from collections import defaultdict


def _load(path: str):
    sys.path.insert(0, ".")
    from hyperspace_tpu.telemetry.trace import read_jsonl_trace

    return read_jsonl_trace(path)


def _walk(span: dict):
    yield span
    for c in span.get("children", []):
        yield from _walk(c)


_QUERY_SPANS = ("serve:query", "serve:admit")


def _query_trees(roots: list[dict], query_id: int) -> list[dict]:
    """The serving spans belonging to ONE query in a mixed trace: every
    ``serve:query`` subtree (and ``serve:admit`` marker) whose query_id
    attr matches, wherever it sits in the forest. A serving query's spans
    root at its own serve:query (thread-local trace stacks), so the
    matched subtrees ARE that query's complete execution. Both spans carry
    a ``tenant`` attribute, rendered with the rest of the attrs."""
    out = []
    for r in roots:
        for s in _walk(r):
            if (
                s["name"] in _QUERY_SPANS
                and (s.get("attrs") or {}).get("query_id") == query_id
            ):
                out.append(s)
    return out


def _tenant_trees(roots: list[dict], tenant: str) -> list[dict]:
    """Every serving query subtree belonging to ONE tenant — the QoS
    companion of --query: ``serve:query``/``serve:admit`` spans whose
    ``tenant`` attribute matches."""
    out = []
    for r in roots:
        for s in _walk(r):
            if (
                s["name"] in _QUERY_SPANS
                and (s.get("attrs") or {}).get("tenant") == tenant
            ):
                out.append(s)
    return out


def _print_trees(roots: list[dict], name_filter: str | None) -> None:
    from hyperspace_tpu.telemetry.trace import profile_string

    if name_filter:
        roots = [
            r
            for r in roots
            if any(name_filter in s["name"] for s in _walk(r))
        ]
    print(profile_string(roots, include_metrics=False))


def _aggregate(roots: list[dict]) -> None:
    agg: dict[str, dict] = defaultdict(
        lambda: {
            "count": 0,
            "total_ms": 0.0,
            "max_ms": 0.0,
            "dispatches": 0,
            "uploads": 0,
            "fetches": 0,
            "upload_bytes": 0,
            "fetch_bytes": 0,
        }
    )
    for r in roots:
        for s in _walk(r):
            a = agg[s["name"]]
            a["count"] += 1
            a["total_ms"] += s.get("duration_ms", 0.0)
            a["max_ms"] = max(a["max_ms"], s.get("duration_ms", 0.0))
            for k in ("dispatches", "uploads", "fetches", "upload_bytes", "fetch_bytes"):
                a[k] += (s.get("rpc") or {}).get(k, 0)
    rows = sorted(agg.items(), key=lambda kv: -kv[1]["total_ms"])
    hdr = f"{'span':<32}{'count':>7}{'total_ms':>12}{'max_ms':>10}{'disp':>6}{'up':>5}{'fetch':>6}{'up_B':>12}{'down_B':>12}"
    print(hdr)
    print("-" * len(hdr))
    for name, a in rows:
        print(
            f"{name:<32}{a['count']:>7}{a['total_ms']:>12.2f}{a['max_ms']:>10.2f}"
            f"{a['dispatches']:>6}{a['uploads']:>5}{a['fetches']:>6}"
            f"{a['upload_bytes']:>12}{a['fetch_bytes']:>12}"
        )


_PLAN_SPAN_PREFIXES = ("query", "serve:query", "exec:", "prune:", "cache:")


def _plan_stats_tree(span: dict) -> "dict | None":
    """The execution skeleton of one span tree: keep query/exec/prune/cache
    spans (the ones plan-stats annotations ride on), splicing out other
    levels so the printed tree mirrors the plan shape. Returns None when
    nothing execution-shaped is underneath."""

    def keep(s: dict) -> bool:
        return any(
            s["name"] == p or s["name"].startswith(p)
            for p in _PLAN_SPAN_PREFIXES
        )

    def kept_children(s: dict) -> list[dict]:
        out = []
        for c in s.get("children", []):
            if keep(c):
                t = dict(c)
                t["children"] = kept_children(c)
                out.append(t)
            else:
                out.extend(kept_children(c))  # splice the level out
        return out

    if keep(span):
        t = dict(span)
        t["children"] = kept_children(span)
        return t
    kids = kept_children(span)
    if not kids:
        return None
    return kids[0] if len(kids) == 1 else {
        "name": "(trace)", "duration_ms": span.get("duration_ms", 0.0),
        "attrs": {}, "rpc": {}, "children": kids,
    }


def _print_plan_stats(roots: list[dict]) -> None:
    """--plan-stats: the annotated execution trees. exec:* spans carry
    rows_out / route / bytes_scanned attributes (set by the executor when
    a plan-stats collector is active, e.g. HYPERSPACE_PLAN_STATS=1) and
    prune:* spans carry the estimator q-error events."""
    from hyperspace_tpu.telemetry.trace import profile_string

    trees = [t for t in (_plan_stats_tree(r) for r in roots) if t is not None]
    if not trees:
        print("(no exec/query spans in this trace)")
        return
    print(profile_string(trees, include_metrics=False))


def _top(roots: list[dict], n: int) -> None:
    spans = [s for r in roots for s in _walk(r)]
    spans.sort(key=lambda s: -s.get("duration_ms", 0.0))
    for s in spans[:n]:
        rpc = s.get("rpc") or {}
        print(
            f"{s.get('duration_ms', 0.0):>10.2f} ms  {s['name']:<28}"
            f" attrs={ {k: v for k, v in (s.get('attrs') or {}).items() if k != 'events'} }"
            f" rpc={ {k: v for k, v in rpc.items() if v} }"
        )


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("path", help="JSONL trace file")
    p.add_argument("--agg", action="store_true", help="aggregate by span name")
    p.add_argument("--top", type=int, metavar="N", help="N slowest spans")
    p.add_argument("--name", help="only trees containing this span-name substring")
    p.add_argument(
        "--query", type=int, metavar="ID",
        help="only the serve:query/serve:admit subtree(s) with this query_id",
    )
    p.add_argument(
        "--tenant", metavar="NAME",
        help="only serve:query/serve:admit subtrees of this tenant",
    )
    p.add_argument(
        "--plan-stats", action="store_true",
        help="render annotated execution trees (exec/prune/cache spans "
             "with plan-stats attributes and q-error events)",
    )
    args = p.parse_args()
    roots = _load(args.path)
    if args.query is not None:
        roots = _query_trees(roots, args.query)
        if not roots:
            print(f"(no serve:query spans with query_id={args.query})")
            return
    if args.tenant is not None:
        roots = _tenant_trees(roots, args.tenant)
        if not roots:
            print(f"(no serve:query spans with tenant={args.tenant!r})")
            return
    if not roots:
        print("(empty trace)")
        return
    if args.plan_stats:
        _print_plan_stats(roots)
    elif args.agg:
        _aggregate(roots)
    elif args.top:
        _top(roots, args.top)
    else:
        _print_trees(roots, args.name)


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:  # e.g. piped into head
        sys.stderr.close()
