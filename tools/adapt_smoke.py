#!/usr/bin/env python
"""Mid-query adaptive re-optimization smoke (HYPERSPACE_ADAPTIVE).

Plants mis-estimates at all three adaptation sites and asserts that every
switch fires AND that adaptive execution stays bit-identical to static:

- **join replan**: footer byte stats tampered 64x low under a small device
  grant — the static plan's banded waves overrun the ledger and park; the
  adaptive run observes decoded actuals on the first bucket pair, flips
  banded→split, and must finish with STRICTLY fewer parks+spills and the
  exact static bits (count/min/max aggregates fold exactly),
- **conjunct reorder**: a worst-order col-vs-col conjunction (no arrow
  pushdown) over enough rows to leave the warmup window — the reordered
  mask must reproduce the static filter bit for bit, and the switch must
  render in EXPLAIN ANALYZE as ``[adapted: ...]``,
- **scan abort-and-replan**: sketch-NDV sidecars tampered 1e9 high so the
  sketch stage promises to keep almost nothing while honest blooms keep
  every row group — the streamed index scan aborts after its warmup
  chunks, the index is vetoed, and the replanned query must match the
  raw (hyperspace-disabled) scan bit for bit.

The whole smoke runs with the lock-order audit forced on
(``HYPERSPACE_LOCK_AUDIT=1``) — any violation across the replan loop
fails it. Prints one JSON line; exit 0 iff every section passes.

    timeout 300 env JAX_PLATFORMS=cpu python tools/adapt_smoke.py

Env: SMOKE_ROWS (events rows, default 60000).
"""

import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


def main() -> int:
    os.environ.setdefault("HYPERSPACE_DEVICE_STRICT", "1")
    os.environ["HYPERSPACE_LOCK_AUDIT"] = "1"
    os.environ.pop("HYPERSPACE_ADAPTIVE", None)
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import tempfile

    import numpy as np

    from hyperspace_tpu import (
        CoveringIndexConfig,
        Hyperspace,
        HyperspaceSession,
    )
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.models import covering
    from hyperspace_tpu.models.dataskipping import sketch_store
    from hyperspace_tpu.plan import Count, Max, Min, col, lit
    from hyperspace_tpu.plan import join_memory
    from hyperspace_tpu.serve import budget as serve_budget
    from hyperspace_tpu.telemetry.metrics import REGISTRY

    n_ev = int(os.environ.get("SMOKE_ROWS", 60_000))
    ws = tempfile.mkdtemp(prefix="hs_adapt_smoke_")
    rng = np.random.default_rng(7)

    def cnt(name: str) -> float:
        return REGISTRY.counter(name).value

    session = HyperspaceSession(warehouse_dir=ws)
    hs = Hyperspace(session)
    out = {"rows": n_ev, "sections": {}}
    failures = []

    # -- section 1: join replan under tampered footer byte stats ----------
    # Fixed geometry (independent of SMOKE_ROWS): 4 buckets of ~37k rows
    # each pad to a 65536-row band wave, so the static banded plan
    # reserves ~2x the decoded bytes and parks under a 2 MB grant, while
    # the adaptive flip to grant-derived split slabs fits exactly. The
    # /64 byte tamper keeps the planned classification banded (row_bytes
    # clamps at 1.0 -> threshold grant/32 rows > any bucket).
    n_join = 150_000
    cio.write_parquet(
        ColumnBatch.from_pydict(
            {
                "k": rng.integers(0, 600, n_join).tolist(),
                "p": rng.uniform(0, 100, n_join).tolist(),
            }
        ),
        os.path.join(ws, "jl", "l.parquet"),
    )
    cio.write_parquet(
        ColumnBatch.from_pydict(
            {
                "rk": list(range(500)),
                "w": rng.uniform(size=500).tolist(),
            }
        ),
        os.path.join(ws, "jr", "r.parquet"),
    )
    session.set_conf(C.INDEX_NUM_BUCKETS, 4)
    hs.create_index(
        session.read.parquet(os.path.join(ws, "jl")),
        CoveringIndexConfig("jl_idx", ["k"], ["p"]),
    )
    hs.create_index(
        session.read.parquet(os.path.join(ws, "jr")),
        CoveringIndexConfig("jr_idx", ["rk"], ["w"]),
    )
    session.enable_hyperspace()
    session.set_conf(C.EXEC_TPU_ENABLED, True)

    real_estimates = join_memory._bucket_estimates
    join_memory._bucket_estimates = lambda side, b: (
        lambda r, nb: (r, nb / 64.0)
    )(*real_estimates(side, b))
    os.environ["HYPERSPACE_JOIN_BROADCAST_ROWS"] = "10"
    os.environ["HYPERSPACE_DEVICE_BUDGET_MB"] = "2.0"
    os.environ["HYPERSPACE_PARK_WAIT_MS"] = "1"
    os.environ["HYPERSPACE_ADAPTIVE_WARMUP_CHUNKS"] = "1"
    serve_budget.reset_device_budget()

    def join_q():
        l = session.read.parquet(os.path.join(ws, "jl")).select("k", "p")
        r = session.read.parquet(os.path.join(ws, "jr")).select("rk", "w")
        return (
            l.join(r, col("k") == col("rk"))
            .group_by("k")
            .agg(
                Count(lit(1)).alias("n"),
                Min(col("p")).alias("lo"),
                Max(col("p")).alias("hi"),
            )
            .to_pydict()
        )

    os.environ["HYPERSPACE_ADAPTIVE"] = "0"
    parks0, spills0 = cnt("join.spill.parks"), cnt("join.spill.spills")
    static = join_q()
    static_parks = cnt("join.spill.parks") - parks0
    static_spills = cnt("join.spill.spills") - spills0

    os.environ["HYPERSPACE_ADAPTIVE"] = "1"
    parks0, spills0 = cnt("join.spill.parks"), cnt("join.spill.spills")
    flips0 = cnt("adaptive.replan")
    adaptive = join_q()
    adapt_parks = cnt("join.spill.parks") - parks0
    adapt_spills = cnt("join.spill.spills") - spills0
    flips = cnt("adaptive.replan") - flips0

    join_match = _bits(adaptive) == _bits(static)
    join_fewer = (adapt_parks + adapt_spills) < (static_parks + static_spills)
    out["sections"]["join_replan"] = {
        "flips": flips,
        "static_parks": static_parks,
        "static_spills": static_spills,
        "adaptive_parks": adapt_parks,
        "adaptive_spills": adapt_spills,
        "results_match_static": join_match,
        "fewer_parks_and_spills": join_fewer,
    }
    if not (join_match and flips >= 1 and join_fewer):
        failures.append("join_replan")
    join_memory._bucket_estimates = real_estimates
    session.set_conf(C.EXEC_TPU_ENABLED, False)
    os.environ.pop("HYPERSPACE_DEVICE_BUDGET_MB", None)
    serve_budget.reset_device_budget()

    # -- section 2: conjunct reorder + EXPLAIN ANALYZE rendering ----------
    # needs more rows than the warmup window (_REORDER_CHUNK_ROWS x
    # (warmup + 1) = 128k at defaults) or every chunk is warmup and the
    # reorder never arms
    n_flt = max(150_000, n_ev)
    cio.write_parquet(
        ColumnBatch.from_pydict(
            {
                "a": rng.integers(0, 100, n_flt).tolist(),
                "b": rng.integers(0, 100, n_flt).tolist(),
                "c": rng.integers(0, 100, n_flt).tolist(),
            }
        ),
        os.path.join(ws, "flt", "p.parquet"),
    )

    def filter_df():
        # written worst-first; col-vs-col never pushes to arrow, so the
        # host Filter node sees every row
        return (
            session.read.parquet(os.path.join(ws, "flt"))
            .filter(
                (col("a") != col("c"))
                & (col("a") > col("b"))
                & (col("b") >= col("c"))
            )
            .select("a", "b", "c")
        )

    os.environ["HYPERSPACE_ADAPTIVE"] = "1"
    reorders0 = cnt("adaptive.reorder")
    adaptive = filter_df().to_pydict()
    reorders = cnt("adaptive.reorder") - reorders0
    report = hs.explain_analyze(filter_df())
    os.environ["HYPERSPACE_ADAPTIVE"] = "0"
    static = filter_df().to_pydict()
    reorder_match = _bits(adaptive) == _bits(static)
    rendered = "[adapted:" in report
    out["sections"]["conjunct_reorder"] = {
        "reorders": reorders,
        "results_match_static": reorder_match,
        "explain_renders_switch": rendered,
        "rows_kept": len(adaptive["a"]),
    }
    if not (reorder_match and reorders >= 1 and rendered):
        failures.append("conjunct_reorder")

    # -- section 3: scan abort-and-replan under tampered sketch NDV -------
    os.environ["HYPERSPACE_SKETCHES"] = "1"
    rgs_orig = covering.INDEX_ROW_GROUP_SIZE
    covering.INDEX_ROW_GROUP_SIZE = 1024
    n_files = 4
    per = n_ev // n_files
    try:
        for i in range(n_files):
            base = i * per
            cio.write_parquet(
                ColumnBatch.from_pydict(
                    {
                        "ev_k": list(range(base, base + per)),
                        "ev_cat": [
                            f"c{(base + j) % 3}" for j in range(per)
                        ],
                        "ev_v": rng.uniform(0, 1, per).tolist(),
                    }
                ),
                os.path.join(ws, "events", f"part-{i:02d}.parquet"),
            )
        session.set_conf(C.INDEX_NUM_BUCKETS, 2)
        hs.create_index(
            session.read.parquet(os.path.join(ws, "events")),
            CoveringIndexConfig("ev_idx", ["ev_k"], ["ev_cat", "ev_v"]),
        )
    finally:
        covering.INDEX_ROW_GROUP_SIZE = rgs_orig
    # plant the mis-estimate: NDV 1e9 says "almost no group holds c1"
    sides = sorted(
        glob.glob(
            os.path.join(ws, "indexes", "ev_idx", "**", "_sketch.*.json"),
            recursive=True,
        )
    )
    for side in sides:
        raw = json.load(open(side))
        if "ev_cat" in raw.get("ndv", {}):
            raw["ndv"]["ev_cat"] = 10**9
            json.dump(raw, open(side, "w"))
    sketch_store._SIDECAR_CACHE.clear()

    session.set_conf(C.EXEC_TPU_ENABLED, True)
    os.environ["HYPERSPACE_STREAM_CHUNK_MB"] = "0.02"

    def scan_q():
        return (
            session.read.parquet(os.path.join(ws, "events"))
            .filter(col("ev_cat") == "c1")
            .group_by("ev_cat")
            .agg(
                Count(lit(1)).alias("n"),
                Min(col("ev_v")).alias("lo"),
                Max(col("ev_v")).alias("hi"),
            )
            .to_pydict()
        )

    session.disable_hyperspace()
    raw = scan_q()
    session.enable_hyperspace()
    os.environ["HYPERSPACE_ADAPTIVE"] = "1"
    aborts0 = cnt("adaptive.abort")
    replans0 = cnt("adaptive.scan_replans")
    adaptive = scan_q()
    aborts = cnt("adaptive.abort") - aborts0
    replans = cnt("adaptive.scan_replans") - replans0
    abort_match = _bits(adaptive) == _bits(raw)
    out["sections"]["scan_abort"] = {
        "aborts": aborts,
        "scan_replans": replans,
        "tampered_sidecars": len(sides),
        "results_match_raw": abort_match,
    }
    if not (abort_match and aborts >= 1 and replans >= 1 and sides):
        failures.append("scan_abort")
    os.environ.pop("HYPERSPACE_ADAPTIVE", None)

    lock_violations = int(cnt("staticcheck.lock.violations"))
    out["lock_violations"] = lock_violations
    out["failures"] = failures
    ok = not failures and lock_violations == 0
    out["ok"] = ok
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
