#!/usr/bin/env python
"""Mesh scale-out smoke: run the TPC-H join bench queries (q3, q10, q17)
over covering join indexes on a FORCED 8-virtual-device CPU mesh with
mesh-sharded execution ON (HYPERSPACE_MESH=1, skew-aware bucket→device
placement) and OFF (=0, everything on device 0) on the same generated
dataset — including the hot-key skew variant where 30% of lineitem rows
carry ONE order key — and assert the results are bit-identical. Placement
must actually engage: >= 4 of the 8 devices used on the skew fixture and a
predicted-bytes imbalance ratio under 2.0 (the fair-share split gate: a
naive per-bucket packing of the hot bucket lands near 3x). Every per-device
memory ledger must drain to zero and the whole smoke runs with
HYPERSPACE_LOCK_AUDIT=1 — any lock-order violation fails it. Prints one
JSON line; exit 0 iff every gate holds.

    timeout 600 env JAX_PLATFORMS=cpu python tools/mesh_smoke.py

Env: SMOKE_ROWS (lineitem rows, default 120000); HYPERSPACE_JOIN_SPLIT_ROWS
is forced small so the hot bucket's probe chunks rotate through their
placed device ranges.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    # the virtual mesh must exist before jax initializes its backends
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ.setdefault("HYPERSPACE_DEVICE_STRICT", "1")
    os.environ.setdefault("HYPERSPACE_JOIN_SPLIT_ROWS", "8192")
    os.environ.setdefault("HYPERSPACE_LOCK_AUDIT", "1")
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import tempfile

    from hyperspace_tpu import CoveringIndexConfig, Hyperspace, HyperspaceSession
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch
    from hyperspace_tpu.serve import budget as serve_budget
    from hyperspace_tpu.telemetry.metrics import REGISTRY
    from hyperspace_tpu.utils.backend import safe_device_count

    rows = int(os.environ.get("SMOKE_ROWS", 120_000))
    ws = tempfile.mkdtemp(prefix="hs_mesh_smoke_")
    generate_tpch(ws, rows_lineitem=rows, seed=11)
    # skew lineitem: rewrite 30% of order keys to ONE hot order so a single
    # bucket dwarfs the rest (the placement fair-share-split target shape)
    _skew_lineitem(ws, hot_frac=0.3)

    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    hs = Hyperspace(session)
    li = session.read.parquet(os.path.join(ws, "lineitem"))
    od = session.read.parquet(os.path.join(ws, "orders"))
    pt = session.read.parquet(os.path.join(ws, "part"))
    hs.create_index(
        li,
        CoveringIndexConfig(
            "li_orderkey",
            ["l_orderkey"],
            ["l_extendedprice", "l_discount", "l_returnflag", "l_quantity"],
        ),
    )
    hs.create_index(
        li,
        CoveringIndexConfig(
            "li_partkey", ["l_partkey"], ["l_quantity", "l_extendedprice"]
        ),
    )
    hs.create_index(
        od,
        CoveringIndexConfig(
            "od_orderkey", ["o_orderkey"], ["o_orderdate", "o_custkey"]
        ),
    )
    hs.create_index(
        pt, CoveringIndexConfig("pt_partkey", ["p_partkey"], ["p_brand"])
    )

    join_queries = ("q3", "q10", "q17")
    devices_visible = safe_device_count()

    def run(mesh: str) -> dict:
        os.environ["HYPERSPACE_MESH"] = mesh
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = {}
        try:
            for name in join_queries:
                out[name] = TPCH_QUERIES[name](session, ws).to_pydict()
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()
        return out

    off = run("0")
    buckets0 = REGISTRY.counter("mesh.placement.buckets").value
    fallbacks0 = REGISTRY.counter("mesh.placement.fallbacks").value
    usage0 = REGISTRY.counter("rules.usage.MeshBucketedExec").value
    on = run("1")
    os.environ.pop("HYPERSPACE_MESH", None)
    placed_buckets = REGISTRY.counter("mesh.placement.buckets").value - buckets0
    fallbacks = REGISTRY.counter("mesh.placement.fallbacks").value - fallbacks0
    usage_events = (
        REGISTRY.counter("rules.usage.MeshBucketedExec").value - usage0
    )
    devices_used = int(REGISTRY.gauge("mesh.placement.devices_used").value)
    imbalance = REGISTRY.gauge("mesh.placement.bytes_imbalance_ratio").value
    ledgers = {
        f"d{o}": acct.held_bytes()
        for o, acct in serve_budget.device_budgets().items()
    }
    ledgers_drained = all(v == 0 for v in ledgers.values()) and all(
        acct.check_consistency()
        for acct in serve_budget.device_budgets().values()
    )

    def bits(d):
        return repr(
            {
                k: [x.hex() if isinstance(x, float) else x for x in v]
                for k, v in d.items()
            }
        )

    mismatches = [name for name in on if bits(on[name]) != bits(off[name])]
    lock_violations = int(
        REGISTRY.counter("staticcheck.lock.violations").value
    )
    result = {
        "rows": rows,
        "queries": len(on),
        "devices_visible": devices_visible,
        "bit_identical": not mismatches,
        "mismatches": mismatches,
        "placed_buckets": placed_buckets,
        "placement_fallbacks": fallbacks,
        "devices_used": devices_used,
        "bytes_imbalance_ratio": round(imbalance, 4),
        "usage_events": usage_events,
        "ledgers_held": ledgers,
        "ledgers_drained": ledgers_drained,
        "lock_violations": lock_violations,
        "mesh_counters": {
            k: v
            for k, v in REGISTRY.snapshot().items()
            if k.startswith(("mesh.", "serve.device_budget"))
            and not isinstance(v, dict)
        },
    }
    print(json.dumps(result))
    ok = (
        not mismatches
        and devices_visible >= 8
        and placed_buckets > 0
        and devices_used >= 4
        and imbalance < 2.0
        and usage_events > 0
        and ledgers_drained
        and lock_violations == 0
    )
    return 0 if ok else 1


def _skew_lineitem(ws: str, hot_frac: float) -> None:
    import glob

    import numpy as np

    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import Column

    files = sorted(glob.glob(os.path.join(ws, "lineitem", "*.parquet")))
    batch = cio.read_parquet(files)
    k = np.asarray(batch.column("l_orderkey").data).copy()
    n_hot = int(len(k) * hot_frac)
    k[:n_hot] = k[0]
    batch = batch.with_column("l_orderkey", Column(k, "int64"))
    for f in files:
        os.remove(f)
    cio.write_parquet(batch, os.path.join(ws, "lineitem", "part-0000.parquet"))


if __name__ == "__main__":
    sys.exit(main())
