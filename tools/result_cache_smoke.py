#!/usr/bin/env python
"""Result-cache gate: a repeat-heavy TPC-H mix through the query scheduler
with a concurrent ``append_batch`` stream into a live index, with
``HYPERSPACE_RESULT_CACHE=1``.

Asserted invariants (exit 0 iff all hold):

- hit ratio > 0 over the serving window (warm repeats actually served from
  the cache), and every served TPC-H result — hit or computed — is
  bit-identical (``float.hex()``) to the cold reference;
- every served result over the LIVE ingested table is bit-identical to a
  cold replay against the exact snapshot the query pinned (the pinned
  entry's immutable file listing, re-read with the cache off) — covering
  hits, folds, and recomputes across every version the stream published;
- a warm hit executes NOTHING: its trace carries the ``cache:probe`` span
  and zero ``exec:`` / ``kernel:`` / ``compile:`` / ``pipeline:`` spans;
- the incremental-view path demonstrably engaged: ``cache.result.folds``
  advanced across the appends, and a deterministic post-window
  append→refresh→query sequence folds and matches its cold replay;
- attribution conservation: for every ``io.* / cache.* / rpc.* /
  pipeline.* / pruning.* / serve.budget.*`` counter, per-query ledger sums
  equal the global deltas across the serving window (background refreshes
  carry their own ledger records, so they conserve too);
- ``staticcheck.lock.violations`` stays 0 with the acquisition-order audit
  forced on; every bounded cache (the result cache included) passes
  ``check_consistency()``; scheduler + refresh plane reach quiescence.

    timeout 300 env JAX_PLATFORMS=cpu python tools/result_cache_smoke.py

Env: SMOKE_CLIENTS (4), SMOKE_CONCURRENT (4), SMOKE_REPEATS (3),
SMOKE_ROWS (60000), SMOKE_INGEST_BATCHES (6), SMOKE_INGEST_ROWS (4000).
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONSERVED_PREFIXES = (
    "io.", "cache.", "rpc.", "pipeline.", "pruning.", "serve.budget.",
)


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


def main() -> int:
    os.environ["HYPERSPACE_RESULT_CACHE"] = "1"
    os.environ.setdefault("HYPERSPACE_DEVICE_STRICT", "1")
    os.environ.setdefault("HYPERSPACE_STREAM_CHUNK_MB", "0.5")
    os.environ.setdefault("HYPERSPACE_IO_THREADS", "4")
    # every served/refresh record must stay in the window or conservation
    # would lose evicted entries' charges
    os.environ.setdefault("HYPERSPACE_QUERY_LOG_WINDOW", "4096")
    # background compaction does unattributed IO; keep it out of the
    # conservation window (the refresh plane, which IS attributed via its
    # own ledger records, is the machinery under test here)
    os.environ.setdefault("HYPERSPACE_COMPACT_RUNS", "100000")
    if os.environ.get("SMOKE_LOCK_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LOCK_AUDIT", "1")
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import tempfile

    import numpy as np

    from hyperspace_tpu import (
        CoveringIndexConfig,
        Hyperspace,
        HyperspaceSession,
        ingest,
        serve,
    )
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes
    from hyperspace_tpu.cache.result_cache import RESULT_CACHE, serve_collect
    from hyperspace_tpu.cache.view_maintenance import refresh_idle
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.ingest.snapshots import pin_scope
    from hyperspace_tpu.plan import Count, Max, Min, Sum, col, lit
    from hyperspace_tpu.plan.nodes import FileScan
    from hyperspace_tpu.plan import kernel_cache as kc
    from hyperspace_tpu.staticcheck import concurrency as cc
    from hyperspace_tpu.telemetry import trace
    from hyperspace_tpu.telemetry.attribution import LEDGER
    from hyperspace_tpu.telemetry.metrics import REGISTRY
    from hyperspace_tpu.utils import device_cache as dc
    from hyperspace_tpu.utils.workers import spawn_thread

    clients = int(os.environ.get("SMOKE_CLIENTS", 4))
    concurrent = int(os.environ.get("SMOKE_CONCURRENT", 4))
    repeats = int(os.environ.get("SMOKE_REPEATS", 3))
    rows = int(os.environ.get("SMOKE_ROWS", 60_000))
    batches = int(os.environ.get("SMOKE_INGEST_BATCHES", 6))
    batch_rows = int(os.environ.get("SMOKE_INGEST_ROWS", 4_000))

    ws = tempfile.mkdtemp(prefix="hs_rc_smoke_")
    generate_tpch(ws, rows_lineitem=rows, seed=31)
    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, ws)

    # the live table the append stream writes into
    def _batch(seed: int) -> dict:
        r = np.random.default_rng(700 + seed)
        return {
            "k": r.integers(0, 64, batch_rows).tolist(),
            "v": r.integers(0, 10_000, batch_rows).tolist(),
            "w": r.integers(0, 100, batch_rows).tolist(),
        }

    ev = os.path.join(ws, "events")
    cio.write_parquet(
        ColumnBatch.from_pydict(_batch(0)), os.path.join(ev, "part0.parquet")
    )
    hs.create_index(
        session.read.parquet(ev),
        CoveringIndexConfig("ev_rc", ["k"], ["v", "w"]),
    )
    session.enable_hyperspace()
    names = list(TPCH_QUERIES)

    def ev_query():
        df = session.read.parquet(ev)
        return df.filter(df["k"] < 40).agg(
            Count(lit(1)).alias("n"),
            Sum(col("v")).alias("sv"),
            Min(col("v")).alias("mn"),
            Max(col("w")).alias("mx"),
        )

    def _cache_off():
        class _Off:
            def __enter__(self):
                self.prev = os.environ.get("HYPERSPACE_RESULT_CACHE")
                os.environ["HYPERSPACE_RESULT_CACHE"] = "0"

            def __exit__(self, *exc):
                os.environ["HYPERSPACE_RESULT_CACHE"] = self.prev
                return False

        return _Off()

    # cold references for the static TPC-H mix (cache off: a true cold run)
    with _cache_off():
        reference = {
            name: _bits(TPCH_QUERIES[name](session, ws).to_pydict())
            for name in names
        }

    # --- warm-hit trace check: zero execution spans on a hit --------------
    TPCH_QUERIES["q6"](session, ws).collect()  # populate
    with trace.capture() as cap:
        TPCH_QUERIES["q6"](session, ws).collect()
    hit_spans = [s.name for s in cap.sink.spans]
    zero_exec_on_hit = "cache:probe" in hit_spans and not [
        n for n in hit_spans
        if n.startswith(("exec:", "kernel:", "compile:", "pipeline:"))
    ]

    def _val(n: str) -> float:
        m = REGISTRY.get(n)
        return 0 if m is None else m.value

    # --- conservation + cache baselines (start of the serving window) -----
    def _conserved_counters() -> dict:
        return {
            name: value
            for name, kind, value in REGISTRY.export()
            if kind == "counter" and name.startswith(CONSERVED_PREFIXES)
        }

    g0 = _conserved_counters()
    l0 = {
        k: v
        for k, v in LEDGER.aggregate_counters().items()
        if k.startswith(CONSERVED_PREFIXES)
    }
    hits0, misses0 = _val("cache.result.hits"), _val("cache.result.misses")
    folds0 = _val("cache.result.folds")

    sched = serve.QueryScheduler(
        max_concurrent=concurrent,
        queue_depth=max(64, clients * (len(names) + 1) * repeats + batches),
    )
    mismatches: list = []
    errors: list = []
    ev_runs: list = []  # (bits(result), executed plan's leaf file listing)
    lock = threading.Lock()
    barrier = threading.Barrier(clients + 1)

    def run_ev():
        """The live-table query, collected in the same two steps
        DataFrame.collect takes, so the EXECUTED plan's leaf file set is
        recorded next to the answer — that file set (index files, or
        index ∪ appended source under a mid-append hybrid scan) is the
        snapshot the post-window cold replay re-reads."""
        df = ev_query()
        with pin_scope():
            plan = df.optimized_plan()
            files = tuple(sorted(
                f.name
                for n in plan.preorder()
                if isinstance(n, FileScan)
                for f in n.files
            ))
            out = serve_collect(session, df.plan, plan)
        return out, files

    def client(tid: int) -> None:
        try:
            barrier.wait()
            for r in range(repeats):
                off = (tid + r) % len(names)
                for name in names[off:] + names[:off]:
                    h = sched.submit(
                        (lambda n=name: TPCH_QUERIES[n](session, ws).collect()),
                        label=f"c{tid}:{name}",
                    )
                    got = _bits(h.result(timeout=300).to_pydict())
                    if got != reference[name]:
                        mismatches.append((tid, name))
                # the live-table query rides along every pass
                h = sched.submit(run_ev, label=f"c{tid}:ev")
                out, files = h.result(timeout=300)
                with lock:
                    ev_runs.append((_bits(out.to_pydict()), files))
        except Exception as e:  # noqa: BLE001 - reported via the gate
            errors.append((tid, repr(e)))

    def ingester() -> None:
        """Appends ride the scheduler too: their IO charges a ledger
        record like any query's, so conservation covers the write path."""
        try:
            barrier.wait()
            for k in range(1, batches + 1):
                h = sched.submit(
                    (lambda kk=k: ingest.append_batch(
                        session, "ev_rc", _batch(kk)
                    )),
                    label=f"ingest:{k}",
                )
                h.result(timeout=300)
                time.sleep(0.05)  # hslint: HS401 — gate tool pacing
        except Exception as e:  # noqa: BLE001 - reported via the gate
            errors.append(("ingester", repr(e)))

    threads = [
        spawn_thread(client, name=f"hs-rcsmoke-{i}", daemon=False, args=(i,))
        for i in range(clients)
    ]
    ing = spawn_thread(ingester, name="hs-rcsmoke-ingester", daemon=False)
    for t in threads:
        t.join()
    ing.join()
    sched.drain(timeout=120)

    # quiesce the refresh plane before measuring conservation
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and not (
        refresh_idle() and ingest.maintenance_idle()
    ):
        time.sleep(0.05)  # hslint: HS401 — gate tool, background settle

    def _conservation_mismatches() -> dict:
        g1 = _conserved_counters()
        deltas = {k: g1.get(k, 0) - g0.get(k, 0) for k in set(g0) | set(g1)}
        lsum = {
            k: v - l0.get(k, 0)
            for k, v in LEDGER.aggregate_counters().items()
            if k.startswith(CONSERVED_PREFIXES)
        }
        return {
            k: {"global_delta": deltas.get(k, 0), "ledger_sum": lsum.get(k, 0)}
            for k in set(deltas) | set(lsum)
            if deltas.get(k, 0) != lsum.get(k, 0)
        }

    conservation = _conservation_mismatches()
    for _ in range(40):
        if not conservation:
            break
        time.sleep(0.25)  # hslint: HS401 — straggler-charge settle
        conservation = _conservation_mismatches()

    hits = _val("cache.result.hits") - hits0
    misses = _val("cache.result.misses") - misses0
    folds_in_window = _val("cache.result.folds") - folds0
    hit_ratio = hits / (hits + misses) if (hits + misses) else 0.0

    state = sched.state()
    quiescent = not state["active"] and not state["queued"]
    sched.shutdown(wait=True)

    # --- deterministic post-window fold: append → refresh folds → replay --
    fold_ok = True
    try:
        ev_query().collect()  # anchor at the current version
        f0 = _val("cache.result.folds")
        ingest.append_batch(session, "ev_rc", _batch(batches + 1))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not refresh_idle():
            time.sleep(0.02)  # hslint: HS401 — gate tool, refresh settle
        folded_advanced = _val("cache.result.folds") > f0
        out, files = run_ev()
        ev_runs.append((_bits(out.to_pydict()), files))
        fold_ok = folded_advanced
    except Exception as e:  # noqa: BLE001 - reported via the gate
        fold_ok = False
        errors.append(("fold-exercise", repr(e)))

    # --- cold replays: every served/folded answer vs its pinned snapshot --
    # (cache off, hyperspace disabled: a direct scan of the executed plan's
    # leaf file set — the pinned index version, plus the appended source
    # parts under a mid-append hybrid plan. The fragment is a global
    # integer aggregate, which is scan-order-free, so the replay is the
    # exact answer AT that snapshot.)
    replay_mismatches = 0
    replay_cache: dict = {}
    session.disable_hyperspace()
    with _cache_off():
        for got, files in ev_runs:
            if not files:
                replay_mismatches += 1
                continue
            want = replay_cache.get(files)
            if want is None:
                df = session.read.parquet(list(files))
                want = _bits(
                    df.filter(df["k"] < 40)
                    .agg(
                        Count(lit(1)).alias("n"),
                        Sum(col("v")).alias("sv"),
                        Min(col("v")).alias("mn"),
                        Max(col("w")).alias("mx"),
                    )
                    .collect()
                    .to_pydict()
                )
                replay_cache[files] = want
            if got != want:
                replay_mismatches += 1

    consistency = {
        "result": RESULT_CACHE.check_consistency(),
        "io.index_chunk": cio._INDEX_CHUNK_CACHE.check_consistency(),
        "io.source_col": cio._SOURCE_COL_CACHE.check_consistency(),
        "io.rowgroup_stats": cio._ROWGROUP_STATS_CACHE.check_consistency(),
        "device": dc.DEVICE_CACHE.check_consistency(),
        "host_derived": dc.HOST_DERIVED_CACHE.check_consistency(),
        "kernel": kc.KERNEL_CACHE.check_consistency(),
        "kernel_join": kc.JOIN_CACHE.check_consistency(),
        "kernel_topk": kc.TOPK_CACHE.check_consistency(),
        "kernel_sort": kc.SORT_CACHE.check_consistency(),
    }
    lock_report = cc.report()
    violations = int(_val("staticcheck.lock.violations"))

    ok = (
        not mismatches
        and not errors
        and replay_mismatches == 0
        and hit_ratio > 0
        and folds_in_window + (1 if fold_ok else 0) > 0
        and fold_ok
        and zero_exec_on_hit
        and not conservation
        and violations == 0
        and all(consistency.values())
        and quiescent
        and refresh_idle()
    )
    out = {
        "rows": rows,
        "clients": clients,
        "repeats": repeats,
        "ingest_batches": batches,
        "served_tpch_runs": clients * repeats * len(names),
        "served_live_runs": len(ev_runs),
        "bit_identical_tpch": not mismatches,
        "replay_mismatches": replay_mismatches,
        "snapshots_replayed": len(replay_cache),
        "errors": errors[:10],
        "hits": int(hits),
        "misses": int(misses),
        "hit_ratio": round(hit_ratio, 4),
        "folds": int(_val("cache.result.folds") - folds0),
        "fold_rows": int(_val("cache.result.fold_rows")),
        "refreshes": int(_val("cache.result.refreshes")),
        "zero_exec_on_hit": zero_exec_on_hit,
        "fold_exercise_ok": fold_ok,
        "attribution_conserved": not conservation,
        "conservation_mismatches": dict(list(conservation.items())[:10]),
        "scheduler_quiescent": quiescent,
        "lock_audit": lock_report["audit_enabled"],
        "lock_violations": violations,
        "cache_consistency": consistency,
        "result_cache": RESULT_CACHE.state(),
        "ok": ok,
    }
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
