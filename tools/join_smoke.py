#!/usr/bin/env python
"""Pipelined-vs-serial JOIN smoke: run the TPC-H join bench queries (q3,
q10, q17) over covering join indexes with the streamed + banded bucketed
join ON (HYPERSPACE_PIPELINE=1) and OFF (=0, the load-all barrier +
global-pad path) on the same generated dataset and assert the results are
bit-identical — including a skewed-key variant where one hot key inflates a
single bucket. A third OVER-BUDGET leg reruns the pipelined queries at a
deliberately tiny HYPERSPACE_DEVICE_BUDGET_MB so every band wave exceeds
the device-memory ledger: the memory-adaptive path must park/spill (not
decline), stay bit-identical to BOTH the unconstrained and the PIPELINE=0
runs, and drain the ledger to zero. The whole smoke runs with
HYPERSPACE_LOCK_AUDIT=1 — any lock-order violation fails it. Prints one
JSON line; exit 0 iff every leg matches, bucket pairs streamed, band waves
dispatched, the over-budget leg actually parked AND spilled, and zero lock
violations.

    timeout 300 env JAX_PLATFORMS=cpu python tools/join_smoke.py

Env: SMOKE_ROWS (lineitem rows, default 120000), HYPERSPACE_JOIN_SPLIT_ROWS
is forced small so oversized buckets exercise the split path too;
SMOKE_DEVICE_BUDGET_MB (default 0.25) sizes the over-budget leg's grant.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    os.environ.setdefault("HYPERSPACE_DEVICE_STRICT", "1")
    os.environ.setdefault("HYPERSPACE_JOIN_SPLIT_ROWS", "8192")
    os.environ.setdefault("HYPERSPACE_LOCK_AUDIT", "1")
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import tempfile

    import numpy as np

    from hyperspace_tpu import CoveringIndexConfig, Hyperspace, HyperspaceSession
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.telemetry.metrics import REGISTRY

    rows = int(os.environ.get("SMOKE_ROWS", 120_000))
    ws = tempfile.mkdtemp(prefix="hs_join_smoke_")
    generate_tpch(ws, rows_lineitem=rows, seed=11)
    # skew lineitem: rewrite 30% of order keys to ONE hot order so a single
    # bucket dwarfs the rest (the banding/splitting target shape)
    _skew_lineitem(ws, hot_frac=0.3)

    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    hs = Hyperspace(session)
    li = session.read.parquet(os.path.join(ws, "lineitem"))
    od = session.read.parquet(os.path.join(ws, "orders"))
    pt = session.read.parquet(os.path.join(ws, "part"))
    hs.create_index(
        li,
        CoveringIndexConfig(
            "li_orderkey",
            ["l_orderkey"],
            ["l_extendedprice", "l_discount", "l_returnflag", "l_quantity"],
        ),
    )
    hs.create_index(
        li,
        CoveringIndexConfig(
            "li_partkey", ["l_partkey"], ["l_quantity", "l_extendedprice"]
        ),
    )
    hs.create_index(
        od,
        CoveringIndexConfig(
            "od_orderkey", ["o_orderkey"], ["o_orderdate", "o_custkey"]
        ),
    )
    hs.create_index(
        pt, CoveringIndexConfig("pt_partkey", ["p_partkey"], ["p_brand"])
    )

    join_queries = ("q3", "q10", "q17")

    def run(pipeline: str) -> dict:
        os.environ["HYPERSPACE_PIPELINE"] = pipeline
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = {}
        try:
            for name in join_queries:
                out[name] = TPCH_QUERIES[name](session, ws).to_pydict()
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()
        return out

    pairs0 = REGISTRY.counter("pipeline.join.pairs").value
    bands0 = REGISTRY.counter("pipeline.join.bands").value
    on = run("1")
    pairs_streamed = REGISTRY.counter("pipeline.join.pairs").value - pairs0
    bands = REGISTRY.counter("pipeline.join.bands").value - bands0
    off = run("0")

    # ---- over-budget leg: every band wave exceeds the device ledger ------
    from hyperspace_tpu.serve import budget as serve_budget

    os.environ["HYPERSPACE_DEVICE_BUDGET_MB"] = os.environ.get(
        "SMOKE_DEVICE_BUDGET_MB", "0.25"
    )
    serve_budget.reset_device_budget()
    parks0 = REGISTRY.counter("join.spill.parks").value
    spills0 = REGISTRY.counter("join.spill.spills").value
    adaptive = run("1")
    parks = REGISTRY.counter("join.spill.parks").value - parks0
    spills = REGISTRY.counter("join.spill.spills").value - spills0
    device_acct = serve_budget.device_budget()
    ledger_drained = (
        device_acct.held_bytes() == 0 and device_acct.check_consistency()
    )
    del os.environ["HYPERSPACE_DEVICE_BUDGET_MB"]
    serve_budget.reset_device_budget()

    def bits(d):
        return repr(
            {
                k: [x.hex() if isinstance(x, float) else x for x in v]
                for k, v in d.items()
            }
        )

    mismatches = [name for name in on if bits(on[name]) != bits(off[name])]
    adaptive_mismatches = [
        name
        for name in on
        if bits(adaptive[name]) != bits(on[name])
        or bits(adaptive[name]) != bits(off[name])
    ]
    lock_violations = int(
        REGISTRY.counter("staticcheck.lock.violations").value
    )
    result = {
        "rows": rows,
        "queries": len(on),
        "pairs_streamed": pairs_streamed,
        "band_dispatches": bands,
        "bit_identical": not mismatches,
        "mismatches": mismatches,
        "overbudget": {
            "device_budget_mb": os.environ.get("SMOKE_DEVICE_BUDGET_MB", "0.25"),
            "parks": parks,
            "spills": spills,
            "bit_identical": not adaptive_mismatches,
            "mismatches": adaptive_mismatches,
            "ledger_drained": ledger_drained,
        },
        "lock_violations": lock_violations,
        "join_counters": {
            k: v
            for k, v in REGISTRY.snapshot().items()
            if (k.startswith("pipeline.join.") or k.startswith("join."))
            and not isinstance(v, dict)
        },
    }
    print(json.dumps(result))
    ok = (
        not mismatches
        and not adaptive_mismatches
        and pairs_streamed > 0
        and bands > 0
        and parks > 0
        and spills > 0
        and ledger_drained
        and lock_violations == 0
    )
    return 0 if ok else 1


def _skew_lineitem(ws: str, hot_frac: float) -> None:
    import glob

    import numpy as np

    from hyperspace_tpu.columnar import io as cio

    files = sorted(glob.glob(os.path.join(ws, "lineitem", "*.parquet")))
    batch = cio.read_parquet(files)
    k = np.asarray(batch.column("l_orderkey").data).copy()
    n_hot = int(len(k) * hot_frac)
    k[:n_hot] = k[0]
    from hyperspace_tpu.columnar.table import Column

    batch = batch.with_column("l_orderkey", Column(k, "int64"))
    for f in files:
        os.remove(f)
    cio.write_parquet(batch, os.path.join(ws, "lineitem", "part-0000.parquet"))


if __name__ == "__main__":
    sys.exit(main())
