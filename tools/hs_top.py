#!/usr/bin/env python
"""hs_top — top(1) for a hyperspace serving process.

Renders the serving telemetry plane as a terminal table: health + breaker
state, scheduler occupancy, global-budget occupancy, the device-memory
ledger (occupancy, parked/spilled/resumed join waves), the per-tenant QoS
table (weights, virtual clocks, delivered share, quota rejections),
serving rates, the active queries, and the tail of the per-query log
(tenant, phase breakdown, bytes, cache hit ratio per query). Three
sources, same payload shape (the exporter's ``/snapshot``):

    python tools/hs_top.py --url http://127.0.0.1:9090           # one shot
    python tools/hs_top.py --url http://127.0.0.1:9090 --watch 2 # live
    python tools/hs_top.py --file snapshots.jsonl                # JSONL sink
    python tools/hs_top.py --file snapshots.jsonl --watch 2      # follow

``--url`` scrapes a live exporter (telemetry/exporter.py, enabled with
``HYPERSPACE_METRICS_PORT``); ``--file`` reads the LAST line of a periodic
snapshot-sink JSONL (``HYPERSPACE_SNAPSHOT_FILE``), so a headless run can
be watched from another terminal. In ``--watch`` mode rates (QPS, bytes/s)
are derived from successive snapshots' counter deltas.

See docs/observability.md ("Query log") for the column definitions.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

_PHASE_SHORT = (
    ("plan", "plan"), ("io", "io"), ("upload", "up"),
    ("dispatch", "disp"), ("fetch", "fetch"), ("fold", "fold"),
    ("park", "park"),
)


def _fetch_url(url: str) -> dict:
    with urllib.request.urlopen(url.rstrip("/") + "/snapshot", timeout=10) as r:
        return json.loads(r.read().decode("utf-8"))


def _fetch_file(path: str) -> dict:
    last = None
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                last = line
    if last is None:
        raise ValueError(f"no snapshots in {path} yet")
    return json.loads(last)


def _mb(n) -> str:
    return f"{(n or 0) / 1e6:.2f}"


def _phase_cell(rec: dict) -> str:
    phases = rec.get("phases_ms") or {}
    parts = [
        f"{short}={phases[name]:.0f}"
        for name, short in _PHASE_SHORT
        if phases.get(name, 0) >= 0.05
    ]
    return " ".join(parts) if parts else "-"


def _adapt_cell(rec: dict) -> str:
    """Mid-query adaptation events charged to this query: the sum of its
    ``adaptive.{replan,reorder,abort}`` site counters ("-" when none)."""
    counters = rec.get("counters") or {}
    n = sum(
        int(v)
        for k, v in counters.items()
        if k in ("adaptive.replan", "adaptive.reorder", "adaptive.abort")
    )
    return str(n) if n else "-"


def _approx_cell(rec: dict) -> str:
    """Approximate-tier column: ``~f`` when the query was served sampled
    at fraction f, ``d`` prefix when the QoS door degraded it (``d!``
    alone = degraded but the plan was ineligible, served exact). "-" for
    plain exact queries."""
    ap = rec.get("approx") or {}
    if not ap:
        return "-"
    deg = "d" if ap.get("degraded") else ""
    if ap.get("engaged"):
        return f"{deg}~{ap.get('fraction', 0):g}"
    return f"{deg}!" if deg else "-"


def _rates(prev: dict | None, cur: dict) -> str:
    """QPS / MB/s derived from two successive snapshots' counters."""
    if prev is None:
        return "rates: (need two snapshots)"
    dt = (cur.get("ts") or 0) - (prev.get("ts") or 0)
    if dt <= 0:
        return "rates: (no time delta)"
    pm, cm = prev.get("metrics") or {}, cur.get("metrics") or {}

    def d(name):
        return (cm.get(name) or 0) - (pm.get(name) or 0)

    return (
        f"rates: {d('serve.query.records') / dt:.2f} qps, "
        f"{d('io.bytes_decoded') / dt / 1e6:.2f} MB/s decoded, "
        f"{d('serve.budget.stalls') / dt:.2f} stalls/s, "
        f"{d('exporter.scrapes') / dt:.2f} scrapes/s over {dt:.1f}s"
    )


def render(snap: dict, prev: dict | None = None, recent: int = 15) -> str:
    serving = snap.get("serving") or {}
    queries = snap.get("queries") or {}
    breaker = snap.get("breaker") or {}
    budget = serving.get("budget") or {}
    totals = serving.get("totals") or {}
    qtotals = queries.get("totals") or {}
    lines = []
    ts = snap.get("ts")
    when = time.strftime("%H:%M:%S", time.localtime(ts)) if ts else "?"
    lines.append(
        f"hs_top @ {when} | breaker={breaker.get('state', '?')} | "
        f"scheduler {len(serving.get('active') or [])} active / "
        f"{len(serving.get('queued') or [])} queued "
        f"(max={serving.get('max_concurrent')}, "
        f"depth={serving.get('queue_depth_limit')})"
    )
    held, limit = budget.get("held_bytes", 0), budget.get("limit_bytes", 0)
    pct = 100.0 * held / limit if limit else 0.0
    lines.append(
        f"budget {_mb(held)}/{_mb(limit)} MB held ({pct:.1f}%), "
        f"{len(budget.get('streams') or [])} stream(s) | "
        f"admitted={totals.get('admitted', 0)} done={totals.get('done', 0)} "
        f"failed={totals.get('failed', 0)} "
        f"cancelled={totals.get('cancelled', 0)} "
        f"rejected={totals.get('rejected', 0)} | "
        f"log recorded={qtotals.get('recorded', 0)} "
        f"slow={qtotals.get('slow', 0)}"
    )
    dev = serving.get("device_budget") or {}
    if dev:
        dheld, dlimit = dev.get("held_bytes", 0), dev.get("limit_bytes", 0)
        if dlimit:
            dpct = 100.0 * dheld / dlimit
            lines.append(
                f"device {_mb(dheld)}/{_mb(dlimit)} MB held ({dpct:.1f}%), "
                f"{len(dev.get('streams') or [])} stream(s) | "
                f"parks={dev.get('parks', 0)} spills={dev.get('spills', 0)} "
                f"resumes={dev.get('resumes', 0)}"
            )
        else:
            lines.append("device ledger disabled (HYPERSPACE_DEVICE_BUDGET_MB=0)")
    rc = snap.get("result_cache") or {}
    if rc and rc.get("mode", "0") != "0":
        looked = (rc.get("hits", 0) or 0) + (rc.get("misses", 0) or 0)
        ratio = 100.0 * rc.get("hits", 0) / looked if looked else 0.0
        lines.append(
            f"result-cache mode={rc.get('mode')} "
            f"{rc.get('entries', 0)} entries "
            f"({rc.get('foldable_entries', 0)} foldable) "
            f"{_mb(rc.get('bytes'))}/{_mb(rc.get('max_bytes'))} MB | "
            f"hits={rc.get('hits', 0)} misses={rc.get('misses', 0)} "
            f"({ratio:.1f}%) folds={rc.get('folds', 0)} "
            f"refreshes={rc.get('refreshes', 0)} "
            f"evictions={rc.get('evictions', 0)}"
        )
    tenants = snap.get("tenants") or {}
    tsched = tenants.get("scheduler") or {}
    trolls = tenants.get("rollups") or {}
    tnames = sorted(set(tsched) | set(trolls))
    # the single zero-config default tenant with nothing notable is noise;
    # any configured weight/quota, rejection, or second tenant prints
    if tnames and not (
        tnames == ["default"]
        and (tsched.get("default") or {}).get("weight", 1.0) == 1.0
        and not any(
            (tsched.get("default") or {}).get(f"rejected_{k}", 0)
            for k in ("rate", "quota", "deadline")
        )
    ):
        lines.append(
            f"TENANTS ({len(tnames)}): "
            f"{'tenant':<12} {'w':>5} {'share':>6} {'vclock':>9} "
            f"{'q/a':>5} {'done':>5} {'rej':>4} {'MB':>8}"
        )
        for name in tnames:
            s = tsched.get(name) or {}
            r = trolls.get(name) or {}
            rej = (
                s.get("rejected_rate", 0) + s.get("rejected_quota", 0)
                + s.get("rejected_deadline", 0)
            )
            lines.append(
                f"  tenant: {name[:12]:<12} {s.get('weight', 1.0):>5.2f} "
                f"{s.get('delivered_share', 0.0):>6.2f} "
                f"{s.get('vclock', 0.0):>9.3f} "
                f"{s.get('queued', 0)}/{s.get('active', 0):>3} "
                f"{s.get('done', 0):>5} {rej:>4} "
                f"{_mb(r.get('bytes_read')):>8}"
            )
    est = snap.get("estimator") or {}
    if est.get("observations"):
        qcells = [
            f"{name} n={h.get('count', 0)} mean={h.get('mean', 0):.2f} "
            f"max={h.get('max', 0):.2f}"
            for name, h in sorted((est.get("qerror") or {}).items())
            if h.get("count")
        ]
        lines.append(
            "estimator q-errors: " + (" | ".join(qcells) or "(none)")
            + f" | corrections={est.get('correction_keys', 0)}"
        )
    wl = snap.get("workload") or {}
    if wl.get("enabled"):
        jst = wl.get("journal") or {}
        lines.append(
            f"workload journal: {jst.get('writes', 0)} writes, "
            f"{jst.get('files', 0)} file(s), "
            f"{jst.get('rotations', 0)} rotation(s), "
            f"{_mb(jst.get('current_bytes'))} MB current"
        )
        idx = wl.get("indexes") or []
        if idx:
            lines.append(
                f"INDEXES ({len(idx)}): "
                f"{'index':<20} {'queries':>7} {'benefit_MB':>10} "
                f"{'skip_MB':>8} {'maint_s':>8} {'net_s':>9}"
            )
            for r in idx:
                lines.append(
                    f"  index: {str(r.get('name', '?'))[:20]:<20} "
                    f"{r.get('queries', 0):>7} "
                    f"{_mb(r.get('benefit_bytes')):>10} "
                    f"{_mb(r.get('bytes_skipped')):>8} "
                    f"{r.get('maintenance_s', 0.0):>8.3f} "
                    f"{r.get('net_utility_s', 0.0):>9.3f}"
                )
            cold = wl.get("cold_indexes") or []
            if cold:
                lines.append(f"  cold candidates: {', '.join(cold)}")
        drift = wl.get("drift") or {}
        regs = drift.get("regressions") or []
        lines.append(
            f"DRIFT: {drift.get('series', 0)} series, "
            f"{len(regs)} regression(s)"
            + (f" [factor={drift.get('factor')}]" if regs else "")
        )
        for r in regs:
            lines.append(
                f"  drift: {r.get('kind')}:{r.get('key')} "
                f"baseline={r.get('baseline')} current={r.get('current')} "
                f"ratio={r.get('ratio')}x"
            )
    ap = snap.get("approx") or {}
    if ap.get("degrades") or ap.get("sampled_queries") or ap.get("ineligible"):
        mean_ci = ap.get("mean_ci_rel")
        lines.append(
            f"APPROX: degrades={ap.get('degrades', 0)} "
            f"sampled={ap.get('sampled_queries', 0)} "
            f"ineligible={ap.get('ineligible', 0)} "
            f"verify_checked={ap.get('verify_checked', 0)}"
            + (f" mean_ci=±{100 * mean_ci:.2f}%" if mean_ci is not None else "")
        )
    lines.append(_rates(prev, snap))
    hdr = (
        f"{'qid':>5} {'label':<20} {'tenant':<10} {'pri':>3} {'outcome':<9} "
        f"{'total_ms':>9} {'queue_ms':>8} {'MB':>7} {'hit%':>5} "
        f"{'stall':>5} {'adapt':>5} {'apx':>6}  phases_ms"
    )
    active = queries.get("active") or []
    lines.append("")
    lines.append(f"ACTIVE ({len(active)})")
    lines.append(hdr)
    rows = active + (queries.get("recent") or [])[-recent:]
    for i, r in enumerate(rows):
        if i == len(active):
            lines.append("")
            lines.append(f"RECENT (last {min(recent, len(rows) - i)})")
            lines.append(hdr)
        ratio = r.get("cache_hit_ratio")
        lines.append(
            f"{r.get('query_id', '?'):>5} {str(r.get('label', ''))[:20]:<20} "
            f"{str(r.get('tenant', '-'))[:10]:<10} "
            f"{r.get('priority', 0):>3} {str(r.get('outcome', '?'))[:9]:<9} "
            f"{r.get('total_ms', 0):>9.1f} {r.get('queue_wait_ms', 0):>8.1f} "
            f"{_mb(r.get('bytes_read')):>7} "
            f"{100 * ratio if ratio is not None else 0:>5.1f} "
            f"{r.get('budget_stalls', 0):>5} {_adapt_cell(r):>5} "
            f"{_approx_cell(r):>6}  "
            f"{_phase_cell(r)}"
        )
    if len(rows) == len(active):
        lines.append("(no finished queries in the log window)")
    return "\n".join(lines)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="exporter base URL (scrapes /snapshot)")
    src.add_argument("--file", help="snapshot-sink JSONL (reads last line)")
    p.add_argument("--watch", type=float, metavar="SECONDS",
                   help="refresh every SECONDS (default: render once)")
    p.add_argument("--recent", type=int, default=15,
                   help="recent-query rows to show (default 15)")
    args = p.parse_args()

    def fetch() -> dict:
        return _fetch_url(args.url) if args.url else _fetch_file(args.file)

    if not args.watch:
        print(render(fetch(), recent=args.recent))
        return 0
    prev = None
    try:
        while True:
            try:
                snap = fetch()
            except Exception as e:  # noqa: BLE001 - keep polling a flaky target
                sys.stdout.write(f"\x1b[2J\x1b[H(snapshot failed: {e!r})\n")
                sys.stdout.flush()
                time.sleep(args.watch)
                continue
            out = render(snap, prev, recent=args.recent)
            sys.stdout.write("\x1b[2J\x1b[H" + out + "\n")
            sys.stdout.flush()
            prev = snap
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
