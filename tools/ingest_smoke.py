#!/usr/bin/env python
"""Continuous-ingestion gate: N ingest batches racing M concurrent query
clients through the scheduler, with background compaction and vacuum
firing mid-run — every query must be bit-identical to a serial replay of
the snapshot it pinned, with zero lock violations, consistent caches, and
no orphan version dirs once the stream drains.

Phase A (serial reference): a twin warehouse replays the same seeded batch
sequence one batch at a time, recording the reference bits of the query
set after each batch — ``bits[k]`` is "the answer over exactly the first
k+1 batches". Queries are order-insensitive by construction (sorted
grouped INT aggregates), so the reference depends only on the visible row
multiset — which compaction and vacuum must preserve.

Phase B (the race): an ingester thread appends the same batches through
``Hyperspace.append`` (auto-scheduling background compaction on the shared
IO pool; an explicit pin-aware vacuum runs mid-stream), while
``SMOKE_CLIENTS`` client threads hammer the query set through ONE
``QueryScheduler``. Each client plans against the file listing of the
latest STABLE snapshot it fetched (the serving-tier metadata-cache
pattern), so the rewrite exact-matches and pins that snapshot; the
immutable log entry's recorded source-part count translates the pin into
the k whose ``bits[k]`` the result MUST equal — a query racing a commit
may legitimately see k or k+1, but never a torn in-between.

Asserted invariants (exit 0 iff all hold):

- every concurrent query's bits == bits[k of the snapshot it pinned (or,
  for the few that lose the fetch→plan race to a commit and read their
  fixed listing raw, the entry it fetched)];
- >= half the served queries demonstrably pinned a snapshot;
- >= 1 compaction and >= 1 vacuum retirement occurred mid-run;
- crash cells: ``ingest.append`` / ``ingest.compact`` crash_before/after
  each recover() to a stable orphan-free index that converges
  bit-identically to a never-crashed twin;
- ``staticcheck.lock.violations`` == 0 with the acquisition-order audit on;
- every bounded cache's ``check_consistency()`` holds at quiescence;
- after the final drain + vacuum: no staging dirs, no ``.tmp-*`` spool
  files, and every surviving ``v__=N`` dir is referenced by the latest
  entry (no orphans);
- the snapshot registry drains to zero active pins.

    timeout 300 env JAX_PLATFORMS=cpu python tools/ingest_smoke.py

Env: SMOKE_CLIENTS (4), SMOKE_CONCURRENT (4), SMOKE_BATCHES (10),
SMOKE_BATCH_ROWS (3000), SMOKE_QUERIES_PER_CLIENT (30).
"""

import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    os.environ.setdefault("HYPERSPACE_DEVICE_STRICT", "1")
    # real pool width on the 1-core container so the shared IO pool and
    # background maintenance actually interleave with serving queries
    os.environ.setdefault("HYPERSPACE_IO_THREADS", "4")
    # compact after a few delta runs so >= 1 compaction happens mid-run
    os.environ.setdefault("HYPERSPACE_COMPACT_RUNS", "3")
    if os.environ.get("SMOKE_LOCK_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LOCK_AUDIT", "1")
    if os.environ.get("SMOKE_LIFECYCLE_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LIFECYCLE_AUDIT", "1")
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import tempfile

    import numpy as np

    from hyperspace_tpu import (
        CoveringIndexConfig,
        Hyperspace,
        HyperspaceSession,
        ingest,
        serve,
    )
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.index_manager import IndexCollectionManager
    from hyperspace_tpu.meta.data_manager import IndexDataManager
    from hyperspace_tpu.meta.log_manager import IndexLogManager, STABLE_STATES
    from hyperspace_tpu.plan import Count, Max, Min, Sum, col, lit
    from hyperspace_tpu.plan import kernel_cache as kc
    from hyperspace_tpu.staticcheck import concurrency as cc
    from hyperspace_tpu.staticcheck import lifecycle as lc
    from hyperspace_tpu.telemetry.metrics import REGISTRY
    from hyperspace_tpu.utils import device_cache as dc, faults

    clients = int(os.environ.get("SMOKE_CLIENTS", 4))
    concurrent = int(os.environ.get("SMOKE_CONCURRENT", 4))
    n_batches = int(os.environ.get("SMOKE_BATCHES", 10))
    batch_rows = int(os.environ.get("SMOKE_BATCH_ROWS", 3000))
    queries_per_client = int(os.environ.get("SMOKE_QUERIES_PER_CLIENT", 30))

    def batch(seed: int) -> dict:
        r = np.random.default_rng(1000 + seed)
        return {
            "k": r.integers(0, 64, batch_rows).tolist(),
            "v": r.integers(0, 10_000, batch_rows).tolist(),
            "w": r.integers(0, 100, batch_rows).tolist(),
        }

    def make_warehouse(prefix: str):
        ws = tempfile.mkdtemp(prefix=prefix)
        src = os.path.join(ws, "events")
        os.makedirs(src)
        cio.write_parquet(
            ColumnBatch.from_pydict(batch(0)), os.path.join(src, "part0.parquet")
        )
        s = HyperspaceSession(warehouse_dir=ws)
        s.set_conf(C.INDEX_NUM_BUCKETS, 8)
        h = Hyperspace(s)
        h.create_index(
            s.read.parquet(src), CoveringIndexConfig("ev", ["k"], ["v", "w"])
        )
        s.enable_hyperspace()
        return ws, src, s, h

    # order-insensitive query set: sorted grouped INT aggregates — the
    # answer is a pure function of the visible row multiset, so compaction
    # and vacuum legitimately cannot change it (and any torn read would)
    def q_group(df):
        return (
            df.filter(df["k"] < 48)
            .group_by("k")
            .agg(
                Sum(col("v")).alias("sv"),
                Count(lit(1)).alias("n"),
                Min(col("w")).alias("mn"),
                Max(col("w")).alias("mx"),
            )
            .sort("k")
            .collect()
        )

    def q_point(df):
        return (
            df.filter(df["k"] == 7)
            .agg(Sum(col("v")).alias("sv"), Count(lit(1)).alias("n"))
            .collect()
        )

    QUERIES = {"group": q_group, "point": q_point}

    def bits(out) -> str:
        d = out.to_pydict()
        return repr(
            {
                kk: [x.hex() if isinstance(x, float) else x for x in vv]
                for kk, vv in d.items()
            }
        )

    failures: list = []

    # ---- phase A: serial reference bits per visible batch count ----------
    ref_ws, ref_src, ref_s, ref_h = make_warehouse("hs_ingest_ref_")

    def ref_bits() -> dict:
        df = ref_s.read.parquet(ref_src)
        return {qn: bits(fn(df)) for qn, fn in QUERIES.items()}

    bits_at: dict[int, dict[str, str]] = {0: ref_bits()}
    for k in range(1, n_batches + 1):
        ingest.append_batch(ref_s, "ev", batch(k))
        bits_at[k] = ref_bits()

    # ---- phase B: concurrent ingest + queries ----------------------------
    ws, src, session, hs = make_warehouse("hs_ingest_race_")
    sched = serve.QueryScheduler(
        max_concurrent=concurrent,
        queue_depth=max(64, clients * queries_per_client),
    )

    ingest_errors: list = []

    def ingester() -> None:
        try:
            for k in range(1, n_batches + 1):
                ingest.append_batch(session, "ev", batch(k))
                if k == (n_batches * 2) // 3:
                    # one explicit pin-aware vacuum mid-stream (background
                    # maintenance also vacuums after each compaction)
                    hs.vacuum_outdated_index("ev")
        except Exception as e:  # noqa: BLE001 - reported via the gate
            ingest_errors.append(repr(e))

    # Serving pattern: each query plans against the file listing of the
    # latest STABLE snapshot it fetched (a real serving tier caches table
    # metadata the same way) — so its signature exact-matches that entry,
    # the rewrite pins the snapshot, and the answer is deterministically
    # "the first k batches". A query that still loses the fetch→plan race
    # to a commit reads its fixed listing raw: same k, no pin — recorded
    # and verified against the FETCHED entry instead.
    # Every (client, query, entry id, pinned?, bits) is recorded; the
    # entry → k translation happens AFTER the race from the immutable log
    # entries themselves (k = recorded source parts - the seed part).
    served_results: list = []
    results_lock = threading.Lock()
    client_errors: list = []
    barrier = threading.Barrier(clients + 1)

    def client(tid: int) -> None:
        try:
            barrier.wait()
            qnames = list(QUERIES)
            for i in range(queries_per_client):
                qn = qnames[(tid + i) % len(qnames)]
                obs = ingest.observe_pins()

                def run(qn=qn, obs=obs):
                    with obs:
                        entry = ingest.latest_stable_entry(session, "ev")
                        files = [
                            f.name for f in entry.relation.content.file_infos()
                        ]
                        return entry.id, QUERIES[qn](session.read.parquet(files))

                h = sched.submit(run, label=f"c{tid}:{qn}")
                fetched_eid, out = h.result(timeout=300)
                got = bits(out)
                pins = [p for p in obs.pins if p.index_name == "ev"]
                eid = pins[0].entry_id if pins else fetched_eid
                with results_lock:
                    served_results.append((tid, qn, eid, bool(pins), got))
        except Exception as e:  # noqa: BLE001 - reported via the gate
            client_errors.append((tid, repr(e)))

    from hyperspace_tpu.utils.workers import spawn_thread

    threads = [
        spawn_thread(client, name=f"hs-ingest-client-{i}", daemon=False, args=(i,))
        for i in range(clients)
    ]
    ing = spawn_thread(ingester, name="hs-ingester", daemon=False)
    barrier.wait()  # clients + main start together; ingester free-runs
    ing.join()
    for t in threads:
        t.join()
    sched.drain(timeout=120)

    # ---- serial replay of each pinned snapshot ---------------------------
    # translate every pinned entry to its visible batch count k from the
    # entry's own immutable record: the relation content lists exactly the
    # source parts this snapshot covered (seed part0 + k ingested batches)
    from hyperspace_tpu.index_manager import index_manager_for

    manager = index_manager_for(session)
    k_of_entry: dict[int, int] = {}
    mismatches: list = []
    pinned_queries = 0
    for tid, qn, eid, was_pinned, got in served_results:
        pinned_queries += was_pinned
        k = k_of_entry.get(eid)
        if k is None:
            e = manager.get_index("ev", log_version=eid)
            if e is None:
                mismatches.append((tid, qn, eid, "entry-vanished"))
                continue
            k = len(e.relation.content.file_infos()) - 1
            k_of_entry[eid] = k
        if got != bits_at[k][qn]:
            mismatches.append((tid, qn, eid, f"diverges-from-snapshot-k={k}"))

    # drain background maintenance, then a final compact+vacuum pass so the
    # end state is canonical (single compacted version, no superseded dirs)
    import time as _time

    deadline = _time.monotonic() + 120
    while _time.monotonic() < deadline and not ingest.maintenance_idle():
        _time.sleep(0.05)  # hslint: HS401 — gate tool, maintenance drain
    maintenance_drained = ingest.maintenance_idle()
    hs.compact_index("ev", min_runs=2)
    hs.vacuum_outdated_index("ev")

    # final-state correctness: the fully-drained warehouse answers exactly
    # like the serial twin at k = n_batches (fresh directory listing — no
    # concurrency left, so the raw-source view and the index view agree)
    final_df = session.read.parquet(src)
    final_ok = all(
        bits(fn(final_df)) == bits_at[n_batches][qn]
        for qn, fn in QUERIES.items()
    )

    # ---- orphan / debris audit ------------------------------------------
    ip = os.path.join(ws, C.INDEXES_DIR, "ev")
    lm, dm = IndexLogManager(ip), IndexDataManager(ip)
    latest = lm.get_latest_log()
    entry = hs.get_index("ev")
    live_dirs = {int(d.split("=")[1]) for d in entry.index_version_dirs()}
    debris: list = []
    if latest is None or latest.state not in STABLE_STATES:
        debris.append(f"unstable log tail: {getattr(latest, 'state', None)}")
    if dm.staged_versions():
        debris.append(f"staging dirs: {dm.staged_versions()}")
    if lm.stale_temp_files():
        debris.append("stale .tmp spool files")
    orphan_dirs = [v for v in dm.get_all_versions() if v not in live_dirs]
    if orphan_dirs:
        debris.append(f"version dirs not referenced by latest: {orphan_dirs}")

    # ---- crash cells for the two new fault points ------------------------
    def crash_cell(action: str, spec: str) -> dict:
        twin_ws, twin_src, ts, th = make_warehouse("hs_ingest_twin_")
        p = os.path.join(twin_src, "p1.parquet")
        cio.write_parquet(ColumnBatch.from_pydict(batch(99)), p)
        th.append("ev", ts.read.parquet(p))
        if action == "compact":
            th.compact_index("ev", min_runs=2)
        twin_bits = bits(q_group(ts.read.parquet(twin_src)))

        cell_ws, cell_src, s, h = make_warehouse("hs_ingest_cell_")
        p = os.path.join(cell_src, "p1.parquet")
        cio.write_parquet(ColumnBatch.from_pydict(batch(99)), p)
        if action == "compact":
            h.append("ev", s.read.parquet(p))
        faults.arm(spec)
        crashed = False
        try:
            if action == "compact":
                h.compact_index("ev", min_runs=2)
            else:
                h.append("ev", s.read.parquet(p))
        except faults.InjectedCrash:
            crashed = True
        finally:
            faults.disarm()
        s2 = HyperspaceSession(warehouse_dir=cell_ws)
        h2 = Hyperspace(s2)
        h2.recover(force=True)
        cip = os.path.join(cell_ws, C.INDEXES_DIR, "ev")
        clm, cdm = IndexLogManager(cip), IndexDataManager(cip)
        cell_debris: list = []
        tail = clm.get_latest_log()
        if tail is None or tail.state not in STABLE_STATES:
            cell_debris.append(f"unstable:{getattr(tail, 'state', None)}")
        if cdm.staged_versions():
            cell_debris.append(f"staging:{cdm.staged_versions()}")
        refs = IndexCollectionManager._referenced_versions(clm)
        orph = [v for v in cdm.get_all_versions() if v not in refs]
        if orph:
            cell_debris.append(f"orphans:{orph}")
        if action == "compact":
            h2.compact_index("ev", min_runs=2)
        else:
            h2.append("ev", s2.read.parquet(p))
        s2.enable_hyperspace()
        identical = bits(q_group(s2.read.parquet(cell_src))) == twin_bits
        return {
            "action": action,
            "spec": spec,
            "crashed": crashed,
            "recovered_clean": not cell_debris,
            "identical": identical,
            "debris": cell_debris,
        }

    crash_cells = [
        crash_cell("append", "ingest.append:crash_before:n=1"),
        crash_cell("append", "ingest.append:crash_after:n=1"),
        crash_cell("compact", "ingest.compact:crash_before:n=1"),
        crash_cell("compact", "ingest.compact:crash_after:n=1"),
    ]
    crash_ok = all(
        c["crashed"] and c["recovered_clean"] and c["identical"]
        for c in crash_cells
    )

    # ---- global invariants ----------------------------------------------
    def val(n: str) -> int:
        m = REGISTRY.get(n)
        return 0 if m is None else int(m.value)

    consistency = {
        "io.index_chunk": cio._INDEX_CHUNK_CACHE.check_consistency(),
        "io.source_col": cio._SOURCE_COL_CACHE.check_consistency(),
        "io.rowgroup_stats": cio._ROWGROUP_STATS_CACHE.check_consistency(),
        "device": dc.DEVICE_CACHE.check_consistency(),
        "host_derived": dc.HOST_DERIVED_CACHE.check_consistency(),
        "kernel": kc.KERNEL_CACHE.check_consistency(),
        "kernel_join": kc.JOIN_CACHE.check_consistency(),
        "kernel_topk": kc.TOPK_CACHE.check_consistency(),
        "kernel_sort": kc.SORT_CACHE.check_consistency(),
    }
    sched.shutdown(wait=True)
    lock_report = cc.report()
    # quiescence: the pin registry draining to zero is necessary but not
    # sufficient — every other handle kind (budget streams, scopes, cache
    # markers) acquired across ingest + serve + crash cells must be gone too
    leaks = [h.describe() for h in lc.check_quiescent(raise_on_leak=False)]
    lifecycle = lc.report()
    violations = val("staticcheck.lock.violations")
    pins_drained = ingest.REGISTRY.active_pins() == 0
    compactions = val("ingest.compact.runs")
    vacuumed = val("ingest.vacuum.versions_removed")
    served = clients * queries_per_client

    ok = (
        not failures
        and not mismatches
        and not client_errors
        and not ingest_errors
        # pinning must demonstrably carry the load: at least half the
        # served queries resolved + pinned a snapshot (the rest lost the
        # fetch→plan race to a commit and read their fixed listing raw —
        # still verified against the fetched entry above)
        and pinned_queries * 2 >= served
        and final_ok
        and maintenance_drained
        and not debris
        and crash_ok
        and violations == 0
        and all(consistency.values())
        and pins_drained
        and compactions >= 1
        and vacuumed >= 1
        and val("ingest.appends") >= 2 * n_batches  # ref + race streams
        and not leaks
    )
    out = {
        "clients": clients,
        "max_concurrent": concurrent,
        "batches": n_batches,
        "batch_rows": batch_rows,
        "served_queries": served,
        "bit_identical": not mismatches and not client_errors,
        "mismatches": mismatches[:10],
        "client_errors": client_errors[:10],
        "ingest_errors": ingest_errors[:5],
        "pinned_queries": pinned_queries,
        "unpinned_queries": served - pinned_queries,
        "final_state_identical": final_ok,
        "maintenance_drained": maintenance_drained,
        "debris": debris,
        "crash_cells": crash_cells,
        "compactions": compactions,
        "vacuumed_versions": vacuumed,
        "vacuum_deferred": val("ingest.vacuum.deferred"),
        "appends": val("ingest.appends"),
        "rows_appended": val("ingest.rows_appended"),
        "snapshot_pins": val("ingest.snapshot.pins"),
        "snapshot_registry": ingest.REGISTRY.state(),
        "pins_drained": pins_drained,
        "lock_audit": lock_report["audit_enabled"],
        "lock_acquisitions": val("staticcheck.lock.acquisitions"),
        "lock_violations": violations,
        "cache_consistency": consistency,
        "lifecycle_audit": lifecycle["audit_enabled"],
        "lifecycle_acquires": lifecycle["acquires"],
        "lifecycle_releases": lifecycle["releases"],
        "lifecycle_leaks": leaks[:10],
        "ok": ok,
    }
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
