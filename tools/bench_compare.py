#!/usr/bin/env python
"""Diff two bench.py JSON artifacts section by section.

    python tools/bench_compare.py BENCH_r05.json BENCH_r06.json
    python tools/bench_compare.py A.json B.json --threshold 5

Walks the per-query sections plus the hybrid-refresh / bloom-skipping /
build / staticcheck / robustness blocks, prints one row per (section,
metric) with the old value, new
value, and signed percent delta (negative = B is faster/smaller). Metrics
present in only one artifact print with a `-` on the missing side.
``--threshold N`` hides rows whose |delta| is under N percent (timings
only; counters always print when changed).
"""

from __future__ import annotations

import argparse
import json
import sys

# per-query timing metrics worth diffing (ms unless noted)
_QUERY_METRICS = (
    "raw_ms",
    "indexed_hostexec_ms",
    "indexed_device_ms",
    "indexed_ms",
    "external_pandas_ms",
    "speedup_self",
    "speedup_vs_external",
)

_SECTION_METRICS = {
    "point_lookup": ("raw_ms", "indexed_ms", "speedup"),
    "hybrid_refresh": (
        "q3_hybrid_ms",
        "refresh_incremental_s",
        "q3_after_refresh_ms",
    ),
    "bloom_skipping": ("index_build_s", "raw_ms", "indexed_ms", "speedup"),
    "build": ("build_s",),
    # memory-adaptive spilling join: over-budget grant vs unconstrained
    "spill_join": (
        "unconstrained_ms",
        "constrained_ms",
        "spill_overhead_pct",
        "parks",
        "spills",
        "concurrent_parks",
    ),
    # mixed read/write serving: freshness lag + query latency under ingest
    "ingest_rw": (
        "wall_s",
        "ingest_rows_per_s",
        "freshness_p50_ms",
        "freshness_max_ms",
        "baseline_p50_ms",
        "baseline_p99_ms",
        "under_ingest_p50_ms",
        "under_ingest_p99_ms",
        "rows_ingested",
        "queries_under_ingest",
    ),
    # mesh-sharded scale-out: band waves across the device mesh vs the
    # single-device reference (bit-identical by construction; timings and
    # placement balance are the diffable signal)
    "mesh_scale": (
        "devices_visible",
        "mesh_off_ms",
        "mesh_on_ms",
        "placed_buckets",
        "placement_fallbacks",
        "devices_used",
        "bytes_imbalance_ratio",
    ),
    # approximate query tier: exact leg vs sampled legs on the dedicated
    # join fixture, plus the acceptance bar (best sampled speedup >= 5x)
    "approx_tier": (
        "index_build_s",
        "exact_ms",
        "best_sampled_speedup",
    ),
    # workload-intelligence plane: all zero with HYPERSPACE_WORKLOAD_DIR
    # unset (the default bench run) — drift here means the disabled plane
    # did work
    "workload": (
        "journal_records",
        "journal_rotations",
        "journal_errors",
        "index_applied",
        "benefit_bytes",
        "bytes_skipped",
        "maintenance_actions",
        "maintenance_s",
        "indexes_tracked",
        "drift_series",
        "drift_regressions",
    ),
}

_TOP_LEVEL = ("value", "vs_baseline", "index_build_gbps", "host_wall_s", "wall_s")


def _load(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        text = f.read().strip()
    # bench prints ONE JSON line, but tolerate logs around it: last line wins
    obj = None
    for line in reversed(text.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    if obj is None:
        try:
            obj = json.loads(text)
        except json.JSONDecodeError:
            raise ValueError(f"no JSON object found in {path}") from None
    if "queries" in obj:
        return obj
    # driver wrapper: {"cmd":..., "rc":..., "tail": <stdout tail>, "parsed": <bench json|null>}
    if isinstance(obj.get("parsed"), dict):
        return obj["parsed"]
    raise ValueError(
        f"{path} holds no bench result (wrapper with parsed=null — the run's "
        "stdout was truncated or the bench failed)"
    )


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3f}" if abs(v) < 100 else f"{v:.1f}"
    return str(v)


def _delta_pct(a, b):
    if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
        return None
    if a == 0:
        return None if b == 0 else float("inf")
    return (b - a) / abs(a) * 100


def compare(a: dict, b: dict) -> list[tuple[str, str, object, object]]:
    """[(section, metric, a_value, b_value)] over every diffable metric."""
    rows: list[tuple[str, str, object, object]] = []
    for m in _TOP_LEVEL:
        rows.append(("total", m, a.get(m), b.get(m)))
    qa, qb = a.get("queries", {}), b.get("queries", {})
    for name in sorted(set(qa) | set(qb)):
        ea, eb = qa.get(name, {}), qb.get(name, {})
        for m in _QUERY_METRICS:
            if m in ea or m in eb:
                rows.append((name, m, ea.get(m), eb.get(m)))
        # per-query join-pipeline counters (pairs/bands/splits/pad savings)
        ja, jb = ea.get("join_pipeline") or {}, eb.get("join_pipeline") or {}
        for m in sorted(set(ja) | set(jb)):
            rows.append((name, f"join_pipeline.{m}", ja.get(m), jb.get(m)))
        # per-query index-pruning counters (files/rowgroups kept vs total)
        pa_, pb = ea.get("pruning") or {}, eb.get("pruning") or {}
        for m in sorted(set(pa_) | set(pb)):
            rows.append((name, f"pruning.{m}", pa_.get(m), pb.get(m)))
    for section, metrics in _SECTION_METRICS.items():
        sa, sb = a.get(section, {}) or {}, b.get(section, {}) or {}
        for m in metrics:
            if m in sa or m in sb:
                rows.append((section, m, sa.get(m), sb.get(m)))
        # nested pruning counter deltas (point_lookup section)
        pa_, pb = sa.get("pruning") or {}, sb.get("pruning") or {}
        for m in sorted(set(pa_) | set(pb)):
            rows.append((section, f"pruning.{m}", pa_.get(m), pb.get(m)))
        # nested ingest counter deltas (ingest_rw section: appends,
        # compaction runs, vacuumed/deferred versions, snapshot pins)
        ia, ib = sa.get("counters") or {}, sb.get("counters") or {}
        for m in sorted(set(ia) | set(ib)):
            rows.append((section, f"counters.{m}", ia.get(m), ib.get(m)))
    # sketch-prune section: per-query raw / minmax-only / sketches-on legs
    # plus their nested pruning counter deltas (bytes_skipped included)
    ska, skb = a.get("sketch_prune") or {}, b.get("sketch_prune") or {}
    for m in ("index_build_s",):
        if m in ska or m in skb:
            rows.append(("sketch_prune", m, ska.get(m), skb.get(m)))
    for sub in sorted(
        k for k in (set(ska) | set(skb))
        if isinstance(ska.get(k) or skb.get(k), dict)
    ):
        ea, eb = ska.get(sub) or {}, skb.get(sub) or {}
        for m in (
            "raw_ms", "minmax_only_ms", "sketch_ms",
            "speedup_vs_raw", "speedup_vs_minmax",
        ):
            if m in ea or m in eb:
                rows.append(("sketch_prune", f"{sub}.{m}", ea.get(m), eb.get(m)))
        pa_, pb = ea.get("pruning") or {}, eb.get("pruning") or {}
        for m in sorted(set(pa_) | set(pb)):
            rows.append(
                ("sketch_prune", f"{sub}.pruning.{m}", pa_.get(m), pb.get(m))
            )

    # adaptive re-optimization section: static vs adaptive legs on TPC-H
    # (overhead + switch counts) and the planted-misestimate join fixture
    # (flips / parks / spills are the signal)
    ada, adb = a.get("adaptive") or {}, b.get("adaptive") or {}
    for leg in ("tpch", "planted"):
        fa, fb = ada.get(leg) or {}, adb.get(leg) or {}
        for m in (
            "static_ms", "adaptive_ms", "adaptive_overhead_pct", "switches",
            "flips", "static_parks", "static_spills", "adaptive_parks",
            "adaptive_spills", "adaptive_speedup",
        ):
            if m in fa or m in fb:
                rows.append(("adaptive", f"{leg}.{m}", fa.get(m), fb.get(m)))

    # sustained-QPS serving section: closed-loop per client count + open loop
    qa_, qb_ = a.get("sustained_qps") or {}, b.get("sustained_qps") or {}
    def _phase_rows(prefix: str, ea: dict, eb: dict) -> None:
        """Per-phase mean/p99 (the attribution-ledger breakdown) under
        ``<prefix>.phase.<name>.<stat>``."""
        pa_, pb = ea.get("phases") or {}, eb.get("phases") or {}
        for ph in sorted(set(pa_) | set(pb)):
            fa, fb = pa_.get(ph) or {}, pb.get(ph) or {}
            for m in ("mean_ms", "p99_ms"):
                if m in fa or m in fb:
                    rows.append(("sustained_qps", f"{prefix}.phase.{ph}.{m}",
                                 fa.get(m), fb.get(m)))

    for tier in sorted(set(qa_.get("closed") or {}) | set(qb_.get("closed") or {})):
        ta = (qa_.get("closed") or {}).get(tier) or {}
        tb = (qb_.get("closed") or {}).get(tier) or {}
        for m in ("qps", "p50_ms", "p99_ms", "wall_s"):
            if m in ta or m in tb:
                rows.append(("sustained_qps", f"closed.{tier}.{m}",
                             ta.get(m), tb.get(m)))
        _phase_rows(f"closed.{tier}", ta, tb)
    oa, ob = qa_.get("open") or {}, qb_.get("open") or {}
    for m in ("offered_qps", "achieved_qps", "p50_ms", "p99_ms", "rejected"):
        if m in oa or m in ob:
            rows.append(("sustained_qps", f"open.{m}", oa.get(m), ob.get(m)))
    _phase_rows("open", oa, ob)
    if "qps_scaling_c4_vs_c1" in qa_ or "qps_scaling_c4_vs_c1" in qb_:
        rows.append(("sustained_qps", "qps_scaling_c4_vs_c1",
                     qa_.get("qps_scaling_c4_vs_c1"),
                     qb_.get("qps_scaling_c4_vs_c1")))
    # multi-tenant QoS section: hog-vs-light queue-wait percentiles with
    # weighted-fair scheduling off vs on, and the isolation ratio
    ma, mb = a.get("multi_tenant") or {}, b.get("multi_tenant") or {}
    for m in (
        "light_p50_off_ms", "light_p50_on_ms", "light_p99_off_ms",
        "light_p99_on_ms", "light_p99_isolation_x",
    ):
        if m in ma or m in mb:
            rows.append(("multi_tenant", m, ma.get(m), mb.get(m)))
    for leg in ("off", "on"):
        for party in ("hog", "light"):
            fa = ((ma.get(leg) or {}).get(party)) or {}
            fb = ((mb.get(leg) or {}).get(party)) or {}
            for m in ("p50_ms", "p99_ms"):
                if m in fa or m in fb:
                    rows.append(("multi_tenant", f"{leg}.{party}.{m}",
                                 fa.get(m), fb.get(m)))
    # result-cache serving section: cold vs warm repeat latency, hit ratio,
    # fold engagement, and the freshness lag under ingest with caching on
    ca, cb = a.get("cached_qps") or {}, b.get("cached_qps") or {}
    for m in (
        "cold_p50_ms", "warm_p50_ms", "repeat_speedup_p50", "hit_ratio",
        "folds", "freshness_p50_ms", "freshness_max_ms",
    ):
        if m in ca or m in cb:
            rows.append(("cached_qps", m, ca.get(m), cb.get(m)))
    for tier in ("cold", "warm"):
        ta, tb = ca.get(tier) or {}, cb.get(tier) or {}
        for m in ("qps", "p50_ms", "p99_ms", "wall_s"):
            if m in ta or m in tb:
                rows.append(("cached_qps", f"{tier}.{m}",
                             ta.get(m), tb.get(m)))
    # approximate-tier section: per-fraction sampled legs (latency, speedup
    # vs exact, realized error vs CI width) and the deadline-degrade leg
    apa, apb = a.get("approx_tier") or {}, b.get("approx_tier") or {}
    for sub in sorted(
        set(apa.get("sampled") or {}) | set(apb.get("sampled") or {})
    ):
        fa = (apa.get("sampled") or {}).get(sub) or {}
        fb = (apb.get("sampled") or {}).get(sub) or {}
        for m in ("sampled_ms", "speedup_vs_exact", "rel_err_max", "ci_rel_max"):
            if m in fa or m in fb:
                rows.append(("approx_tier", f"{sub}.{m}", fa.get(m), fb.get(m)))
    dga, dgb = apa.get("degrade") or {}, apb.get("degrade") or {}
    for m in (
        "deadline_s", "degraded_ms", "degraded_fraction", "speedup_vs_exact",
    ):
        if m in dga or m in dgb:
            rows.append(("approx_tier", f"degrade.{m}", dga.get(m), dgb.get(m)))
    for section in (
        "kernel_cache", "pipeline", "pruning", "device_cache", "staticcheck",
        "robustness", "serving", "ingest", "approx", "estimator",
    ):
        sa, sb = a.get(section, {}) or {}, b.get(section, {}) or {}
        for m in sorted(set(sa) | set(sb)):
            va, vb = sa.get(m), sb.get(m)
            if isinstance(va, dict) or isinstance(vb, dict):
                continue  # histogram summaries: not a scalar diff
            rows.append((section, m, va, vb))
    # nested lock-order audit block (staticcheck.concurrency)
    ca = (a.get("staticcheck") or {}).get("concurrency") or {}
    cb = (b.get("staticcheck") or {}).get("concurrency") or {}
    for m in sorted(set(ca) | set(cb)):
        rows.append(("staticcheck", f"concurrency.{m}", ca.get(m), cb.get(m)))
    # nested robustness blocks: breaker state machine + recovery-pass counts
    for sub in ("breaker", "recovery"):
        ra = (a.get("robustness") or {}).get(sub) or {}
        rb = (b.get("robustness") or {}).get(sub) or {}
        for m in sorted(set(ra) | set(rb)):
            rows.append(("robustness", f"{sub}.{m}", ra.get(m), rb.get(m)))
    return rows


def render(rows, threshold: float = 0.0) -> str:
    out = []
    header = f"{'section':<16} {'metric':<26} {'A':>12} {'B':>12} {'Δ%':>9}"
    out.append(header)
    out.append("-" * len(header))
    for section, metric, va, vb in rows:
        d = _delta_pct(va, vb)
        is_timing = metric.endswith(("_ms", "_s", "_gbps")) or metric in (
            "value", "vs_baseline", "speedup", "speedup_self",
            "speedup_vs_external",
        )
        if threshold and is_timing and d is not None and abs(d) < threshold:
            continue
        if threshold and not is_timing and va == vb:
            continue
        ds = "-" if d is None else ("inf" if d == float("inf") else f"{d:+.1f}")
        out.append(
            f"{section:<16} {metric:<26} {_fmt(va):>12} {_fmt(vb):>12} {ds:>9}"
        )
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("a", help="baseline BENCH_*.json")
    p.add_argument("b", help="candidate BENCH_*.json")
    p.add_argument(
        "--threshold", type=float, default=0.0,
        help="hide timing rows with |delta| below this percent",
    )
    args = p.parse_args(argv)
    a, b = _load(args.a), _load(args.b)
    # device-topology guard: timings from different mesh sizes are not
    # comparable (an 8-device mesh run vs a single-device run diffs
    # placement, not the engine). Older artifacts without the fact pass.
    da = (a.get("host") or {}).get("devices_visible")
    db = (b.get("host") or {}).get("devices_visible")
    if da is not None and db is not None and da != db:
        print(
            f"refusing to compare: device counts differ "
            f"({args.a}: {da} visible devices, {args.b}: {db}); "
            "re-run one side under the other's topology "
            "(XLA_FLAGS=--xla_force_host_platform_device_count=N)",
            file=sys.stderr,
        )
        return 2
    rows = compare(a, b)
    print(render(rows, args.threshold))
    return 0


if __name__ == "__main__":
    sys.exit(main())
