#!/usr/bin/env python
"""Multi-tenant QoS gate: weighted tenants through one scheduler must
deliver cost in proportion to their weights, reject over-quota tenants
with the typed errors, keep per-tenant attribution conserved, and stay
bit-identical to serial — all under the lock-order audit.

Three tenants at weights 1:2:3 each run a closed-loop feeder keeping a
constant backlog of the mixed TPC-H query set against ONE shared
``QueryScheduler``; because every tenant is continuously backlogged, the
weighted-fair virtual clocks equalize delivered cost per unit weight. A
quota exercise then drives the typed rejections (token bucket,
``max_in_flight``, deadline), and a conservation pass extends the PR-9
invariant to the tenant dimension.

Asserted invariants (exit 0 iff all hold):

- every served result matches the serial reference bit for bit;
- delivered-share fairness: cost_delivered / weight is equal across the
  three backlogged tenants within tolerance (max/min ratio <= FAIR_TOL,
  default 1.8 — a weight-blind FIFO scores ~3.0 on this workload);
- quota rejections are TYPED: the rate-limited and quota-capped tenants
  raise ``TenantQuotaExceeded`` (not ``AdmissionRejected``), an
  unmeetable deadline raises ``DeadlineUnmeetable``, and the
  ``serve.tenant.rejected.*`` counters record each kind;
- per-tenant attribution conservation: for every ``io.* / cache.* /
  rpc.* / pipeline.* / pruning.* / serve.budget.*`` counter, the sum over
  per-TENANT rollups equals the global counter delta across the window
  (sum over tenants == sum over queries == global);
- ``staticcheck.lock.violations`` stays 0 with the acquisition-order
  audit forced on (``SMOKE_LOCK_AUDIT=0`` opts out);
- the global budget ledger drains, every bounded cache stays consistent,
  and the scheduler reaches quiescence.

    timeout 300 env JAX_PLATFORMS=cpu python tools/qos_smoke.py

Env: SMOKE_CONCURRENT (4), SMOKE_TARGET served queries in the fairness
window (60), SMOKE_BACKLOG per-tenant in-flight depth (4), SMOKE_ROWS
(40000), FAIR_TOL (1.8).
"""

import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONSERVED_PREFIXES = (
    "io.", "cache.", "rpc.", "pipeline.", "pruning.", "serve.budget.",
)


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


def main() -> int:
    os.environ.setdefault("HYPERSPACE_DEVICE_STRICT", "1")
    os.environ.setdefault("HYPERSPACE_STREAM_CHUNK_MB", "0.5")
    os.environ.setdefault("HYPERSPACE_IO_THREADS", "4")
    # the fairness window must keep every served query in the ledger
    os.environ.setdefault("HYPERSPACE_QUERY_LOG_WINDOW", "8192")
    if os.environ.get("SMOKE_LOCK_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LOCK_AUDIT", "1")
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import tempfile

    from hyperspace_tpu import Hyperspace, HyperspaceSession, serve
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.plan import kernel_cache as kc
    from hyperspace_tpu.serve import qos
    from hyperspace_tpu.serve.tenant import TENANTS, TenantQuotaExceeded
    from hyperspace_tpu.staticcheck import concurrency as cc
    from hyperspace_tpu.telemetry.attribution import LEDGER
    from hyperspace_tpu.telemetry.metrics import REGISTRY
    from hyperspace_tpu.utils import device_cache as dc
    from hyperspace_tpu.utils.workers import spawn_thread

    concurrent = int(os.environ.get("SMOKE_CONCURRENT", 4))
    target = int(os.environ.get("SMOKE_TARGET", 60))
    backlog = int(os.environ.get("SMOKE_BACKLOG", 4))
    rows = int(os.environ.get("SMOKE_ROWS", 40_000))
    fair_tol = float(os.environ.get("FAIR_TOL", 1.8))

    ws = tempfile.mkdtemp(prefix="hs_qos_smoke_")
    generate_tpch(ws, rows_lineitem=rows, seed=29)
    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, ws)
    session.enable_hyperspace()

    names = list(TPCH_QUERIES)
    serial = {
        name: _bits(TPCH_QUERIES[name](session, ws).to_pydict())
        for name in names
    }

    weights = {"bronze": 1.0, "silver": 2.0, "gold": 3.0}
    for name, w in weights.items():
        TENANTS.configure(name, weight=w)

    # --- conservation baseline (after warmup, before any served query) ----
    def _conserved_counters() -> dict:
        return {
            name: value
            for name, kind, value in REGISTRY.export()
            if kind == "counter" and name.startswith(CONSERVED_PREFIXES)
        }

    def _tenant_ledger_sums() -> dict:
        out: dict = {}
        for counters in LEDGER.aggregate_counters_by_tenant().values():
            for k, v in counters.items():
                if k.startswith(CONSERVED_PREFIXES):
                    out[k] = out.get(k, 0) + v
        return out

    g0 = _conserved_counters()
    t0 = _tenant_ledger_sums()

    sched = serve.QueryScheduler(
        max_concurrent=concurrent, queue_depth=max(64, 4 * backlog * 3)
    )
    mismatches: list = []
    errors: list = []
    served = {"n": 0}
    served_lock = threading.Lock()
    stop = threading.Event()

    def feeder(tenant: str, tid: int) -> None:
        """Closed loop with a constant in-flight backlog: the tenant stays
        continuously backlogged, which is the regime weighted-fair shares
        are defined over."""
        try:
            inflight: list = []
            i = 0
            while not stop.is_set():
                while len(inflight) < backlog and not stop.is_set():
                    name = names[(tid + i) % len(names)]
                    i += 1
                    inflight.append((name, sched.submit(
                        (lambda n=name: TPCH_QUERIES[n](session, ws)
                         .collect()),
                        label=name, tenant=tenant,
                    )))
                if not inflight:
                    break
                name, h = inflight.pop(0)
                got = _bits(h.result(timeout=300).to_pydict())
                if got != serial[name]:
                    mismatches.append((tenant, name))
                with served_lock:
                    served["n"] += 1
                    if served["n"] >= target:
                        stop.set()
            for name, h in inflight:  # drain the tail
                got = _bits(h.result(timeout=300).to_pydict())
                if got != serial[name]:
                    mismatches.append((tenant, name))
        except Exception as e:  # noqa: BLE001 - reported via the gate
            errors.append((tenant, repr(e)))

    threads = [
        spawn_thread(feeder, name=f"hs-qos-{t}", daemon=False, args=(t, i))
        for i, t in enumerate(weights)
    ]
    for t in threads:
        t.join()
    sched.drain(timeout=120)

    # --- fairness: delivered cost per unit weight equal across tenants ----
    tenants_state = sched.state()["tenants"]
    per_weight = {
        name: tenants_state[name]["cost_s"] / weights[name]
        for name in weights
        if name in tenants_state
    }
    fairness_ratio = (
        max(per_weight.values()) / max(1e-9, min(per_weight.values()))
        if len(per_weight) == len(weights) else float("inf")
    )
    fairness_ok = fairness_ratio <= fair_tol

    # --- typed quota / rate / deadline rejections -------------------------
    rejections = {"quota": False, "rate": False, "deadline": False,
                  "quota_not_admission": False}
    try:
        TENANTS.configure("capped", max_in_flight=1)
        gate = threading.Event()
        running = sched.submit(lambda: gate.wait(30), tenant="capped",
                               label="capped-runner")
        try:
            sched.submit(lambda: 1, tenant="capped", label="capped-over")
        except TenantQuotaExceeded as e:
            rejections["quota"] = True
            rejections["quota_not_admission"] = not isinstance(
                e, serve.AdmissionRejected
            )
        gate.set()
        running.result(30)

        TENANTS.configure("ratey", rate_qps=0.001, burst=1)
        sched.submit(lambda: 1, tenant="ratey", label="ratey-1").result(30)
        try:
            sched.submit(lambda: 2, tenant="ratey", label="ratey-2")
        except TenantQuotaExceeded:
            rejections["rate"] = True

        qos.COST_MODEL.update("deadline-probe", 0.5)
        try:
            sched.submit(lambda: 3, label="deadline-probe",
                         deadline_s=0.001)
        except serve.DeadlineUnmeetable:
            rejections["deadline"] = True
    except Exception as e:  # noqa: BLE001 - reported via the gate
        errors.append(("rejection-exercise", repr(e)))
    sched.drain(timeout=60)

    # --- per-tenant conservation: sum over tenant rollups == global deltas
    import time as _time

    def _conservation_mismatches() -> dict:
        g1 = _conserved_counters()
        deltas = {k: g1.get(k, 0) - g0.get(k, 0) for k in set(g0) | set(g1)}
        tsum = {
            k: v - t0.get(k, 0) for k, v in _tenant_ledger_sums().items()
        }
        return {
            k: {"global_delta": deltas.get(k, 0), "tenant_sum": tsum.get(k, 0)}
            for k in set(deltas) | set(tsum)
            if deltas.get(k, 0) != tsum.get(k, 0)
        }

    conservation = _conservation_mismatches()
    for _ in range(40):
        if not conservation:
            break
        _time.sleep(0.25)  # hslint: HS401 — gate tool, straggler-charge settle
        conservation = _conservation_mismatches()

    state = sched.state()
    budget = serve.global_budget()
    quiescent = not state["active"] and not state["queued"]
    budget_drained = budget.held_bytes() == 0 and budget.check_consistency()
    sched.shutdown(wait=True)
    TENANTS.reset_for_testing()

    consistency = {
        "io.index_chunk": cio._INDEX_CHUNK_CACHE.check_consistency(),
        "io.source_col": cio._SOURCE_COL_CACHE.check_consistency(),
        "io.rowgroup_stats": cio._ROWGROUP_STATS_CACHE.check_consistency(),
        "device": dc.DEVICE_CACHE.check_consistency(),
        "host_derived": dc.HOST_DERIVED_CACHE.check_consistency(),
        "kernel": kc.KERNEL_CACHE.check_consistency(),
        "kernel_join": kc.JOIN_CACHE.check_consistency(),
        "kernel_topk": kc.TOPK_CACHE.check_consistency(),
        "kernel_sort": kc.SORT_CACHE.check_consistency(),
    }
    lock_report = cc.report()

    def val(n: str) -> int:
        m = REGISTRY.get(n)
        return 0 if m is None else int(m.value)

    violations = val("staticcheck.lock.violations")
    ok = (
        not mismatches
        and not errors
        and fairness_ok
        and all(rejections.values())
        and val("serve.tenant.rejected.quota") >= 1
        and val("serve.tenant.rejected.rate") >= 1
        and val("serve.tenant.rejected.deadline") >= 1
        and violations == 0
        and all(consistency.values())
        and budget_drained
        and quiescent
        and not conservation
        and served["n"] >= target
        and val("serve.budget.reservations") > 0
    )
    out = {
        "rows": rows,
        "tenants": {n: {"weight": weights[n],
                        **{k: tenants_state.get(n, {}).get(k)
                           for k in ("done", "cost_s", "delivered_share",
                                     "vclock")}}
                    for n in weights},
        "served": served["n"],
        "bit_identical": not mismatches and not errors,
        "mismatches": mismatches[:10],
        "errors": errors[:10],
        "cost_per_weight": {k: round(v, 4) for k, v in per_weight.items()},
        "fairness_ratio": round(fairness_ratio, 3),
        "fairness_tolerance": fair_tol,
        "fairness_ok": fairness_ok,
        "typed_rejections": rejections,
        "tenant_rejection_counters": {
            k: val(f"serve.tenant.rejected.{k}")
            for k in ("rate", "quota", "deadline")
        },
        "attribution_conserved_per_tenant": not conservation,
        "conservation_mismatches": dict(list(conservation.items())[:10]),
        "scheduler_quiescent": quiescent,
        "budget_drained": budget_drained,
        "lock_audit": lock_report["audit_enabled"],
        "lock_acquisitions": val("staticcheck.lock.acquisitions"),
        "lock_violations": violations,
        "cache_consistency": consistency,
        "ok": ok,
    }
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
