#!/usr/bin/env python
"""Race-stress gate: N threads of mixed TPC-H queries against the shared
caches/pools must be bit-identical to serial execution, with zero
lock-order violations and consistent cache byte accounting.

The serial pass runs every query once (warming the kernel / chunk / stats /
device caches); then ``STRESS_THREADS`` threads (default 8) each run the
whole mixed query set ``STRESS_REPEATS`` times (default 2) in a
thread-rotated order, so every shared structure sees concurrent hits,
misses, and evictions. Asserted invariants:

- every threaded result matches the serial reference at ``float.hex()``
  bit precision (no torn cache entries, no cross-query state bleed);
- ``staticcheck.lock.violations`` stays 0 with the acquisition-order audit
  forced on (``HYPERSPACE_LOCK_AUDIT=1``; ``STRESS_LOCK_AUDIT=0`` opts out);
- every bounded cache's byte accounting is internally consistent at
  quiescence (occupancy == sum of resident entries, within budget, no
  leaked single-flight markers).

Prints one JSON line (including the lock-order report: registered locks,
observed edges, acquisition counts); exit 0 iff all three gates hold.

    timeout 300 env JAX_PLATFORMS=cpu python tools/race_stress.py

Env: STRESS_THREADS (8), STRESS_REPEATS (2), SMOKE_ROWS (60000).
"""

import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


def main() -> int:
    os.environ.setdefault("HYPERSPACE_DEVICE_STRICT", "1")
    # small chunks so the streaming executor engages even at smoke row counts
    os.environ.setdefault("HYPERSPACE_STREAM_CHUNK_MB", "0.5")
    if os.environ.get("STRESS_LOCK_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LOCK_AUDIT", "1")
    if os.environ.get("STRESS_LIFECYCLE_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LIFECYCLE_AUDIT", "1")
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import tempfile

    from hyperspace_tpu import Hyperspace, HyperspaceSession
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.plan import kernel_cache as kc
    from hyperspace_tpu.staticcheck import concurrency as cc
    from hyperspace_tpu.staticcheck import lifecycle as lc
    from hyperspace_tpu.telemetry.metrics import REGISTRY
    from hyperspace_tpu.utils import device_cache as dc

    n_threads = int(os.environ.get("STRESS_THREADS", 8))
    repeats = int(os.environ.get("STRESS_REPEATS", 2))
    rows = int(os.environ.get("SMOKE_ROWS", 60_000))

    ws = tempfile.mkdtemp(prefix="hs_race_stress_")
    generate_tpch(ws, rows_lineitem=rows, seed=11)
    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, ws)
    session.enable_hyperspace()

    names = list(TPCH_QUERIES)

    # serial reference (also warms every shared cache)
    serial = {name: _bits(TPCH_QUERIES[name](session, ws).to_pydict()) for name in names}

    mismatches: list = []
    errors: list = []
    barrier = threading.Barrier(n_threads)

    def worker(tid: int) -> None:
        try:
            barrier.wait()  # maximal overlap: all threads start together
            for r in range(repeats):
                # rotate per thread so different queries collide on the
                # shared caches in every wave
                order = names[(tid + r) % len(names):] + names[: (tid + r) % len(names)]
                for name in order:
                    got = _bits(TPCH_QUERIES[name](session, ws).to_pydict())
                    if got != serial[name]:
                        mismatches.append((tid, name))
        except Exception as e:  # noqa: BLE001 - reported via the gate
            errors.append((tid, repr(e)))

    # stress threads are the experiment itself, not engine internals — the
    # workers chokepoint is still the constructor
    from hyperspace_tpu.utils.workers import spawn_thread

    threads = [
        spawn_thread(worker, name=f"hs-stress-{i}", daemon=False, args=(i,))
        for i in range(n_threads)
    ]
    for t in threads:
        t.join()

    consistency = {
        "io.index_chunk": cio._INDEX_CHUNK_CACHE.check_consistency(),
        "io.source_col": cio._SOURCE_COL_CACHE.check_consistency(),
        "io.rowgroup_stats": cio._ROWGROUP_STATS_CACHE.check_consistency(),
        "device": dc.DEVICE_CACHE.check_consistency(),
        "host_derived": dc.HOST_DERIVED_CACHE.check_consistency(),
        "kernel": kc.KERNEL_CACHE.check_consistency(),
        "kernel_join": kc.JOIN_CACHE.check_consistency(),
        "kernel_topk": kc.TOPK_CACHE.check_consistency(),
        "kernel_sort": kc.SORT_CACHE.check_consistency(),
    }

    lock_report = cc.report()
    # quiescence: every handle the whole stress run acquired (pins, budget
    # streams, ledger waves, scopes, in-flight markers) must be released
    leaks = [h.describe() for h in lc.check_quiescent(raise_on_leak=False)]
    lifecycle = lc.report()

    def val(n: str) -> int:
        m = REGISTRY.get(n)
        return 0 if m is None else int(m.value)

    violations = val("staticcheck.lock.violations")
    ok = (
        not mismatches
        and not errors
        and violations == 0
        and all(consistency.values())
        and not leaks
    )
    out = {
        "rows": rows,
        "threads": n_threads,
        "repeats": repeats,
        "queries": names,
        "runs": n_threads * repeats * len(names),
        "bit_identical": not mismatches and not errors,
        "mismatches": mismatches[:10],
        "errors": errors[:10],
        "lock_audit": lock_report["audit_enabled"],
        "lock_acquisitions": val("staticcheck.lock.acquisitions"),
        "lock_edges": lock_report["edges"],
        "lock_violations": violations,
        "registered_locks": lock_report["locks"],
        "cache_consistency": consistency,
        "lifecycle_audit": lifecycle["audit_enabled"],
        "lifecycle_acquires": lifecycle["acquires"],
        "lifecycle_releases": lifecycle["releases"],
        "lifecycle_leaks": leaks[:10],
        "ok": ok,
    }
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
