#!/usr/bin/env python
"""Plan-statistics smoke: EXPLAIN ANALYZE / estimator-accuracy gate.

Four legs, one JSON line, exit 0 iff every check passes:

1. **Analyze bit-identity**: every TPC-H bench query plus pruned
   point/range/IN queries over a bucketed covering index runs once plain
   and once with the plan-statistics collector installed
   (``plan_stats.collect_scope`` — the ``hs.explain_analyze`` driver); the
   two results must be bitwise identical (floats at .hex() precision).
   The collector is observe-only by construction; this gate pins it.
2. **Feedback-off / feedback-on identity**: with
   ``HYPERSPACE_ESTIMATOR_FEEDBACK=1`` the ranker may re-rank candidates,
   but every rewrite is correctness-preserving, so results must STAY
   bitwise identical to the plain run.
3. **Annotated output**: ``hs.explain_analyze`` on the pruned point query
   must show per-node actual rows/bytes and a scan-fraction q-error.
4. **Concurrent conservation**: 4 concurrent served queries through one
   scheduler; the q-error observations (``estimator.qerror.*`` histogram
   counts) summed over the 4 per-query ledger records must equal the
   global histogram deltas (attribution conservation extended to the
   estimator plane), with observations > 0 and 0 lock violations
   (HYPERSPACE_LOCK_AUDIT=1 forced).

    timeout 300 env JAX_PLATFORMS=cpu python tools/plan_stats_smoke.py

Env: SMOKE_ROWS (lineitem rows, default 120000).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


def main() -> int:
    os.environ.setdefault("HYPERSPACE_DEVICE_STRICT", "1")
    os.environ.setdefault("HYPERSPACE_STREAM_CHUNK_MB", "0.5")
    os.environ["HYPERSPACE_LOCK_AUDIT"] = "1"
    os.environ["HYPERSPACE_IO_THREADS"] = "4"
    os.environ.pop("HYPERSPACE_ESTIMATOR_FEEDBACK", None)
    os.environ.pop("HYPERSPACE_PLAN_STATS", None)
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import tempfile

    import numpy as np

    from hyperspace_tpu import (
        CoveringIndexConfig,
        Hyperspace,
        HyperspaceSession,
    )
    from hyperspace_tpu import constants as C
    from hyperspace_tpu import serve
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.plan import col
    from hyperspace_tpu.telemetry import plan_stats
    from hyperspace_tpu.telemetry.attribution import LEDGER
    from hyperspace_tpu.telemetry.metrics import REGISTRY

    rows = int(os.environ.get("SMOKE_ROWS", 120_000))
    ws = tempfile.mkdtemp(prefix="hs_plan_stats_smoke_")
    generate_tpch(ws, rows_lineitem=rows, seed=7)

    rng = np.random.default_rng(3)
    n_ev = max(rows, 80_000)
    n_files = 8
    per = n_ev // n_files
    for i in range(n_files):
        data = {
            "ev_k": (np.arange(per, dtype=np.int64) + i * per).tolist(),
            "ev_q": rng.integers(1, 50, per).tolist(),
            "ev_v": rng.uniform(0, 100, per).tolist(),
        }
        cio.write_parquet(
            ColumnBatch.from_pydict(data),
            os.path.join(ws, "events", f"part-{i:02d}.parquet"),
        )

    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, ws)
    hs.create_index(
        session.read.parquet(os.path.join(ws, "events")),
        CoveringIndexConfig("ev_k_idx", ["ev_k"], ["ev_q", "ev_v"]),
    )
    session.enable_hyperspace()

    ev = lambda: session.read.parquet(os.path.join(ws, "events"))
    k_point = int(n_ev * 5 // 8 + 17)
    lo, hi = int(n_ev // 8 + 100), int(n_ev // 8 + 2100)
    sections = {
        "point": lambda: ev()
        .filter(col("ev_k") == k_point)
        .select("ev_k", "ev_q", "ev_v")
        .to_pydict(),
        "range": lambda: ev()
        .filter((col("ev_k") >= lo) & (col("ev_k") < hi))
        .select("ev_k", "ev_v")
        .to_pydict(),
        "in": lambda: ev()
        .filter(col("ev_k").isin([3, k_point, int(n_ev - 5)]))
        .select("ev_k", "ev_q")
        .to_pydict(),
    }
    for name, q in TPCH_QUERIES.items():
        sections[name] = (lambda n=name: TPCH_QUERIES[n](session, ws).to_pydict())

    # --- leg 1+2: plain vs analyze vs feedback-on, all bitwise ------------
    mismatches = []
    plain_bits = {}
    for name, q in sections.items():
        plain_bits[name] = _bits(q())
        with plan_stats.collect_scope() as colr:
            analyzed = _bits(q())
        if analyzed != plain_bits[name]:
            mismatches.append(("analyze", name))
        if not colr.nodes:
            mismatches.append(("no-node-stats", name))
    os.environ["HYPERSPACE_ESTIMATOR_FEEDBACK"] = "1"
    for name, q in sections.items():
        if _bits(q()) != plain_bits[name]:
            mismatches.append(("feedback", name))
    del os.environ["HYPERSPACE_ESTIMATOR_FEEDBACK"]

    # --- leg 3: the annotated EXPLAIN ANALYZE surface ---------------------
    report = hs.explain_analyze(
        ev().filter(col("ev_k") == k_point).select("ev_k", "ev_q", "ev_v")
    )
    annotated_ok = (
        "rows=" in report
        and "bytes=" in report
        and "scan_fraction" in report
        and "q=" in report
    )

    # --- leg 4: 4 concurrent served queries, q-error ledger conserved -----
    def _qerror_globals() -> dict:
        return {
            name: value["count"]
            for name, kind, value in REGISTRY.export()
            if kind == "histogram" and name.startswith("estimator.qerror.")
        }

    g0 = _qerror_globals()
    seq0 = LEDGER.last_seq()
    sched = serve.QueryScheduler(max_concurrent=4, queue_depth=16)
    try:
        handles = [
            sched.submit(
                (lambda k=k_point + i: ev()
                 .filter(col("ev_k") == k)
                 .select("ev_k", "ev_q")
                 .collect()),
                label=f"est:{i}",
            )
            for i in range(4)
        ]
        for h in handles:
            h.result(timeout=300)
    finally:
        sched.shutdown(wait=True)
    g1 = _qerror_globals()
    global_delta = {
        k: g1.get(k, 0) - g0.get(k, 0) for k in set(g0) | set(g1)
    }
    served = [
        r for r in LEDGER.recent_records(since_seq=seq0)
        if r["label"].startswith("est:")
    ]
    ledger_sum: dict = {}
    for r in served:
        for name, h in r["histograms"].items():
            if name.startswith("estimator.qerror."):
                ledger_sum[name] = ledger_sum.get(name, 0) + h["count"]
    conserved = (
        len(served) == 4
        and sum(global_delta.values()) > 0
        and all(
            global_delta.get(k, 0) == ledger_sum.get(k, 0)
            for k in set(global_delta) | set(ledger_sum)
        )
    )

    def val(n: str) -> int:
        m = REGISTRY.get(n)
        return 0 if m is None else int(m.value)

    violations = val("staticcheck.lock.violations")
    observations = val("estimator.observations")
    ok = (
        not mismatches
        and annotated_ok
        and conserved
        and observations > 0
        and violations == 0
    )
    out = {
        "rows": rows,
        "sections": len(sections),
        "bit_identical": not mismatches,
        "mismatches": mismatches[:10],
        "annotated_ok": annotated_ok,
        "estimator_observations": observations,
        "qerror_conserved": conserved,
        "qerror_global_delta": global_delta,
        "qerror_ledger_sum": ledger_sum,
        "served_records": len(served),
        "accuracy": plan_stats.ACCURACY.snapshot()["qerror"],
        "lock_violations": violations,
        "ok": ok,
    }
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
