#!/usr/bin/env python
"""Chaos-stress gate: deterministic fault injection across queries and
maintenance actions must never produce a wrong answer or an unrecoverable
warehouse.

Two sweeps, one contract ("bit-identical or typed error, never wrong
answers" — docs/robustness.md):

1. **Query sweep** — each armed ``HYPERSPACE_FAULTS`` spec (transient IO
   errors, OOMs, device/tunnel failures, compile failures; nth-hit and
   seeded-probabilistic triggers) runs the full TPC-H query set against a
   warmed indexed warehouse. Every single run must either match the clean
   reference at ``float.hex()`` bit precision (retries / the device
   breaker / host fallback absorbed the fault) or raise a typed
   ``HyperspaceError`` — a bare builtin or a silently wrong result fails
   the gate.

2. **Crash matrix** — maintenance actions (create / refresh / optimize /
   delete) run with ``InjectedCrash`` armed before and after every
   ``log.write`` and ``data.publish`` they perform, in a fresh warehouse
   per cell. After each simulated death, ``recover(force=True)`` must
   return the index to a stable state with no orphans: stable (or empty)
   log tail, no ``_staging`` dirs, no ``.tmp-*`` spool files, no data
   version unreferenced by the log. The action then re-runs and the final
   query must match a never-crashed twin warehouse bit-for-bit.

After both sweeps every bounded cache must pass ``check_consistency()``.
Prints one JSON line (per-spec outcomes, per-point injection counts,
retry/breaker/recovery counters); exit 0 iff all gates hold.

    timeout 600 env JAX_PLATFORMS=cpu python tools/chaos_stress.py

Env: SMOKE_ROWS (30000), CHAOS_CELL_ROWS (4000).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


# fault specs swept over the query set: transient kinds only (crash kinds
# simulate process death and belong to the crash matrix)
QUERY_SPECS = [
    "io.read_file:ioerror:n=1",
    "io.read_file:ioerror:n=3",
    "io.read_file:ioerror:p=0.02,seed=7",
    "io.read_file:oom:n=2",
    "io.footer:ioerror:n=1",
    "device.upload:ioerror:n=1",
    "device.dispatch:ioerror:n=1",
    "device.dispatch:oom:n=1",
    "device.fetch:ioerror:n=1",
    "device.*:ioerror:p=0.05,seed=3",
    "kernel.compile:ioerror:n=1",
]

# (action, fault specs): every log.write / data.publish the action performs,
# killed immediately before and immediately after the atomic step
_LOG_CRASHES = [
    "log.write:crash_before:n=1",   # begin() transient entry never lands
    "log.write:crash_after:n=1",    # transient entry lands, op never runs
    "log.write:crash_before:n=2",   # end() final entry never lands
    "log.write:crash_after:n=2",    # final entry lands, pointer rewrite lost
]
_PUBLISH_CRASHES = [
    "data.publish:crash_before:n=1",  # staged build never promoted
    "data.publish:crash_after:n=1",   # version live, final log.write lost
]
_APPEND_CRASHES = [
    "ingest.append:crash_before:n=1",  # staging created, delta never built
    "ingest.append:crash_after:n=1",   # delta published, final log.write lost
]
_COMPACT_CRASHES = [
    "ingest.compact:crash_before:n=1",  # staging created, merge never ran
    "ingest.compact:crash_after:n=1",   # compacted version live, log lost
]
CRASH_MATRIX = [
    ("create", _LOG_CRASHES + _PUBLISH_CRASHES),
    ("refresh", _LOG_CRASHES + _PUBLISH_CRASHES),
    ("optimize", _LOG_CRASHES + _PUBLISH_CRASHES),
    ("delete", _LOG_CRASHES),  # delete moves no data, only log entries
    ("append", _LOG_CRASHES + _PUBLISH_CRASHES + _APPEND_CRASHES),
    ("compact", _LOG_CRASHES + _PUBLISH_CRASHES + _COMPACT_CRASHES),
]


def main() -> int:
    # NOT strict: the breaker's degrade-to-host path is part of what this
    # gate verifies. Small chunks so the streamed executor engages.
    os.environ.setdefault("HYPERSPACE_STREAM_CHUNK_MB", "0.5")
    if os.environ.get("STRESS_LIFECYCLE_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LIFECYCLE_AUDIT", "1")
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    import tempfile

    from hyperspace_tpu import Hyperspace, HyperspaceSession
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.exceptions import HyperspaceError
    from hyperspace_tpu.meta.data_manager import IndexDataManager
    from hyperspace_tpu.meta.log_manager import IndexLogManager, STABLE_STATES
    from hyperspace_tpu.plan import kernel_cache as kc
    from hyperspace_tpu.staticcheck import lifecycle as lc
    from hyperspace_tpu.telemetry.metrics import REGISTRY
    from hyperspace_tpu.utils import backend, device_cache as dc, faults

    rows = int(os.environ.get("SMOKE_ROWS", 30_000))
    cell_rows = int(os.environ.get("CHAOS_CELL_ROWS", 4_000))

    def val(n: str) -> int:
        m = REGISTRY.get(n)
        return 0 if m is None else int(m.value)

    failures: list = []

    # ---- sweep 1: queries under transient faults -------------------------
    ws = tempfile.mkdtemp(prefix="hs_chaos_q_")
    generate_tpch(ws, rows_lineitem=rows, seed=11)
    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, ws)
    session.enable_hyperspace()
    names = list(TPCH_QUERIES)
    clean = {n: _bits(TPCH_QUERIES[n](session, ws).to_pydict()) for n in names}
    # second reference with the device tier off: a degraded run must equal
    # EITHER the full device answer or the full host recompute — the same
    # bits the engine produces with the tier disabled. Anything else is a
    # torn/partial result and fails the gate.
    session.set_conf(C.EXEC_TPU_ENABLED, False)
    clean_host = {n: _bits(TPCH_QUERIES[n](session, ws).to_pydict()) for n in names}
    session.set_conf(C.EXEC_TPU_ENABLED, True)

    def clear_engine_caches() -> None:
        """Warm caches absorb most injection points (a cached chunk never
        re-reads, a cached kernel never re-compiles); each spec starts cold
        so its point actually gets hit."""
        cio._INDEX_CHUNK_CACHE.clear()
        cio._SOURCE_COL_CACHE.clear()
        cio._ROWGROUP_STATS_CACHE.clear()
        dc.DEVICE_CACHE.clear()
        dc.HOST_DERIVED_CACHE.clear()
        for cache in (kc.KERNEL_CACHE, kc.JOIN_CACHE, kc.TOPK_CACHE, kc.SORT_CACHE):
            cache.clear()

    query_sweep = []
    point_fired: dict = {p: 0 for p in faults.POINTS}
    for spec in QUERY_SPECS:
        clear_engine_caches()
        rules = faults.arm(spec)
        outcomes = {"identical": 0, "degraded_identical": 0, "typed_error": 0}
        try:
            for n in names:
                try:
                    got = _bits(TPCH_QUERIES[n](session, ws).to_pydict())
                except faults.InjectedCrash:
                    raise  # crash kinds never belong in this sweep
                except HyperspaceError:
                    outcomes["typed_error"] += 1
                    continue
                except MemoryError as e:
                    # an unabsorbed OOM injection is typed (InjectedOOMError
                    # is a HyperspaceError); a bare MemoryError is a bug
                    if isinstance(e, HyperspaceError):
                        outcomes["typed_error"] += 1
                        continue
                    failures.append(f"query {n} under {spec!r}: bare {e!r}")
                    continue
                except Exception as e:
                    failures.append(f"query {n} under {spec!r}: untyped {e!r}")
                    continue
                if got == clean[n]:
                    outcomes["identical"] += 1
                elif got == clean_host[n]:
                    outcomes["degraded_identical"] += 1
                else:
                    failures.append(f"query {n} under {spec!r}: WRONG RESULT")
        finally:
            snap = faults.snapshot()
            faults.disarm()
        fired = sum(r["fired"] for r in snap)
        for r in snap:
            base = r["point"][:-2] if r["point"].endswith(".*") else r["point"]
            for p in point_fired:
                if p == r["point"] or (r["point"].endswith(".*") and p.startswith(base)):
                    point_fired[p] += r["fired"]
        query_sweep.append({"spec": spec, "fired": fired, **outcomes})
        # a transient device fault legitimately opens the breaker; runs are
        # independent experiments, so re-arm the device tier between specs
        backend._reset_for_testing()

    # ---- sweep 2: crash matrix over maintenance actions ------------------
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu.models.covering import CoveringIndexConfig

    def write_part(src: str, part: int, n: int) -> None:
        rng = np.random.default_rng(100 + part)
        t = pa.table(
            {
                "k": rng.integers(0, 50, n),
                "v": rng.random(n),
                "w": rng.integers(0, 1000, n),
            }
        )
        pq.write_table(t, os.path.join(src, f"part{part}.parquet"))

    def fresh_session(root: str):
        s = HyperspaceSession(warehouse_dir=root)
        s.set_conf(C.INDEX_NUM_BUCKETS, 4)
        return s, Hyperspace(s)

    def run_action(h, s, root: str, action: str, phase: str) -> None:
        """phase 'setup' brings the warehouse to the action's precondition;
        phase 'act' performs the action under test."""
        src = os.path.join(root, "src")
        if phase == "setup":
            os.makedirs(src)
            write_part(src, 0, cell_rows)
            write_part(src, 1, cell_rows)
            if action != "create":
                h.create_index(
                    s.read.parquet(src), CoveringIndexConfig("cidx", ["k"], ["v", "w"])
                )
            if action == "optimize":
                # an incremental refresh adds a second small file per bucket
                # so quick-optimize has compaction work
                write_part(src, 2, cell_rows)
                h.refresh_index("cidx", C.REFRESH_MODE_INCREMENTAL)
            if action == "compact":
                # an ingest append gives every bucket a second (delta) run
                write_part(src, 2, cell_rows)
                h.append("cidx", s.read.parquet(os.path.join(src, "part2.parquet")))
            return
        if action == "create":
            h.create_index(
                s.read.parquet(src), CoveringIndexConfig("cidx", ["k"], ["v", "w"])
            )
        elif action == "refresh":
            write_part(src, 2, cell_rows)
            h.refresh_index("cidx", C.REFRESH_MODE_FULL)
        elif action == "optimize":
            h.optimize_index("cidx")
        elif action == "append":
            # the source part is written ONCE (act may re-run to converge
            # after a crash: an already-appended file must look unchanged
            # so the retry no-ops instead of double-indexing its rows)
            p2 = os.path.join(src, "part2.parquet")
            if not os.path.exists(p2):
                write_part(src, 2, cell_rows)
            h.append("cidx", s.read.parquet(p2))
        elif action == "compact":
            h.compact_index("cidx", min_runs=2)
        elif action == "delete":
            h.delete_index("cidx")

    def query_bits(s, root: str) -> str:
        df = s.read.parquet(os.path.join(root, "src"))
        out = (
            df.filter(df["k"] == 7).select("v", "w").collect().to_pydict()
        )
        return _bits(out)

    def index_debris(root: str) -> list:
        """Orphan report for every index under the warehouse's system dir."""
        bad = []
        sys_dir = os.path.join(root, C.INDEXES_DIR)
        if not os.path.isdir(sys_dir):
            return bad
        for name in os.listdir(sys_dir):
            ip = os.path.join(sys_dir, name)
            if not os.path.isdir(ip):
                continue
            lm = IndexLogManager(ip)
            dm = IndexDataManager(ip)
            latest = lm.get_latest_log()
            if latest is not None and latest.state not in STABLE_STATES:
                bad.append(f"{name}: unstable log tail {latest.state}")
            if dm.staged_versions():
                bad.append(f"{name}: staging dirs {dm.staged_versions()}")
            if lm.stale_temp_files():
                bad.append(f"{name}: stale .tmp files")
            from hyperspace_tpu.index_manager import IndexCollectionManager

            refs = IndexCollectionManager._referenced_versions(lm)
            if latest is not None and latest.state == "DOESNOTEXIST":
                refs = set()
            orphans = [v for v in dm.get_all_versions() if v not in refs]
            if orphans:
                bad.append(f"{name}: orphan data versions {orphans}")
        return bad

    crash_matrix = []
    twin_bits: dict = {}
    for action, specs in CRASH_MATRIX:
        # never-crashed twin (one per action; cells reuse its reference bits)
        twin = tempfile.mkdtemp(prefix=f"hs_chaos_twin_{action}_")
        ts, th = fresh_session(twin)
        run_action(th, ts, twin, action, "setup")
        run_action(th, ts, twin, action, "act")
        ts.enable_hyperspace()
        twin_bits[action] = query_bits(ts, twin)

        for spec in specs:
            cell = tempfile.mkdtemp(prefix=f"hs_chaos_{action}_")
            s, h = fresh_session(cell)
            run_action(h, s, cell, action, "setup")
            crashed = False
            faults.arm(spec)
            try:
                run_action(h, s, cell, action, "act")
            except faults.InjectedCrash:
                crashed = True
            finally:
                snap = faults.snapshot()
                faults.disarm()
            fired = sum(r["fired"] for r in snap)
            # a fresh manager (the "restarted process") repairs the debris
            s2, h2 = fresh_session(cell)
            h2.recover(force=True)
            debris = index_debris(cell)
            if debris:
                failures.append(f"{action} under {spec!r}: {debris}")
            # converge to the twin's logical end state, then compare
            try:
                run_action(h2, s2, cell, action, "act")
            except HyperspaceError:
                # already completed before the crash (e.g. final entry
                # landed); the state assertions below still apply
                pass  # hslint: HS402 — convergence retry; debris check is the gate
            s2.enable_hyperspace()
            got = query_bits(s2, cell)
            identical = got == twin_bits[action]
            if not identical:
                failures.append(f"{action} under {spec!r}: post-recovery result diverges")
            crash_matrix.append(
                {
                    "action": action,
                    "spec": spec,
                    "fired": fired,
                    "crashed": crashed,
                    "recovered_clean": not debris,
                    "identical": identical,
                }
            )

    # ---- global invariants ----------------------------------------------
    consistency = {
        "io.index_chunk": cio._INDEX_CHUNK_CACHE.check_consistency(),
        "io.source_col": cio._SOURCE_COL_CACHE.check_consistency(),
        "io.rowgroup_stats": cio._ROWGROUP_STATS_CACHE.check_consistency(),
        "device": dc.DEVICE_CACHE.check_consistency(),
        "host_derived": dc.HOST_DERIVED_CACHE.check_consistency(),
        "kernel": kc.KERNEL_CACHE.check_consistency(),
        "kernel_join": kc.JOIN_CACHE.check_consistency(),
        "kernel_topk": kc.TOPK_CACHE.check_consistency(),
        "kernel_sort": kc.SORT_CACHE.check_consistency(),
    }

    # quiescence: every injected fault unwound through cleanup; any handle
    # still live (pin, budget stream, ledger wave, scope, in-flight marker)
    # is a leak the crash/fault paths failed to release
    leaks = [h.describe() for h in lc.check_quiescent(raise_on_leak=False)]
    lifecycle = lc.report()

    injected = val("faults.injected")
    crashes_fired = sum(c["fired"] for c in crash_matrix)
    ok = (
        not failures
        and all(consistency.values())
        and injected > 0
        and crashes_fired > 0
        and all(c["crashed"] or c["fired"] == 0 for c in crash_matrix)
        and not leaks
    )
    out = {
        "rows": rows,
        "cell_rows": cell_rows,
        "query_specs": len(QUERY_SPECS),
        "query_runs": len(QUERY_SPECS) * len(names),
        "query_sweep": query_sweep,
        "crash_cells": len(crash_matrix),
        "crash_matrix": crash_matrix,
        "point_fired": point_fired,
        "injected_total": injected,
        "io_retry_attempts": val("io.retry.attempts"),
        "io_retry_gave_up": val("io.retry.gave_up"),
        "breaker": backend.breaker_snapshot(),
        "recovery_rolled_back": val("recovery.rolled_back"),
        "recovery_orphan_versions": val("recovery.orphan_versions"),
        "recovery_staging_removed": val("recovery.staging_removed"),
        "recovery_pointer_fixed": val("recovery.pointer_fixed"),
        "cache_consistency": consistency,
        "lifecycle_audit": lifecycle["audit_enabled"],
        "lifecycle_acquires": lifecycle["acquires"],
        "lifecycle_releases": lifecycle["releases"],
        "lifecycle_leaks": leaks[:10],
        "failures": failures[:20],
        "ok": ok,
    }
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
