"""Index lifecycle E2E tests (ref: IndexManagerTest, per-action suites,
CancelActionTest state-machine paths)."""

import os

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.actions import states as S
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.meta.log_manager import IndexLogManager


@pytest.fixture()
def env(tmp_session, tmp_path):
    data = {
        "k": list(range(100)),
        "v": [i * 1.5 for i in range(100)],
        "s": [f"s{i % 7}" for i in range(100)],
    }
    src = tmp_path / "src"
    cio.write_parquet(ColumnBatch.from_pydict(data), str(src / "part-0.parquet"))
    hs = Hyperspace(tmp_session)
    df = tmp_session.read.parquet(str(src))
    return tmp_session, hs, df, src


class TestCreate:
    def test_create_and_layout(self, env, tmp_path):
        session, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"], ["v"]))
        root = tmp_path / "indexes" / "idx1"
        assert (root / "_hyperspace_log" / "0").exists()  # CREATING
        assert (root / "_hyperspace_log" / "1").exists()  # ACTIVE
        assert (root / "_hyperspace_log" / "latestStable").exists()
        assert (root / "v__=0").is_dir()
        files = os.listdir(root / "v__=0")
        assert files and all(f.endswith(".parquet") for f in files)
        entry = hs.get_index("idx1")
        assert entry.state == S.ACTIVE
        assert entry.derived_dataset.indexed_columns() == ["k"]
        assert len(entry.source_file_infos()) == 1

    def test_index_data_is_projection(self, env, tmp_path):
        session, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"], ["v"]))
        entry = hs.get_index("idx1")
        batch = cio.read_parquet(entry.content.files())
        assert set(batch.schema.names) == {"k", "v"}
        assert batch.num_rows == 100
        assert sorted(batch.to_pydict()["k"]) == list(range(100))

    def test_bucketed_and_sorted(self, env):
        session, hs, df, _ = env
        session.set_conf(C.INDEX_NUM_BUCKETS, 4)
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"], ["v"]))
        entry = hs.get_index("idx1")
        from hyperspace_tpu.models.covering import bucket_id_from_filename
        from hyperspace_tpu.ops.bucketize import bucket_ids_for_batch

        for f in entry.content.files():
            b = bucket_id_from_filename(f)
            assert b is not None and 0 <= b < 4
            batch = cio.read_parquet([f])
            ids = bucket_ids_for_batch(batch, ["k"], 4)
            assert (ids == b).all()
            ks = batch.column("k").data
            assert (np.diff(ks) >= 0).all()  # sorted within bucket

    def test_duplicate_name_rejected(self, env):
        _, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))
        with pytest.raises(HyperspaceError, match="already exists"):
            hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))

    def test_unresolvable_column_rejected(self, env):
        _, hs, df, _ = env
        with pytest.raises(HyperspaceError, match="resolved"):
            hs.create_index(df, CoveringIndexConfig("bad", ["nope"]))

    def test_case_insensitive_columns(self, env):
        _, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["K"], ["V"]))
        entry = hs.get_index("idx1")
        assert entry.derived_dataset.indexed_columns() == ["k"]

    def test_lineage_column_written(self, env):
        session, hs, df, _ = env
        session.set_conf(C.INDEX_LINEAGE_ENABLED, True)
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"], ["v"]))
        entry = hs.get_index("idx1")
        assert entry.has_lineage_column()
        batch = cio.read_parquet(entry.content.files())
        assert C.DATA_FILE_NAME_ID in batch.schema.names
        assert (batch.column(C.DATA_FILE_NAME_ID).data == 0).all()


class TestLifecycle:
    def test_delete_restore(self, env):
        _, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))
        hs.delete_index("idx1")
        assert hs.get_index("idx1").state == S.DELETED
        hs.restore_index("idx1")
        assert hs.get_index("idx1").state == S.ACTIVE

    def test_delete_requires_active(self, env):
        _, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))
        hs.delete_index("idx1")
        with pytest.raises(HyperspaceError):
            hs.delete_index("idx1")

    def test_vacuum_removes_data(self, env, tmp_path):
        _, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))
        hs.delete_index("idx1")
        hs.vacuum_index("idx1")
        root = tmp_path / "indexes" / "idx1"
        assert not (root / "v__=0").exists()
        entry_state = IndexLogManager(str(root)).get_latest_log().state
        assert entry_state == S.DOESNOTEXIST

    def test_vacuum_requires_deleted(self, env):
        _, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))
        with pytest.raises(HyperspaceError):
            hs.vacuum_index("idx1")

    def test_missing_index_errors(self, env):
        _, hs, _, _ = env
        with pytest.raises(HyperspaceError, match="could not be found"):
            hs.delete_index("ghost")

    def test_cancel_rolls_back(self, env, tmp_path):
        session, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))
        # simulate a crashed refresh: write a transient REFRESHING entry
        lm = IndexLogManager(str(tmp_path / "indexes" / "idx1"))
        from hyperspace_tpu.meta.entry import LogEntry

        e = LogEntry(state=S.REFRESHING)
        e.stamp()
        assert lm.write_log(2, e)
        hs.cancel("idx1")
        assert hs.get_index("idx1").state == S.ACTIVE

    def test_cancel_on_stable_rejected(self, env):
        _, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))
        with pytest.raises(HyperspaceError, match="transient"):
            hs.cancel("idx1")

    def test_creating_failure_then_cancel_doesnotexist(self, env, tmp_path):
        session, hs, df, _ = env
        lm = IndexLogManager(str(tmp_path / "indexes" / "broken"))
        from hyperspace_tpu.meta.entry import LogEntry

        e = LogEntry(state=S.CREATING)
        e.stamp()
        lm.write_log(0, e)
        hs.cancel("broken")
        assert lm.get_latest_log().state == S.DOESNOTEXIST


class TestRefresh:
    def _append(self, src, offset=1000, n=20):
        data = {
            "k": list(range(offset, offset + n)),
            "v": [i * 1.5 for i in range(n)],
            "s": [f"s{i % 7}" for i in range(n)],
        }
        cio.write_parquet(
            ColumnBatch.from_pydict(data), str(src / f"part-{offset}.parquet")
        )

    def test_refresh_full(self, env):
        session, hs, df, src = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"], ["v"]))
        self._append(src)
        hs.refresh_index("idx1", "full")
        entry = hs.get_index("idx1")
        assert entry.state == S.ACTIVE
        assert len(entry.source_file_infos()) == 2
        batch = cio.read_parquet(entry.content.files())
        assert batch.num_rows == 120
        # new version dir
        assert any("v__=1" in f for f in entry.content.files())

    def test_refresh_no_change_is_noop(self, env):
        _, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))
        v_before = hs.get_index_versions("idx1")
        hs.refresh_index("idx1", "full")  # NoChangesError swallowed
        assert hs.get_index_versions("idx1") == v_before

    def test_refresh_incremental_append(self, env):
        session, hs, df, src = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"], ["v"]))
        self._append(src)
        hs.refresh_index("idx1", "incremental")
        entry = hs.get_index("idx1")
        batch = cio.read_parquet(entry.content.files())
        assert batch.num_rows == 120  # merged content covers both versions
        files = entry.content.files()
        assert any("v__=0" in f for f in files) and any("v__=1" in f for f in files)

    def test_refresh_incremental_delete_requires_lineage(self, env, tmp_path):
        session, hs, df, src = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))
        os.unlink(src / "part-0.parquet")
        self._append(src)
        with pytest.raises(HyperspaceError, match="lineage"):
            hs.refresh_index("idx1", "incremental")

    def test_refresh_incremental_with_deletes(self, env, tmp_path):
        session, hs, df, src = env
        session.set_conf(C.INDEX_LINEAGE_ENABLED, True)
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"], ["v"]))
        self._append(src, offset=500, n=10)
        os.unlink(src / "part-0.parquet")
        hs.refresh_index("idx1", "incremental")
        entry = hs.get_index("idx1")
        batch = cio.read_parquet(entry.content.files())
        # original 100 rows gone, 10 appended remain
        assert batch.num_rows == 10
        assert sorted(batch.to_pydict()["k"]) == list(range(500, 510))

    def test_refresh_quick_records_delta(self, env):
        session, hs, df, src = env
        session.set_conf(C.INDEX_LINEAGE_ENABLED, True)
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"], ["v"]))
        self._append(src)
        hs.refresh_index("idx1", "quick")
        entry = hs.get_index("idx1")
        assert len(entry.appended_files()) == 1
        assert not entry.deleted_files()
        # index data untouched
        batch = cio.read_parquet(entry.content.files())
        assert batch.num_rows == 100


class TestOptimize:
    def test_optimize_compacts_buckets(self, env, tmp_path, monkeypatch):
        session, hs, df, src = env
        session.set_conf(C.INDEX_NUM_BUCKETS, 2)
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"], ["v"]))
        # incremental refresh after append creates a second file per bucket
        data = {"k": list(range(200, 260)), "v": [0.0] * 60, "s": ["x"] * 60}
        cio.write_parquet(ColumnBatch.from_pydict(data), str(src / "p2.parquet"))
        hs.refresh_index("idx1", "incremental")
        entry = hs.get_index("idx1")
        files_before = entry.content.files()
        assert len(files_before) > 2  # multiple files in some bucket
        hs.optimize_index("idx1", "quick")
        entry2 = hs.get_index("idx1")
        files_after = entry2.content.files()
        # compaction: one file per bucket now
        from hyperspace_tpu.models.covering import bucket_id_from_filename

        buckets = [bucket_id_from_filename(f) for f in files_after]
        assert len(buckets) == len(set(buckets))
        batch = cio.read_parquet(files_after)
        assert batch.num_rows == 160

    def test_optimize_noop_when_single_files(self, env):
        _, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))
        v = hs.get_index_versions("idx1")
        hs.optimize_index("idx1", "quick")  # nothing to do
        assert hs.get_index_versions("idx1") == v

    def test_invalid_mode(self, env):
        _, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))
        with pytest.raises(HyperspaceError, match="Invalid optimize mode"):
            hs.optimize_index("idx1", "bogus")


class TestVacuumOutdated:
    def test_drops_old_versions(self, env, tmp_path, src_append=None):
        session, hs, df, src = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"], ["v"]))
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [1000], "v": [0.0], "s": ["x"]}),
            str(src / "p2.parquet"),
        )
        hs.refresh_index("idx1", "full")  # content now only v__=1
        root = tmp_path / "indexes" / "idx1"
        assert (root / "v__=0").is_dir()
        hs.vacuum_outdated_index("idx1")
        assert not (root / "v__=0").exists()
        assert (root / "v__=1").is_dir()
        assert hs.get_index("idx1").state == S.ACTIVE


class TestIndexesListing:
    def test_indexes_df(self, env):
        _, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idxA", ["k"], ["v"]))
        hs.create_index(df, CoveringIndexConfig("idxB", ["v"]))
        out = hs.indexes().to_pydict()
        assert sorted(out["name"]) == ["idxA", "idxB"]
        assert set(out["state"]) == {S.ACTIVE}
        one = hs.index("idxA").to_pydict()
        assert one["name"] == ["idxA"]
        assert one["numIndexFiles"][0] >= 1

    def test_get_index_versions(self, env):
        _, hs, df, _ = env
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))
        assert hs.get_index_versions("idx1") == [1, 0]  # ACTIVE@1, CREATING@0
        assert hs.get_index_versions("idx1", [S.ACTIVE]) == [1]


class TestTelemetry:
    def test_events_captured(self, env):
        session, hs, df, _ = env
        import importlib

        from hyperspace_tpu.telemetry.logger import clear_event_logger_cache

        clear_event_logger_cache(session)
        session.set_conf(
            C.EVENT_LOGGER_CLASS, "tests.test_index_manager.CapturingLogger"
        )
        # the logger factory resolves the dotted path through importlib, which
        # may load a second copy of this module — assert against that copy
        canonical = importlib.import_module("tests.test_index_manager").CapturingLogger
        canonical.events.clear()
        hs.create_index(df, CoveringIndexConfig("idx1", ["k"]))
        hs.delete_index("idx1")
        names = [type(e).__name__ for e in canonical.events]
        assert "CreateActionEvent" in names
        assert "DeleteActionEvent" in names
        msgs = [e.message for e in canonical.events]
        assert "started" in msgs and "succeeded" in msgs
        clear_event_logger_cache(session)


class CapturingLogger:
    events: list = []

    def log_event(self, event):
        CapturingLogger.events.append(event)



class TestIndexesOverCsvJson:
    def test_covering_index_over_csv(self, tmp_session, tmp_path):
        from hyperspace_tpu.plan import col

        (tmp_path / "c").mkdir()
        (tmp_path / "c" / "a.csv").write_text("k,v\n1,1.5\n2,2.5\n")
        (tmp_path / "c" / "b.csv").write_text("k,v\n3,3.5\n")
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.csv(str(tmp_path / "c"))
        hs.create_index(df, CoveringIndexConfig("csvidx", ["k"], ["v"]))
        tmp_session.enable_hyperspace()
        q = tmp_session.read.csv(str(tmp_path / "c")).filter(col("k") == 2).select("k", "v")
        plan = q.optimized_plan()
        assert any(getattr(n, "index_info", None) for n in plan.preorder())
        assert q.to_pydict() == {"k": [2], "v": [2.5]}
        # refresh after an append to the csv source
        tmp_session.disable_hyperspace()
        (tmp_path / "c" / "d.csv").write_text("k,v\n9,9.5\n")
        hs.refresh_index("csvidx", "full")
        tmp_session.enable_hyperspace()
        q2 = tmp_session.read.csv(str(tmp_path / "c")).filter(col("k") == 9).select("v")
        assert any(
            getattr(n, "index_info", None) for n in q2.optimized_plan().preorder()
        ), "refreshed index must serve the query"
        assert q2.to_pydict() == {"v": [9.5]}

    def test_covering_index_over_json(self, tmp_session, tmp_path):
        from hyperspace_tpu.plan import col

        (tmp_path / "j").mkdir()
        (tmp_path / "j" / "a.json").write_text('{"k": 1, "v": 10.0}\n{"k": 2, "v": 20.0}\n')
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.json(str(tmp_path / "j"))
        hs.create_index(df, CoveringIndexConfig("jidx", ["k"], ["v"]))
        tmp_session.enable_hyperspace()
        q = tmp_session.read.json(str(tmp_path / "j")).filter(col("k") == 2).select("k", "v")
        assert any(getattr(n, "index_info", None) for n in q.optimized_plan().preorder())
        assert q.to_pydict() == {"k": [2], "v": [20.0]}
