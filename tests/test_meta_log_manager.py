"""Transaction log tests (ref: IndexLogManagerImplTest — optimistic
concurrency write races, stable-log fallback scan)."""

import json
import os

from hyperspace_tpu.meta.log_manager import IndexLogManager
from hyperspace_tpu.meta.entry import LogEntry
from hyperspace_tpu.meta.data_manager import IndexDataManager
from hyperspace_tpu.meta.path_resolver import PathResolver
from hyperspace_tpu.config import HyperspaceConf


def entry(state, log_id=0):
    e = LogEntry(state=state, id=log_id)
    e.stamp()
    return e


class TestIndexLogManager:
    def test_write_then_read(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        assert m.get_latest_id() is None
        assert m.write_log(0, entry("CREATING"))
        got = m.get_log(0)
        assert got is not None and got.state == "CREATING" and got.id == 0

    def test_write_existing_id_fails(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        assert m.write_log(0, entry("CREATING"))
        assert not m.write_log(0, entry("CREATING"))  # optimistic loss

    def test_latest_stable_pointer(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        m.write_log(0, entry("CREATING"))
        m.write_log(1, entry("ACTIVE"))
        assert m.create_latest_stable_log(1)
        stable = m.get_latest_stable_log()
        assert stable.state == "ACTIVE" and stable.id == 1

    def test_stable_pointer_refused_for_transient(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        m.write_log(0, entry("CREATING"))
        assert not m.create_latest_stable_log(0)

    def test_backward_scan_fallback(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        m.write_log(0, entry("CREATING"))
        m.write_log(1, entry("ACTIVE"))
        m.write_log(2, entry("REFRESHING"))
        # no pointer file; scan should pass REFRESHING and find ACTIVE@1
        stable = m.get_latest_stable_log()
        assert stable.state == "ACTIVE" and stable.id == 1

    def test_backward_scan_stops_at_creating(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        m.write_log(0, entry("CREATING"))
        assert m.get_latest_stable_log() is None

    def test_get_index_versions(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        m.write_log(0, entry("CREATING"))
        m.write_log(1, entry("ACTIVE"))
        m.write_log(2, entry("REFRESHING"))
        m.write_log(3, entry("ACTIVE"))
        assert m.get_index_versions(["ACTIVE"]) == [3, 1]
        assert m.get_index_versions() == [3, 2, 1, 0]

    def test_latest_log(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        m.write_log(0, entry("CREATING"))
        m.write_log(1, entry("ACTIVE"))
        assert m.get_latest_log().id == 1

    def test_on_disk_layout(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        m.write_log(0, entry("CREATING"))
        m.write_log(1, entry("ACTIVE"))
        m.create_latest_stable_log(1)
        log_dir = tmp_path / "idx" / "_hyperspace_log"
        assert sorted(os.listdir(log_dir)) == ["0", "1", "latestStable"]
        with open(log_dir / "1") as f:
            d = json.load(f)
        assert d["state"] == "ACTIVE" and d["version"] == "0.1"


class TestIndexDataManager:
    def test_versions(self, tmp_path):
        dm = IndexDataManager(str(tmp_path / "idx"))
        assert dm.get_all_versions() == []
        assert dm.get_latest_version() is None
        os.makedirs(dm.version_path(0))
        os.makedirs(dm.version_path(2))
        assert dm.get_all_versions() == [0, 2]
        assert dm.get_latest_version() == 2
        assert dm.version_path(2).endswith("v__=2")
        dm.delete_version(0)
        assert dm.get_all_versions() == [2]


class TestPathResolver:
    def test_default_system_path(self, tmp_path):
        r = PathResolver(HyperspaceConf({}), warehouse_dir=str(tmp_path))
        assert r.system_path == str(tmp_path / "indexes")

    def test_conf_override(self, tmp_path):
        conf = HyperspaceConf({"hyperspace.system.path": str(tmp_path / "custom")})
        r = PathResolver(conf, warehouse_dir="ignored")
        assert r.system_path == str(tmp_path / "custom")

    def test_case_insensitive_match(self, tmp_path):
        root = tmp_path / "indexes"
        (root / "MyIndex").mkdir(parents=True)
        r = PathResolver(HyperspaceConf({}), warehouse_dir=str(tmp_path))
        assert r.get_index_path("myindex") == str(root / "MyIndex")
        assert r.get_index_path("other") == str(root / "other")
