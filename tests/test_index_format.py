"""Arrow IPC index data format (conf hyperspace.tpu.index.format=arrow):
same layout, filenames (modulo extension), query results, and lifecycle
behavior as the default parquet format — readers dispatch per file
extension, so indexes built under either setting (or a mix, e.g. a refresh
under a different conf) stay readable.
"""

import os

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.models.zorder import ZOrderCoveringIndexConfig
from hyperspace_tpu.plan import col, Sum
from hyperspace_tpu.plan.nodes import FileScan


def _index_scans(plan):
    return [
        n for n in plan.preorder()
        if isinstance(n, FileScan) and n.index_info is not None
    ]


@pytest.fixture()
def env(tmp_session, tmp_path):
    n = 2000
    data = {
        "k": [i % 40 for i in range(n)],
        "v": [float(i) for i in range(n)],
        "s": [f"tag-{i % 7}" for i in range(n)],
        "d": [(i * 13) % 365 for i in range(n)],
    }
    cio.write_parquet(
        ColumnBatch.from_pydict(data), str(tmp_path / "t" / "part-0.parquet")
    )
    hs = Hyperspace(tmp_session)
    return tmp_session, hs, tmp_path


def _query(session, root):
    df = session.read.parquet(str(root / "t"))
    return (
        df.filter(col("k") == 7)
        .group_by("k")
        .agg(Sum(col("v")).alias("sv"))
        .collect()
        .to_pydict()
    )


class TestArrowIndexFormat:
    def test_conf_validation(self, tmp_session):
        tmp_session.set_conf(C.INDEX_FORMAT, "feather")
        from hyperspace_tpu.exceptions import HyperspaceError

        with pytest.raises(HyperspaceError):
            tmp_session.conf.index_format

    def test_covering_arrow_end_to_end(self, env):
        session, hs, root = env
        expected = _query(session, root)
        session.set_conf(C.INDEX_FORMAT, "arrow")
        df = session.read.parquet(str(root / "t"))
        hs.create_index(df, CoveringIndexConfig("ci_arrow", ["k"], ["v", "s"]))

        entry = hs.get_index("ci_arrow")
        files = entry.content.files()
        assert files and all(f.endswith(".arrow") for f in files)

        session.enable_hyperspace()
        q = session.read.parquet(str(root / "t")).filter(col("k") == 7).group_by(
            "k"
        ).agg(Sum(col("v")).alias("sv"))
        assert _index_scans(q.optimized_plan()), "index must apply"
        got = q.collect().to_pydict()
        session.disable_hyperspace()
        assert got == expected

    def test_zorder_arrow_and_mixed_refresh(self, env):
        session, hs, root = env
        session.set_conf(C.INDEX_FORMAT, "arrow")
        df = session.read.parquet(str(root / "t"))
        # include the string column: mixed-extension layouts must also merge
        # dictionary-typed (new) with plain-string (old/externally-written)
        # files at scan time
        hs.create_index(
            df, ZOrderCoveringIndexConfig("z_arrow", ["d"], ["v", "s"])
        )
        files = hs.get_index("z_arrow").content.files()
        assert files and all(f.endswith(".arrow") for f in files)

        session.enable_hyperspace()
        q = (
            session.read.parquet(str(root / "t"))
            .filter((col("d") >= 10) & (col("d") < 50))
            .agg(Sum(col("v")).alias("sv"))
        )
        got = q.collect().to_pydict()
        session.disable_hyperspace()
        raw = q.collect().to_pydict()
        assert got == raw

        # append source data, refresh incrementally under the PARQUET conf:
        # the index becomes a mixed-extension layout and must stay readable
        extra = {
            "k": [1, 2], "v": [10.5, 11.5], "s": ["tag-1", "tag-2"], "d": [10, 11],
        }
        cio.write_parquet(
            ColumnBatch.from_pydict(extra), str(root / "t" / "part-1.parquet")
        )
        session.set_conf(C.INDEX_FORMAT, "parquet")
        hs.refresh_index("z_arrow", "incremental")
        exts = {
            os.path.splitext(f)[1]
            for f in hs.get_index("z_arrow").content.files()
        }
        assert ".arrow" in exts and ".parquet" in exts
        session.enable_hyperspace()
        got2 = q.collect().to_pydict()
        session.disable_hyperspace()
        raw2 = q.collect().to_pydict()
        assert got2 == raw2

    def test_optimize_compacts_arrow_buckets(self, env):
        session, hs, root = env
        session.set_conf(C.INDEX_FORMAT, "arrow")
        df = session.read.parquet(str(root / "t"))
        hs.create_index(df, CoveringIndexConfig("ci_opt", ["k"], ["v"]))
        extra = {"k": [3] * 5, "v": [1.0] * 5, "s": ["tag-0"] * 5, "d": [1] * 5}
        cio.write_parquet(
            ColumnBatch.from_pydict(extra), str(root / "t" / "part-2.parquet")
        )
        hs.refresh_index("ci_opt", "incremental")
        n_before = len(hs.get_index("ci_opt").content.files())
        hs.optimize_index("ci_opt", "full")
        files = hs.get_index("ci_opt").content.files()
        assert len(files) <= n_before
        assert all(f.endswith(".arrow") for f in files)
        session.enable_hyperspace()
        q = session.read.parquet(str(root / "t")).filter(col("k") == 3).agg(
            Sum(col("v")).alias("sv")
        )
        got = q.collect().to_pydict()
        session.disable_hyperspace()
        assert got == q.collect().to_pydict()


class TestLegacyStringMix:
    def test_plain_and_dictionary_string_files_concat(self, tmp_path):
        """Files written before the dictionary-emission change (plain string
        columns) must read together with files written after it."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        old = tmp_path / "old.parquet"
        pq.write_table(
            pa.table({"k": [1, 2], "s": ["a", "b"]}), str(old)
        )
        new = tmp_path / "new.parquet"
        cio.write_index_file(
            ColumnBatch.from_pydict({"k": [3, 4], "s": ["b", "c"]}), str(new)
        )
        batch = cio.read_parquet([str(old), str(new)], ["k", "s"])
        got = batch.to_pydict()
        assert got["k"] == [1, 2, 3, 4]
        assert got["s"] == ["a", "b", "b", "c"]

    def test_parquet_and_arrow_string_files_concat(self, tmp_path):
        old = tmp_path / "a.parquet"
        pq_table = ColumnBatch.from_pydict({"k": [1], "s": ["x"]})
        import pyarrow as pa
        import pyarrow.parquet as pq

        pq.write_table(pa.table({"k": [1], "s": ["x"]}), str(old))
        new = tmp_path / "b.arrow"
        cio.write_index_file(
            ColumnBatch.from_pydict({"k": [2], "s": ["y"]}), str(new)
        )
        got = cio.read_parquet([str(old), str(new)], ["k", "s"]).to_pydict()
        assert got["k"] == [1, 2] and got["s"] == ["x", "y"]


class TestArrowDeviceTier:
    def test_int_sum_on_arrow_index_does_not_crash(self, env):
        """The TPU tier's metadata row-count screen must dispatch per file
        extension (ArrowInvalid is not OSError) and decline gracefully."""
        session, hs, root = env
        session.set_conf(C.INDEX_FORMAT, "arrow")
        # int column so _has_int_sum engages the row-count screen
        big = {
            "k": [i % 10 for i in range(3000)],
            "q": [i * 1000 for i in range(3000)],
        }
        cio.write_parquet(
            ColumnBatch.from_pydict(big), str(root / "ti" / "p.parquet")
        )
        df = session.read.parquet(str(root / "ti"))
        hs.create_index(df, CoveringIndexConfig("ci_int", ["k"], ["q"]))
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            got = (
                session.read.parquet(str(root / "ti"))
                .filter(col("k") == 3)
                .agg(Sum(col("q")).alias("s"))
                .collect()
                .to_pydict()
            )
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()
        expected = sum(i * 1000 for i in range(3000) if i % 10 == 3)
        assert got["s"] == [expected]


class TestUserExportSchema:
    def test_write_parquet_keeps_plain_string_schema(self, tmp_path):
        """User-facing exports must not leak the internal dictionary
        encoding: external readers expect plain string columns."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        p = str(tmp_path / "out.parquet")
        cio.write_parquet(ColumnBatch.from_pydict({"s": ["a", "b", "a"]}), p)
        assert pa.types.is_string(pq.read_schema(p).field("s").type)
        # engine-owned index files keep the fast dictionary schema
        p2 = str(tmp_path / "ix.parquet")
        cio.write_index_file(ColumnBatch.from_pydict({"s": ["a", "b"]}), p2)
        assert pa.types.is_dictionary(pq.read_schema(p2).field("s").type)


class TestIndexWriteOpts:
    """Stats scoping + compression knobs for index data files
    (INDEX_STATS_COLUMNS / INDEX_COMPRESSION)."""

    def _covering_env(self, tmp_session, tmp_path, **conf):
        from hyperspace_tpu import CoveringIndexConfig, Hyperspace

        cio.write_parquet(
            ColumnBatch.from_pydict(
                {"k": list(range(200)), "v": [float(i) for i in range(200)]}
            ),
            str(tmp_path / "src" / "p.parquet"),
        )
        for key, val in conf.items():
            tmp_session.set_conf(key, val)
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(tmp_path / "src"))
        hs.create_index(df, CoveringIndexConfig("ci", ["k"], ["v"]))
        entry = hs.get_index("ci")
        return tmp_session, hs, [f.name for f in entry.index_data_files()]

    def test_clustered_stats_scope_default(self, tmp_session, tmp_path):
        import pyarrow.parquet as pq

        _s, _hs, files = self._covering_env(tmp_session, tmp_path)
        md = pq.ParquetFile(files[0]).metadata
        rg = md.row_group(0)
        stats = {
            rg.column(i).path_in_schema: rg.column(i).statistics
            for i in range(rg.num_columns)
        }
        assert stats["k"] is not None and stats["k"].has_min_max
        # include column carries no stats under the default "clustered" scope
        assert stats["v"] is None or not stats["v"].has_min_max

    def test_all_stats_scope(self, tmp_session, tmp_path):
        import pyarrow.parquet as pq

        from hyperspace_tpu import constants as C

        _s, _hs, files = self._covering_env(
            tmp_session, tmp_path, **{C.INDEX_STATS_COLUMNS: "all"}
        )
        rg = pq.ParquetFile(files[0]).metadata.row_group(0)
        stats = {
            rg.column(i).path_in_schema: rg.column(i).statistics
            for i in range(rg.num_columns)
        }
        assert stats["k"].has_min_max and stats["v"].has_min_max

    def test_compression_knob_roundtrip(self, tmp_session, tmp_path):
        import pyarrow.parquet as pq

        from hyperspace_tpu import constants as C
        from hyperspace_tpu.plan import col

        session, hs, files = self._covering_env(
            tmp_session, tmp_path, **{C.INDEX_COMPRESSION: "none"}
        )
        rg = pq.ParquetFile(files[0]).metadata.row_group(0)
        assert rg.column(0).compression == "UNCOMPRESSED"
        session.enable_hyperspace()
        q = (
            session.read.parquet(str(tmp_path / "src"))
            .filter(col("k") == 7)
            .select("k", "v")
        )
        assert "Hyperspace(" in q.explain_plan()
        assert q.to_pydict() == {"k": [7], "v": [7.0]}
        session.disable_hyperspace()

    def test_invalid_conf_values_raise(self, tmp_session):
        import pytest

        from hyperspace_tpu import constants as C
        from hyperspace_tpu.exceptions import HyperspaceError

        tmp_session.set_conf(C.INDEX_STATS_COLUMNS, "some")
        with pytest.raises(HyperspaceError, match="statsColumns"):
            tmp_session.conf.index_stats_columns
        tmp_session.set_conf(C.INDEX_STATS_COLUMNS, "clustered")
        tmp_session.set_conf(C.INDEX_COMPRESSION, "brotli9")
        with pytest.raises(HyperspaceError, match="compression"):
            tmp_session.conf.index_compression

    def test_zorder_keeps_stats_on_all_indexed_fields(self, tmp_session, tmp_path):
        import pyarrow.parquet as pq

        from hyperspace_tpu import Hyperspace
        from hyperspace_tpu.models.zorder import ZOrderCoveringIndexConfig

        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "a": list(range(500)),
                    "b": list(range(500, 0, -1)),
                    "x": [float(i) for i in range(500)],
                }
            ),
            str(tmp_path / "zsrc" / "p.parquet"),
        )
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(tmp_path / "zsrc"))
        hs.create_index(df, ZOrderCoveringIndexConfig("zi", ["a", "b"], ["x"]))
        files = [f.name for f in hs.get_index("zi").index_data_files()]
        rg = pq.ParquetFile(files[0]).metadata.row_group(0)
        stats = {
            rg.column(i).path_in_schema: rg.column(i).statistics
            for i in range(rg.num_columns)
        }
        # both z-order fields are clustered by the curve: stats stay
        assert stats["a"].has_min_max and stats["b"].has_min_max
        assert stats["x"] is None or not stats["x"].has_min_max
