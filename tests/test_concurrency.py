"""Reader/writer consistency: queries racing refresh/optimize cycles must
always return a correct result (old or new index state, never a broken mix).

The reference gets this from immutable log entries + versioned data dirs
(old versions survive until vacuumOutdated); this pins the same guarantee.
"""

import threading

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col, lit, Count, Sum


class TestQueryDuringMaintenance:
    def test_queries_race_refresh_cycles(self, tmp_session, tmp_path):
        session = tmp_session
        session.set_conf(C.INDEX_CACHE_EXPIRY_SECONDS, 0)  # always re-read log
        src = tmp_path / "src"
        base_n = 500
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {"k": list(range(base_n)), "v": [1.0] * base_n}
            ),
            str(src / "p0.parquet"),
        )
        hs = Hyperspace(session)
        df = session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("ridx", ["k"], ["v"]))
        session.enable_hyperspace()

        errors: list = []
        stop = threading.Event()

        def reader():
            # the reader holds a FIXED source snapshot (file listing pinned at
            # read time), so its correct answer never changes while refreshes
            # race underneath — any deviation is a consistency bug
            q = df.filter(col("k") < base_n).agg(
                Sum(col("v")).alias("s"), Count(lit(1)).alias("n")
            )
            while not stop.is_set():
                try:
                    out = q.to_pydict()
                    if out["n"][0] != base_n or abs(out["s"][0] - base_n) > 1e-9:
                        errors.append(("wrong result", out))
                        return
                except Exception as e:  # noqa: BLE001
                    errors.append(("exception", repr(e)))
                    return

        threads = [threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            for i in range(5):
                cio.write_parquet(
                    ColumnBatch.from_pydict(
                        {"k": [base_n + i], "v": [5.0]}
                    ),
                    str(src / f"extra{i}.parquet"),
                )
                hs.refresh_index("ridx", "full")
                hs.optimize_index("ridx", "quick")  # may no-op
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors, errors[:3]
        # final state sane: new files indexed
        entry = hs.get_index("ridx")
        batch = cio.read_parquet(entry.content.files())
        assert batch.num_rows == base_n + 5

    def test_concurrent_writers_one_wins_per_cycle(self, tmp_session, tmp_path):
        """Two threads refreshing the same index: optimistic concurrency must
        serialize them (one ConcurrentWriteError or clean interleave), never
        corrupt the log."""
        from hyperspace_tpu.exceptions import ConcurrentWriteError, HyperspaceError

        session = tmp_session
        src = tmp_path / "s2"
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [1], "v": [1.0]}), str(src / "p.parquet")
        )
        hs = Hyperspace(session)
        df = session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("widx", ["k"], ["v"]))
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [2], "v": [2.0]}), str(src / "p2.parquet")
        )
        results = []

        def refresher():
            try:
                hs.refresh_index("widx", "full")
                results.append("ok")
            except (ConcurrentWriteError, HyperspaceError) as e:
                results.append(type(e).__name__)

        ts = [threading.Thread(target=refresher) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert "ok" in results
        entry = hs.get_index("widx")
        assert entry.state == "ACTIVE"
        # log remains a clean sequence readable end to end
        versions = hs.get_index_versions("widx")
        assert versions == sorted(versions, reverse=True)
