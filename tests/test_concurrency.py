"""Concurrency soundness: reader/writer consistency under maintenance races,
the TrackedLock acquisition-order graph (a planted inversion must raise
LockOrderError naming the cycle), the guarded-state registry, single-flight
get_or_put atomicity, the HS304–HS306 lint rules, and N-thread query stress
over the shared caches.

The reference gets reader/writer consistency from immutable log entries +
versioned data dirs (old versions survive until vacuumOutdated); this pins
the same guarantee — and PR 6 adds the static+dynamic lock discipline the
ROADMAP-1 concurrent-serving layer depends on.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col, lit, Count, Sum
from hyperspace_tpu.staticcheck import concurrency as cc

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HSLINT = os.path.join(REPO_ROOT, "tools", "hslint.py")


@pytest.fixture()
def lock_audit():
    """Force the acquisition-order audit on for the test and restore the
    prior state (plus a clean edge graph) afterwards."""
    prev = cc.set_audit(True)
    try:
        yield
    finally:
        cc.set_audit(prev)
        cc.reset_order_graph()


class TestQueryDuringMaintenance:
    def test_queries_race_refresh_cycles(self, tmp_session, tmp_path):
        session = tmp_session
        session.set_conf(C.INDEX_CACHE_EXPIRY_SECONDS, 0)  # always re-read log
        src = tmp_path / "src"
        base_n = 500
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {"k": list(range(base_n)), "v": [1.0] * base_n}
            ),
            str(src / "p0.parquet"),
        )
        hs = Hyperspace(session)
        df = session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("ridx", ["k"], ["v"]))
        session.enable_hyperspace()

        errors: list = []
        stop = threading.Event()

        def reader():
            # the reader holds a FIXED source snapshot (file listing pinned at
            # read time), so its correct answer never changes while refreshes
            # race underneath — any deviation is a consistency bug
            q = df.filter(col("k") < base_n).agg(
                Sum(col("v")).alias("s"), Count(lit(1)).alias("n")
            )
            while not stop.is_set():
                try:
                    out = q.to_pydict()
                    if out["n"][0] != base_n or abs(out["s"][0] - base_n) > 1e-9:
                        errors.append(("wrong result", out))
                        return
                except Exception as e:  # noqa: BLE001
                    errors.append(("exception", repr(e)))
                    return

        threads = [threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            for i in range(5):
                cio.write_parquet(
                    ColumnBatch.from_pydict(
                        {"k": [base_n + i], "v": [5.0]}
                    ),
                    str(src / f"extra{i}.parquet"),
                )
                hs.refresh_index("ridx", "full")
                hs.optimize_index("ridx", "quick")  # may no-op
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors, errors[:3]
        # final state sane: new files indexed
        entry = hs.get_index("ridx")
        batch = cio.read_parquet(entry.content.files())
        assert batch.num_rows == base_n + 5

    def test_concurrent_writers_one_wins_per_cycle(self, tmp_session, tmp_path):
        """Two threads refreshing the same index: optimistic concurrency must
        serialize them (one ConcurrentWriteError or clean interleave), never
        corrupt the log."""
        from hyperspace_tpu.exceptions import ConcurrentWriteError, HyperspaceError

        session = tmp_session
        src = tmp_path / "s2"
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [1], "v": [1.0]}), str(src / "p.parquet")
        )
        hs = Hyperspace(session)
        df = session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("widx", ["k"], ["v"]))
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [2], "v": [2.0]}), str(src / "p2.parquet")
        )
        results = []

        def refresher():
            try:
                hs.refresh_index("widx", "full")
                results.append("ok")
            except (ConcurrentWriteError, HyperspaceError) as e:
                results.append(type(e).__name__)

        ts = [threading.Thread(target=refresher) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert "ok" in results
        entry = hs.get_index("widx")
        assert entry.state == "ACTIVE"
        # log remains a clean sequence readable end to end
        versions = hs.get_index_versions("widx")
        assert versions == sorted(versions, reverse=True)


# ---------------------------------------------------------------------------
# TrackedLock + acquisition-order graph
# ---------------------------------------------------------------------------

class TestTrackedLock:
    def test_behaves_like_a_lock(self):
        lk = cc.TrackedLock("t_basic")
        assert lk.acquire()
        assert lk.locked()
        lk.release()
        assert not lk.locked()
        with lk:
            assert lk.locked()
        assert not lk.locked()

    def test_reentrant_variant(self):
        lk = cc.TrackedLock("t_reentrant", reentrant=True)
        with lk:
            with lk:  # RLock: same thread may nest
                assert True

    def test_registry_lists_every_named_lock(self):
        cc.TrackedLock("t_registered")
        locks = cc.registered_locks()
        assert locks.get("t_registered", 0) >= 1
        # the engine's own migrated locks are present (import side effect)
        import hyperspace_tpu.plan.kernel_cache  # noqa: F401
        import hyperspace_tpu.utils.device_cache  # noqa: F401

        locks = cc.registered_locks()
        for expected in (
            "metrics.registry", "trace.roots", "rpc_meter",
            "kernel_cache.kernel", "kernel_cache.kernel_join",
            "device_cache.device", "io.cache.index_chunk",
        ):
            assert expected in locks, expected

    def test_audit_off_records_nothing(self):
        prev = cc.set_audit(False)
        try:
            cc.reset_order_graph()
            a, b = cc.TrackedLock("t_off_a"), cc.TrackedLock("t_off_b")
            with a:
                with b:
                    pass
            assert cc.report()["edges"] == []
        finally:
            cc.set_audit(prev)


class TestLockOrderGraph:
    def test_consistent_order_never_raises(self, lock_audit):
        a, b = cc.TrackedLock("t_ok_a"), cc.TrackedLock("t_ok_b")
        for _ in range(3):
            with a:
                with b:
                    pass
        edges = {(e["from"], e["to"]) for e in cc.report()["edges"]}
        assert ("t_ok_a", "t_ok_b") in edges

    def test_planted_inversion_raises_naming_the_cycle(self, lock_audit):
        a, b = cc.TrackedLock("t_inv_a"), cc.TrackedLock("t_inv_b")
        with a:
            with b:
                pass
        with pytest.raises(cc.LockOrderError) as ei:
            with b:
                with a:
                    pass
        err = ei.value
        assert err.cycle == ("t_inv_b", "t_inv_a")
        msg = str(err)
        assert "t_inv_a" in msg and "t_inv_b" in msg
        # both stack sites land in the message (this file)
        assert msg.count("test_concurrency.py") >= 2

    def test_transitive_cycle_detected(self, lock_audit):
        a = cc.TrackedLock("t_tr_a")
        b = cc.TrackedLock("t_tr_b")
        c = cc.TrackedLock("t_tr_c")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with pytest.raises(cc.LockOrderError) as ei:
            with c:
                with a:
                    pass
        assert ei.value.cycle == ("t_tr_c", "t_tr_a", "t_tr_b")

    def test_violation_counter_increments(self, lock_audit):
        from hyperspace_tpu.telemetry.metrics import REGISTRY

        a, b = cc.TrackedLock("t_ctr_a"), cc.TrackedLock("t_ctr_b")
        with a:
            with b:
                pass
        before = REGISTRY.counter("staticcheck.lock.violations").value
        with pytest.raises(cc.LockOrderError):
            with b:
                with a:
                    pass
        after = REGISTRY.counter("staticcheck.lock.violations").value
        assert after == before + 1

    def test_cross_thread_edges_share_one_graph(self, lock_audit):
        """Thread 1 establishes a->b; thread 2's b->a nesting must raise
        even though neither thread ever saw both orders itself."""
        a, b = cc.TrackedLock("t_x_a"), cc.TrackedLock("t_x_b")
        caught: list = []

        def establish():
            with a:
                with b:
                    pass

        def invert():
            try:
                with b:
                    with a:
                        pass
            except cc.LockOrderError as e:
                caught.append(e)

        t1 = threading.Thread(target=establish)
        t1.start(); t1.join()
        t2 = threading.Thread(target=invert)
        t2.start(); t2.join()
        assert len(caught) == 1
        assert caught[0].cycle == ("t_x_b", "t_x_a")

    def test_declare_order_seeds_the_graph(self, lock_audit):
        cc.declare_order("t_dec_outer", "t_dec_inner")
        outer = cc.TrackedLock("t_dec_outer")
        inner = cc.TrackedLock("t_dec_inner")
        # declared direction is fine
        with outer:
            with inner:
                pass
        # the inverse nesting violates the declaration immediately
        with pytest.raises(cc.LockOrderError):
            with inner:
                with outer:
                    pass

    def test_release_out_of_order_tolerated(self, lock_audit):
        a, b = cc.TrackedLock("t_rel_a"), cc.TrackedLock("t_rel_b")
        a.acquire(); b.acquire()
        a.release()  # non-LIFO release must not corrupt the held-set
        b.release()
        with a:
            with b:
                pass  # and ordering still records cleanly


class TestGuardedStateRegistry:
    def test_round_trip(self):
        lk = cc.TrackedLock("t_guard_lock")
        state = cc.guarded_by({}, lk, name="test.state", note="unit fixture")
        entry = cc.guard_of(state)
        assert entry is not None
        assert entry.name == "test.state"
        assert entry.lock == "t_guard_lock"
        assert entry.kind == "dict"
        assert entry.note == "unit fixture"
        assert any(g.name == "test.state" for g in cc.guarded_state())

    def test_import_time_state_declares_none(self):
        state = cc.guarded_by([], None, name="test.import_time")
        assert cc.guard_of(state).lock == "<import-time>"

    def test_engine_state_is_declared(self):
        import hyperspace_tpu.rules.base  # noqa: F401
        import hyperspace_tpu.telemetry.trace  # noqa: F401
        import hyperspace_tpu.utils.backend  # noqa: F401

        names = {g.name for g in cc.guarded_state()}
        for expected in (
            "telemetry.trace._roots",
            "utils.backend._state",
            "rules.base._ANALYSIS_SESSIONS",
        ):
            assert expected in names, expected

    def test_report_carries_everything(self):
        rep = cc.report()
        assert set(rep) >= {
            "audit_enabled", "locks", "edges", "guarded",
            "acquisitions", "edge_count", "violations",
        }


# ---------------------------------------------------------------------------
# single-flight get_or_put atomicity
# ---------------------------------------------------------------------------

class TestGetOrPutAtomicity:
    def test_bounded_lru_factory_runs_once(self):
        from hyperspace_tpu.utils.lru import BoundedLRU

        lru = BoundedLRU(8, name="t_single_flight")
        calls: list = []
        gate = threading.Event()

        def factory():
            calls.append(1)
            gate.wait(2)  # hold every concurrent miss open
            return "value"

        results: list = []

        def worker():
            results.append(lru.get_or_put("k", factory))

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        gate.set()
        for t in threads:
            t.join()
        assert results == ["value"] * 8
        assert len(calls) == 1  # the old get/set gap double-computed here

    def test_bounded_lru_failed_build_hands_over(self):
        from hyperspace_tpu.utils.lru import BoundedLRU

        lru = BoundedLRU(8)
        attempts: list = []

        def flaky():
            attempts.append(1)
            if len(attempts) == 1:
                raise RuntimeError("first build fails")
            return 42

        with pytest.raises(RuntimeError):
            lru.get_or_put("k", flaky)
        assert lru.get_or_put("k", flaky) == 42

    def test_bytes_lru_single_flight_and_accounting(self):
        lru = cio._BytesBoundedLRU(10_000, metric_name="")
        calls: list = []
        gate = threading.Event()

        def factory():
            calls.append(1)
            gate.wait(2)
            return b"x" * 100, 100

        results: list = []

        def worker():
            results.append(lru.get_or_put("chunk", factory))

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        gate.set()
        for t in threads:
            t.join()
        assert len(calls) == 1
        assert all(r == b"x" * 100 for r in results)
        assert lru.check_consistency()

    def test_bytes_lru_eviction_accounting_stays_consistent(self):
        lru = cio._BytesBoundedLRU(250, metric_name="")
        for i in range(20):
            lru.get_or_put(i, lambda i=i: (bytes(100), 100))
        assert len(lru._d) <= 2
        assert lru.check_consistency()

    def test_kernel_cache_single_flight_builds_once(self):
        from hyperspace_tpu.plan.kernel_cache import KernelCache

        kc = KernelCache("t_single", 8)
        builds: list = []
        gate = threading.Event()

        def builder():
            builds.append(1)
            gate.wait(2)
            return lambda x: x + 1

        results: list = []

        def worker():
            results.append(kc.get_or_build(("fp",), builder, "t_kind"))

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        gate.set()
        for t in threads:
            t.join()
        assert len(builds) == 1  # concurrent misses used to trace N times
        assert len({id(r) for r in results}) == 1
        assert results[0](1) == 2
        assert kc.check_consistency()


# ---------------------------------------------------------------------------
# hslint HS304-HS306
# ---------------------------------------------------------------------------

class TestHslintConcurrencyRules:
    def _lint(self, path):
        return subprocess.run(
            [sys.executable, HSLINT, str(path), "--no-baseline"],
            capture_output=True, text=True, timeout=120,
        )

    def test_planted_violations_caught(self, tmp_path):
        bad = tmp_path / "bad_concurrency.py"
        bad.write_text(
            "import threading\n"
            "from concurrent.futures import ThreadPoolExecutor\n"
            "_SHARED: dict = {}\n"
            "_a_lock = threading.Lock()\n"
            "_b_lock = threading.Lock()\n"
            "def f():\n"
            "    _SHARED['k'] = 1\n"
            "    t = threading.Thread(target=f)\n"
            "    pool = ThreadPoolExecutor(max_workers=2)\n"
            "    with _a_lock:\n"
            "        with _b_lock:\n"
            "            pass\n"
        )
        proc = self._lint(bad)
        assert proc.returncode == 1
        for code in ("HS304", "HS305", "HS306"):
            assert code in proc.stdout, f"{code} missing:\n{proc.stdout}"
        assert proc.stdout.count("HS304") == 2  # Thread AND pool ctor

    def test_guard_declaration_and_declared_edge_silence(self, tmp_path):
        ok = tmp_path / "ok_concurrency.py"
        ok.write_text(
            "import threading\n"
            "from hyperspace_tpu.staticcheck.concurrency import guarded_by\n"
            "DECLARED_EDGES = {('_a_lock', '_b_lock')}\n"
            "_SHARED: dict = {}\n"
            "guarded_by(_SHARED, None, name='fixture')\n"
            "_a_lock = threading.Lock()\n"
            "_b_lock = threading.Lock()\n"
            "def f():\n"
            "    _SHARED['k'] = 1\n"
            "    with _a_lock:\n"
            "        with _b_lock:\n"
            "            pass\n"
        )
        proc = self._lint(ok)
        assert proc.returncode == 0, proc.stdout

    def test_suppression_comments_silence(self, tmp_path):
        ok = tmp_path / "ok_suppressed.py"
        ok.write_text(
            "import threading\n"
            "_SHARED: dict = {}  # hslint: HS305 — fixture\n"
            "_a_lock = threading.Lock()\n"
            "_b_lock = threading.Lock()\n"
            "def f():\n"
            "    _SHARED['k'] = 1\n"
            "    t = threading.Thread(target=f)  # hslint: HS304 — fixture\n"
            "    with _a_lock:\n"
            "        # hslint: HS306 — fixture\n"
            "        with _b_lock:\n"
            "            pass\n"
        )
        proc = self._lint(ok)
        assert proc.returncode == 0, proc.stdout

    def test_nested_function_does_not_inherit_lock_context(self, tmp_path):
        ok = tmp_path / "ok_nested_def.py"
        ok.write_text(
            "import threading\n"
            "_a_lock = threading.Lock()\n"
            "_b_lock = threading.Lock()\n"
            "def f():\n"
            "    with _a_lock:\n"
            "        def later():\n"
            "            with _b_lock:  # runs later, not nested\n"
            "                pass\n"
            "        return later\n"
        )
        proc = self._lint(ok)
        assert proc.returncode == 0, proc.stdout


# ---------------------------------------------------------------------------
# N-thread stress over the shared kernel/chunk/device caches
# ---------------------------------------------------------------------------

class TestThreadedQueryStress:
    def _bits(self, d):
        return repr(
            {
                k: [x.hex() if isinstance(x, float) else x for x in v]
                for k, v in d.items()
            }
        )

    def test_eight_threads_bit_identical_to_serial(
        self, tmp_session, tmp_path, lock_audit
    ):
        from hyperspace_tpu.telemetry.metrics import REGISTRY

        session = tmp_session
        src = tmp_path / "stress_src"
        rng = np.random.default_rng(5)
        n = 4000
        for i in range(4):  # multi-file: engages the streaming reader
            cio.write_parquet(
                ColumnBatch.from_pydict(
                    {
                        "k": (np.arange(n, dtype=np.int64) + i * n).tolist(),
                        "g": rng.integers(0, 50, n).tolist(),
                        "v": rng.uniform(0, 100, n).tolist(),
                    }
                ),
                str(src / f"p{i}.parquet"),
            )
        hs = Hyperspace(session)
        df = session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("sidx", ["k"], ["g", "v"]))
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)

        queries = {
            "agg": lambda: df.filter(col("k") < 3 * n).agg(
                Count(lit(1)).alias("n"), Sum(col("g")).alias("sg")
            ).to_pydict(),
            "point": lambda: df.filter(col("k") == 1234).select(
                "k", "g", "v"
            ).to_pydict(),
            "range": lambda: df.filter(
                (col("k") >= n) & (col("k") < n + 500)
            ).select("k", "v").to_pydict(),
        }
        serial = {name: self._bits(q()) for name, q in queries.items()}

        before_violations = REGISTRY.counter("staticcheck.lock.violations").value
        mismatches: list = []
        errors: list = []
        names = list(queries)
        barrier = threading.Barrier(8)

        def worker(tid):
            try:
                barrier.wait()
                for r in range(2):
                    for off in range(len(names)):
                        name = names[(tid + r + off) % len(names)]
                        if self._bits(queries[name]()) != serial[name]:
                            mismatches.append((tid, name))
            except Exception as e:  # noqa: BLE001
                errors.append((tid, repr(e)))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
        assert not mismatches, mismatches[:5]
        after_violations = REGISTRY.counter("staticcheck.lock.violations").value
        assert after_violations == before_violations
        # shared-cache byte accounting survived the stampede
        assert cio._INDEX_CHUNK_CACHE.check_consistency()
        assert cio._ROWGROUP_STATS_CACHE.check_consistency()
        from hyperspace_tpu.plan import kernel_cache as kc
        from hyperspace_tpu.utils import device_cache as dc

        assert kc.KERNEL_CACHE.check_consistency()
        assert dc.DEVICE_CACHE.check_consistency()
        assert dc.HOST_DERIVED_CACHE.check_consistency()
