"""Kernel-layer tests: hashing, bucketize, sketches, z-order, join prims.

Each device kernel has a host (numpy) reference; tests assert agreement, the
analogue of the reference's expression-level unit tests (e.g. ZOrderFieldTest
bit-level checks, BloomFilter sketch tests).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperspace_tpu.ops import hashing as H
from hyperspace_tpu.ops import bucketize as B
from hyperspace_tpu.ops import sketch as SK
from hyperspace_tpu.ops import zorder as Z
from hyperspace_tpu.ops import join as J
from hyperspace_tpu.columnar.table import ColumnBatch


class TestHashing:
    def test_np_jnp_agree_int32(self):
        x = np.arange(-500, 500, dtype=np.int32)
        hn = H.hash32_np([x])
        hj = np.asarray(H.hash32_jnp([jnp.asarray(x)]))
        assert np.array_equal(hn, hj)

    def test_np_jnp_agree_float32(self):
        x = np.linspace(-1e6, 1e6, 1000).astype(np.float32)
        assert np.array_equal(
            H.hash32_np([x]), np.asarray(H.hash32_jnp([jnp.asarray(x)]))
        )

    def test_int64_words_agree_with_split(self):
        x = np.array([0, 1, -1, 2**40, -(2**40), 2**62], dtype=np.int64)
        lo, hi = H.split64_np(x)
        # hashing int64 directly must equal hashing its (lo, hi) words
        assert np.array_equal(H.hash32_np([x]), H.hash32_np([lo, hi]))
        assert np.array_equal(H.merge64_np(lo, hi, np.int64), x)

    def test_bucket_distribution(self):
        x = np.arange(100000, dtype=np.int64)
        b = H.bucket_ids_np([x], 8)
        counts = np.bincount(b, minlength=8)
        assert counts.min() > 100000 / 8 * 0.9  # roughly uniform

    def test_string_hash_stable_across_vocab_order(self):
        words1 = H.string_key_words(np.array([0, 1, 2]), ["a", "b", "c"])
        words2 = H.string_key_words(np.array([2, 1, 0]), ["c", "b", "a"])
        assert np.array_equal(words1, words2)

    def test_multi_column(self):
        a = np.array([1, 1, 2], dtype=np.int32)
        b = np.array([1, 2, 1], dtype=np.int32)
        h = H.hash32_np([a, b])
        assert h[0] != h[1] and h[0] != h[2] and h[1] != h[2]


class TestBucketize:
    def test_partition_covers_all_rows(self):
        batch = ColumnBatch.from_pydict(
            {"k": list(range(1000)), "v": [i * 2 for i in range(1000)]}
        )
        parts = B.partition_batch(batch, ["k"], 8)
        all_rows = np.concatenate([rows for _, rows in parts])
        assert sorted(all_rows.tolist()) == list(range(1000))
        ids = B.bucket_ids_for_batch(batch, ["k"], 8)
        for b, rows in parts:
            assert (ids[rows] == b).all()

    def test_string_bucket_keys(self):
        batch = ColumnBatch.from_pydict({"s": ["x", "y", "z", "x"]})
        ids = B.bucket_ids_for_batch(batch, ["s"], 4)
        assert ids[0] == ids[3]

    def test_sort_within(self):
        batch = ColumnBatch.from_pydict({"a": [3, 1, 2], "b": ["c", "a", "b"]})
        order = B.sort_indices_within(batch, ["a"])
        assert order.tolist() == [1, 2, 0]
        order2 = B.sort_indices_within(batch, ["b"])
        assert order2.tolist() == [1, 2, 0]


class TestSketch:
    def test_segment_min_max_agree(self):
        rng = np.random.default_rng(1)
        vals = rng.normal(size=1000).astype(np.float32)
        segs = rng.integers(0, 10, 1000)
        mn_np, mx_np = SK.segment_min_max_np(vals, segs, 10)
        mn_j, mx_j = SK.segment_min_max_jnp(
            jnp.asarray(vals), jnp.asarray(segs), 10
        )
        assert np.allclose(mn_np, np.asarray(mn_j))
        assert np.allclose(mx_np, np.asarray(mx_j))

    def test_bloom_no_false_negatives(self):
        bf = SK.BloomFilter.create(1000, 0.01)
        keys = np.arange(1000, dtype=np.int64)
        bf.add_words([keys])
        assert bf.might_contain_words([keys]).all()

    def test_bloom_fpp_reasonable(self):
        bf = SK.BloomFilter.create(1000, 0.01)
        bf.add_words([np.arange(1000, dtype=np.int64)])
        probe = np.arange(100000, 200000, dtype=np.int64)
        fp_rate = bf.might_contain_words([probe]).mean()
        assert fp_rate < 0.05

    def test_bloom_merge_and_serialize(self):
        a = SK.BloomFilter.create(100, 0.01)
        b = SK.BloomFilter.create(100, 0.01)
        a.add_words([np.array([1, 2, 3], dtype=np.int64)])
        b.add_words([np.array([100, 200], dtype=np.int64)])
        m = a.merge(b)
        assert m.might_contain_words([np.array([2, 200], dtype=np.int64)]).all()
        rt = SK.BloomFilter.from_dict(m.to_dict())
        assert rt == m

    def test_device_build_matches_host(self):
        keys32 = np.arange(500, dtype=np.int32)
        host = SK.BloomFilter.create(500, 0.01)
        host.add_words([keys32])
        unpacked = SK.bloom_build_bits_jnp(
            [jnp.asarray(keys32)], host.num_bits, host.num_hashes
        )
        packed = SK.pack_bits(np.asarray(unpacked))
        assert np.array_equal(packed, host.bits[: len(packed)])

    def test_device_probe(self):
        keys32 = np.arange(500, dtype=np.int32)
        m, k = SK.bloom_params(500, 0.01)
        bits = SK.bloom_build_bits_jnp([jnp.asarray(keys32)], m, k)
        hits = SK.bloom_probe_bits_jnp(bits, [jnp.asarray(keys32)], k)
        assert np.asarray(hits).all()


class TestZOrder:
    def test_two_field_interleave(self):
        # x=0b10, y=0b01 -> MSB-first round robin: x1 y0 x0 y1 = 0b1001
        x = np.array([0b10], dtype=np.uint64)
        y = np.array([0b01], dtype=np.uint64)
        z = Z.interleave_bits([(x, 2), (y, 2)])
        assert z[0] == 0b1001

    def test_uneven_bits(self):
        # a has 3 bits (0b111), b has 1 bit (0b1): a2 b0 a1 a0 -> 0b1111
        a = np.array([0b111], dtype=np.uint64)
        b = np.array([0b1], dtype=np.uint64)
        z = Z.interleave_bits([(a, 3), (b, 1)])
        assert z[0] == 0b1111

    def test_jnp_agrees(self):
        rng = np.random.default_rng(2)
        a = rng.integers(0, 256, 100).astype(np.uint64)
        b = rng.integers(0, 256, 100).astype(np.uint64)
        zn = Z.interleave_bits([(a, 8), (b, 8)])
        zj = Z.interleave_bits_jnp(
            [(jnp.asarray(a.astype(np.uint32)), 8), (jnp.asarray(b.astype(np.uint32)), 8)]
        )
        assert np.array_equal(zn.astype(np.uint32), np.asarray(zj))

    def test_locality(self):
        # points near each other in 2D should be near in z-order on average
        xs, ys = np.meshgrid(np.arange(16, dtype=np.uint64), np.arange(16, dtype=np.uint64))
        z = Z.interleave_bits([(xs.ravel(), 4), (ys.ravel(), 4)])
        assert len(np.unique(z)) == 256  # bijective

    def test_scale_min_max(self):
        v = np.array([0.0, 50.0, 100.0])
        s = Z.scale_min_max(v, 0.0, 100.0, 4)
        assert s[0] == 0 and s[2] == 15 and 6 <= s[1] <= 8

    def test_scale_percentile(self):
        v = np.array([1.0, 5.0, 100.0, 1000.0])
        bounds = np.array([2.0, 50.0, 500.0])  # 2 bits -> 4 buckets
        s = Z.scale_percentile(v, bounds, 2)
        assert s.tolist() == [0, 1, 2, 3]

    def test_too_many_bits_raises(self):
        from hyperspace_tpu.exceptions import HyperspaceError

        with pytest.raises(HyperspaceError):
            Z.interleave_bits([(np.zeros(1, np.uint64), 40), (np.zeros(1, np.uint64), 40)])


class TestJoinPrims:
    def test_merge_match_counts(self):
        left = jnp.asarray(np.array([1, 2, 2, 5], dtype=np.int32))
        right = jnp.asarray(np.array([2, 2, 3, 5, 5, 5], dtype=np.int32))
        lo, counts = J.merge_match_counts(left, right)
        assert np.asarray(counts).tolist() == [0, 2, 2, 3]

    def test_segment_sum_by_sorted_key(self):
        keys = jnp.asarray(np.array([1, 1, 2, 2, 2, 7], dtype=np.int32))
        vals = jnp.asarray(np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0], dtype=np.float32))
        uniq = jnp.asarray(np.array([1, 2, 5, 7], dtype=np.int32))
        sums = J.segment_sum_by_sorted_key(keys, vals, uniq)
        assert np.asarray(sums).tolist() == [3.0, 12.0, 0.0, 6.0]

    def test_lookup_sorted(self):
        tk = jnp.asarray(np.array([1, 3, 5], dtype=np.int32))
        tv = jnp.asarray(np.array([10, 30, 50], dtype=np.int32))
        q = jnp.asarray(np.array([3, 4, 5, 0], dtype=np.int32))
        vals, found = J.lookup_sorted(tk, tv, q, jnp.int32(-1))
        assert np.asarray(vals).tolist() == [30, -1, 50, -1]
        assert np.asarray(found).tolist() == [True, False, True, False]

    def test_host_merge_join(self):
        li, ri = J.host_merge_join_indices(
            np.array([1, 2, 2, 5]), np.array([2, 2, 3, 5])
        )
        pairs = list(zip(li.tolist(), ri.tolist()))
        assert pairs == [(1, 0), (1, 1), (2, 0), (2, 1), (3, 3)]


class TestSegmentMinMaxNaN:
    def test_np_masks_nan(self):
        import numpy as np
        from hyperspace_tpu.ops.sketch import segment_min_max_np

        vals = np.array([1.0, np.nan, 3.0, np.nan], np.float32)
        segs = np.array([0, 0, 1, 1])
        mins, maxs = segment_min_max_np(vals, segs, 2)
        assert mins[0] == 1.0 and maxs[0] == 1.0
        assert mins[1] == 3.0 and maxs[1] == 3.0

    def test_jnp_matches_np(self):
        import numpy as np
        import jax.numpy as jnp
        from hyperspace_tpu.ops.sketch import segment_min_max_jnp, segment_min_max_np

        vals = np.array([1.0, np.nan, 3.0, 2.0, np.nan], np.float32)
        segs = np.array([0, 0, 1, 1, 1])
        mn, mx = segment_min_max_np(vals, segs, 2)
        jmn, jmx = segment_min_max_jnp(jnp.asarray(vals), jnp.asarray(segs), 2)
        np.testing.assert_array_equal(mn, np.asarray(jmn))
        np.testing.assert_array_equal(mx, np.asarray(jmx))

    def test_all_nan_segment_keeps_empty_bounds(self):
        import numpy as np
        from hyperspace_tpu.ops.sketch import segment_min_max_np

        vals = np.array([np.nan, np.nan], np.float32)
        segs = np.array([0, 0])
        mins, maxs = segment_min_max_np(vals, segs, 1)
        # inverted (empty) interval: no finite value matches, same as an
        # empty file — equality predicates correctly skip it
        assert mins[0] == np.inf and maxs[0] == -np.inf
